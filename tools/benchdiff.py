#!/usr/bin/env python
"""benchdiff — the bench-trajectory regression gate.

Compares two or more driver bench artifacts (`BENCH_r*.json`)
metric-by-metric: the LAST file is the candidate round, the metric
baseline is the most recent EARLIER round carrying that metric (phases
come and go across rounds; a metric new in the candidate has no baseline
and is reported as such, never gated). Each artifact is the driver's
record: `{n, cmd, rc, tail, parsed}` where `parsed` is bench.py's final
stdout JSON line (`{metric, value, unit, vs_baseline, phases: {...}}`).

Why this exists: BENCH_r05 came back `rc=124, parsed: null` and nothing
noticed — the perf trajectory was blind, so no PR could prove it didn't
regress the 2.8M rows/s headline. This gate makes two failure classes
loud and machine-checkable:

- a candidate round that FAILED to produce an artifact (`parsed` null /
  nonzero rc) exits nonzero by itself — a dead bench is a regression;
- a HEADLINE metric (the tumbling rows/s line, full-pipe rows/s, e2e
  p99) regressing beyond its noise tolerance exits nonzero.

Everything else — per-phase rows/s, latency percentiles, degradation —
is compared with the same direction-aware noise tolerance and flagged in
the report, but only headline metrics gate (phase metrics on a shared CI
box are noisy; the gate must not cry wolf).

Usage:
  python tools/benchdiff.py BENCH_r04.json BENCH_r06.json
  python tools/benchdiff.py BENCH_r0*.json          # trajectory view
  python tools/benchdiff.py --tolerance 0.15 A.json B.json
  python tools/benchdiff.py --smoke                 # tier-1 self-test

Exit codes: 0 ok; 1 headline regression or failed candidate round;
2 usage/artifact error.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

#: metrics that GATE (exit 1 on regression): (flat key, tolerance).
#: Tolerances are per-metric noise allowances measured off the recorded
#: round-to-round jitter — throughput on a quiet box swings ~10%, e2e
#: tail latency much more (one GC pause moves a p99), so the p99 gate
#: only catches step-function regressions, not jitter.
HEADLINE = (
    ("headline.value", 0.10),
    ("phases.full_pipe.rows_per_sec", 0.15),
    ("phases.full_pipe.e2e_p99_ms", 0.50),
    # QoS churn soak (ISSUE 9): healthy-rule emit p99 under sustained
    # rule churn + skew shifts + backpressure — same loose tail
    # tolerance as the full-pipe p99 (one GC pause moves a p99)
    ("phases.churn_soak.soak_p99_ms", 0.50),
    # sliding DABA rings (ISSUE 11): trigger→sink emit tail on the
    # constant-time sliding path, saturated + paced twins — a sliding
    # latency regression gates ci_gate every round, not report-only
    ("phases.sliding_saturated.emit_p99_ms", 0.50),
    ("phases.sliding_paced.emit_p99_ms", 0.50),
    # compiled expression IR (ISSUE 12): a filter-heavy rule must stay
    # fold-limited — its throughput gates alongside the tumbling line,
    # and the predicate-lifted shared fold's dedup ratio must hold
    ("phases.filter_heavy.rows_per_sec", 0.15),
    # device relational tier (ISSUE 19): interval-join match throughput
    # and the per-window emission tail through the join ring — a kernel
    # or emission-reconstruction regression gates every round
    ("phases.join_heavy.rows_per_sec", 0.15),
    ("phases.join_heavy.emit_p99_ms", 0.50),
    ("phases.multi_rule_shared_mixed.mixed_where_dedup_ratio", 0.10),
    # tiered key state (ISSUE 13): sustained rows/s and emit tail while
    # the cold tier absorbs a 1M->10M cardinality sweep under a fixed
    # HBM budget — a tiering-policy regression (demote storms stalling
    # folds, promote misses) shows up in exactly these two
    ("phases.key_cardinality.rows_per_sec", 0.15),
    ("phases.key_cardinality.emit_p99_ms", 0.50),
    # multi-chip sharded serving (ISSUE 15): the saturated tumbling full
    # pipe on the device mesh gates every round instead of a dryrun —
    # same throughput tolerance as the single-chip full-pipe line
    ("phases.multichip_full_pipe.rows_per_sec", 0.15),
    # AOT executable cache (ISSUE 16): rule-create→first-fold on a warm
    # disk cache is the zero-compile-restart claim — a serve-path
    # compile sneaking back in moves this from tens of ms to seconds,
    # far past any tolerance; ordinary scheduler jitter stays inside it
    ("phases.cold_start.warm.rule_create_to_first_fold_ms", 0.50),
)

#: default noise tolerance for every non-headline comparison
DEFAULT_TOLERANCE = 0.10

#: flat-key suffixes where LOWER is better; everything else numeric that
#: we compare is higher-better (throughput-shaped). Order matters only
#: for readability — first suffix match wins.
LOWER_IS_BETTER = ("_ms", "_us", "us_per_call", "_pct", "_bytes_peak",
                   # fleet observatory (ISSUE 20), report-only — shard
                   # imbalance and priced collective time should trend
                   # down (observatory_overhead_pct rides the _pct rule)
                   "skew_ratio", "collective_ms_p50")

#: suffixes compared at all — a flat key must end in one of these (either
#: direction) to be diffed; other numeric leaves (counts, booleans,
#: config echoes like pool/shards/burners) are context, not performance
HIGHER_IS_BETTER = ("_per_sec", "_per_s", "rows_per_sec", "dedup_ratio",
                    "roofline_util", "_util")


def classify(key: str) -> Optional[str]:
    """'higher' | 'lower' | None (not a perf metric)."""
    if key == "headline.value":  # the tumbling rows/s line
        return "higher"
    leaf = key.rsplit(".", 1)[-1]
    for suf in LOWER_IS_BETTER:
        if leaf.endswith(suf):
            return "lower"
    for suf in HIGHER_IS_BETTER:
        if leaf.endswith(suf):
            return "higher"
    return None


def flatten(artifact: Dict[str, Any]) -> Dict[str, float]:
    """Numeric perf metrics of one round as flat dotted keys:
    `headline.value` plus every classified leaf under `parsed.phases`."""
    parsed = artifact.get("parsed") or {}
    out: Dict[str, float] = {}
    v = parsed.get("value")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        out["headline.value"] = float(v)

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, dict):
            for k, sub in node.items():
                walk(f"{prefix}.{k}", sub)
        elif (isinstance(node, (int, float))
              and not isinstance(node, bool)
              and math.isfinite(float(node))
              and classify(prefix) is not None):
            out[prefix] = float(node)

    walk("phases", parsed.get("phases") or {})
    return out


def round_ok(artifact: Dict[str, Any]) -> Tuple[bool, str]:
    """(usable, reason). A round is usable when it carries a parsed
    artifact; rc is reported but only a MISSING artifact disqualifies
    (the bench's own watchdogs exit rc=3 WITH a valid final JSON)."""
    rc = artifact.get("rc")
    if not isinstance(artifact.get("parsed"), dict):
        return False, f"parsed is null (rc={rc}) — the r05 failure class"
    if not flatten(artifact):
        return False, f"parsed carries no comparable metrics (rc={rc})"
    return True, f"rc={rc}"


def compare(rounds: List[Tuple[str, Dict[str, Any]]],
            tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Diff the last round against per-metric baselines from the earlier
    ones. Returns {candidate, baseline_names, rows, regressions,
    headline_regressions, candidate_ok, candidate_reason}; `rows` is one
    entry per metric present in the candidate or any baseline."""
    cand_name, cand = rounds[-1]
    ok, reason = round_ok(cand)
    out: Dict[str, Any] = {
        "candidate": cand_name, "candidate_ok": ok,
        "candidate_reason": reason,
        "baselines": [n for n, _ in rounds[:-1]],
        "rows": [], "regressions": [], "headline_regressions": [],
    }
    if not ok:
        return out
    flats = [(name, flatten(a)) for name, a in rounds]
    cand_flat = flats[-1][1]
    headline_tol = dict(HEADLINE)
    keys = sorted({k for _, f in flats for k in f})
    for key in keys:
        cur = cand_flat.get(key)
        base = base_name = None
        for name, f in reversed(flats[:-1]):  # most recent earlier round
            if key in f:
                base, base_name = f[key], name
                break
        row: Dict[str, Any] = {"metric": key, "baseline": base,
                               "baseline_round": base_name,
                               "candidate": cur}
        if base is None or cur is None:
            row["status"] = ("new" if base is None else "dropped")
            if cur is None and key in headline_tol:
                # a HEADLINE metric that VANISHES gates like a regression:
                # a partially-dead bench (full_pipe child timed out, the
                # tumbling headline still printed) must not pass the
                # trajectory gate on whole-artifact survival alone
                out["regressions"].append(row)
                out["headline_regressions"].append(row)
            out["rows"].append(row)
            continue
        direction = classify(key)
        tol = headline_tol.get(key, tolerance)
        if base == 0.0:
            # no ratio exists over a zero baseline: a nonzero value
            # appearing is a full-size change, never inside tolerance
            # (a 0ms stall becoming 500ms must flag, not divide by zero)
            delta = math.inf if cur > 0 else (
                -math.inf if cur < 0 else 0.0)
            row["delta_pct"] = None if cur else 0.0
        else:
            delta = (cur - base) / abs(base)
            row["delta_pct"] = round(delta * 100.0, 1)
        worse = -delta if direction == "higher" else delta
        if worse > tol:
            row["status"] = "REGRESSION"
            out["regressions"].append(row)
            if key in headline_tol:
                out["headline_regressions"].append(row)
        elif worse < -tol:
            row["status"] = "improved"
        else:
            row["status"] = "ok"
        row["tolerance_pct"] = round(tol * 100.0, 1)
        out["rows"].append(row)
    return out


def report(cmp: Dict[str, Any], verbose: bool = False) -> None:
    """Human-readable diff on stdout (the gate's evidence trail)."""
    base = ", ".join(cmp["baselines"]) or "(none)"
    print(f"benchdiff: {base} -> {cmp['candidate']}")
    if not cmp["candidate_ok"]:
        print(f"  CANDIDATE ROUND FAILED: {cmp['candidate_reason']}")
        return
    for row in cmp["rows"]:
        status = row.get("status")
        gates = row in cmp["headline_regressions"]
        if status in ("ok", "new", "dropped") and not verbose and not gates:
            continue
        if status in ("new", "dropped"):
            print(f"  {'!! ' if gates else ''}{status:<10} {row['metric']}"
                  + (" (HEADLINE vanished — gates)" if gates else ""))
            continue
        mark = {"REGRESSION": "!!", "improved": "++"}.get(status, "  ")
        dp = row["delta_pct"]
        delta_txt = f"{dp:+.1f}%" if dp is not None else "from zero"
        print(f"  {mark} {status:<10} {row['metric']}: "
              f"{row['baseline']:g} -> {row['candidate']:g} "
              f"({delta_txt}, tol ±{row['tolerance_pct']}%)")
    n_reg = len(cmp["regressions"])
    n_head = len(cmp["headline_regressions"])
    print(f"  {len(cmp['rows'])} metrics compared, {n_reg} regression(s), "
          f"{n_head} headline")


def gate(cmp: Dict[str, Any]) -> int:
    """Exit code for one comparison: 1 on failed candidate or headline
    regression, else 0 (non-headline regressions are report-only)."""
    if not cmp["candidate_ok"]:
        return 1
    return 1 if cmp["headline_regressions"] else 0


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        d = json.load(f)
    if not isinstance(d, dict):
        raise ValueError(f"{path}: artifact must be a JSON object")
    return d


# --------------------------------------------------------------------- smoke
def smoke() -> int:
    """Tier-1 self-test (like kuiperdiag --smoke): synthetic artifacts
    exercise the pass / headline-regression / failed-round paths without
    touching real BENCH files."""

    def art(value, phases=None, rc=0, parsed=True):
        return {"n": 1, "cmd": "bench", "rc": rc, "tail": "",
                "parsed": ({"metric": "t", "value": value, "unit": "rows/s",
                            "phases": phases or {}} if parsed else None)}

    base = art(2_800_000, {
        "full_pipe": {"rows_per_sec": 1_000_000.0, "e2e_p99_ms": 4.0,
                      "decoder": "native"},
        "sliding_saturated": {"fold_stall_p50_ms": 50.0}})
    problems = []
    # 1) small wobble inside tolerance + a phase improvement -> exit 0
    good = art(2_700_000, {
        "full_pipe": {"rows_per_sec": 1_050_000.0, "e2e_p99_ms": 4.2,
                      "decoder": "native"},
        "sliding_saturated": {"fold_stall_p50_ms": 20.0}})
    cmp1 = compare([("r1", base), ("r2", good)])
    if gate(cmp1) != 0 or cmp1["regressions"]:
        problems.append(f"clean round flagged: {cmp1['regressions']}")
    if not any(r["status"] == "improved" for r in cmp1["rows"]):
        problems.append("sliding stall improvement not detected")
    # 2) headline collapse -> exit 1, named in headline_regressions
    bad = art(1_500_000, {"full_pipe": {"rows_per_sec": 990_000.0,
                                        "e2e_p99_ms": 4.0}})
    cmp2 = compare([("r1", base), ("r2", bad)])
    if gate(cmp2) != 1:
        problems.append("headline -46% did not gate")
    if [r["metric"] for r in cmp2["headline_regressions"]] != \
            ["headline.value"]:
        problems.append(f"wrong headline set: {cmp2['headline_regressions']}")
    # 3) non-headline regression alone -> flagged but exit 0
    slow = art(2_800_000, {
        "full_pipe": {"rows_per_sec": 1_000_000.0, "e2e_p99_ms": 4.0},
        "sliding_saturated": {"fold_stall_p50_ms": 400.0}})
    cmp3 = compare([("r1", base), ("r2", slow)])
    if gate(cmp3) != 0 or len(cmp3["regressions"]) != 1:
        problems.append(f"phase-only regression mishandled: "
                        f"{cmp3['regressions']}")
    # 4) the r05 class: candidate parsed null -> exit 1
    cmp4 = compare([("r1", base), ("r2", art(0, rc=124, parsed=False))])
    if gate(cmp4) != 1 or cmp4["candidate_ok"]:
        problems.append("parsed-null candidate did not gate")
    # 5) metric baseline skips rounds that lack it (r05-shaped hole)
    hole = art(2_750_000)  # no phases at all, still has headline
    cmp5 = compare([("r1", base), ("r2", hole), ("r3", good)])
    row = next(r for r in cmp5["rows"]
               if r["metric"] == "phases.full_pipe.rows_per_sec")
    if row.get("baseline_round") != "r1":
        problems.append(f"baseline did not skip the hole: {row}")
    # 6) a HEADLINE metric vanishing (full_pipe child died, tumbling
    # headline survived) gates even though the artifact parsed fine
    gone = art(2_800_000)  # headline only, no phases
    cmp6 = compare([("r1", base), ("r2", gone)])
    if gate(cmp6) != 1 or not any(
            r["status"] == "dropped" for r in cmp6["headline_regressions"]):
        problems.append("vanished headline metric did not gate")
    if problems:
        print("benchdiff --smoke: FAILED: " + "; ".join(problems))
        return 1
    print("benchdiff --smoke: OK (6 scenarios)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifacts", nargs="*",
                    help="BENCH_r*.json driver artifacts, oldest first; "
                         "the last is the candidate")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="noise tolerance for non-headline metrics "
                         f"(fraction, default {DEFAULT_TOLERANCE})")
    ap.add_argument("--verbose", action="store_true",
                    help="also print unchanged/new/dropped metrics")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-test and exit")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    if len(args.artifacts) < 2:
        ap.error("need at least two artifacts (or --smoke)")
    try:
        rounds = [(os.path.basename(p), _load(p)) for p in args.artifacts]
    except (OSError, ValueError) as exc:
        print(f"benchdiff: {exc}", file=sys.stderr)
        return 2
    cmp = compare(rounds, tolerance=args.tolerance)
    report(cmp, verbose=args.verbose)
    return gate(cmp)


if __name__ == "__main__":
    sys.exit(main())
