#!/usr/bin/env python
"""chaos — fault-injection churn harness for the QoS control plane.

Drives an in-process engine (RestApi over the memory connector) through
the failure shapes ROADMAP item 5 names, so the `churn_soak` bench phase
and the control-plane tests exercise the SAME storm:

- **rule churn**: create/update/delete cycles over a fleet of host-path
  rules (hundreds over a soak) while a small set of device-path workload
  rules keeps folding — admission control prices every create;
- **hot-key skew shift**: a zipf-flavored publisher whose hot key moves,
  the cardinality/imbalance shape that breaks static tuning;
- **backpressure waves**: periodic burst publishes that overflow node
  buffers (drop-oldest) and light up the queue-depth high-water marks;
- **kill/restore mid-storm**: a hard topo teardown (NO stop-time state
  save — recovery must come from the last checkpoint barrier) followed
  by `RuleRegistry.recover()`.

Everything the harness observes comes from the public surfaces (REST
dispatch, StatManager drop taxonomy, flight recorder, controller
diagnostics), so a green summary here is the same evidence kuiperdiag
would collect postmortem.

CLI (a compressed self-contained storm, mostly for manual poking):
  python tools/chaos.py [--seconds 20] [--churn-rules 40] [--json]
"""
from __future__ import annotations

import json
import os
import random
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the engine's closed drop taxonomy — a reason outside this set is an
#: UNEXPLAINED drop and fails the soak (utils/metrics.py + node.py +
#: runtime/control.py shed gate)
DROP_TAXONOMY = frozenset({
    "buffer_full", "pane_recycle", "decode_error", "stale_watermark",
    "shed_qos",
})


class ChaosHarness:
    """One storm over one in-process RestApi. All rule CRUD goes through
    REST dispatch so admission control prices it exactly as production
    traffic would."""

    def __init__(self, api, stream: str = "chaos",
                 topic: str = "chaos/t", seed: int = 23,
                 pool: int = 0) -> None:
        self.api = api
        self.stream = stream
        self.topic = topic
        # pool > 0 runs the device-path rules over POOLED sources
        # (decode_pool_size>0): the storm then exercises the decode
        # pool / ingest ring end-to-end, which is what the QoS
        # autosize actuator resizes — inline sources (the default) are
        # contractually never converted, so a soak over them can never
        # see an autosize event
        self.pool = int(pool)
        self.rng = random.Random(seed)
        self.counters: Dict[str, int] = {
            "created": 0, "updated": 0, "deleted": 0,
            "create_rejected": 0, "create_queued": 0, "create_failed": 0,
        }
        self._churn_ids: List[str] = []
        self._churn_seq = 0

    # ------------------------------------------------------------- setup
    def ensure_stream(self) -> None:
        code, out = self.api.dispatch("POST", "/streams", {
            "sql": f"CREATE STREAM {self.stream} "
                   "(deviceId STRING, v FLOAT) "
                   f'WITH (DATASOURCE="{self.topic}", TYPE="memory", '
                   'FORMAT="JSON")'}, {})
        if code not in (200, 201) and "already" not in str(out):
            raise RuntimeError(f"stream create failed: {out}")

    def _opts(self, options: Dict[str, Any]) -> Dict[str, Any]:
        """Rule options + the harness's source-pool configuration. The
        pool knobs are part of the subtopo key, so rules created with
        the same values share one pooled source pipeline."""
        if self.pool > 0:
            options = {"decodePoolSize": self.pool,
                       "ingestRingDepth": 2, **options}
        return options

    def _create(self, rule_json: Dict[str, Any]) -> Optional[str]:
        code, out = self.api.dispatch("POST", "/rules", rule_json, {})
        if code in (200, 201):
            self.counters["created"] += 1
            if isinstance(out, dict) and out.get("admission") == "queued":
                self.counters["create_queued"] += 1
            return rule_json["id"]
        if code == 429:
            # structured admission rejection — the decision payload is
            # the contract under test (reason + price, not a bare error)
            self.counters["create_rejected"] += 1
            adm = (out or {}).get("admission") or {}
            if not adm.get("reason") or "price" not in adm:
                raise RuntimeError(
                    f"unstructured admission rejection: {out}")
            return None
        self.counters["create_failed"] += 1
        raise RuntimeError(f"rule create failed ({code}): {out}")

    def workload_rules(self, n: int = 4, window_s: int = 1,
                       slo_p99_ms: int = 5000) -> List[str]:
        """Correlated device-path rules (they share one pane fold, so N
        rules cost ~1 compile on CPU) with a healthy SLO."""
        ids = []
        for i in range(n):
            rid = f"chaos_work{i}"
            self._create({
                "id": rid,
                "sql": ("SELECT deviceId, avg(v) AS a, count(*) AS c "
                        f"FROM {self.stream} GROUP BY deviceId, "
                        f"TUMBLINGWINDOW(ss, {window_s})"),
                "actions": [{"nop": {}}],
                # critical: the workload fleet is the "healthy rules
                # must HOLD their p99" control group — exempt from
                # shedding, relieved by the victim/churn sheds instead
                "options": self._opts(
                    {"qosClass": "critical",
                     "slo": {"latencyP99Ms": slo_p99_ms}}),
            })
            ids.append(rid)
        return ids

    def victim_rule(self, rid: str = "chaos_victim") -> str:
        """A private device rule with an unmeetable SLO (p99 <= 1ms) and
        the `low` qos class: it WILL breach under load, and the control
        plane must shed ITS input while the workload rules hold."""
        self._create({
            "id": rid,
            "sql": ("SELECT deviceId, avg(v) AS a FROM "
                    f"{self.stream} GROUP BY deviceId, "
                    "TUMBLINGWINDOW(ss, 1)"),
            "actions": [{"nop": {}}],
            # bufferLength 2: under storm load its queues overflow
            # constantly, so DROP burn breaches it deterministically
            # even when its (compile-delayed) window emissions are too
            # sparse for the latency windows to accrue consecutively
            "options": self._opts(
                {"sharedFold": False, "qosClass": "low",
                 "bufferLength": 2,
                 "slo": {"latencyP99Ms": 1, "target": 0.99,
                         "maxDropRatio": 0.00001}}),
        })
        return rid

    def checkpoint_rule(self, rid: str = "chaos_ckpt") -> str:
        """qos=1 rule whose state survives the hard kill through the
        checkpoint path (not the graceful stop-time save)."""
        self._create({
            "id": rid,
            "sql": (f"SELECT deviceId, count(*) AS c FROM {self.stream} "
                    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 2)"),
            "actions": [{"nop": {}}],
            # e2e of a 2s window is ~2s by construction — the SLO must
            # bound the TAIL beyond that, not the window dwell itself
            "options": self._opts(
                {"qos": 1, "checkpointInterval": 1000,
                 "qosClass": "high",
                 "slo": {"latencyP99Ms": 10_000}}),
        })
        return rid

    # ------------------------------------------------------------- churn
    def churn_step(self, target_live: int = 40) -> None:
        """One create/update/delete step over the host-path churn fleet,
        biased to keep ~target_live rules alive."""
        op = self.rng.random()
        if not self._churn_ids or (op < 0.5
                                   and len(self._churn_ids) < target_live):
            self._churn_seq += 1
            rid = f"chaos_churn{self._churn_seq}"
            thr = round(self.rng.uniform(-1.0, 1.0), 3)
            if self._create({
                "id": rid,
                "sql": (f"SELECT deviceId, v FROM {self.stream} "
                        f"WHERE v > {thr}"),
                "actions": [{"nop": {}}],
                "options": {"qosClass": "low"},
            }) is not None:
                self._churn_ids.append(rid)
        elif op < 0.75 and self._churn_ids:
            rid = self.rng.choice(self._churn_ids)
            thr = round(self.rng.uniform(-1.0, 1.0), 3)
            code, out = self.api.dispatch("PUT", f"/rules/{rid}", {
                "id": rid,
                "sql": (f"SELECT deviceId, v FROM {self.stream} "
                        f"WHERE v > {thr}"),
                "actions": [{"nop": {}}],
                "options": {"qosClass": "low"},
            }, {})
            if code == 200:
                self.counters["updated"] += 1
        else:
            rid = self._churn_ids.pop(
                self.rng.randrange(len(self._churn_ids)))
            code, _out = self.api.dispatch("DELETE", f"/rules/{rid}",
                                           None, {})
            if code == 200:
                self.counters["deleted"] += 1

    # ---------------------------------------------------------- publishing
    def publish_skew(self, rows: int, hot_key: int, n_keys: int = 256,
                     hot_share: float = 0.8) -> None:
        """One skewed drain: `hot_share` of rows hit `hot_key`, the rest
        spread uniformly — shift `hot_key` between calls to model a skew
        shift."""
        from ekuiper_tpu.io import memory as mem

        payloads = []
        for _ in range(rows):
            if self.rng.random() < hot_share:
                k = hot_key
            else:
                k = self.rng.randrange(n_keys)
            payloads.append(json.dumps({
                "deviceId": f"dev_{k}",
                "v": round(self.rng.gauss(0.0, 1.0), 3),
            }).encode())
        mem.publish(self.topic, payloads)

    def backpressure_wave(self, rows: int = 20_000,
                          n_keys: int = 256) -> None:
        """A burst big enough to overflow 1024-deep node buffers — the
        drop-oldest path must absorb it WITH taxonomy reasons."""
        self.publish_skew(rows, hot_key=self.rng.randrange(n_keys),
                          n_keys=n_keys, hot_share=0.3)

    # -------------------------------------------------------- kill/restore
    def hard_kill(self) -> List[str]:
        """Tear every live topo down WITHOUT the graceful stop-time state
        save — the crash shape. Returns the rule ids that were running
        (recover() must bring them back from their checkpoints)."""
        from ekuiper_tpu.runtime.rule import RunState

        running = []
        for entry in self.api.rules.list():
            rid = entry["id"]
            rs = self.api.rules.state(rid)
            if rs is None or rs.topo is None:
                continue
            running.append(rid)
            rs._stop_supervision.set()
            topo = rs.topo
            topo.close()  # node teardown only — NO save_state_now()
            with rs._lock:
                rs.topo = None
                rs.state = RunState.STOPPED
        return running

    def recover(self, expect_running: List[str],
                timeout_s: float = 20.0) -> Dict[str, Any]:
        """Boot-style recovery over the same store; waits for every
        expected rule's topo to come back."""
        self.api.rules.recover()
        deadline = time.time() + timeout_s
        missing = list(expect_running)
        while missing and time.time() < deadline:
            missing = [rid for rid in expect_running
                       if (self.api.rules.state(rid) is None
                           or self.api.rules.state(rid).topo is None)]
            time.sleep(0.05)
        return {"expected": len(expect_running),
                "recovered": len(expect_running) - len(missing),
                "missing": missing}

    # ------------------------------------------------------------- summary
    def drops_by_reason(self) -> Dict[str, Dict[str, int]]:
        """{rule: {reason: n}} across every live node (own + shared)."""
        out: Dict[str, Dict[str, int]] = {}
        for entry in self.api.rules.list():
            rid = entry["id"]
            rs = self.api.rules.state(rid)
            if rs is None or rs.topo is None:
                continue
            agg: Dict[str, int] = {}
            nodes = list(rs.topo.all_nodes())
            for st, _ in rs.topo.live_shared():
                nodes.extend(getattr(st, "nodes", []))
            for n in nodes:
                for reason, c in n.stats.dropped.items():
                    agg[reason] = agg.get(reason, 0) + c
            if agg:
                out[rid] = agg
        return out

    def unexplained_drops(self) -> Dict[str, Dict[str, int]]:
        """Drop counts whose reason is outside the taxonomy — must be
        empty for a green soak."""
        bad: Dict[str, Dict[str, int]] = {}
        for rid, agg in self.drops_by_reason().items():
            unknown = {r: c for r, c in agg.items()
                       if r not in DROP_TAXONOMY and c > 0}
            if unknown:
                bad[rid] = unknown
        return bad

    def e2e_p99_ms(self, rule_ids: List[str]) -> Dict[str, float]:
        out = {}
        for rid in rule_ids:
            rs = self.api.rules.state(rid)
            if rs is None or rs.topo is None:
                continue
            snap = rs.topo.e2e_hist.snapshot()
            if snap.get("count"):
                out[rid] = float(snap["p99"])
        return out

    def summary(self) -> Dict[str, Any]:
        from ekuiper_tpu.runtime import control

        ctl = control.controller()
        out: Dict[str, Any] = {
            "churn": dict(self.counters),
            "live_rules": len(self.api.rules.list()),
            "drops_by_reason": self.drops_by_reason(),
            "unexplained_drops": self.unexplained_drops(),
        }
        if ctl is not None:
            out["admission"] = ctl.admission_counts()
            out["shed_totals"] = {
                f"{rid}|{qos}": n
                for (rid, qos), n in sorted(ctl.shed_totals().items())}
            out["autosize_events"] = ctl.autosize_events
        return out


def _cli() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--churn-rules", type=int, default=40)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # health cadence must be >= the workload window (1s): the burn
    # windows decay between ticks, and a tick that lands between two
    # window emissions sees zero samples -> burn 0 -> the FSM never
    # accrues consecutive breaching ticks
    os.environ.setdefault("KUIPER_HEALTH_INTERVAL_MS", "1500")
    os.environ.setdefault("KUIPER_CONTROL_INTERVAL_MS", "500")
    from ekuiper_tpu.server.rest import RestApi
    from ekuiper_tpu.store import kv

    api = RestApi(kv.get_store())
    h = ChaosHarness(api)
    h.ensure_stream()
    work = h.workload_rules(4)
    victim = h.victim_rule()
    ck = h.checkpoint_rule()
    deadline = time.time() + args.seconds
    hot = 0
    last_shift = time.time()
    killed_at = time.time() + args.seconds / 2
    killed = False
    while time.time() < deadline:
        h.churn_step(target_live=args.churn_rules)
        h.publish_skew(2000, hot_key=hot)
        if time.time() - last_shift >= 5.0:
            hot = (hot + 17) % 256  # one discrete shift per interval
            last_shift = time.time()
        if not killed and time.time() >= killed_at:
            running = h.hard_kill()
            rec = h.recover(running)
            print(f"# kill/restore: {rec}", file=sys.stderr)
            killed = True
        time.sleep(0.05)
    out = h.summary()
    out["e2e_p99_ms"] = h.e2e_p99_ms(work + [victim, ck])
    if args.json:
        print(json.dumps(out, indent=2, default=str))
    else:
        print(json.dumps(out, default=str))
    ok = not out["unexplained_drops"]
    # hard exit (kuiperdiag --smoke precedent): daemon node threads +
    # live jax state can segfault interpreter teardown after the verdict
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0 if ok else 1)


if __name__ == "__main__":
    sys.exit(_cli())
