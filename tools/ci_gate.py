#!/usr/bin/env python
"""ci_gate — one tier-1-safe entry point for the static-analysis suite.

Runs, in order, each gate the repo already trusts individually and
folds their outcomes into ONE JSON verdict (exit 0 iff every gate
passed):

  kuiperlint        python -m tools.kuiperlint ekuiper_tpu/   (8 passes)
  jitcert certify   derivations deterministic, closed, exercised
  jitcert diff      observed XLA signatures ⊆ certificates (CPU battery)
  probe_exprs       expression-IR smoke: CASE+IN+temporal rule plans
                    device-fused, fold parity, jitcert clean
  probe_tiering     tiered key state smoke: demote/promote parity,
                    slot recycling, cross-tier checkpoint, jitcert clean
  probe_multichip   sharded serving smoke: full-pipe parity on the
                    8-virtual-device CPU mesh, cross-mesh restore,
                    placement admission, jitcert clean
  probe_joins       device relational tier smoke: join/analytic rules
                    lift, mask+emission parity vs the host nested loop,
                    structured join_* fallbacks, jitcert clean
  check_metrics     Prometheus catalog lint (synthetic scrape vs docs)
  benchdiff --smoke trajectory-gate self-test (synthetic artifacts)
  cold_start        AOT cache round trip: bake the jitcert battery,
                    restart in-process, assert zero serve-path compiles

Usage:
  python tools/ci_gate.py [--json] [--skip GATE[,GATE...]]

Every gate runs in a subprocess with CPU jax so a crash in one cannot
take the verdict down with it; per-gate stdout tails are carried in the
JSON for postmortems. tests/test_ci_gate.py runs the full gate in
tier-1. docs/STATIC_ANALYSIS.md § CI gate.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: gate name -> argv (cwd=REPO, CPU jax)
GATES: Dict[str, List[str]] = {
    "kuiperlint": [sys.executable, "-m", "tools.kuiperlint",
                   "ekuiper_tpu/"],
    "jitcert_certify": [sys.executable, "-m", "tools.jitcert", "certify"],
    "jitcert_diff": [sys.executable, "-m", "tools.jitcert", "diff"],
    "probe_exprs": [sys.executable, "tools/probe_exprs.py"],
    "probe_tiering": [sys.executable, "tools/probe_tiering.py"],
    "probe_multichip": [sys.executable, "tools/probe_multichip.py"],
    "probe_joins": [sys.executable, "tools/probe_joins.py"],
    "probe_fleetobs": [sys.executable, "tools/probe_fleetobs.py"],
    "check_metrics": [sys.executable, "tools/check_metrics.py"],
    "benchdiff_smoke": [sys.executable, "tools/benchdiff.py", "--smoke"],
    "cold_start": [sys.executable, "-m", "tools.aot", "coldstart"],
}

#: per-gate wall bound — generous; the whole gate must stay tier-1-safe
GATE_TIMEOUT_S = 420


def run_gate(name: str, argv: List[str]) -> Dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=GATE_TIMEOUT_S, cwd=REPO, env=env)
        rc = proc.returncode
        out = (proc.stdout or "") + (proc.stderr or "")
    except subprocess.TimeoutExpired as exc:
        rc = 124
        out = (f"timeout after {GATE_TIMEOUT_S}s\n"
               f"{exc.stdout or ''}{exc.stderr or ''}")
    except OSError as exc:
        rc = 127
        out = str(exc)
    return {
        "gate": name,
        "ok": rc == 0,
        "returncode": rc,
        "seconds": round(time.perf_counter() - t0, 2),
        # enough tail for a postmortem without ballooning the verdict
        "output_tail": out[-2000:],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON verdict")
    ap.add_argument("--skip", default="",
                    help="comma-separated gate names to skip "
                         f"(of: {', '.join(GATES)})")
    args = ap.parse_args(argv)
    skip = {s.strip() for s in args.skip.split(",") if s.strip()}
    unknown = skip - set(GATES)
    if unknown:
        print(f"ci_gate: unknown gate(s) in --skip: "
              f"{', '.join(sorted(unknown))}", file=sys.stderr)
        return 2
    results = [run_gate(name, cmd) for name, cmd in GATES.items()
               if name not in skip]
    verdict = {
        "ok": all(r["ok"] for r in results),
        "gates": results,
        "skipped": sorted(skip),
        "total_seconds": round(sum(r["seconds"] for r in results), 2),
    }
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        for r in results:
            mark = "ok " if r["ok"] else "FAIL"
            print(f"  [{mark}] {r['gate']:<16} rc={r['returncode']} "
                  f"({r['seconds']}s)")
            if not r["ok"]:
                tail = r["output_tail"].strip().splitlines()[-8:]
                for line in tail:
                    print(f"         {line}")
        state = "OK" if verdict["ok"] else "FAILED"
        print(f"ci_gate: {state} ({len(results)} gate(s), "
              f"{verdict['total_seconds']}s)")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
