#!/usr/bin/env python
"""probe_tiering — tier-1 smoke for the tiered key state
(ops/tierstore.py, docs/TIERED_STATE.md).

Builds a hopping-window fused node with a deliberately tiny HBM budget
so the tier layer engages, streams keys past the hot target, forces a
demotion round, lets demoted keys reappear, and asserts:

  1. the tier engages (layout planned, touch column in the state
     pytree, key table logging new keys),
  2. emission parity: the tiered node's windows carry exactly the
     untiered reference node's groups and values — demotion, spilled
     host-side emission, and promotion are invisible in the output,
  3. slots recycle: demoted keys' slots serve new keys without growing
     the device capacity,
  4. cross-tier checkpoint: a snapshot taken with keys demoted restores
     into a fresh node that keeps answering exactly,
  5. every traced signature (fold with the touch column,
     tierstore.demote/promote) is inside its jitcert certificate
     (diff_live clean).

Run directly or through tools/ci_gate.py (gate name `probe_tiering`).
Exit 0 on success.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

SQL = ("SELECT deviceId, sum(v) AS s, count(*) AS c, min(v) AS mn "
       "FROM demo GROUP BY deviceId, HOPPINGWINDOW(ss, 4, 2)")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import Trigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils import timex

    timex.set_mock_clock(0)
    problems = []
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None

    def mk(tier_mb):
        n = FusedWindowAggNode(
            "probe_tier", stmt.window, plan,
            [d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128, prefinalize_lead_ms=0,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            emit_columnar=False, tier_budget_mb=tier_mb)
        n.state = n.gb.init_state()
        out = []
        n.emit = lambda item, count=None, _o=out: _o.append(item)
        return n, out

    tiered, out_t = mk(0.001)  # tiny budget -> layout engages
    plain, out_p = mk(0.0)
    if tiered.tier is None:
        problems.append("tier did not engage under a tight budget")
        print(json.dumps({"ok": False, "problems": problems}))
        return 1
    if "touch" not in tiered.state:
        problems.append("touch column missing from the state pytree")

    rng = np.random.default_rng(7)

    def batch(ids, vals):
        ids = np.array(ids, dtype=np.object_)
        return ColumnBatch(
            n=len(ids),
            columns={"deviceId": ids,
                     "v": np.asarray(vals, np.float64)},
            timestamps=np.zeros(len(ids), np.int64), emitter="demo")

    def feed(ids):
        vals = np.rint(rng.normal(50, 10, len(ids))).astype(np.float64)
        b1, b2 = batch(list(ids), vals), batch(list(ids), vals)
        tiered.process(b1)
        plain.process(b2)

    def boundary(ts):
        tiered.on_trigger(Trigger(ts=ts))
        plain.on_trigger(Trigger(ts=ts))

    # round 1: a cold tail of keys + a hot core
    feed([f"cold{i}" for i in range(24)] + ["hot0", "hot1"])
    boundary(2000)
    # force a demotion plan for the cold tail (the policy worker would
    # choose these after idle scans; the probe pins the decision)
    cold_slots = [i for i in range(24)]
    tiered.tier._plan = cold_slots
    tiered._tier_boundary()
    demoted = tiered.tier.demoted_total
    if demoted == 0:
        problems.append("no slots demoted")
    free_before = len(tiered.kt.free_slots())
    cap_before = tiered.gb.capacity
    # round 2: half the cold keys reappear (promotion), new keys arrive
    # (must recycle freed slots, not grow)
    feed([f"cold{i}" for i in range(0, 24, 2)]
         + [f"new{i}" for i in range(8)] + ["hot0", "hot1"])
    boundary(4000)
    if tiered.tier.promoted_total + tiered.tier.recycled_total == 0:
        problems.append("no promotions/recycles after reappearance")
    if tiered.gb.capacity != cap_before:
        problems.append(
            f"capacity grew {cap_before}->{tiered.gb.capacity} despite "
            f"{free_before} free slots")
    boundary(6000)
    tiered._drain_async_emits()
    plain._drain_async_emits()

    def flat(msgs):
        rows = {}
        for m in msgs:
            for r in (m if isinstance(m, list) else [m]):
                k = tuple(sorted(r.items()))
                rows[k] = rows.get(k, 0) + 1
        return rows

    if flat(out_t) != flat(out_p):
        a, b = flat(out_t), flat(out_p)
        diff = set(a.items()) ^ set(b.items())
        problems.append(f"emission mismatch vs untiered: {list(diff)[:4]}")

    # cross-tier checkpoint: snapshot with keys demoted, restore fresh
    snap = tiered.snapshot_state()
    restored, out_r = mk(0.001)
    restored.restore_state(snap)
    if len(restored.tier.store) != len(tiered.tier.store):
        problems.append("cold tier did not survive the checkpoint")
    out_t.clear()
    feed2 = [f"cold{i}" for i in range(1, 24, 2)]  # still-demoted keys
    vals = np.ones(len(feed2), np.float64)
    restored.process(batch(feed2, vals))
    tiered.process(batch(feed2, vals))
    restored.on_trigger(Trigger(ts=8000))
    tiered.on_trigger(Trigger(ts=8000))
    restored._drain_async_emits()
    tiered._drain_async_emits()
    if flat(out_r) != flat(out_t):
        problems.append("restored node diverged from the live node")

    d = jitcert.diff_live()
    if not d["clean"]:
        problems.append(
            "jitcert diff not clean: "
            + "; ".join(f"{u['op']}: {u['signature'][:80]}"
                        for u in d["uncertified"][:3]))

    report = {
        "ok": not problems,
        "problems": problems,
        "demoted": tiered.tier.demoted_total,
        "promoted": tiered.tier.promoted_total,
        "recycled": tiered.tier.recycled_total,
        "resident": len(tiered.tier.store),
        "host_bytes": tiered.tier.store.nbytes(),
        "free_slots": len(tiered.kt.free_slots()),
        "jitcert_clean": d["clean"],
    }
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
