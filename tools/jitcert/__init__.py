"""jitcert CLI — certify + diff the engine's compile contracts headlessly.

Two subcommands, both tier-1-safe on CPU jax (tools/ci_gate.py runs
them; tests/test_jitcert.py asserts on them):

  python -m tools.jitcert certify [--json]
      Derive certificates for a canonical battery of kernel shapes
      (tumbling / hopping / multirule / heavy-hitters / sketch) and
      verify each one is MACHINE-CHECKABLE: re-deriving from the
      recorded params reproduces the signature set bit-for-bit, the set
      is closed (not truncated), and every SITE_DERIVATIONS op is
      exercised by at least one battery kernel. Exit 1 on any failure.

  python -m tools.jitcert diff [--json]
      Drive the same battery through real folds/finalizes on CPU jax,
      then diff devwatch's OBSERVED signatures against the registered
      certificates (observability/jitcert.py diff_live). Exit 1 when
      any observed signature falls outside its certificate — the same
      gate bench rounds and /diagnostics/xla apply to live engines.

The battery intentionally exercises the signature axes the derivations
encode: capacity growth across the slot-dtype boundary, validity-mask
presence flips, event-time pane vectors, masked edge refolds, dynamic
pane masks, and the sketch's pow-2 value pad ladder.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))  # repo root


def _battery():
    """Construct the canonical kernel battery. Imports jax lazily so
    `certify --help` works anywhere."""
    import numpy as np  # noqa: F401

    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.groupby import DeviceGroupBy
    from ekuiper_tpu.ops.sketches import CountMinSketch
    from ekuiper_tpu.parallel.multirule import (BatchedGroupBy,
                                                build_rule_batch)
    from ekuiper_tpu.sql.parser import parse_select

    def plan(sql):
        p = extract_kernel_plan(parse_select(sql))
        assert p is not None, sql
        return p

    from ekuiper_tpu.ops.slidingring import RingLayout, SlidingRing

    tumbling = plan("SELECT deviceId, avg(v) AS a, count(*) AS c "
                    "FROM s GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
    hopping = plan("SELECT deviceId, min(v) AS mn, max(v) AS mx FROM s "
                   "GROUP BY deviceId, HOPPINGWINDOW(ss, 4, 1)")
    # sliding ring battery kernel: additive (count/hist) + two-stack
    # (min) components over a small plan-time ring geometry
    sliding = plan("SELECT deviceId, count(*) AS c, min(v) AS mn, "
                   "percentile_approx(v, 0.5) AS p FROM s GROUP BY "
                   "deviceId, SLIDINGWINDOW(ss, 2) OVER (WHEN v > 90)")
    sliding_gb = DeviceGroupBy(sliding, capacity=32, n_panes=5,
                               micro_batch=16)
    sliding_ring = SlidingRing(
        sliding_gb,
        RingLayout(bucket_ms=500, n_ring_panes=4, n_panes=5,
                   span_buckets=3, scratch_pane=4))
    hh = plan("SELECT deviceId, heavy_hitters(tag, 2) AS hh FROM s "
              "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
    # expression-IR kernel: device-compiled CASE + string-dict IN +
    # temporal WHERE — the fold signature family gains int32 derived
    # columns (__sd_*/__ts32_*, KernelPlan.col_dtypes), which the
    # _derive_fold dtype axis must close over
    expr = plan("SELECT deviceId, sum(CASE WHEN status = 'ok' THEN v "
                "ELSE 0.0 END) AS s, count(*) AS c FROM s "
                "WHERE status IN ('ok', 'warn') AND hour(ets) < 23 "
                "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
    mr_sqls = [
        f"SELECT deviceId, count(*) AS c FROM s WHERE v > {t} "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)" for t in (1.0, 2.0)]
    mr_spec = build_rule_batch(
        ["jc_r1", "jc_r2"],
        [parse_select(q) for q in mr_sqls])
    # tiered kernel (ops/tierstore.py): the touch column changes EVERY
    # groupby site's state signature, and the demote/promote gather/
    # scatter sites get their own certificates — both derive here and
    # drive in the diff battery (incl. a grow across a doubling)
    from ekuiper_tpu.ops.tierstore import TierLayout, TierStore

    tiered = plan("SELECT deviceId, avg(v) AS a, min(v) AS mn FROM s "
                  "GROUP BY deviceId, HOPPINGWINDOW(ss, 2, 1)")
    tiered_gb = DeviceGroupBy(tiered, capacity=32, n_panes=2,
                              micro_batch=16, track_touch=True)
    tier_store = TierStore(
        tiered_gb, TierLayout(hot_slots=16, demote_batch=4,
                              scan_interval_ms=500, min_idle_scans=1))
    kernels = {
        "groupby_tumbling": DeviceGroupBy(tumbling, capacity=32,
                                          n_panes=1, micro_batch=16),
        "groupby_hopping": DeviceGroupBy(hopping, capacity=32, n_panes=4,
                                         micro_batch=16),
        "groupby_hh": DeviceGroupBy(hh, capacity=32, n_panes=1,
                                    micro_batch=16),
        "groupby_expr": DeviceGroupBy(expr, capacity=32, n_panes=1,
                                      micro_batch=16),
        "multirule": BatchedGroupBy(mr_spec, capacity=32, n_panes=1,
                                    micro_batch=16),
        "sketch": CountMinSketch(depth=2, width=64, max_candidates=16),
        "sliding_ring": sliding_ring,
        "groupby_tiered": tiered_gb,
        "tier_store": tier_store,
    }
    # relational tier (ops/joinring.py, ops/segscan.py): interval join
    # with an ON residual (the residual column dtypes enter the match
    # signature) and the analytic scan pair, driven across a capacity
    # doubling in the diff battery
    from ekuiper_tpu.planner import relational
    from ekuiper_tpu.ops.segscan import SegScan

    jstmt = parse_select(
        "SELECT l.v, r.w FROM l INNER JOIN r ON l.k = r.k "
        "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 AND l.v > r.w "
        "GROUP BY TUMBLINGWINDOW(ss, 1)")
    kernels["join_ring"] = relational.lower_join(
        jstmt, jstmt.joins).build_ring(capacity=32)
    kernels["segscan"] = SegScan(capacity=32)
    # sharded battery kernel (multi-chip serving, parallel/sharded.py):
    # the shard_map fold/finalize family driven across a capacity
    # doubling — needs >= 4 devices (2x2 mesh); the CLI forces 8 virtual
    # CPU devices (main() below) so CI always has them, and certify's
    # exemption stays honest on a 1-device box
    try:
        import jax

        from ekuiper_tpu.parallel.mesh import make_mesh
        from ekuiper_tpu.parallel.sharded import ShardedGroupBy

        devs = jax.devices()
        if len(devs) >= 4:
            mesh = make_mesh(rows=2, keys=2, devices=devs[:4])
            sharded_plan = plan(
                "SELECT deviceId, avg(v) AS a, min(v) AS mn, "
                "count(*) AS c FROM s GROUP BY deviceId, "
                "HOPPINGWINDOW(ss, 2, 1)")
            kernels["sharded_fold"] = ShardedGroupBy(
                sharded_plan, mesh, capacity=32, n_panes=2,
                micro_batch=16)
    except Exception as exc:
        # recorded, not swallowed: certify() fails when a >=4-device
        # host cannot construct the sharded kernel — silently re-opening
        # the sharded exemption would hide exactly the regression class
        # the battery exists to catch
        _SHARDED_BATTERY_ERROR.append(str(exc))
    return kernels


#: last sharded-battery construction failure (certify surfaces it)
_SHARDED_BATTERY_ERROR: list = []


def certify(as_json: bool = False) -> int:
    from ekuiper_tpu.observability import jitcert

    kernels = _battery()
    report: Dict[str, Any] = {"kernels": {}, "problems": []}
    ops_seen: set = set()
    for name, kernel in kernels.items():
        certs = jitcert.certificates_for(kernel)
        recheck = jitcert.certificates_for(kernel)
        entries: List[Dict[str, Any]] = []
        for c, c2 in zip(certs, recheck):
            ops_seen.add(c.op)
            entry = c.to_json()
            if c.truncated:
                report["problems"].append(
                    f"{name}:{c.op} certificate is truncated (open set)")
            if c.signatures != c2.signatures:
                report["problems"].append(
                    f"{name}:{c.op} derivation is not deterministic")
            if not c.signatures:
                report["problems"].append(
                    f"{name}:{c.op} derived an empty signature set")
            entries.append(entry)
        report["kernels"][name] = entries
    # the sharded battery kernel needs a >= 4-device ("rows","keys")
    # mesh (the CLI forces 8 virtual CPU devices); only when even that
    # is absent do the sharded ops fall back to the shared _derive_*
    # builder coverage above
    have_sharded = any(getattr(k, "watch_prefix", "") == "sharded"
                       for k in kernels.values())
    if not have_sharded:
        try:
            import jax

            if len(jax.devices()) >= 4:
                report["problems"].append(
                    "sharded battery kernel failed to construct on a "
                    ">=4-device host: "
                    + (_SHARDED_BATTERY_ERROR[-1]
                       if _SHARDED_BATTERY_ERROR else "unknown"))
        except Exception:
            pass
    unexercised = {
        op for op in jitcert.SITE_DERIVATIONS
        if op not in ops_seen
        and not (op.startswith("sharded.") and not have_sharded)}
    for op in sorted(unexercised):
        report["problems"].append(
            f"SITE_DERIVATIONS op {op} not exercised by the battery")
    report["ok"] = not report["problems"]
    report["ops_certified"] = sorted(ops_seen)
    report["total_signatures"] = sum(
        e["n_signatures"] for entries in report["kernels"].values()
        for e in entries)
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        state = "OK" if report["ok"] else "FAILED"
        print(f"jitcert certify: {state} — {len(ops_seen)} site "
              f"families, {report['total_signatures']} certified "
              f"signatures across {len(kernels)} battery kernels"
              + ("" if report["ok"]
                 else "\n  " + "\n  ".join(report["problems"])))
    return 0 if report["ok"] else 1


def _drive(kernels) -> None:
    """Exercise every battery kernel's jit sites across the signature
    axes the certificates promise to close."""
    import numpy as np

    from ekuiper_tpu.ops.groupby import DeviceGroupBy

    def feed(gb: DeviceGroupBy, with_masks: bool, pane_vec: bool,
             n_keys: int = 8):
        from ekuiper_tpu.ops.groupby import col_np_dtype

        cols = {}
        valid = {}
        n = 10
        for name in gb.plan.columns:
            if name.startswith("__hhc__"):
                cols[name] = np.arange(n, dtype=np.float32) % 3
            else:
                dt = col_np_dtype(gb.plan, name)
                cols[name] = np.arange(n).astype(
                    dt if dt != np.dtype(np.float32) else np.float64)
            if with_masks:
                valid[name] = np.ones(n, dtype=np.bool_)
        slots = (np.arange(n, dtype=np.int32) % n_keys)
        pane = (np.zeros(n, dtype=np.int64) if pane_vec else 0)
        return cols, valid, slots, pane

    for name, gb in kernels.items():
        if name == "tier_store":
            # demote/promote across a capacity doubling: the gather/
            # scatter re-specialization must stay inside the certified
            # ladder (the paired groupby_tiered kernel drives the
            # touch-bearing fold/finalize family via the generic loop)
            gb2 = gb.gb
            state = gb2.init_state()
            cols, valid, slots, pane = feed(gb2, with_masks=False,
                                            pane_vec=False)
            state = gb2.fold(state, cols, slots, pane_idx=pane)
            state, packed = gb.demote(state, np.array([1, 2], np.int32))
            state = gb.promote(state, np.asarray(packed)[:2],
                               np.array([1, 2], np.int32))
            state = gb2.grow(state, gb2.capacity * 2)
            state, packed = gb.demote(state, np.array([1], np.int32))
            state = gb.promote(state, np.asarray(packed)[:1],
                               np.array([1], np.int32))
            continue
        if name == "join_ring":
            from ekuiper_tpu.ops.joinring import SideBatch

            def side(n, prefix, base):
                b = SideBatch(n=n)
                b.key_cols.append([f"k{i % 5}" for i in range(n)])
                b.band = [base + i for i in range(n)]
                col = "__jl_v" if prefix == "l" else "__jr_w"
                b.cols[col] = [float(i) for i in range(n)]
                return b

            # two pad-pair steps of the certified (PL, PR) ladder, plus
            # a key-table doubling (capacity is not a match leaf — the
            # signature must NOT change across the grow)
            gb.match(side(10, "l", 0), side(10, "r", 0))
            gb.match(side(300, "l", 0), side(10, "r", 0))
            gb.match(side(40, "l", 0), side(300, "r", 0))
            continue
        if name == "segscan":
            # micro-batch pad ladder + a carry-capacity doubling (slot
            # beyond capacity forces grow; the shift signature's carry
            # dims step one rung)
            slots = (np.arange(10) % 8).astype(np.int32)
            vals = np.arange(10, dtype=np.float32)
            gb.shift(slots, vals, 10)
            gb.ranks(slots, vals, 10)
            big = (np.arange(300) % 40).astype(np.int32)
            gb.shift(big, np.arange(300, dtype=np.float32), 300)
            gb.ranks(big, np.arange(300, dtype=np.float32), 300)
            continue
        if name == "sketch":
            gb.update(np.arange(10, dtype=np.float32))
            gb.update(np.arange(300, dtype=np.float32))  # next pad bucket
            gb.heavy_hitters(3)
            continue
        if name == "sliding_ring":
            ring_kernel = gb
            gb2 = ring_kernel.gb
            state = gb2.init_state()
            cols, valid, slots, pane = feed(gb2, with_masks=False,
                                            pane_vec=False)
            state = gb2.fold(state, cols, slots, pane_idx=pane)
            ring = ring_kernel.init_state()
            ring = ring_kernel.advance(ring, state, 0, True, 1, False)
            ring = ring_kernel.flip(
                ring, state, 0,
                np.ones(ring_kernel.n_ring_panes, dtype=np.bool_))
            from ekuiper_tpu.ops.slidingring import QUERY_ADJ

            adj = np.zeros(QUERY_ADJ, dtype=np.int32)
            ring_kernel.query_begin(
                ring, state, body_on=True, f_on=True, f_slot=0,
                adj_slots=adj,
                adj_weights=np.zeros(QUERY_ADJ, dtype=np.float32),
                adj_mm=np.zeros(QUERY_ADJ, dtype=np.bool_)).get()
            gb2.components_begin_dyn(
                state, np.zeros(gb2.n_panes, dtype=np.bool_)).get()
            # capacity growth across a doubling: ring re-specialization
            # must stay inside the certified ladder
            state = gb2.grow(state, gb2.capacity * 2)
            ring = ring_kernel.grow(ring, gb2.capacity)
            ring = ring_kernel.advance(ring, state, 0, True, 1, False)
            continue
        state = gb.init_state()
        cols, valid, slots, pane = feed(gb, with_masks=False,
                                        pane_vec=False)
        state = gb.fold(state, cols, slots, pane_idx=pane)
        cols, valid, slots, pane = feed(gb, with_masks=True,
                                        pane_vec=gb.n_panes > 1)
        state = gb.fold(state, cols, slots, valid=valid, pane_idx=pane)
        outs, act = gb.finalize(state, 8)
        if gb.n_panes > 1:
            outs, act = gb.finalize(state, 8, panes=[0, 1])
        state = gb.reset_pane(state, 0)
        # capacity growth across a doubling: re-specialization must stay
        # inside the certified ladder
        state = gb.grow(state, gb.capacity * 2)
        cols, valid, slots, pane = feed(gb, with_masks=False,
                                        pane_vec=False)
        state = gb.fold(state, cols, slots, pane_idx=pane)
        outs, act = gb.finalize(state, 8)


def diff(as_json: bool = False) -> int:
    from ekuiper_tpu.observability import jitcert

    kernels = _battery()
    _drive(kernels)
    report = jitcert.diff_live()
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        state = "OK" if report["clean"] else "FAILED"
        print(f"jitcert diff: {state} — {report['observed_signatures']} "
              f"observed signatures over {report['sites_observed']} live "
              f"sites, {report['certified_signatures']} certified"
              + ("" if report["clean"] else "\n  " + "\n  ".join(
                  f"{u['op']} [{u['rule'] or '__engine__'}]: "
                  f"{u['signature'][:140]}"
                  for u in report["uncertified"])))
    return 0 if report["clean"] else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tools.jitcert", description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=["certify", "diff"])
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # 8 virtual CPU devices so the sharded battery kernel constructs
    # (must land before the first jax import initializes the backend)
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    if args.command == "certify":
        return certify(as_json=args.json)
    return diff(as_json=args.json)
