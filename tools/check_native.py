#!/usr/bin/env python
"""Native-decoder preflight: fail LOUDLY when ekjsoncol silently falls
back to the Python path.

PR 1 found the seed's native decoder had NEVER built in-image (GCC 10
lacks float std::to_chars) while every "native" bench phase silently ran
the Python fallback — this class of regression must never recur unnoticed.
The check builds the extension synchronously if needed, then proves the
decode AND the key-slot table actually serve:

  exit 0 — native decode + keytab probes passed
  exit 1 — extension unavailable or a probe failed (details on stderr)

Run standalone (`python tools/check_native.py`) or from the bench/test
preflight (tests/test_native_preflight.py wraps it tier-1).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def check(verbose: bool = True) -> list:
    """Returns a list of failure strings; empty = native path healthy."""
    failures = []

    def note(msg):
        if verbose:
            print(f"check_native: {msg}", file=sys.stderr)

    from ekuiper_tpu.io import fastjson

    fastjson.ensure_native(background=False)
    mod = fastjson._load()
    if mod is None:
        return ["ekjsoncol extension did not build/load — the native "
                "decode path is silently running the Python fallback"]

    # decode probe: typed columns out of raw JSON, no Fallback
    from ekuiper_tpu.data.types import DataType, Field, Schema

    schema = Schema(fields=[
        Field("k", DataType.STRING),
        Field("v", DataType.FLOAT),
        Field("n", DataType.BIGINT),
    ])
    spec = fastjson.schema_field_spec(schema)
    payloads = [b'{"k": "a", "v": 1.5, "n": 7}',
                b'{"k": null, "v": "2.5"}',
                b'{"k": "a", "n": -3}']
    out = fastjson.decode_columns(payloads, spec, shards=2)
    if out is None:
        failures.append("decode_columns returned None for a trivially "
                        "decodable batch — native decode is falling back")
    else:
        cols, valid, bad = out
        if bad.any():
            failures.append(f"decode marked good payloads bad: {bad.tolist()}")
        if cols["v"].tolist()[:2] != [1.5, 2.5]:
            failures.append(f"decode value mismatch: {cols['v'].tolist()}")
        if cols["k"][0] != "a" or cols["k"][0] is not cols["k"][2]:
            failures.append("string interning broken (same value, "
                            "different objects)")

    # key-slot table probe: the persistent native encode behind
    # KeyTable._native_encode (stale prebuilt .so lacks the API)
    if not fastjson.has_keytab():
        failures.append("loaded ekjsoncol lacks the keytab API — stale "
                        "prebuilt .so; key-slot encode is falling back")
    else:
        import numpy as np

        from ekuiper_tpu.ops.keytable import KeyTable

        kt = KeyTable()
        col = np.array(["x", None, "", "x", "y"], dtype=object)
        slots, _ = kt.encode_column(col)
        if kt._ntab is None or not kt._native_ok:
            failures.append("KeyTable did not engage the native key-slot "
                            "table for a plain string column")
        ref = KeyTable()
        ref._native_ok = False
        ref_slots, _ = ref.encode_column(col)
        if slots.tolist() != ref_slots.tolist() \
                or kt.decode_all() != ref.decode_all():
            failures.append(
                f"native/python slot divergence: {slots.tolist()} vs "
                f"{ref_slots.tolist()}")

    for f in failures:
        note(f"FAIL: {f}")
    if not failures:
        note("native decode + key-slot table OK")
    return failures


def main() -> int:
    return 1 if check() else 0


if __name__ == "__main__":
    sys.exit(main())
