#!/usr/bin/env python
"""Metrics-exposition lint — run from the tier-1 suite (like
tools/check_native.py): renders a full synthetic Prometheus scrape and
fails loudly when any emitted metric

  1. is not `kuiper_`-prefixed,
  2. lacks a `# TYPE` or `# HELP` header, or
  3. is missing from the docs/OBSERVABILITY.md catalog,

and — the reverse direction — when any family with a catalog row in
docs/OBSERVABILITY.md fails to render a sample in the synthetic scrape
(dead doc rows for renamed/removed metrics; see RENDER_EXEMPT).

The synthetic registry exercises every family render() can emit: a rule
with a staged + pooled node, a shared subtopo node, and a populated
end-to-end histogram — so a new metric added without docs or headers
cannot slip through a scrape that simply never hit its branch.

Exit 0 = clean; exit 1 prints one line per violation.
"""
from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{|\s)")


#: catalog families the synthetic scrape legitimately cannot render —
#: every entry must carry a reason; an undocumented reason is a lint bug
RENDER_EXEMPT: dict = {}


def catalog_families(docs_text: str) -> set:
    """Families with a ROW in the docs/OBSERVABILITY.md catalog table
    (`| \\`kuiper_...\\` | type | ...`) — prose mentions and label
    examples do not count. This is the reverse lint's contract set."""
    return set(re.findall(r"^\|\s*`(kuiper_[a-z0-9_]+)`", docs_text,
                          re.MULTILINE))


def rendered_families(text: str) -> set:
    """Base family names with at least one sample line in a scrape."""
    types = set()
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 3:
                types.add(parts[2])
    seen = set()
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group(1)
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                name = name[: -len(suffix)]
                break
        seen.add(name)
    return seen


def reverse_lint(text: str, docs_text: str) -> list:
    """The catalog must stay honest in BOTH directions: every documented
    family must actually render a sample in the synthetic scrape, or the
    doc row is dead (a renamed/removed metric nobody pruned) and the
    forward lint can never catch it."""
    missing = catalog_families(docs_text) - rendered_families(text) \
        - set(RENDER_EXEMPT)
    return [f"{fam}: documented in docs/OBSERVABILITY.md but never "
            "rendered by the synthetic scrape (dead catalog row, or the "
            "synthetic registry lost its branch)"
            for fam in sorted(missing)]


def documented_families(docs_path: str = DOCS) -> set:
    """Every kuiper_* family named in docs/OBSERVABILITY.md — the
    catalog this lint (and kuiperlint's static metric-hygiene pass)
    treats as the registered set. Empty when the catalog is missing."""
    try:
        with open(docs_path) as f:
            text = f.read()
    except OSError:
        return set()
    return set(re.findall(r"kuiper_[a-z0-9_]+", text))


def _synthetic_scrape() -> str:
    """Render a scrape covering every metric family."""
    from ekuiper_tpu.observability.histogram import LatencyHistogram
    from ekuiper_tpu.observability.prometheus import render
    from ekuiper_tpu.utils.metrics import StatManager

    class FakeQueue:
        @staticmethod
        def qsize():
            return 2

    class Node:
        def __init__(self, name, op_type="op", pooled=False):
            self.name = name
            self.op_type = op_type
            self.inq = FakeQueue()
            self.stats = StatManager(op_type, name)
            self.stats.rule_id = "lint_rule"
            self.stats.inc_in(3)
            self.stats.inc_out(2)
            self.stats.inc_dropped("buffer_full")
            self.stats.observe_stage("decode", 120.0, 3)
            self.stats.observe_queue_wait(42.0)
            self.stats.process_begin()
            self.stats.process_end()
            if pooled:
                self.pool_depths = lambda: (1, 0)

    class SubTopo:
        nodes = [Node("shared_src", op_type="source", pooled=True)]

    # one REAL watermark node so the health evaluator's event-time probe
    # (and with it kuiper_watermark_lag_ms) renders a sample
    from ekuiper_tpu.runtime.nodes_window import WatermarkNode

    wm_node = WatermarkNode("wm_lint")
    wm_node.max_ts = 1  # watermark established → lag is reportable

    class Topo:
        e2e_hist = LatencyHistogram()

        def all_nodes(self):
            return [Node("src", "source"), Node("op1"), wm_node,
                    Node("sink", "sink")]

        def live_shared(self):
            return [(SubTopo(), None)]

    Topo.e2e_hist.record(7)
    Topo.e2e_hist.record(42)

    class State:
        topo = Topo()

    class Registry:
        def list(self):
            return [{"id": "lint_rule", "status": "running"}]

        def state(self, rid):
            return State()

    # a pooled shared fold so the kuiper_shared_fold_* families render
    from ekuiper_tpu.runtime import nodes_sharedfold

    class FakeStore:
        name = "shared_fold[lint]"
        windows_emitted = 3

        def member_count(self):
            return 2

        def fold_dedup_ratio(self):
            return 0.5

    nodes_sharedfold._stores["__lint__"] = FakeStore()
    # engine-health families: one populated compile watch (with a compile
    # sample so kuiper_xla_compile_seconds renders buckets) and one memory
    # probe — render() reads the module registries directly
    from ekuiper_tpu.observability import devwatch, kernwatch, memwatch

    watch = devwatch.registry().register("lint.fold", "lint_rule")
    watch.calls = 5
    watch.on_compile(12_000.0, (), {})
    # kernel observatory (observability/kernwatch.py): one sampled site
    # with a synthetic XLA cost so all five kuiper_kernel_* families
    # (device/dispatch time counters, flops/bytes gauges, roofline
    # utilization) render samples
    watch.kern.set_cost(flops=2e6, bytes_=1.12e7)
    watch.kern.record_sample(dispatch_us=50.0, total_us=850.0)

    class MemOwner:
        pass

    owner = MemOwner()
    memwatch.register("lint_component", owner, lambda o: 4096,
                      rule="lint_rule")
    # tiered key state (ops/tierstore.py): one registered manager so all
    # four kuiper_spill_*/kuiper_tier_host_bytes families render samples
    from ekuiper_tpu.ops import tierstore

    class FakeTierStore:
        def __len__(self):
            return 2

        def nbytes(self):
            return 4096

    class FakeTier:
        demoted_total = 3
        promoted_total = 1
        prefetch_hits = 0
        store = FakeTierStore()

    tier_mgr = FakeTier()
    tierstore.registry().register(tier_mgr, "lint_rule")
    # multi-chip sharded serving (parallel/sharded.py): one registered
    # fake kernel so kuiper_shard_rows_total / kuiper_shard_keys render
    from ekuiper_tpu.parallel import sharded as sharded_mod

    class FakeSharded:
        mesh_tag = "1x2"
        capacity = 64

        def shard_stats(self):
            # >= KUIPER_MESH_SKEW_MIN_ROWS total so the fleet
            # observatory computes a skew ratio on the first observe
            return [{"shard": 0, "rows": 300, "keys": 3, "slots": 32,
                     "state_bytes": 128},
                    {"shard": 1, "rows": 100, "keys": 1, "slots": 32,
                     "state_bytes": 128}]

        def collective_bytes_per_fold(self):
            return 192

    shard_kernel = FakeSharded()
    sharded_mod.registry().register(shard_kernel, "lint_rule")
    # fleet observatory (observability/meshwatch.py): one sampled
    # sharded fold site + an observe pass so all four kuiper_mesh_*
    # families render samples
    from ekuiper_tpu.observability import meshwatch

    meshwatch.reset()
    mesh_site = devwatch.registry().register("sharded.fold_step",
                                             "lint_rule")
    mesh_site.kern.set_cost(flops=1e6, bytes_=1e6)
    mesh_site.kern.record_sample(dispatch_us=10.0, total_us=500.0)
    meshwatch.observe()
    # durable telemetry timeline (observability/timeline.py): install
    # over a throwaway dir + one snapshot so kuiper_timeline_* render
    import shutil
    import tempfile

    from ekuiper_tpu.observability import timeline as timeline_mod

    tl_dir = tempfile.mkdtemp(prefix="lint_timeline_")
    tl = timeline_mod.install(scrape_fn=lambda: "kuiper_rule_status 1\n",
                              base_dir=tl_dir, interval_ms=0)
    tl.snapshot()
    # relational tier (ops/joinring.py / ops/segscan.py): one fake ring
    # and one fake scan kernel so the kuiper_join_* / kuiper_segscan_*
    # families all render samples
    from ekuiper_tpu.ops import joinring as joinring_mod
    from ekuiper_tpu.ops import segscan as segscan_mod

    class FakeRing:
        rows_total = {"l": 5, "r": 4}
        matches_total = 3
        fallback_windows_total = 1

        @staticmethod
        def nbytes():
            return 2048

    class FakeSegScan:
        rows_total = 7
        spills_total = 2

    join_ring = FakeRing()
    seg_kernel = FakeSegScan()
    joinring_mod.registry().register(join_ring, "lint_rule")
    segscan_mod.registry().register(seg_kernel, "lint_rule")
    # health plane: an installed evaluator with one ticked verdict so the
    # kuiper_rule_health / kuiper_slo_burn_rate / kuiper_watermark_lag_ms
    # / kuiper_bottleneck_stage families all render samples
    from ekuiper_tpu.observability import health

    hev = health.install(lambda: [("lint_rule", Topo(), {})], start=False)
    hev.tick()
    # QoS control plane (runtime/control.py): an installed controller
    # with one decision of each kind, a shed total, and an autosize
    # event so kuiper_admission_total / kuiper_shed_total /
    # kuiper_autosize_events_total all render samples
    from ekuiper_tpu.runtime import control

    ctl = control.install(lambda: [], start=False)
    for decision in ("accept", "reject", "queue"):
        ctl.note_admission(decision)
    ctl._shed_totals[("lint_rule", "standard")] = 42
    ctl.autosize_events = 1
    try:
        return render(Registry())
    finally:
        control.reset()
        health.reset()
        nodes_sharedfold._stores.pop("__lint__", None)
        devwatch.registry().clear()
        kernwatch.reset()
        memwatch.registry().clear()
        tierstore.reset()
        sharded_mod.reset()
        joinring_mod.reset()
        segscan_mod.reset()
        meshwatch.reset()
        timeline_mod.reset()
        shutil.rmtree(tl_dir, ignore_errors=True)
        del owner
        del tier_mgr
        del shard_kernel
        del join_ring
        del seg_kernel


def lint(text: str, docs_text: str) -> list:
    errors = []
    types: dict = {}
    helps: set = set()
    seen: dict = {}  # base family name -> first sample line no
    for i, line in enumerate(text.splitlines(), 1):
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps.add(parts[2])
            continue
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {i}: unparseable sample line: {line!r}")
            continue
        name = m.group(1)
        base = name
        # histogram/summary series roll up to their family name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
                break
        seen.setdefault(base, i)
    for base, line_no in sorted(seen.items(), key=lambda kv: kv[1]):
        if not base.startswith("kuiper_"):
            errors.append(f"{base}: not kuiper_-prefixed (line {line_no})")
        if base not in types:
            errors.append(f"{base}: no # TYPE header (line {line_no})")
        if base not in helps:
            errors.append(f"{base}: no # HELP header (line {line_no})")
        # word-boundary match: a family must appear as a whole name —
        # substring hits (kuiper_op_stage_us inside kuiper_op_stage_us_total)
        # must not count as documentation
        if not re.search(rf"(?<![A-Za-z0-9_]){re.escape(base)}(?![A-Za-z0-9_])",
                         docs_text):
            errors.append(
                f"{base}: not documented in docs/OBSERVABILITY.md "
                f"(line {line_no})")
    return errors


def main() -> int:
    try:
        with open(DOCS) as f:
            docs_text = f.read()
    except FileNotFoundError:
        print(f"check_metrics: missing {DOCS}")
        return 1
    text = _synthetic_scrape()
    errors = lint(text, docs_text) + reverse_lint(text, docs_text)
    if errors:
        print(f"check_metrics: {len(errors)} violation(s)")
        for e in errors:
            print("  " + e)
        return 1
    n = len({ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE ")})
    print(f"check_metrics: OK ({n} metric families, all prefixed, "
          "typed, helped, documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
