#!/usr/bin/env python
"""kuiperdiag — one-shot support bundle for a live engine.

Collects everything a human (or a later session) needs to diagnose an
engine remotely, into ONE self-contained JSON document:

  - server info + component versions (engine / python / jax / numpy)
  - every rule: registry entry, /status snapshot, plan /explain
  - the full Prometheus scrape (text, verbatim)
  - the flight recorder's event ring (/diagnostics/events)
  - device/host memory accounting (/diagnostics/memory)
  - XLA compile watcher state (/diagnostics/xla)
  - kernel observatory: sampled device-time split, XLA cost estimates
    and roofline utilization per jit site (/diagnostics/kernels)
  - fleet observatory: per-rule shard-skew report + collective split
    (/diagnostics/mesh) and the durable telemetry timeline
    (/diagnostics/timeline; --timeline packs the raw ring segments)
  - the runtime config overlay (/configs)

Usage:
  kuiperdiag.py [--host 127.0.0.1] [--port 9081] [--out bundle.json]
  kuiperdiag.py --profile [--profile-ms 1000]
                               # also trigger POST /diagnostics/profile
                               # (bounded jax.profiler trace + devwatch
                               # dump) and record its bundle dir
  kuiperdiag.py --events-since SEQ
                               # tail the event ring incrementally from
                               # a prior bundle's events.last_seq
  kuiperdiag.py --smoke        # tier-1 self-test: in-process engine,
                               # no network, validates bundle shape +
                               # /diagnostics/health + a 1s profile

Every section degrades independently: an endpoint that errors contributes
{"error": ...} instead of killing the bundle — a half-dead engine is
exactly when a bundle matters most.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

Fetch = Callable[[str], Tuple[int, Any]]
Post = Callable[[str, dict], Tuple[int, Any]]

#: sections (beyond per-rule detail) a valid bundle must carry
REQUIRED_SECTIONS = ("server", "rules", "metrics", "events", "memory",
                     "xla", "kernels", "health", "control", "configs",
                     "versions", "mesh", "timeline")


def _versions() -> Dict[str, Any]:
    out: Dict[str, Any] = {"python": sys.version.split()[0]}
    try:
        import ekuiper_tpu

        out["engine"] = getattr(ekuiper_tpu, "__version__", "unknown")
    except Exception as exc:
        out["engine"] = f"unavailable: {exc}"
    for mod in ("jax", "numpy"):
        try:
            out[mod] = __import__(mod).__version__
        except Exception as exc:
            out[mod] = f"unavailable: {exc}"
    return out


def collect(fetch: Fetch, events_limit: int = 1000,
            events_since: Optional[int] = None,
            profile_ms: int = 0, post: Optional[Post] = None,
            profile_dir: Optional[str] = None,
            timeline_dump: bool = False) -> Dict[str, Any]:
    """Assemble the bundle through `fetch(path) -> (status, payload)` —
    HTTP against a live server, or in-process dispatch for --smoke.
    `events_since` tails the flight-recorder ring incrementally (pass a
    prior bundle's `events.last_seq`); `profile_ms > 0` also triggers a
    bounded profiler capture through `post` and records the result;
    `timeline_dump` packs the raw on-disk telemetry segments (bounded)
    so the bundle carries the replayable ring, not just a query."""

    def get(path: str) -> Any:
        try:
            code, obj = fetch(path)
        except Exception as exc:
            return {"error": str(exc)}
        if code != 200:
            return {"error": f"status {code}", "body": obj}
        return obj

    bundle: Dict[str, Any] = {
        "bundle_version": 2,
        "generated_at_ms": int(time.time() * 1000),
        "versions": _versions(),
    }
    bundle["server"] = get("/")
    rules = get("/rules")
    bundle["rules"] = rules
    details: Dict[str, Any] = {}
    if isinstance(rules, list):
        for entry in rules:
            rid = entry.get("id")
            if not rid:
                continue
            details[rid] = {
                "status": get(f"/rules/{rid}/status"),
                "explain": get(f"/rules/{rid}/explain"),
                "health": get(f"/rules/{rid}/health"),
            }
    bundle["rule_details"] = details
    bundle["metrics"] = get("/metrics")
    ev_path = f"/diagnostics/events?limit={events_limit}"
    if events_since is not None:
        ev_path += f"&since={events_since}"
    bundle["events"] = get(ev_path)
    bundle["memory"] = get("/diagnostics/memory")
    bundle["xla"] = get("/diagnostics/xla")
    bundle["kernels"] = get("/diagnostics/kernels")
    bundle["health"] = get("/diagnostics/health")
    bundle["control"] = get("/diagnostics/control")
    bundle["mesh"] = get("/diagnostics/mesh")
    tl_path = "/diagnostics/timeline?limit=100"
    if timeline_dump:
        tl_path += "&dump=1"
    bundle["timeline"] = get(tl_path)
    bundle["configs"] = get("/configs")
    if profile_ms > 0 and post is not None:
        body = {"duration_ms": profile_ms}
        if profile_dir:
            body["out_dir"] = profile_dir
        try:
            code, obj = post("/diagnostics/profile", body)
            bundle["profile"] = (obj if code == 200
                                 else {"error": f"status {code}",
                                       "body": obj})
        except Exception as exc:
            bundle["profile"] = {"error": str(exc)}
    return bundle


# ------------------------------------------------------------------ fetchers
def http_fetch(host: str, port: int, timeout: float = 10.0) -> Fetch:
    from urllib.error import HTTPError
    from urllib.request import urlopen

    def fetch(path: str) -> Tuple[int, Any]:
        url = f"http://{host}:{port}{path}"
        try:
            with urlopen(url, timeout=timeout) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
                code = resp.status
        except HTTPError as exc:  # non-2xx still carries a body
            raw = exc.read()
            ctype = exc.headers.get("Content-Type", "")
            code = exc.code
        if "json" in ctype:
            return code, json.loads(raw.decode() or "null")
        return code, raw.decode(errors="replace")

    return fetch


def http_post(host: str, port: int, timeout: float = 60.0) -> Post:
    """POST (the profile trigger) — long timeout: the capture itself
    blocks for its duration."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    def post(path: str, body: dict) -> Tuple[int, Any]:
        req = Request(f"http://{host}:{port}{path}",
                      data=json.dumps(body).encode(),
                      headers={"Content-Type": "application/json"},
                      method="POST")
        try:
            with urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read().decode()
                                               or "null")
        except HTTPError as exc:
            raw = exc.read()
            try:
                return exc.code, json.loads(raw.decode() or "null")
            except Exception:
                return exc.code, raw.decode(errors="replace")

    return post


def inproc_fetch(api) -> Fetch:
    """Dispatch straight into a RestApi (no socket) — the --smoke path."""
    from urllib.parse import parse_qs, urlparse

    def fetch(path: str) -> Tuple[int, Any]:
        parsed = urlparse(path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        code, result = api.dispatch(
            "GET", parsed.path.rstrip("/") or "/", None, query)
        # TextResponse (the /metrics scrape) json-serializes as its str
        return code, (str(result) if hasattr(result, "content_type")
                      else result)

    return fetch


def inproc_post(api) -> Post:
    def post(path: str, body: dict) -> Tuple[int, Any]:
        return api.dispatch("POST", path, body, {})

    return post


# --------------------------------------------------------------------- smoke
def smoke() -> int:
    """Tier-1 self-test: boot an in-process engine with one live rule,
    collect a bundle, validate its shape. No network, CPU jax, mock-free
    real clock (nothing here is timing-sensitive)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.runtime.events import recorder
    from ekuiper_tpu.server.rest import RestApi
    from ekuiper_tpu.store import kv

    store = kv.get_store()
    api = RestApi(store)
    rid = "kuiperdiag_smoke"
    profile_dir = None
    try:
        code, out = api.dispatch("POST", "/streams", {
            "sql": "CREATE STREAM diagsmoke (deviceId STRING, v FLOAT) "
                   'WITH (DATASOURCE="topic/diagsmoke", TYPE="memory", '
                   'FORMAT="JSON")'}, {})
        if code not in (200, 201):
            print(f"kuiperdiag --smoke: stream create failed: {out}")
            return 1
        code, out = api.dispatch("POST", "/rules", {
            "id": rid,
            "sql": "SELECT deviceId, avg(v) AS a FROM diagsmoke "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "actions": [{"nop": {}}]}, {})
        if code not in (200, 201):
            print(f"kuiperdiag --smoke: rule create failed: {out}")
            return 1
        # rule start is async (FSM action queue): wait for the live topo,
        # the health sections below evaluate only running rules
        deadline = time.time() + 10.0
        while time.time() < deadline:
            rs = api.rules.state(rid)
            if rs is not None and rs.topo is not None:
                break
            time.sleep(0.05)
        mem.publish("topic/diagsmoke",
                    [b'{"deviceId": "d1", "v": 1.5}',
                     b'{"deviceId": "d2", "v": 2.5}'])
        # the REST boundary only accepts capture dirs under the store
        # path; exercise the smoke capture through the same constraint
        from ekuiper_tpu.utils.config import get_config

        profile_dir = os.path.join(get_config().store.path, "profiles",
                                   f"ekdiag_smoke_{os.getpid()}")
        # force one telemetry snapshot so the timeline section carries a
        # real record even if the periodic timer has not fired yet
        tl = getattr(api, "timeline", None)
        if tl is not None:
            tl.snapshot()
        bundle = collect(inproc_fetch(api), events_limit=100,
                         profile_ms=1000, post=inproc_post(api),
                         profile_dir=profile_dir, timeline_dump=True)
        missing = [k for k in REQUIRED_SECTIONS
                   if not bundle.get(k)
                   or (isinstance(bundle[k], dict) and "error" in bundle[k])]
        problems = list(missing)
        if rid not in bundle.get("rule_details", {}):
            problems.append(f"rule_details[{rid}]")
        if "kuiper_rule_status" not in str(bundle.get("metrics", "")):
            problems.append("metrics scrape content")
        if not recorder().total_recorded:
            problems.append("flight recorder (no rule_state events)")
        # health plane: the rule's verdict must be present with a state
        health = bundle.get("health") or {}
        if rid not in (health.get("rules") or {}):
            problems.append(f"health.rules[{rid}]")
        if not (bundle.get("rule_details", {}).get(rid, {})
                .get("health", {}).get("state")):
            problems.append(f"rule_details[{rid}].health.state")
        # QoS control plane: the section must carry the admission
        # decision counters and the shed/autosize views (all may be
        # zero this early — shape is what a postmortem needs)
        ctl = bundle.get("control") or {}
        decisions = (ctl.get("admission") or {}).get("decisions")
        if not isinstance(decisions, dict) or "accept" not in decisions:
            problems.append("control.admission.decisions")
        if "shedding" not in ctl or "autosize" not in ctl:
            problems.append("control.shedding/autosize")
        # jitcert compile-contract diff: the xla section must carry the
        # certificate diff with a verdict and the uncertified report
        # list (empty on a healthy engine — observed ⊆ certified)
        jc = (bundle.get("xla") or {}).get("jitcert") or {}
        if "clean" not in jc or not isinstance(jc.get("uncertified"),
                                               list):
            problems.append("xla.jitcert diff shape")
        elif jc.get("sites_certified", 0) <= 0:
            problems.append("xla.jitcert.sites_certified (live rule has "
                            "no registered certificates)")
        elif jc.get("sites_open", 0) > 0:
            problems.append(
                "xla.jitcert open (unenforced) sites: "
                + "; ".join(f"{u['op']}" for u in jc["open_sites"][:3]))
        elif not jc["clean"]:
            problems.append(
                "xla.jitcert uncertified signatures: "
                + "; ".join(f"{u['op']}: {u['signature'][:80]}"
                            for u in jc["uncertified"][:3]))
        # kernel observatory: the section must name the device and carry
        # the site list (sampling may legitimately be empty this early)
        kern = bundle.get("kernels") or {}
        if not (kern.get("device") or {}).get("kind"):
            problems.append("kernels.device.kind")
        if not isinstance(kern.get("sites"), list):
            problems.append("kernels.sites")
        # fleet observatory: the mesh section must carry the skew report
        # (empty dict on an unsharded engine — shape, not content) and
        # the link-speed table lookup must have resolved
        msh = bundle.get("mesh") or {}
        if not isinstance(msh.get("skew"), dict):
            problems.append("mesh.skew")
        if not isinstance(msh.get("collective"), list):
            problems.append("mesh.collective")
        if not (msh.get("link_gbs") or 0) > 0:
            problems.append("mesh.link_gbs")
        # durable telemetry ring: the forced snapshot above must have
        # landed on disk and replayed back through the query + dump
        tls = bundle.get("timeline") or {}
        if not tls.get("dir") or not isinstance(tls.get("segments"), int):
            problems.append("timeline stats shape")
        if not any(r.get("kind") == "snapshot"
                   for r in tls.get("records") or []):
            problems.append("timeline snapshot records")
        if not tls.get("segment_dump"):
            problems.append("timeline segment_dump")
        # incremental tailing: the recorded last_seq must tail cleanly
        last_seq = (bundle.get("events") or {}).get("last_seq")
        if not isinstance(last_seq, int) or last_seq <= 0:
            problems.append("events.last_seq")
        # profile capture: the bundle dir must exist and carry the
        # devwatch dump (the jax trace itself may degrade on bare CPU —
        # that is recorded in profile.trace, not a smoke failure)
        profile = bundle.get("profile") or {}
        pdir = profile.get("dir")
        if not pdir or not os.path.isdir(pdir):
            problems.append(f"profile.dir ({profile})")
        elif "devwatch_dump.json" not in (profile.get("files") or []):
            problems.append("profile devwatch_dump.json")
        # the whole point: the bundle must round-trip as ONE json document
        encoded = json.dumps(bundle)
        if problems:
            print("kuiperdiag --smoke: FAILED sections: "
                  + ", ".join(problems))
            return 1
        print(f"kuiperdiag --smoke: OK ({len(encoded)} bytes, "
              f"{len(bundle['rule_details'])} rule(s), "
              f"{bundle['events'].get('returned', 0)} event(s), "
              f"last_seq={last_seq}, profile trace "
              f"{profile.get('trace', '?')})")
        return 0
    finally:
        try:
            api.rules.delete(rid)
        except Exception:
            pass
        mem.reset()
        if profile_dir:
            import shutil

            shutil.rmtree(profile_dir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9081)
    ap.add_argument("--out", default="-",
                    help="output file (default: stdout)")
    ap.add_argument("--events-limit", type=int, default=1000)
    ap.add_argument("--events-since", type=int, default=None,
                    help="tail the event ring from this seq (a prior "
                         "bundle's events.last_seq)")
    ap.add_argument("--profile", action="store_true",
                    help="also trigger a bounded profiler capture "
                         "(POST /diagnostics/profile) and record its "
                         "bundle directory")
    ap.add_argument("--profile-ms", type=int, default=1000,
                    help="profiler capture duration (server-capped)")
    ap.add_argument("--timeline", action="store_true",
                    help="also pack the raw on-disk telemetry ring "
                         "segments (bounded) into the bundle")
    ap.add_argument("--smoke", action="store_true",
                    help="in-process self-test (tier-1)")
    args = ap.parse_args()
    if args.smoke:
        rc = smoke()
        # hard exit: the in-process engine leaves daemon node/timer
        # threads running, and interpreter teardown with live jax state
        # can segfault AFTER the verdict is printed — the bundle check is
        # done, skip teardown entirely
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    bundle = collect(
        http_fetch(args.host, args.port),
        events_limit=args.events_limit,
        events_since=args.events_since,
        profile_ms=args.profile_ms if args.profile else 0,
        post=http_post(args.host, args.port,
                       timeout=max(args.profile_ms / 1000.0 + 30.0, 60.0))
        if args.profile else None,
        timeline_dump=args.timeline)
    text = json.dumps(bundle, indent=2, default=str)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"kuiperdiag: bundle written to {args.out} "
              f"({len(text)} bytes)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
