#!/usr/bin/env python
"""kuiperdiag — one-shot support bundle for a live engine.

Collects everything a human (or a later session) needs to diagnose an
engine remotely, into ONE self-contained JSON document:

  - server info + component versions (engine / python / jax / numpy)
  - every rule: registry entry, /status snapshot, plan /explain
  - the full Prometheus scrape (text, verbatim)
  - the flight recorder's event ring (/diagnostics/events)
  - device/host memory accounting (/diagnostics/memory)
  - XLA compile watcher state (/diagnostics/xla)
  - the runtime config overlay (/configs)

Usage:
  kuiperdiag.py [--host 127.0.0.1] [--port 9081] [--out bundle.json]
  kuiperdiag.py --smoke        # tier-1 self-test: in-process engine,
                               # no network, validates bundle shape

Every section degrades independently: an endpoint that errors contributes
{"error": ...} instead of killing the bundle — a half-dead engine is
exactly when a bundle matters most.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

Fetch = Callable[[str], Tuple[int, Any]]

#: sections (beyond per-rule detail) a valid bundle must carry
REQUIRED_SECTIONS = ("server", "rules", "metrics", "events", "memory",
                     "xla", "configs", "versions")


def _versions() -> Dict[str, Any]:
    out: Dict[str, Any] = {"python": sys.version.split()[0]}
    try:
        import ekuiper_tpu

        out["engine"] = getattr(ekuiper_tpu, "__version__", "unknown")
    except Exception as exc:
        out["engine"] = f"unavailable: {exc}"
    for mod in ("jax", "numpy"):
        try:
            out[mod] = __import__(mod).__version__
        except Exception as exc:
            out[mod] = f"unavailable: {exc}"
    return out


def collect(fetch: Fetch, events_limit: int = 1000) -> Dict[str, Any]:
    """Assemble the bundle through `fetch(path) -> (status, payload)` —
    HTTP against a live server, or in-process dispatch for --smoke."""

    def get(path: str) -> Any:
        try:
            code, obj = fetch(path)
        except Exception as exc:
            return {"error": str(exc)}
        if code != 200:
            return {"error": f"status {code}", "body": obj}
        return obj

    bundle: Dict[str, Any] = {
        "bundle_version": 1,
        "generated_at_ms": int(time.time() * 1000),
        "versions": _versions(),
    }
    bundle["server"] = get("/")
    rules = get("/rules")
    bundle["rules"] = rules
    details: Dict[str, Any] = {}
    if isinstance(rules, list):
        for entry in rules:
            rid = entry.get("id")
            if not rid:
                continue
            details[rid] = {
                "status": get(f"/rules/{rid}/status"),
                "explain": get(f"/rules/{rid}/explain"),
            }
    bundle["rule_details"] = details
    bundle["metrics"] = get("/metrics")
    bundle["events"] = get(f"/diagnostics/events?limit={events_limit}")
    bundle["memory"] = get("/diagnostics/memory")
    bundle["xla"] = get("/diagnostics/xla")
    bundle["configs"] = get("/configs")
    return bundle


# ------------------------------------------------------------------ fetchers
def http_fetch(host: str, port: int, timeout: float = 10.0) -> Fetch:
    from urllib.error import HTTPError
    from urllib.request import urlopen

    def fetch(path: str) -> Tuple[int, Any]:
        url = f"http://{host}:{port}{path}"
        try:
            with urlopen(url, timeout=timeout) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
                code = resp.status
        except HTTPError as exc:  # non-2xx still carries a body
            raw = exc.read()
            ctype = exc.headers.get("Content-Type", "")
            code = exc.code
        if "json" in ctype:
            return code, json.loads(raw.decode() or "null")
        return code, raw.decode(errors="replace")

    return fetch


def inproc_fetch(api) -> Fetch:
    """Dispatch straight into a RestApi (no socket) — the --smoke path."""
    from urllib.parse import parse_qs, urlparse

    def fetch(path: str) -> Tuple[int, Any]:
        parsed = urlparse(path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        code, result = api.dispatch(
            "GET", parsed.path.rstrip("/") or "/", None, query)
        # TextResponse (the /metrics scrape) json-serializes as its str
        return code, (str(result) if hasattr(result, "content_type")
                      else result)

    return fetch


# --------------------------------------------------------------------- smoke
def smoke() -> int:
    """Tier-1 self-test: boot an in-process engine with one live rule,
    collect a bundle, validate its shape. No network, CPU jax, mock-free
    real clock (nothing here is timing-sensitive)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.runtime.events import recorder
    from ekuiper_tpu.server.rest import RestApi
    from ekuiper_tpu.store import kv

    store = kv.get_store()
    api = RestApi(store)
    rid = "kuiperdiag_smoke"
    try:
        code, out = api.dispatch("POST", "/streams", {
            "sql": "CREATE STREAM diagsmoke (deviceId STRING, v FLOAT) "
                   'WITH (DATASOURCE="topic/diagsmoke", TYPE="memory", '
                   'FORMAT="JSON")'}, {})
        if code not in (200, 201):
            print(f"kuiperdiag --smoke: stream create failed: {out}")
            return 1
        code, out = api.dispatch("POST", "/rules", {
            "id": rid,
            "sql": "SELECT deviceId, avg(v) AS a FROM diagsmoke "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "actions": [{"nop": {}}]}, {})
        if code not in (200, 201):
            print(f"kuiperdiag --smoke: rule create failed: {out}")
            return 1
        mem.publish("topic/diagsmoke",
                    [b'{"deviceId": "d1", "v": 1.5}',
                     b'{"deviceId": "d2", "v": 2.5}'])
        bundle = collect(inproc_fetch(api), events_limit=100)
        missing = [k for k in REQUIRED_SECTIONS
                   if not bundle.get(k)
                   or (isinstance(bundle[k], dict) and "error" in bundle[k])]
        problems = list(missing)
        if rid not in bundle.get("rule_details", {}):
            problems.append(f"rule_details[{rid}]")
        if "kuiper_rule_status" not in str(bundle.get("metrics", "")):
            problems.append("metrics scrape content")
        if not recorder().total_recorded:
            problems.append("flight recorder (no rule_state events)")
        # the whole point: the bundle must round-trip as ONE json document
        encoded = json.dumps(bundle)
        if problems:
            print("kuiperdiag --smoke: FAILED sections: "
                  + ", ".join(problems))
            return 1
        print(f"kuiperdiag --smoke: OK ({len(encoded)} bytes, "
              f"{len(bundle['rule_details'])} rule(s), "
              f"{bundle['events'].get('returned', 0)} event(s))")
        return 0
    finally:
        try:
            api.rules.delete(rid)
        except Exception:
            pass
        mem.reset()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9081)
    ap.add_argument("--out", default="-",
                    help="output file (default: stdout)")
    ap.add_argument("--events-limit", type=int, default=1000)
    ap.add_argument("--smoke", action="store_true",
                    help="in-process self-test (tier-1)")
    args = ap.parse_args()
    if args.smoke:
        rc = smoke()
        # hard exit: the in-process engine leaves daemon node/timer
        # threads running, and interpreter teardown with live jax state
        # can segfault AFTER the verdict is printed — the bundle check is
        # done, skip teardown entirely
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    bundle = collect(http_fetch(args.host, args.port),
                     events_limit=args.events_limit)
    text = json.dumps(bundle, indent=2, default=str)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"kuiperdiag: bundle written to {args.out} "
              f"({len(text)} bytes)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
