"""TPU tunnel health probe — run BEFORE any bench session.

The axon tunnel can die such that `jax.devices()` blocks forever inside
`make_c_api_client` (observed round 5: 8+ hours). This probe runs the
device enumeration in a subprocess with a hard timeout and, when healthy,
measures the round-trip characteristics the bench methodology depends on
(docs/PERF_NOTES.md):

    python tools/check_tpu.py [--timeout 60]

Exit 0 = healthy (prints device kind + RTT/upload numbers),
exit 1 = tunnel dead/hung.
"""
from __future__ import annotations

import argparse
import subprocess
import sys

_PROBE = r"""
import time
import numpy as np
import jax, jax.numpy as jnp

devs = jax.devices()
print(f"devices: {devs}")
x = jnp.zeros(8)
jax.block_until_ready(x)
ts = []
for _ in range(5):
    t0 = time.time()
    np.asarray(jnp.sum(x))
    ts.append((time.time() - t0) * 1000)
print(f"tiny dispatch->fetch roundtrip p50: {sorted(ts)[len(ts)//2]:.0f}ms")
up = np.zeros((65536, 2), np.float32)
t0 = time.time()
jax.block_until_ready(jnp.asarray(up))
print(f"0.5MB upload+sync: {(time.time() - t0) * 1000:.0f}ms")
"""


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args()
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE],
                           capture_output=True, text=True,
                           timeout=args.timeout)
    except subprocess.TimeoutExpired:
        print(f"TPU DEAD: device init hung past {args.timeout:.0f}s "
              "(tunnel down — do not start a bench)", file=sys.stderr)
        return 1
    sys.stdout.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-2000:])
        print("TPU DEAD: probe crashed", file=sys.stderr)
        return 1
    print("TPU healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
