"""Mini heterogeneous fan-out profile: a scaled-down _hetero_main (2 vmapped
families x8 + 2 solo rules over one shared source) run twice — shared
ingest prep ON vs OFF — to measure what one-encode/one-upload-per-batch
buys on the real chip without the full 256-rule compile bill.

Run: python tools/profile_hetero.py
"""
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def run(shared: bool, seconds: float = 8.0):
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule, plan_rule_group
    from ekuiper_tpu.runtime import subtopo as subtopo_mod
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    if not shared:
        orig_enc = FusedWindowAggNode._shared_encode
        orig_dev = FusedWindowAggNode._shared_device_inputs
        FusedWindowAggNode._shared_encode = lambda self, sub, frozen: None
        FusedWindowAggNode._shared_device_inputs = \
            lambda self, sub, cols, valid, slots: None
    try:
        mem.reset()
        store = kv.get_store()
        try:
            StreamProcessor(store).exec_stmt(
                'CREATE STREAM sensors (deviceId STRING, temperature FLOAT, '
                'pressure FLOAT, humidity FLOAT) '
                'WITH (DATASOURCE="topic/sensors", TYPE="memory", '
                'FORMAT="JSON")')
        except Exception:
            pass
        tag = "s" if shared else "u"
        families = [
            (f"fa{tag}", "SELECT deviceId, avg(temperature) AS a, count(*) "
             "AS c FROM sensors WHERE temperature > {x} "
             "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 14.0, 0.05),
            (f"fb{tag}", "SELECT deviceId, min(pressure) AS mn, max(pressure)"
             " AS mx FROM sensors WHERE pressure > {x} "
             "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 0.4, 0.002),
        ]
        topos = []
        for name, sql, base, step in families:
            rules = [RuleDef(id=f"{name}{i}", sql=sql.format(x=base + step * i),
                             actions=[{"nop": {}}],
                             options={"micro_batch_rows": 16384})
                     for i in range(8)]
            topos.append(plan_rule_group(name, rules, store))
        solos = [
            "SELECT deviceId, sum(humidity) AS s, stddev(humidity) AS sd "
            "FROM sensors GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "SELECT deviceId, avg(humidity) AS ah, min(temperature) AS mt "
            "FROM sensors GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)",
        ]
        for i, sql in enumerate(solos):
            topos.append(plan_rule(
                RuleDef(id=f"solo{tag}{i}", sql=sql, actions=[{"nop": {}}],
                        options={"micro_batch_rows": 16384}), store))
        for t in topos:
            t.open()
        try:
            import json as _json

            src = topos[0]._live_shared[0][0].source
            rng = np.random.default_rng(31)
            n_dev = 4096
            ids = np.array([f"dev_{i}" for i in range(n_dev)],
                           dtype=np.object_)
            drains = []
            for _ in range(8):
                k = 16384
                drains.append([
                    _json.dumps(
                        {"deviceId": d, "temperature": t_, "pressure": p,
                         "humidity": h}).encode()
                    for d, t_, p, h in zip(
                        ids[rng.integers(0, n_dev, k)],
                        rng.normal(20, 5, k).round(2),
                        rng.random(k).round(3),
                        rng.normal(50, 15, k).round(2))
                ])
            deadline = time.time() + 600
            for _ in range(2):
                for d in drains:
                    src.ingest(d)
                while time.time() < deadline and \
                        not all(t.wait_idle(5.0) for t in topos):
                    pass
            fused = [n for t in topos for n in t.ops
                     if "Fused" in type(n).__name__]
            n_rules = 18
            rows = 0
            n = 0
            stall = 0.0
            t0 = time.time()
            while time.time() - t0 < seconds:
                src.ingest(drains[n % len(drains)])
                rows += len(drains[0])
                n += 1
                ts = time.time()
                while max(f.inq.qsize() for f in fused) > 6:
                    time.sleep(0.002)
                stall += time.time() - ts
            for t in topos:
                t.wait_idle(timeout=30.0)
            elapsed = time.time() - t0
            print(f"shared={shared}: {rows:,} rows x {n_rules} rules in "
                  f"{elapsed:.2f}s = {rows * n_rules / elapsed:,.0f} "
                  f"rule-rows/s, {rows/elapsed:,.0f} rows/s "
                  f"({stall:.1f}s stalled, {100*stall/elapsed:.0f}%)")
        finally:
            for t in topos:
                t.close()
            mem.reset()
    finally:
        if not shared:
            FusedWindowAggNode._shared_encode = orig_enc
            FusedWindowAggNode._shared_device_inputs = orig_dev


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("both", "off"):
        run(False)
    if which in ("both", "on"):
        run(True)
