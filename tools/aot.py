#!/usr/bin/env python
"""aot — build / verify / cold-start-check the AOT executable cache.

The runtime's zero-compile serving plane (ekuiper_tpu/runtime/aotcache.py)
keys persisted XLA executables by jitcert certificate signature strings,
so the certification battery (tools/jitcert.py) doubles as the cache's
build manifest. Three subcommands, all tier-1-safe on CPU jax:

  python -m tools.aot build --dir DIR [--json]
      Fleet image bake: drive the jitcert kernel battery with the disk
      cache enabled inside an aotcache.building() scope — every jit
      site × certified signature the battery exercises is lowered,
      compiled, and persisted under DIR, and DIR/manifest.json records
      what was built (op, signature, cache key, toolchain fingerprint).

  python -m tools.aot verify --dir DIR [--json]
      Check a baked cache against the image that will serve from it:
      every manifest entry must resolve to a disk entry whose metadata
      matches the CURRENT toolchain fingerprint — a jax/jaxlib upgrade
      or mesh change fails verify instead of silently compiling at
      serve time. Exit 1 on any missing or stale entry.

  python -m tools.aot coldstart [--dir DIR] [--json]
      The ci_gate "cold-start" gate: build the cache, restart
      in-process (fresh kernels, fresh registries — only the disk
      survives, like a process restart), re-drive the full battery and
      assert ZERO serve-path compiles: every executable must come from
      the cache. Exit 1 when any site compiled on the second pass.

docs/AOT_CACHE.md documents the cache layout and the bake workflow.
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))  # repo root


def _manifest_entries(root: str) -> List[Dict[str, Any]]:
    """Read every cache entry's metadata (never the payloads)."""
    out = []
    for fn in sorted(os.listdir(root)):
        if not fn.endswith(".aotx"):
            continue
        path = os.path.join(root, fn)
        try:
            with open(path, "rb") as fh:
                blob = pickle.load(fh)
            meta = dict(blob.get("meta") or {})
        except Exception as exc:
            meta = {"error": f"{type(exc).__name__}: {exc}"[:160]}
        meta["key"] = fn[:-len(".aotx")]
        meta["bytes"] = os.path.getsize(path)
        out.append(meta)
    return out


def build(root: str, as_json: bool = False) -> int:
    os.environ["KUIPER_AOT_CACHE_DIR"] = root
    os.makedirs(root, exist_ok=True)
    from tools import jitcert as jitcert_cli

    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.runtime import aotcache

    t0 = time.perf_counter()
    with aotcache.building():
        kernels = jitcert_cli._battery()
        jitcert_cli._drive(kernels)
    wall = time.perf_counter() - t0
    snap = aotcache.stats().snapshot()
    entries = _manifest_entries(root)
    certs = jitcert.live_certificates()
    manifest = {
        "fingerprint": aotcache.fingerprint(),
        "entries": [{k: e.get(k) for k in ("key", "op", "signature",
                                           "compile_s", "bytes")}
                    for e in entries],
        "certified_signatures": sum(len(v["signatures"])
                                    for v in certs.values()),
        "battery_kernels": sorted(kernels.keys()),
        "build_wall_s": round(wall, 2),
        "build_compile_s": snap["build_seconds"],
    }
    with open(os.path.join(root, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)
    report = {
        "ok": True, "dir": root, "executables": len(entries),
        "builds": snap["builds"], "disk_loads": snap["disk_loads"],
        "build_wall_s": manifest["build_wall_s"],
        "build_compile_s": snap["build_seconds"],
        "fingerprint": manifest["fingerprint"],
    }
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        print(f"aot build: OK — {report['executables']} executables under "
              f"{root} ({snap['builds']} compiled in "
              f"{snap['build_seconds']:.1f}s, {snap['disk_loads']} "
              "already baked)")
    return 0


def verify(root: str, as_json: bool = False) -> int:
    from ekuiper_tpu.runtime import aotcache

    problems: List[str] = []
    mpath = os.path.join(root, "manifest.json")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except Exception as exc:
        problems.append(f"manifest unreadable: {exc}")
        manifest = {"entries": []}
    fp = aotcache.fingerprint()
    if manifest.get("fingerprint") not in (None, fp):
        problems.append(
            "manifest fingerprint mismatch (cache baked for "
            f"{manifest.get('fingerprint')!r}, this image is {fp!r})")
    checked = 0
    for e in manifest.get("entries", []):
        op, sig = e.get("op"), e.get("signature")
        key = e.get("key") or ""
        path = os.path.join(root, f"{key}.aotx")
        if not os.path.exists(path):
            problems.append(f"{op}: entry {key[:12]}… missing on disk")
            continue
        if op is not None and sig is not None \
                and aotcache.cache_key(op, sig, fp) != key:
            problems.append(
                f"{op}: key does not re-derive under the current "
                "fingerprint (stale toolchain/mesh — rebake)")
            continue
        checked += 1
    report = {
        "ok": not problems, "dir": root, "checked": checked,
        "problems": problems, "fingerprint": fp,
    }
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        state = "OK" if report["ok"] else "FAILED"
        print(f"aot verify: {state} — {checked} entries match the "
              "current fingerprint"
              + ("" if report["ok"]
                 else "\n  " + "\n  ".join(problems)))
    return 0 if report["ok"] else 1


def coldstart(root: str, as_json: bool = False) -> int:
    """Build, then simulate a restart (fresh kernels + registries, disk
    survives) and assert the battery re-drives with zero compiles."""
    import gc

    os.environ["KUIPER_AOT_CACHE_DIR"] = root
    os.makedirs(root, exist_ok=True)
    from tools import jitcert as jitcert_cli

    from ekuiper_tpu.observability import devwatch, jitcert
    from ekuiper_tpu.runtime import aotcache

    t0 = time.perf_counter()
    with aotcache.building():
        kernels = jitcert_cli._battery()
        jitcert_cli._drive(kernels)
    build_s = time.perf_counter() - t0
    built = aotcache.stats().snapshot()
    # ---- in-process restart: drop every kernel and registry; only the
    # disk layer survives, exactly like a process restart on the image
    del kernels
    gc.collect()
    devwatch.registry().clear()
    jitcert.reset()
    aotcache.reset()
    t1 = time.perf_counter()
    kernels = jitcert_cli._battery()
    jitcert_cli._drive(kernels)
    warm_s = time.perf_counter() - t1
    warm = aotcache.stats().snapshot()
    diff_report = jitcert.diff_live()
    problems: List[str] = []
    if warm["misses"] > 0:
        problems.append(
            f"{warm['misses']} serve-path compile(s) after restart — "
            "cache coverage gap (see aot_cache_miss flight events)")
    if warm["disk_loads"] == 0:
        problems.append("warm pass loaded nothing from disk — cache "
                        "was not exercised")
    if not diff_report["clean"]:
        problems.append("jitcert diff not clean on the warm pass")
    report = {
        "ok": not problems,
        "dir": root,
        "cold": {"seconds": round(build_s, 2), "builds": built["builds"],
                 "compile_s": built["build_seconds"]},
        "warm": {"seconds": round(warm_s, 2), "misses": warm["misses"],
                 "disk_loads": warm["disk_loads"], "hits": warm["hits"]},
        "speedup": round(build_s / warm_s, 1) if warm_s > 0 else None,
        "problems": problems,
    }
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        state = "OK" if report["ok"] else "FAILED"
        print(f"aot coldstart: {state} — cold {build_s:.1f}s "
              f"({built['builds']} compiles) vs warm {warm_s:.1f}s "
              f"({warm['disk_loads']} disk loads, {warm['misses']} "
              "compiles)"
              + ("" if report["ok"] else "\n  " + "\n  ".join(problems)))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.aot", description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=["build", "verify", "coldstart"])
    ap.add_argument("--dir", default=None,
                    help="cache directory (default: $KUIPER_AOT_CACHE_DIR;"
                         " coldstart falls back to a temp dir)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # 8 virtual CPU devices so the sharded battery kernel constructs
    # (must land before the first jax import initializes the backend)
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    root = args.dir or os.environ.get("KUIPER_AOT_CACHE_DIR") or None
    if args.command == "build":
        if root is None:
            print("aot: --dir (or KUIPER_AOT_CACHE_DIR) is required",
                  file=sys.stderr)
            return 2
        return build(root, as_json=args.json)
    if args.command == "verify":
        if root is None:
            print("aot: --dir (or KUIPER_AOT_CACHE_DIR) is required",
                  file=sys.stderr)
            return 2
        return verify(root, as_json=args.json)
    if root is not None:
        return coldstart(root, as_json=args.json)
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="kuiper-aot-")
    try:
        return coldstart(root, as_json=args.json)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
