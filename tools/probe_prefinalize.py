"""Diagnostic probe for the prefinalize fetch path (VERDICT r2 weak #1).

Question: why did 50/50 bench windows find NO landed device fetch
(BENCH_r02 `storm windows=50`) despite 0.44-1.33s of lead time, when a
sync finalize takes ~160ms with an idle host?

Hypotheses probed, each under (a) idle main thread and (b) a main thread
spinning the same numpy work the bench does (HostShadow bincounts + key
encode):
  1. thread-fetch: the r2 design — a Python thread blocking in
     np.asarray(stacked). If (b) is much slower than (a), the blocking
     wait is GIL-starved.
  2. is_ready-poll: no thread — copy_to_host_async at dispatch, poll
     jax.Array.is_ready() from the main loop, np.asarray at the boundary.
     Measures boundary-time asarray cost after is_ready() goes true.

Run on the real TPU: python tools/probe_prefinalize.py
"""
from __future__ import annotations

import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")

BATCH = 65_536
CAP = 16_384


def make_gb():
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.groupby import DeviceGroupBy
    from ekuiper_tpu.sql.parser import parse_select

    stmt = parse_select(
        "SELECT deviceId, avg(temperature) AS a, count(*) AS c, "
        "min(temperature) AS mn, max(temperature) AS mx "
        "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
    )
    plan = extract_kernel_plan(stmt)
    return DeviceGroupBy(plan, capacity=CAP, micro_batch=BATCH)


def busy_host_work(stop: threading.Event, slots, vals):
    """Mimic the bench's per-batch host load: shadow bincounts + a dict
    encode pass. Runs until stop is set; returns iterations."""
    it = 0
    acc = np.zeros(CAP, dtype=np.float64)
    while not stop.is_set():
        acc += np.bincount(slots, weights=vals, minlength=CAP)[:CAP]
        acc += np.bincount(slots, weights=vals * vals, minlength=CAP)[:CAP]
        np.minimum.at(acc, slots[:1024], vals[:1024])
        d = {}
        for x in range(3000):
            d[x] = x
        it += 1
    return it


def probe(mode: str, busy: bool, gb, state, reps: int = 5):
    import jax

    rng = np.random.default_rng(0)
    slots = rng.integers(0, CAP, BATCH).astype(np.int32)
    vals = rng.normal(20, 5, BATCH).astype(np.float32)
    out = []
    for _ in range(reps):
        stop = threading.Event()
        worker = None
        if busy:
            worker = threading.Thread(
                target=busy_host_work, args=(stop, slots, vals), daemon=True
            )
        t0 = time.time()
        stacked = gb._components(state, (True,))
        try:
            stacked.copy_to_host_async()
        except AttributeError:
            pass
        if mode == "thread":
            done = threading.Event()
            res = {}

            def fetch():
                res["a"] = np.asarray(stacked)
                done.set()

            threading.Thread(target=fetch, daemon=True).start()
            if worker:
                worker.start()
            while not done.is_set():
                time.sleep(0.001)
                if time.time() - t0 > 10:
                    break
            t_ready = time.time() - t0
            t_get = 0.0
        else:  # is_ready poll
            if worker:
                worker.start()
            while not stacked.is_ready():
                time.sleep(0.001)
                if time.time() - t0 > 10:
                    break
            t_ready = time.time() - t0
            t1 = time.time()
            np.asarray(stacked)
            t_get = time.time() - t1
        stop.set()
        out.append((t_ready * 1000, t_get * 1000))
    lab = f"{mode:>8} busy={int(busy)}"
    r = np.array(out)
    print(
        f"{lab}: ready p50={np.percentile(r[:, 0], 50):7.1f}ms "
        f"max={r[:, 0].max():7.1f}ms; boundary-get "
        f"p50={np.percentile(r[:, 1], 50):6.1f}ms max={r[:, 1].max():6.1f}ms"
    )


def main():
    import jax

    print(f"device: {jax.devices()[0].device_kind}")
    gb = make_gb()
    state = gb.init_state()
    rng = np.random.default_rng(0)
    slots = rng.integers(0, CAP, BATCH).astype(np.int32)
    cols = {"temperature": rng.normal(20, 5, BATCH).astype(np.float32)}
    state = gb.fold(state, cols, slots)
    # warm the components program + transfer path
    np.asarray(gb._components(state, (True,)))
    for mode in ("thread", "is_ready"):
        for busy in (False, True):
            probe(mode, busy, gb, state)


if __name__ == "__main__":
    main()
