#!/usr/bin/env python
"""kuipertop — live fleet console over a running engine's REST plane.

`top` for the mesh: refreshes a one-screen fleet view off `/metrics`
plus the `/diagnostics/{health,control,mesh}` views —

- header: uptime, device kind, admission decisions, compile storms,
  AOT serve-misses (the zero-compile-serving tripwire);
- per-rule table: fold rows/s (delta between refreshes), health
  verdict, fast-window SLO burn, shed level/rows, bottleneck stage;
- mesh panel: per-shard load bars (rows/s EWMA from meshwatch) with
  skew ratio + hot-shard marker per sharded rule, committed HBM per
  placement shard, collective-vs-compute share of the sharded folds;
- timeline footer: on-disk telemetry ring segments/bytes.

Stdlib only (urllib + ANSI), same as every tool here. Usage:

    python tools/kuipertop.py [--url http://127.0.0.1:9081]
                              [--interval 2.0] [--once] [--no-color]

`--once` paints a single frame without clearing the screen (smoke tests
and `watch -n` users).
"""
from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

Sample = Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]


def fetch(url: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


def fetch_json(url: str, timeout: float = 3.0) -> Dict[str, Any]:
    try:
        return json.loads(fetch(url, timeout))
    except (urllib.error.URLError, ValueError, OSError):
        return {}


def parse_metrics(text: str) -> Sample:
    out: Sample = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            v = float(val)
        except ValueError:
            continue
        name, _, rest = key.partition("{")
        labels = tuple(sorted(
            (m.group(1), m.group(2)) for m in LABEL_RE.finditer(rest)))
        out[(name, labels)] = v
    return out


def series(sample: Sample, name: str):
    for (n, labels), v in sample.items():
        if n == name:
            yield dict(labels), v


def total(sample: Sample, name: str) -> float:
    return sum(v for _, v in series(sample, name))


def bar(frac: float, width: int = 20) -> str:
    frac = max(0.0, min(1.0, frac))
    n = int(round(frac * width))
    return "█" * n + "·" * (width - n)


def fmt_rate(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.1f}M"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k"
    return f"{v:.0f}"


HEALTH_NAMES = {0: "healthy", 1: "DEGRADED", 2: "BREACHING"}


class Console:
    def __init__(self, url: str, color: bool = True) -> None:
        self.url = url.rstrip("/")
        self.color = color
        self.prev: Optional[Sample] = None
        self.prev_t: Optional[float] = None

    def _c(self, code: str, s: str) -> str:
        return f"\x1b[{code}m{s}\x1b[0m" if self.color else s

    def _delta_rate(self, cur: Sample, name: str, dt: float,
                    by: str = "rule") -> Dict[str, float]:
        """Per-<by> rate of a counter between refreshes (0 on frame 1)."""
        rates: Dict[str, float] = {}
        if self.prev is None or dt <= 0:
            return rates
        for (n, labels), v in cur.items():
            if n != name:
                continue
            prev_v = self.prev.get((n, labels))
            if prev_v is None or v < prev_v:
                continue
            key = dict(labels).get(by, "")
            rates[key] = rates.get(key, 0.0) + (v - prev_v) / dt
        return rates

    def frame(self) -> str:
        now = time.time()
        try:
            cur = parse_metrics(fetch(self.url + "/metrics"))
        except (urllib.error.URLError, OSError) as exc:
            return f"kuipertop: {self.url} unreachable: {exc}"
        mesh = fetch_json(self.url + "/diagnostics/mesh")
        control = fetch_json(self.url + "/diagnostics/control")
        dt = (now - self.prev_t) if self.prev_t else 0.0

        lines = []
        # ---- header
        uptime = total(cur, "kuiper_uptime_seconds")
        storms = total(cur, "kuiper_xla_compile_storms_total")
        serve_miss = total(cur, "kuiper_aot_serve_misses_total")
        adm = {d.get("decision", ""): int(v)
               for d, v in series(cur, "kuiper_admission_total")}
        head = (f"kuipertop — {self.url}  up {uptime:.0f}s  "
                f"admission a/r/q {adm.get('accept', 0)}/"
                f"{adm.get('reject', 0)}/{adm.get('queue', 0)}  ")
        head += (self._c("31", f"storms {storms:.0f}") if storms
                 else "storms 0")
        head += "  "
        head += (self._c("31", f"aot-serve-miss {serve_miss:.0f}")
                 if serve_miss else "aot-serve-miss 0")
        lines.append(self._c("1", head))

        # ---- per-rule table: rows/s (fold-stage delta), health, burn,
        # shed, bottleneck
        fold_rates = self._delta_rate(
            cur, "kuiper_op_stage_rows_total", dt)
        shed_rates = self._delta_rate(cur, "kuiper_shed_total", dt)
        health = {dict_l.get("rule", ""): int(v)
                  for dict_l, v in series(cur, "kuiper_rule_health")}
        burn = {d.get("rule", ""): v
                for d, v in series(cur, "kuiper_slo_burn_rate")
                if d.get("window") == "fast"}
        bn = {d.get("rule", ""): d.get("stage", "")
              for d, v in series(cur, "kuiper_bottleneck_stage")}
        rules = sorted(set(health) | set(fold_rates) | set(burn),
                       key=lambda r: -fold_rates.get(r, 0.0))
        lines.append(self._c(
            "4", f"{'rule':<24}{'rows/s':>10}{'health':>11}"
                 f"{'burn':>7}{'shed/s':>9}  bottleneck"))
        for r in rules[:12]:
            hv = health.get(r, 0)
            hname = HEALTH_NAMES.get(hv, "?")
            if hv and self.color:
                hname = self._c("31" if hv == 2 else "33", hname)
            stage = bn.get(r, "")
            if stage == "shard_skew" and self.color:
                stage = self._c("35", stage)
            hw = 20 if self.color and hv else 11  # ANSI codes are 9 chars
            lines.append(
                f"{r[:23]:<24}{fmt_rate(fold_rates.get(r, 0.0)):>10}"
                f"{hname:>{hw}}"
                f"{burn.get(r, 0.0):>7.1f}"
                f"{fmt_rate(shed_rates.get(r, 0.0)):>9}  {stage}")
        if not rules:
            lines.append("  (no rules reporting)")

        # ---- mesh panel: shard bars + skew + collective split
        skew = (mesh.get("skew") or {})
        if skew:
            lines.append(self._c("1", "mesh"))
            for rule in sorted(skew):
                e = skew[rule]
                shards = e.get("shards") or []
                peak = max((s.get("rows_per_s", 0.0) for s in shards),
                           default=0.0) or 1.0
                ratio = e.get("skew_ratio")
                tag = f"skew {ratio:.2f}x" if ratio is not None else ""
                if e.get("skewed"):
                    tag = self._c("31", tag + " ⚠ rebalance")
                lines.append(f"  {rule[:22]:<23} mesh {e.get('mesh', '')}"
                             f"  {tag}")
                for s in shards:
                    mark = "←hot" if (e.get("skewed") and
                                      s["shard"] == e.get("hot_shard")) \
                        else ""
                    lines.append(
                        f"    shard {s['shard']:<2} "
                        f"{bar(s.get('rows_per_s', 0.0) / peak)} "
                        f"{fmt_rate(s.get('rows_per_s', 0.0)):>8}/s "
                        f"keys {s.get('keys', 0):<6}{mark}")
        hbm = sorted(series(cur, "kuiper_shard_hbm_committed_bytes"),
                     key=lambda t: t[0].get("shard", ""))
        if hbm:
            peak_b = max((v for _, v in hbm), default=0.0) or 1.0
            lines.append("  committed HBM per chip")
            for d, v in hbm:
                lines.append(f"    chip {d.get('shard', '?'):<3} "
                             f"{bar(v / peak_b)} {v / 1e6:8.1f} MB")
        coll = mesh.get("collective") or []
        for c in coll[:6]:
            lines.append(
                f"  {c.get('op', ''):<28} collective "
                f"{100.0 * c.get('share', 0.0):5.1f}% of "
                f"{c.get('device_us', 0.0) / 1e3:.1f}ms sampled device "
                f"time ({c.get('samples', 0)} samples)")
        hints = ((control.get("mesh") or {})
                 .get("rebalance_hints_total", 0))
        if hints:
            lines.append(self._c("33", f"  rebalance hints: {hints}"))

        # ---- timeline footer
        segs = total(cur, "kuiper_timeline_segments")
        tl_bytes = total(cur, "kuiper_timeline_bytes")
        if segs:
            lines.append(
                f"timeline: {segs:.0f} segments, "
                f"{tl_bytes / 1024:.0f} KB on disk "
                f"(GET /diagnostics/timeline)")
        self.prev, self.prev_t = cur, now
        return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="http://127.0.0.1:9081")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="paint one frame and exit")
    ap.add_argument("--no-color", action="store_true")
    args = ap.parse_args(argv)
    con = Console(args.url, color=not args.no_color and
                  sys.stdout.isatty())
    if args.once:
        print(con.frame())
        return 0
    try:
        while True:
            frame = con.frame()
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
