# Marker so `python -m tools.kuiperlint` resolves from the repo root.
