#!/usr/bin/env python
"""Sharing probe — print the shared-fold decision + estimated savings for
a rule set, EXPLAIN-driven (planner/sharing.py), without running anything.

Usage:
    python tools/probe_sharing.py [ruleset.json]

ruleset.json:
    {"streams": ["CREATE STREAM demo (...) WITH (...)", ...],
     "rules":   [{"id": "r1", "sql": "SELECT ...", "options": {...}}, ...]}

Without an argument a built-in demo set (8 correlated rules over one
stream — the bench's multi_rule_shared shape) is probed. Rules are
declared in listing order, so the table shows exactly what a same-order
CREATE sequence would plan: later rules see earlier ones as peers and the
pane is the GCD across the declared set.

Run from the tier-1 suite as a smoke test (tests/test_shared_fold.py).
Exit 0 = probe rendered; exit 1 = a stream/rule failed to parse or plan.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEMO = {
    "streams": [
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="t/probe", TYPE="memory", FORMAT="JSON")',
    ],
    "rules": [
        {"id": "dash_avg", "sql":
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c FROM "
            "demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"},
        {"id": "dash_minmax", "sql":
            "SELECT deviceId, min(temperature) AS mn, max(temperature) AS "
            "mx FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"},
        {"id": "alert_sum", "sql":
            "SELECT deviceId, sum(temperature) AS s FROM demo "
            "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)"},
        {"id": "alert_cnt", "sql":
            "SELECT deviceId, count(*) AS c FROM demo "
            "GROUP BY deviceId, HOPPINGWINDOW(ss, 20, 5)"},
        {"id": "trend_avg", "sql":
            "SELECT deviceId, avg(temperature) AS a FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 20)"},
        {"id": "spread", "sql":
            "SELECT deviceId, stddev(temperature) AS sd FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"},
        {"id": "fast_sum", "sql":
            "SELECT deviceId, sum(temperature) AS s, count(*) AS c FROM "
            "demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)"},
        {"id": "ckpt_avg", "sql":
            "SELECT deviceId, avg(temperature) AS a FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
         "options": {"qos": 1}},
    ],
}


def probe(doc: dict) -> int:
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.planner import sharing
    from ekuiper_tpu.planner.planner import (
        RuleDef, _subtopo_spec, device_path_eligible, merged_options)
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.store import kv

    kv.setup("memory")
    store = kv.get_store()
    sp = StreamProcessor(store)
    for sql in doc.get("streams", []):
        sp.exec_stmt(sql)

    rows = []
    for rdef in doc.get("rules", []):
        rule = RuleDef.from_dict(rdef)
        stmt = parse_select(rule.sql)
        opts = merged_options(rule)
        plan = device_path_eligible(stmt, opts)
        if plan is None or len(stmt.sources) != 1 or stmt.joins:
            rows.append((rule.id, "host/private", "-",
                         "not device-fusable (no sharing candidate)"))
            continue
        subkey, _, _ = _subtopo_spec(
            stmt.sources[0].name, stmt.sources[0].name, opts, store)
        dims = [d.expr.name for d in stmt.dimensions]
        direct = build_direct_emit(stmt, plan, dims)
        d = sharing.decide(stmt, opts, plan, subkey, rule.id,
                           has_direct_emit=direct is not None)
        if d.eligible:
            # declare so later rules in the listing see this one as a peer
            length = stmt.window.length_ms()
            interval = stmt.window.interval_ms() or length
            sharing.declare(d.store_key, rule.id, length, interval, plan)
        if d.share:
            est = d.estimates
            rows.append((
                rule.id, "shared",
                f"pane {est['pane_ms']}ms x{est['span_panes']}",
                f"saved {est['saved_fold_us_per_s']:.0f}us/s vs "
                f"{est['emit_overhead_us_per_s']:.0f}us/s combine "
                f"({est['peers']} peer(s))"))
        else:
            rows.append((rule.id, "private", "-", d.reason))

    widths = [max(len(str(r[i])) for r in rows + [("rule", "decision",
                                                   "panes", "why")])
              for i in range(4)]
    header = ("rule", "decision", "panes", "why")
    for r in (header,) + tuple(rows):
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    n_shared = sum(1 for r in rows if r[1] == "shared")
    print(f"\n{n_shared}/{len(rows)} rule(s) would share a pane fold.")
    return 0


def main() -> int:
    if len(sys.argv) > 1:
        with open(sys.argv[1]) as f:
            doc = json.load(f)
    else:
        doc = DEMO
    try:
        return probe(doc)
    except Exception as exc:  # noqa: BLE001
        print(f"probe_sharing: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
