"""Diagnostic probe for sliding-trigger emission latency (VERDICT r4 weak
#3: paced p50 407ms vs <150ms target; fold-stall max 865ms).

Breaks one _emit_sliding into its cost components on the real TPU:
  A. ring-refold size: how many scratch rows/segments the two edge buckets
     contribute at a paced 1M rows/s load
  B. scratch upload+fold dispatch time (host-side, enters fold stream)
  C. finalize dispatch time
  D. fetch wait: dispatch->values-on-host for the async emit worker
  E. candidate fix: include the CURRENT bucket's pane in the mask instead
     of refolding it through scratch (halves the refold) — parity checked

Run solo on the TPU: python tools/probe_sliding.py
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

BATCH = 65_536
CAP = 16_384
N_KEYS = 10_000


def main() -> None:
    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils import timex

    import jax

    sql = ("SELECT deviceId, percentile_approx(temperature, 0.99) AS p99, "
           "count(*) AS c FROM demo GROUP BY deviceId, "
           "SLIDINGWINDOW(ss, 10) OVER (WHEN temperature > 44.5)")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    # this probe decomposes the LEGACY refold trigger path (scratch
    # refolds / fold_masked) — pin slidingImpl=refold so it keeps probing
    # that path now that DABA rings are the default (ops/slidingring.py)
    node = FusedWindowAggNode(
        "slide", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=CAP, micro_batch=BATCH,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True, sliding_impl="refold")
    node.state = node.gb.init_state()
    print(f"bucket_ms={node.bucket_ms} ring_panes={node.n_ring_panes}",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    devs = np.array([f"dev{i}" for i in range(N_KEYS)], dtype=object)
    batches = []
    for _ in range(8):
        batches.append({
            "deviceId": devs[rng.integers(0, N_KEYS, BATCH)],
            "temperature": rng.uniform(20, 40, BATCH).astype(np.float32),
        })

    emits = []

    def grab(item):
        emits.append((time.time(), getattr(node, "last_emit_info", None)))

    node.broadcast = grab

    def stamped(i, spike=False):
        cols = dict(batches[i % len(batches)])
        if spike:
            t = cols["temperature"].copy()
            t[0] = 99.0
            cols["temperature"] = t
        return ColumnBatch(n=BATCH, columns=cols,
                           timestamps=np.full(BATCH, timex.now_ms(),
                                              dtype=np.int64))

    # warm — including fold_masked via the node's own warmup compile
    node._warmup()
    node.process(stamped(0))
    node._emit_sliding(timex.now_ms())
    node._drain_async_emits()
    jax.block_until_ready(node.state)

    # pace 1M rows/s for 12s; every 5th batch carries a trigger row.
    # instrument _emit_sliding internals via monkeypatched gb.fold counting
    interval = BATCH / 1_000_000
    orig_fold = node.gb.fold
    fold_calls = {"scratch": 0, "scratch_rows": 0, "in_emit": False}

    def counting_fold(state, cols, slots, valid=None, pane=0, **kw):
        if fold_calls["in_emit"]:
            fold_calls["scratch"] += 1
            fold_calls["scratch_rows"] += len(slots)
        return orig_fold(state, cols, slots, valid, pane, **kw)

    node.gb.fold = counting_fold
    orig_fm = node.gb.fold_masked

    def counting_fm(state, dev_all, s_dev, mask, pane):
        if fold_calls["in_emit"]:
            fold_calls["scratch"] += 1
            fold_calls["scratch_rows"] += int(mask.sum())
        return orig_fm(state, dev_all, s_dev, mask, pane)

    node.gb.fold_masked = counting_fm

    orig_emit = node._emit_sliding
    stats = []

    def timed_emit(t):
        fold_calls["in_emit"] = True
        fold_calls["scratch"] = 0
        fold_calls["scratch_rows"] = 0
        t0 = time.time()
        orig_emit(t)
        d = (time.time() - t0) * 1000
        fold_calls["in_emit"] = False
        stats.append((t0, d, fold_calls["scratch"],
                      fold_calls["scratch_rows"]))

    node._emit_sliding = timed_emit

    emits.clear()
    t0 = time.time()
    n = 0
    while time.time() - t0 < 12.0:
        target = t0 + n * interval
        delay = target - time.time()
        if delay > 0:
            time.sleep(delay)
        node.process(stamped(n, spike=(n % 5 == 4)))
        n += 1
    node._drain_async_emits()
    jax.block_until_ready(node.state)

    stalls = [d for _, d, _, _ in stats]
    segs = [s for _, _, s, _ in stats]
    rows = [r for _, _, _, r in stats]
    print(f"triggers={len(stats)} "
          f"fold-stall p50={np.percentile(stalls, 50):.1f}ms "
          f"p90={np.percentile(stalls, 90):.1f}ms max={max(stalls):.0f}ms",
          file=sys.stderr)
    print(f"scratch segments p50={np.percentile(segs, 50):.0f} "
          f"max={max(segs)}; scratch rows p50={np.percentile(rows, 50):.0f} "
          f"max={max(rows)}", file=sys.stderr)
    # issue->delivered
    issue_ts = [t for t, _, _, _ in stats]
    deliv_ts = [t for t, _ in emits]
    lat = [(d - i) * 1000 for i, d in zip(issue_ts, deliv_ts)]
    if lat:
        print(f"issue→delivered p50={np.percentile(lat, 50):.0f}ms "
              f"p90={np.percentile(lat, 90):.0f}ms max={max(lat):.0f}ms",
              file=sys.stderr)
    fms = [i["fetch_ms"] for _, i in emits
           if i and i.get("fetch_ms") is not None]
    if fms:
        print(f"worker fetch_ms p50={np.percentile(fms, 50):.0f} "
              f"p90={np.percentile(fms, 90):.0f} max={max(fms):.0f}",
              file=sys.stderr)
    info = getattr(node, "last_emit_info", None)
    print(f"last_emit_info={info}", file=sys.stderr)

    # idle-cost decomposition: one fold, one finalize+fetch, with nothing
    # else on the link
    import jax.numpy as jnp

    for name, fn in (
        ("fold", lambda: jax.block_until_ready(
            node.process(stamped(0)) or node.state["act"])),
        ("finalize_dispatch", lambda: node.gb._finalize_dyn(
            node.state, np.ones(node.gb.n_panes, dtype=np.bool_))),
    ):
        t0 = time.time()
        r = fn()
        d1 = (time.time() - t0) * 1000
        if name == "finalize_dispatch":
            t1 = time.time()
            _ = np.asarray(r)
            d2 = (time.time() - t1) * 1000
            print(f"idle {name}: dispatch={d1:.1f}ms fetch={d2:.1f}ms "
                  f"bytes={_.nbytes}", file=sys.stderr)
        else:
            print(f"idle {name}: {d1:.1f}ms", file=sys.stderr)


if __name__ == "__main__":
    main()
