#!/usr/bin/env python
"""probe_joins — tier-1 smoke for the device relational tier.

Covers the join-ring + segmented-scan subsystem end to end:

  1. lift engagement: the planner builds a DeviceJoinNode for a
     canonical interval join, a DeviceAnalyticNode for a lag() rule and
     a VectorWindowFuncNode for a rank() rule — no silent host routing,
  2. mask parity: randomized windows (NULL keys, NULL event times, NULL
     residual operands) through the certified match kernel equal the
     numpy shadow twin bit-for-bit,
  3. emission parity: full DeviceJoinNode._join_step windows reproduce
     the host nested loop's messages AND emission order for INNER and
     FULL joins,
  4. fallback taxonomy: a non-liftable ON clause surfaces a structured
     `join_*` reason in /rules/{id}/explain's expressions report and in
     the kuiper_expr_host_fallback_total counter — never an exception,
  5. every traced signature is inside its jitcert certificate
     (diff_live clean) — the bounded-signature-family acceptance gate.

Run directly or through tools/ci_gate.py (gate name `probe_joins`).
Exit 0 on success. docs/JOINS.md documents the subsystem.
"""
from __future__ import annotations

import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

JOIN_SQL = ("SELECT ls.v, rs.w FROM ls INNER JOIN rs ON ls.id = rs.id "
            "AND ls.ts - rs.ts >= -5 AND ls.ts - rs.ts <= 5 "
            "AND ls.v > rs.w GROUP BY TUMBLINGWINDOW(ss, 10)")
LIKE_SQL = ("SELECT ls.v FROM ls INNER JOIN rs ON ls.id LIKE rs.id "
            "GROUP BY TUMBLINGWINDOW(ss, 10)")
LAG_SQL = ("SELECT id, lag(v) OVER (PARTITION BY id) AS prev FROM ls")
RANK_SQL = ("SELECT id, rank(v) OVER (PARTITION BY id) AS rk FROM ls "
            "GROUP BY TUMBLINGWINDOW(ss, 10)")


def _mk_streams(store):
    from ekuiper_tpu.server.processors import StreamProcessor

    sp = StreamProcessor(store)
    sp.exec_stmt('CREATE STREAM ls (id STRING, v FLOAT, ts BIGINT) '
                 'WITH (DATASOURCE="pj/l", TYPE="memory", FORMAT="JSON")')
    sp.exec_stmt('CREATE STREAM rs (id STRING, w FLOAT, ts BIGINT) '
                 'WITH (DATASOURCE="pj/r", TYPE="memory", FORMAT="JSON")')


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ekuiper_tpu.data.rows import JoinTuple, Tuple
    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.ops.joinring import SideBatch
    from ekuiper_tpu.planner import relational
    from ekuiper_tpu.planner.planner import RuleDef, explain, plan_rule
    from ekuiper_tpu.runtime.nodes_relational import (DeviceAnalyticNode,
                                                      DeviceJoinNode,
                                                      VectorWindowFuncNode)
    from ekuiper_tpu.sql.compiler import host_fallback_counts
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.store import kv

    problems = []
    store = kv.get_store()
    _mk_streams(store)

    # ---- 1. lift engagement through the real planner -----------------
    def node_types(sql, rid):
        topo = plan_rule(RuleDef(id=rid, sql=sql,
                                 actions=[{"log": {}}], options={}), store)
        return [type(n).__name__ for n in topo.ops]

    if not any(t == "DeviceJoinNode"
               for t in node_types(JOIN_SQL, "pj_join")):
        problems.append("interval join rule did not build a DeviceJoinNode")
    if not any(t == "DeviceAnalyticNode"
               for t in node_types(LAG_SQL, "pj_lag")):
        problems.append("lag rule did not build a DeviceAnalyticNode")
    if not any(t == "VectorWindowFuncNode"
               for t in node_types(RANK_SQL, "pj_rank")):
        problems.append("rank rule did not build a VectorWindowFuncNode")

    # ---- 2. randomized mask parity: device kernel vs numpy twin ------
    stmt = parse_select(JOIN_SQL)
    low = relational.lower_join(stmt, stmt.joins)
    ring = low.build_ring(capacity=64)
    rng = random.Random(19)

    def side(n, col):
        b = SideBatch(n=n)
        b.key_cols.append(
            [rng.choice(["a", "b", None, ""]) for _ in range(n)])
        b.band = [rng.choice([rng.randint(0, 30), None]) for _ in range(n)]
        b.cols[col] = [rng.choice([1.0, 5.0, None]) for _ in range(n)]
        return b

    for trial in range(6):
        left = side(rng.randint(0, 16), "__jl_v")
        right = side(rng.randint(0, 16), "__jr_w")
        dev = ring.match(left, right)
        host = ring.match_host(left, right)
        if not np.array_equal(dev, host):
            problems.append(f"mask parity break at trial {trial}: "
                            f"{dev.tolist()} != {host.tolist()}")
            break

    # ---- 3. emission parity: full node vs host nested loop -----------
    from ekuiper_tpu.runtime.nodes_join import JoinNode

    for jt in ("INNER", "FULL"):
        sql = (f"SELECT l.v, r.w FROM l {jt} JOIN r ON l.k = r.k "
               "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 "
               "GROUP BY TUMBLINGWINDOW(ss, 1)")
        s2 = parse_select(sql)
        lw = relational.lower_join(s2, s2.joins)
        host_n = JoinNode("join", s2.joins, left_name="l")
        dev_n = DeviceJoinNode("join", s2.joins, left_name="l", lowering=lw)
        for trial in range(4):
            def rows(sd, n):
                out = []
                for _ in range(n):
                    ts = rng.randint(0, 25)
                    msg = {"k": rng.choice(["a", "b", None]), "ts": ts,
                           ("v" if sd == "l" else "w"): rng.random()}
                    out.append(Tuple(emitter=sd, message=msg, timestamp=ts))
                return out

            lrows = [JoinTuple(tuples=[t])
                     for t in rows("l", rng.randint(0, 8))]
            rrows = rows("r", rng.randint(0, 8))
            eh = host_n._join_step(lrows, rrows, s2.joins[0])
            ed = dev_n._join_step(lrows, rrows, s2.joins[0])
            got_h = [[t.message for t in j.tuples] for j in eh]
            got_d = [[t.message for t in j.tuples] for j in ed]
            if got_h != got_d:
                problems.append(f"{jt} emission parity break: "
                                f"{got_h} != {got_d}")
                break
        if dev_n.ring.fallback_windows_total:
            problems.append(f"{jt} parity windows took the fallback path")

    # ---- 4. fallback taxonomy is structured, not an exception --------
    before = dict(host_fallback_counts())
    types = node_types(LIKE_SQL, "pj_like")
    if any(t == "DeviceJoinNode" for t in types):
        problems.append("LIKE-ON join must not lift to DeviceJoinNode")
    if not any(t == "JoinNode" for t in types):
        problems.append(f"LIKE-ON join lost its host JoinNode: {types}")
    after = host_fallback_counts()
    gained = {k: after.get(k, 0) - before.get(k, 0)
              for k in after if after.get(k, 0) > before.get(k, 0)}
    if not any(k.startswith("join_") for k in gained):
        problems.append(f"no join_* host-fallback counter recorded "
                        f"for the LIKE-ON plan: {gained}")
    exp = explain(RuleDef(id="pj_like", sql=LIKE_SQL,
                          actions=[{"log": {}}], options={}), store)
    pieces = (exp.get("expressions") or {}).get("pieces") or []
    join_pieces = [p for p in pieces if p.get("kind") == "join"]
    if not join_pieces:
        problems.append(f"explain has no join piece: {pieces}")
    elif not (join_pieces[0].get("path") == "host"
              and str(join_pieces[0].get("reason", "")).startswith("join_")):
        problems.append(f"explain join piece lacks a join_* host reason: "
                        f"{join_pieces[0]}")

    # ---- 5. certificate closure --------------------------------------
    d = jitcert.diff_live()
    if not d["clean"]:
        problems.append(f"jitcert diff not clean: {d['uncertified'][:4]}")

    report = {"ok": not problems, "problems": problems}
    print(json.dumps(report, indent=2) if problems else
          "probe_joins: OK — join/analytic rules lift, mask+emission "
          "parity holds, fallbacks are structured, jitcert clean")
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
