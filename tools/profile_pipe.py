"""Stage-by-stage profile of the full-pipe ingest path on the real chip.

Measures, in one process (like _full_pipe_main):
  A. native decode_columns alone (bytes -> columns)
  B. KeyTable.encode_column alone (object strings -> slots)
  C. fused node consumption alone (prebuilt ColumnBatches, same shapes the
     source emits) -- the single-thread ceiling
  D. the real topo pipe (source thread + fused worker), with per-stage
     counters sampled from the nodes

Run: python tools/profile_pipe.py
"""
import json as _json
import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")

N_DEVICES = 10_000
DRAIN_ROWS = 3072


def make_drains(n=12):
    rng = np.random.default_rng(23)
    drains = []
    for _ in range(n):
        drains.append([
            _json.dumps({
                "deviceId": f"dev_{rng.integers(0, N_DEVICES)}",
                "temperature": round(float(rng.normal(20, 5)), 2),
            }).encode()
            for _ in range(DRAIN_ROWS)
        ])
    return drains


def stage_a_decode(drains):
    from ekuiper_tpu.data.types import DataType, Field, Schema
    from ekuiper_tpu.io import fastjson

    fastjson.ensure_native(background=False)
    schema = Schema(fields=[Field("deviceId", DataType.STRING),
                            Field("temperature", DataType.FLOAT)])
    spec = fastjson.schema_field_spec(schema)
    # warm
    fastjson.decode_columns(drains[0], spec)
    t0 = time.time()
    rows = 0
    n = 0
    while time.time() - t0 < 3.0:
        out = fastjson.decode_columns(drains[n % len(drains)], spec)
        assert out is not None
        rows += DRAIN_ROWS
        n += 1
    dt = time.time() - t0
    print(f"A decode_columns: {rows/dt:,.0f} rows/s ({dt/ n*1e3:.2f} ms/drain)")
    return out


def stage_b_keytable(drains):
    from ekuiper_tpu.data.types import DataType, Field, Schema
    from ekuiper_tpu.io import fastjson
    from ekuiper_tpu.ops.keytable import KeyTable

    schema = Schema(fields=[Field("deviceId", DataType.STRING),
                            Field("temperature", DataType.FLOAT)])
    spec = fastjson.schema_field_spec(schema)
    cols, _, _ = fastjson.decode_columns(drains[0], spec)
    kt = KeyTable(16384)
    kt.encode_column(cols["deviceId"])  # warm: inserts
    t0 = time.time()
    rows = 0
    while time.time() - t0 < 2.0:
        kt.encode_column(cols["deviceId"])
        rows += DRAIN_ROWS
    dt = time.time() - t0
    print(f"B keytable encode: {rows/dt:,.0f} rows/s")


def make_batches(drains, batch_rows):
    """Build the ColumnBatches the source WOULD emit at a given flush size."""
    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.types import DataType, Field, Schema
    from ekuiper_tpu.io import fastjson

    schema = Schema(fields=[Field("deviceId", DataType.STRING),
                            Field("temperature", DataType.FLOAT)])
    spec = fastjson.schema_field_spec(schema)
    flat = [p for d in drains for p in d]
    batches = []
    for i in range(0, len(flat) - batch_rows + 1, batch_rows):
        chunk = flat[i:i + batch_rows]
        cols, valid, bad = fastjson.decode_columns(chunk, spec)
        ts = np.full(batch_rows, 1000, dtype=np.int64)
        batches.append(ColumnBatch(
            n=batch_rows, columns=cols, valid={},
            timestamps=ts, emitter="pipe"))
    return batches


def stage_c_fused(drains, batch_rows, seconds=8.0):
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    import jax

    stmt = parse_select(
        "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
        "FROM pipe GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "f", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=16384, micro_batch=max(batch_rows, 512),
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    node.broadcast = lambda item: None
    batches = make_batches(drains, batch_rows)
    node.process(batches[0])  # warm compile
    jax.block_until_ready(node.state)
    t0 = time.time()
    rows = 0
    n = 0
    t_sub = {}
    while time.time() - t0 < seconds:
        node.process(batches[n % len(batches)])
        rows += batch_rows
        n += 1
        if n % 16 == 0:
            jax.block_until_ready(node.state)
    jax.block_until_ready(node.state)
    dt = time.time() - t0
    print(f"C fused consume (batch={batch_rows}): {rows/dt:,.0f} rows/s "
          f"({dt/n*1e3:.1f} ms/batch)")


def stage_d_topo(flush_rows, linger_ms, seconds=10.0):
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv
    from ekuiper_tpu.io import fastjson

    mem.reset()
    fastjson.ensure_native(background=False)
    store = kv.get_store()
    try:
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM pipe (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="topic/pipe", TYPE="memory", FORMAT="JSON")')
    except Exception:
        pass
    rule = RuleDef(
        id="pipe1", sql=(
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
            "FROM pipe GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        actions=[{"nop": {}}],
        options={"bufferLength": 64, "micro_batch_rows": flush_rows,
                 "micro_batch_linger_ms": linger_ms, "key_slots": 16384})
    topo = plan_rule(rule, store)
    fused = next(n for n in topo.ops
                 if type(n).__name__ == "FusedWindowAggNode")
    topo.open()
    src = (topo.sources[0] if topo.sources
           else topo._live_shared[0][0].source)
    drains = make_drains()
    try:
        deadline = time.time() + 600
        for _ in range(2):  # real warm: inline flush + full key coverage
            for d in drains:
                src.ingest(d)
            while time.time() < deadline and not topo.wait_idle(5.0):
                pass
        batch_sizes = []
        orig_process = fused.process
        t_proc = [0.0]

        def timed_process(item):
            t = time.time()
            orig_process(item)
            t_proc[0] += time.time() - t
            if hasattr(item, "n"):
                batch_sizes.append(item.n)
        fused.process = timed_process
        t_flush = [0.0]
        orig_flush = src._flush_raw

        def timed_flush(raws, rtss):
            t = time.time()
            orig_flush(raws, rtss)
            t_flush[0] += time.time() - t
        src._flush_raw = timed_flush

        rows = 0
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            src.ingest(drains[n % len(drains)])
            rows += DRAIN_ROWS
            n += 1
            while fused.inq.qsize() > 8:
                time.sleep(0.002)
        topo.wait_idle(timeout=30.0)
        dt = time.time() - t0
        bs = np.array(batch_sizes) if batch_sizes else np.array([0])
        print(f"D topo pipe (flush={flush_rows}, linger={linger_ms}): "
              f"{rows/dt:,.0f} rows/s | fused.process busy {t_proc[0]:.1f}s "
              f"({100*t_proc[0]/dt:.0f}%), src._flush_raw busy "
              f"{t_flush[0]:.1f}s ({100*t_flush[0]/dt:.0f}%) | "
              f"batches n={len(batch_sizes)} "
              f"size p50={np.percentile(bs,50):,.0f} "
              f"p90={np.percentile(bs,90):,.0f} max={bs.max():,}")
    finally:
        topo.close()
        mem.reset()


if __name__ == "__main__":
    drains = make_drains()
    stage_a_decode(drains)
    stage_b_keytable(drains)
    stage_c_fused(drains, 32768)
    stage_c_fused(drains, 8192)
    stage_d_topo(32768, 50)
