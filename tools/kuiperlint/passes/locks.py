"""lock-order — static lock-acquisition graph, fail on cycles.

The ABBA class (PR 6: a mock-clock advance held the timex clock lock
while ticking the health evaluator, which took the StatManager lock —
while scrape threads took them in the opposite order) is mechanically
visible before it deadlocks: build the acquisition-order graph and fail
on any cycle.

Graph construction (conservative — unresolvable expressions are
skipped, never guessed):

* A lock NODE is a `threading.Lock/RLock/Condition/Semaphore` assigned
  to `self.X` (node id `module.Class.X`) or a module-level name
  (`module.X`). `Condition(existing_lock)` aliases to the lock it wraps
  — taking the condition IS taking the lock.
* An ACQUISITION is `with <lock>` (scoped to the with body) or an
  explicit `<lock>.acquire()` statement, held through the following
  statements (including `try:` bodies) until the matching
  `<lock>.release()` — the `acquire(); try: ... finally: release()`
  idiom. Non-blocking tries (`acquire(blocking=False)`) are skipped: a
  failed try-lock cannot deadlock an ABBA square.
* An EDGE A -> B is added when B is acquired while A is held, or when a
  call made while holding A resolves (same-class method, same-module
  function, or imported module function) to a function whose transitive
  acquire set contains B.
* A cycle in the resulting graph means two code paths can take the same
  locks in opposite orders; the report names the cycle and one witness
  site per edge.

The dynamic twin (ekuiper_tpu/utils/lockcheck.py) checks the orders
actually exercised at runtime under tests; this pass covers paths tests
never schedule.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import LintFile, Pass, Report, register

LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}


def _module_id(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Imports:
    """Import resolution with package-relative handling (`from ..utils
    import timex` inside ekuiper_tpu/runtime/x.py -> ekuiper_tpu.utils
    .timex), which the generic ImportMap skips."""

    def __init__(self, tree: ast.AST, module_id: str) -> None:
        self.aliases: Dict[str, str] = {}
        pkg = module_id.split(".")[:-1]
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg[: len(pkg) - (node.level - 1)]
                    mod = ".".join(base + ([node.module] if node.module
                                           else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name)

    def resolve(self, func: ast.AST) -> Optional[str]:
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])


class _FnInfo:
    __slots__ = ("acquires", "calls_under", "calls")

    def __init__(self) -> None:
        self.acquires: Set[str] = set()
        # (held_lock_id, callee_key, path, line)
        self.calls_under: List[Tuple[str, str, str, int]] = []
        self.calls: Set[str] = set()  # every resolvable callee


@register
class LockOrder(Pass):
    name = "lock-order"
    description = ("static `with <lock>` acquisition graph across "
                   "modules must be acyclic (ABBA deadlock class)")
    scope = ("ekuiper_tpu/**",)

    def begin(self) -> None:
        self.locks: Set[str] = set()
        self.cond_alias: Dict[str, str] = {}
        self.fns: Dict[str, _FnInfo] = {}
        # (held, acquired) -> first witness (path, line)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # (path, line) sites carrying a justified lock-order pragma: a
        # cycle is suppressed when ANY of its witness edges is blessed
        # (the report anchors at one arbitrary edge; the user pragmas
        # the edge they can argue about)
        self.pragma_sites: Set[Tuple[str, int]] = set()

    # ------------------------------------------------------------ per file
    def visit(self, f: LintFile, report: Report) -> None:
        mod = _module_id(f.path)
        imports = _Imports(f.tree, mod)
        for plist in f.pragmas.values():
            for pr in plist:
                if self.name in pr.rules and pr.justified:
                    self.pragma_sites.add((f.path, pr.line))
                    if pr.own_line:
                        self.pragma_sites.add((f.path, pr.line + 1))
        self._collect_locks(f.tree, mod, imports)
        for scope_name, fn_node, class_name in _functions(f.tree, mod):
            info = self.fns.setdefault(scope_name, _FnInfo())
            self._walk_fn(fn_node.body, [], info, f, mod, class_name,
                          imports)

    def _collect_locks(self, tree: ast.AST, mod: str,
                       imports: _Imports) -> None:
        for cls_name, target, value in _assignments(tree):
            if not isinstance(value, ast.Call):
                continue
            factory = imports.resolve(value.func)
            if factory not in LOCK_FACTORIES:
                continue
            lock_id = self._target_id(target, mod, cls_name)
            if lock_id is None:
                continue
            self.locks.add(lock_id)
            # Condition(existing_lock): alias to the wrapped lock's node
            if (factory == "threading.Condition" and value.args
                    and isinstance(value.args[0], (ast.Attribute, ast.Name))):
                wrapped = self._expr_lock_id(value.args[0], mod, cls_name)
                if wrapped is not None:
                    self.cond_alias[lock_id] = wrapped

    @staticmethod
    def _target_id(target: ast.AST, mod: str,
                   cls_name: Optional[str]) -> Optional[str]:
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and cls_name):
            return f"{mod}.{cls_name}.{target.attr}"
        if isinstance(target, ast.Name):
            scope = f"{mod}.{cls_name}" if cls_name else mod
            return f"{scope}.{target.id}"
        return None

    def _expr_lock_id(self, expr: ast.AST, mod: str,
                      cls_name: Optional[str]) -> Optional[str]:
        """Resolve a `with <expr>` / Condition(<expr>) operand to a known
        lock node id, chasing condition aliases."""
        cand: Optional[str] = None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            if expr.value.id == "self" and cls_name:
                cand = f"{mod}.{cls_name}.{expr.attr}"
            else:
                # module_alias._lock style: only same-module globals resolve
                cand = None
        elif isinstance(expr, ast.Name):
            for scope in ((f"{mod}.{cls_name}", mod) if cls_name
                          else (mod,)):
                if f"{scope}.{expr.id}" in self.locks:
                    cand = f"{scope}.{expr.id}"
                    break
        if cand is None or cand not in self.locks:
            return None
        seen = set()
        while cand in self.cond_alias and cand not in seen:
            seen.add(cand)
            cand = self.cond_alias[cand]
        return cand

    # --------------------------------------------------------- fn walking
    def _walk_fn(self, body, held: List[str], info: _FnInfo, f: LintFile,
                 mod: str, cls_name: Optional[str],
                 imports: _Imports) -> None:
        # a LOCAL mutable copy: explicit `<lock>.acquire()` statements
        # extend the held set for the REST of this statement sequence
        # (and its nested bodies — the shared list flows into compound
        # statements), `<lock>.release()` retires them; `with` blocks
        # keep their lexical scoping via the copy made per call
        held = list(held)
        for stmt in body:
            self._walk_stmt(stmt, held, info, f, mod, cls_name, imports)

    def _explicit_lock_call(self, node: ast.AST, mod: str,
                            cls_name: Optional[str]):
        """(kind, lock_id, call) for a statement-level explicit
        `<lock>.acquire()` / `<lock>.release()`, else None. Kind is
        'acquire' | 'release'; non-blocking acquires return None."""
        call = None
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Call):
            call = node.value
        if call is None or not isinstance(call.func, ast.Attribute):
            return None
        kind = call.func.attr
        if kind not in ("acquire", "release"):
            return None
        lock_id = self._expr_lock_id(call.func.value, mod, cls_name)
        if lock_id is None:
            return None
        if kind == "acquire":
            for i, a in enumerate(call.args):
                if i == 0 and isinstance(a, ast.Constant) and a.value is False:
                    return None  # non-blocking try-lock
            for kw in call.keywords:
                if (kw.arg == "blocking"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False):
                    return None
        return kind, lock_id, call

    def _walk_stmt(self, node: ast.AST, held: List[str], info: _FnInfo,
                   f: LintFile, mod: str, cls_name: Optional[str],
                   imports: _Imports) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs get their own scope via _functions()
        explicit = self._explicit_lock_call(node, mod, cls_name)
        if explicit is not None:
            kind, lock_id, call = explicit
            # argument expressions run before the acquisition
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                self._scan_calls(a, held, info, f, mod, cls_name, imports)
            if kind == "acquire":
                info.acquires.add(lock_id)
                for h in held:
                    if h != lock_id:
                        self.edges.setdefault((h, lock_id),
                                              (f.path, call.lineno))
                held.append(lock_id)
            elif lock_id in held:
                held.remove(lock_id)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                lock_id = self._expr_lock_id(item.context_expr, mod, cls_name)
                # calls inside the context expr run before acquisition
                self._scan_calls(item.context_expr, held, info, f, mod,
                                 cls_name, imports)
                if lock_id is None:
                    continue
                info.acquires.add(lock_id)
                for h in held + acquired:
                    if h != lock_id:
                        self.edges.setdefault(
                            (h, lock_id), (f.path, item.context_expr.lineno))
                acquired.append(lock_id)
            # the with's OWN acquisitions scope to its body, but an
            # explicit `<lock>.acquire()` INSIDE the body outlives the
            # block — walk the body on a working list, then carry its
            # net effect (minus the with-scoped locks) back out
            inner = held + acquired
            for stmt in node.body:
                self._walk_stmt(stmt, inner, info, f, mod, cls_name,
                                imports)
            for lock_id in acquired:
                if lock_id in inner:
                    inner.remove(lock_id)
            held[:] = inner
            return
        # non-with statement: record calls (with held context), then
        # recurse into compound-statement bodies — including non-stmt
        # containers that carry statement lists (ast.ExceptHandler,
        # ast.match_case): exception paths are exactly where ABBA
        # cleanup acquisitions hide
        for fld in ast.iter_fields(node):
            value = fld[1]
            items = value if isinstance(value, list) else [value]
            for it in items:
                if isinstance(it, ast.stmt):
                    self._walk_stmt(it, held, info, f, mod, cls_name,
                                    imports)
                elif isinstance(it, ast.expr):
                    self._scan_calls(it, held, info, f, mod, cls_name,
                                     imports)
                elif isinstance(it, ast.AST):
                    self._walk_stmt(it, held, info, f, mod, cls_name,
                                    imports)

    def _scan_calls(self, expr: ast.AST, held: List[str], info: _FnInfo,
                    f: LintFile, mod: str, cls_name: Optional[str],
                    imports: _Imports) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            key = self._callee_key(node.func, mod, cls_name, imports)
            if key is None:
                continue
            info.calls.add(key)
            for h in held:
                info.calls_under.append((h, key, f.path, node.lineno))

    @staticmethod
    def _callee_key(func: ast.AST, mod: str, cls_name: Optional[str],
                    imports: _Imports) -> Optional[str]:
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)):
            if func.value.id == "self" and cls_name:
                return f"{mod}.{cls_name}.{func.attr}"
            resolved = imports.resolve(func)
            return resolved
        if isinstance(func, ast.Name):
            resolved = imports.resolve(func)
            if resolved == func.id:
                return f"{mod}.{func.id}"  # same-module function
            return resolved
        return None

    # ------------------------------------------------------------- finalize
    def finalize(self, report: Report) -> None:
        # transitive acquire closure over the (partial) call graph
        eff: Dict[str, Set[str]] = {k: set(v.acquires)
                                    for k, v in self.fns.items()}
        changed = True
        while changed:
            changed = False
            for name, info in self.fns.items():
                for callee in info.calls:
                    extra = eff.get(callee)
                    if extra and not extra <= eff[name]:
                        eff[name] |= extra
                        changed = True
        # call-mediated edges: held A while calling f => A -> eff(f)
        for info in self.fns.values():
            for held, callee, path, line in info.calls_under:
                for acquired in eff.get(callee, ()):
                    if acquired != held:
                        self.edges.setdefault((held, acquired), (path, line))

        cycles = _find_cycles({a: {b for (x, b) in self.edges if x == a}
                               for (a, _b) in self.edges})
        for cycle in cycles:
            if any(self.edges.get((a, b)) in self.pragma_sites
                   for a, b in zip(cycle, cycle[1:])):
                continue  # an edge of this cycle is pragma-blessed
            first_edge = (cycle[0], cycle[1])
            path, line = self.edges.get(first_edge, ("<graph>", 0))
            chain = " -> ".join(cycle)
            witnesses = "; ".join(
                f"{a}->{b} at {self.edges[(a, b)][0]}:{self.edges[(a, b)][1]}"
                for a, b in zip(cycle, cycle[1:])
                if (a, b) in self.edges)
            report.add_at(
                self.name, path, line, 1,
                f"lock-order cycle: {chain} (two paths can take these "
                f"locks in opposite orders; witnesses: {witnesses})")


def _assignments(tree: ast.AST):
    """Yield (enclosing_class_name_or_None, target, value) for every
    simple assignment, walking into classes and functions."""
    def walk(node, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(child, cls_name)
            elif isinstance(child, ast.Assign):
                for t in child.targets:
                    yield (cls_name, t, child.value)
            elif isinstance(child, ast.AnnAssign) and child.value is not None:
                yield (cls_name, child.target, child.value)
            else:
                yield from walk(child, cls_name)
    yield from walk(tree, None)


def _functions(tree: ast.AST, mod: str):
    """Yield (qualname, fn_node, enclosing_class_or_None)."""
    def walk(node, prefix, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}.{child.name}", child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (f"{prefix}.{child.name}", child, cls_name)
                yield from walk(child, f"{prefix}.{child.name}", cls_name)
            else:
                yield from walk(child, prefix, cls_name)
    yield from walk(tree, mod, None)


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Minimal cycle witnesses, one per strongly-connected component
    (Tarjan; SCCs of size 1 without a self-edge are acyclic)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    nodes = set(graph) | {w for vs in graph.values() for w in vs}
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    cycles: List[List[str]] = []
    for comp in sccs:
        comp_set = set(comp)
        # walk inside the SCC until a node repeats -> concrete cycle
        start = sorted(comp)[0]
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = sorted(w for w in graph.get(cur, ())
                         if w in comp_set)
            if not nxt:
                break
            cur = nxt[0]
            if cur in seen:
                path.append(cur)
                cycles.append(path[path.index(cur):])
                break
            seen.add(cur)
            path.append(cur)
    return cycles
