"""jit-coverage — every jit site must ride devwatch's watched_jit.

kernwatch/devwatch attribution (recompile storms, cache hits, roofline,
device-time split) is only exhaustive because EVERY `jax.jit` call goes
through `observability.devwatch.watched_jit`. A bare `jax.jit` site is
invisible to the flight recorder: its recompiles don't count, its
kernels never appear in /diagnostics/kernels, and a compile storm there
bisects to nothing. devwatch.py itself is the one place allowed to call
`jax.jit` (it IS the wrapper).
"""
from __future__ import annotations

import ast

from .. import ImportMap, LintFile, Pass, Report, register

BANNED = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


@register
class JitCoverage(Pass):
    name = "jit-coverage"
    description = ("bare jax.jit outside devwatch.py — wrap with "
                   "observability.devwatch.watched_jit")
    scope = ("ekuiper_tpu/**",)
    allow = ("ekuiper_tpu/observability/devwatch.py",
             # the AOT cache IS a jit wrapper: it owns the lowering seam
             # (jax.jit(...).lower(...).compile()) behind aot_jit, and
             # every site it wraps still registers a devwatch OpWatch
             "ekuiper_tpu/runtime/aotcache.py")

    def visit(self, f: LintFile, report: Report) -> None:
        imports = ImportMap(f.tree)
        for node in ast.walk(f.tree):
            # bare `@jax.jit` decorator: an Attribute/Name in the
            # decorator list, not a Call — the most common jit shape
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if (not isinstance(dec, ast.Call)
                            and imports.resolve_call(dec) in BANNED):
                        report.add(
                            self.name, f, dec,
                            f"bare @{imports.resolve_call(dec)} decorator "
                            "escapes devwatch — use watched_jit(fn, "
                            "op=...) so XLA recompile/kernel attribution "
                            "stays exhaustive")
                continue
            if not isinstance(node, ast.Call):
                continue
            target = imports.resolve_call(node.func)
            flagged = target in BANNED
            if not flagged and target in ("functools.partial", "partial"):
                # functools.partial(jax.jit, ...) is still a bare jit site
                flagged = any(
                    imports.resolve_call(a) in BANNED
                    for a in node.args if isinstance(a, (ast.Attribute,
                                                         ast.Name)))
            if flagged:
                report.add(
                    self.name, f, node,
                    f"bare {target or 'jax.jit'}() escapes devwatch — use "
                    "watched_jit(fn, op=..., **jit_kwargs) so XLA "
                    "recompile/kernel attribution stays exhaustive")
