"""clock-discipline — engine paths must use the mockable timex clock.

A raw `time.time()` / `time.monotonic()` / `time.sleep()` in runtime/,
ops/, planner/ or observability/ silently breaks mock-clock determinism:
tests advance `timex` but the wall clock keeps running, so timing
telemetry (and anything gated on it) diverges between test and prod
(the ops/prefinalize.py:432 class this pass was built from).
`time.perf_counter()` stays legal — it measures durations, never a
point on the engine's timeline.

Plugin IPC and the standalone tools under ekuiper_tpu/tools talk to
real external processes and are allowlisted wholesale.
"""
from __future__ import annotations

import ast

from .. import ImportMap, LintFile, Pass, Report, register

BANNED = {
    "time.time": "timex.now_ms() (engine clock)",
    "time.time_ns": "timex.now_ms() (engine clock)",
    "time.monotonic": "timex.now_ms(), or time.perf_counter() for durations",
    "time.monotonic_ns": "timex.now_ms(), or time.perf_counter() for durations",
    "time.sleep": "timex.sleep() / timex.after() (mock-clock aware)",
}


@register
class ClockDiscipline(Pass):
    name = "clock-discipline"
    description = ("no raw time.time/monotonic/sleep in engine paths — "
                   "use ekuiper_tpu.utils.timex")
    scope = (
        "ekuiper_tpu/runtime/**",
        "ekuiper_tpu/ops/**",
        "ekuiper_tpu/planner/**",
        "ekuiper_tpu/observability/**",
    )
    allow = (
        # plugin IPC handshakes block on real subprocesses
        "ekuiper_tpu/plugin/**",
        # standalone operator tools run outside the engine clock
        "ekuiper_tpu/tools/**",
    )

    def visit(self, f: LintFile, report: Report) -> None:
        imports = ImportMap(f.tree)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            target = imports.resolve_call(node.func)
            if target in BANNED:
                report.add(
                    self.name, f, node,
                    f"wall-clock call {target}() in an engine path — use "
                    f"{BANNED[target]}")
