"""host-sync — no implicit device→host sync in per-batch hot paths.

`float(x[i])`, `.item()`, `np.asarray(dev)`, `jax.device_get`, and
`.block_until_ready()` on a device value stall the dispatch pipeline:
the host blocks until every queued XLA program ahead of it retires, so
one stray `.item()` in a fold/emit path turns the async device feed
back into lock-step (the perf footgun the PR 2 upload pipeline and the
PR 7 kernel split exist to avoid). Boundary paths that are MEANT to
fetch (emit workers, prefinalize threads) carry a pragma naming the
intended sync point.

Scope: functions on the per-batch path — names matching fold/emit/
absorb/combine/deliver/drain/trigger/process in runtime/ and ops/.
"""
from __future__ import annotations

import ast
import re
from typing import Optional

from .. import ImportMap, LintFile, Pass, Report, register

HOT_FN = re.compile(
    r"(^|_)(fold|emit|absorb|combine|deliver|drain|trigger|process)")

SYNC_CALLS = {
    "numpy.asarray": "np.asarray on a device value blocks on the fetch",
    "numpy.array": "np.array on a device value blocks on the fetch",
    "jax.device_get": "device_get blocks on the transfer",
}
SYNC_METHODS = {
    "item": ".item() forces a device->host scalar sync",
    "block_until_ready": "block_until_ready stalls the dispatch pipeline",
    "copy_to_host": "synchronous host copy",
}


@register
class HostSync(Pass):
    name = "host-sync"
    description = ("no implicit device sync (float()/.item()/np.asarray/"
                   "block_until_ready) in per-batch fold/emit paths")
    scope = ("ekuiper_tpu/runtime/**", "ekuiper_tpu/ops/**")

    def visit(self, f: LintFile, report: Report) -> None:
        imports = ImportMap(f.tree)
        for fn in ast.walk(f.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not HOT_FN.search(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node, imports)
                if msg:
                    report.add(self.name, f, node,
                               f"{msg} inside hot path {fn.name}() — move "
                               "to a boundary/worker thread or pragma the "
                               "intended sync point")

    @staticmethod
    def _classify(node: ast.Call, imports: ImportMap) -> Optional[str]:
        target = imports.resolve_call(node.func)
        if target in SYNC_CALLS:
            return SYNC_CALLS[target]
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
                # np.asarray(...).item() style or obj.item() — both count;
                # module-attr functions (time.sleep) resolved above already
                and target not in SYNC_CALLS):
            return SYNC_METHODS[node.func.attr]
        # float(x[i]) on a subscript: the classic one-scalar implicit
        # sync; float(name)/float(literal) stay legal (host math), and
        # int(x[i]) is not flagged — the tree's int() subscripts are
        # overwhelmingly host-side numpy index math (np.nonzero results)
        if (isinstance(node.func, ast.Name)
                and node.func.id == "float" and node.args
                and isinstance(node.args[0], ast.Subscript)):
            return ("float() over a subscripted array forces a "
                    "per-element device sync")
        return None
