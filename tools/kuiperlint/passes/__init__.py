"""Pass modules — importing this package registers every pass."""
from . import clock  # noqa: F401
from . import donation  # noqa: F401
from . import hostsync  # noqa: F401
from . import jit  # noqa: F401
from . import jitcert  # noqa: F401
from . import locks  # noqa: F401
from . import metric_hygiene  # noqa: F401
