"""donation-safety — a donated buffer may not be read after the call.

`donate_argnums` hands the argument's device buffer to XLA for reuse;
on donation-honoring backends the original array is DELETED the moment
the call dispatches. Reading it afterwards raises (TPU) or silently
reads stale memory — and on CPU, which ignores donation, the bug stays
invisible until the first TPU run (PR 7's `_block_marker` class).

The pass tracks names bound to `watched_jit(..., donate_argnums=...)` /
`jax.jit(..., donate_argnums=...)` (locals and `self._fold`-style
attributes), and inside each function flags any read of a donated
argument (a plain name or a `self.X` attribute) AFTER the jitted call,
unless the name was reassigned first — `state = fold(state, ...)` is
the blessed shape.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import ImportMap, LintFile, Pass, Report, register

JIT_WRAPPERS = ("watched_jit", "jax.jit",
                "ekuiper_tpu.observability.devwatch.watched_jit",
                "aot_jit",
                "ekuiper_tpu.runtime.aotcache.aot_jit")


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()  # dynamic spec: positions unknown -> don't guess
    return None


def _expr_key(node: ast.AST) -> Optional[str]:
    """Stable key for trackable value expressions: bare names ("state")
    and self attributes ("self.state")."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return None


@register
class DonationSafety(Pass):
    name = "donation-safety"
    description = ("an argument donated via donate_argnums may not be "
                   "read after the jitted call in the same scope")
    scope = ("ekuiper_tpu/**",)

    def visit(self, f: LintFile, report: Report) -> None:
        imports = ImportMap(f.tree)
        # 1) collect donated callables: "self._fold"/"fold" -> positions
        donated: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call):
                continue
            target_fn = imports.resolve_call(node.value.func)
            if target_fn not in JIT_WRAPPERS:
                continue
            pos = _donated_positions(node.value)
            if not pos:
                continue
            for t in node.targets:
                key = _expr_key(t)
                if key:
                    donated[key] = pos
        if not donated:
            return
        # 2) per function: linear read-after-donation scan
        for fn in ast.walk(f.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(fn, donated, f, report)

    def _scan_fn(self, fn: ast.AST, donated: Dict[str, Tuple[int, ...]],
                 f: LintFile, report: Report) -> None:
        # Events ordered by EXECUTION position, not lexical position:
        #  * a donation takes effect at the END of the jitted call (arg
        #    reads inside the call itself are the donation, not a bug)
        #  * an assignment's store lands at the END of the statement
        #    (`state = fold(state)` stores after the call dispatches)
        events: List[Tuple[Tuple[int, int], int, str, str, ast.AST]] = []
        # kind priority breaks position ties: load < donate < store
        PRIO = {"load": 0, "donate": 1, "store": 2}

        def add(pos, kind, key, node):
            events.append((pos, PRIO[kind], kind, key, node))

        def end(node):
            return (node.end_lineno or node.lineno,
                    node.end_col_offset or node.col_offset)

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _expr_key(node.func)
                if callee in donated:
                    for i in donated[callee]:
                        if i < len(node.args):
                            key = _expr_key(node.args[i])
                            if key:
                                add(end(node), "donate", key, node)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for sub in ast.walk(t):
                        key = _expr_key(sub)
                        if key and isinstance(getattr(sub, "ctx", None),
                                              ast.Store):
                            add(end(node), "store", key, sub)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(node.target):
                    key = _expr_key(sub)
                    if key:
                        add((node.lineno, node.col_offset), "store", key,
                            sub)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        for sub in ast.walk(item.optional_vars):
                            key = _expr_key(sub)
                            if key:
                                add((node.lineno, node.col_offset),
                                    "store", key, sub)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    key = _expr_key(t)
                    if key:
                        add(end(node), "store", key, t)
            key = _expr_key(node)
            if key is not None and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                add((node.lineno, node.col_offset), "load", key, node)
        events.sort(key=lambda e: (e[0], e[1]))
        events = [(pos, kind, key, node)
                  for pos, _prio, kind, key, node in events]
        dead: Dict[str, Tuple[int, int]] = {}  # key -> donation site
        for pos, kind, key, node in events:
            if kind == "donate":
                dead[key] = pos
            elif kind == "store":
                dead.pop(key, None)
            elif kind == "load" and key in dead and pos > dead[key]:
                dline, _ = dead[key]
                report.add(
                    self.name, f, node,
                    f"{key} was donated to a jitted call at line {dline} "
                    "and read again — the device buffer is deleted on "
                    "donation-honoring backends (rebind the result or "
                    "snapshot a copy before the call)")
                dead.pop(key)  # one report per donation
