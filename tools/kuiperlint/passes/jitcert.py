"""jitcert static passes — compile-contract enforcement at lint time.

Two rules close the loop that ekuiper_tpu/observability/jitcert.py opens:

* **cert-coverage** — every `watched_jit` site in ops/ and parallel/
  must resolve (statically) to an op name with a registered certificate
  derivation (`jitcert.SITE_DERIVATIONS`). A jit site nobody can derive
  a closed signature set for is exactly the site whose recompile storm
  devwatch will one day flag at runtime — fail it at lint time instead.
  Op names resolve from the `op=` keyword: a string literal, or
  `self._watch_op("<suffix>")` combined with the enclosing class's (or a
  same-file base class's) literal `watch_prefix`.

* **sig-stability** — signature-unstable idioms inside jit-traced bodies
  (the functions handed to watched_jit, plus same-file helpers they pass
  traced values into):
    - branching (`if`/`while`/ternary/`assert`) on a traced value —
      trace-time control flow silently specializes one executable per
      branch outcome. Structure tests (`x is None`), shape reads
      (`x.shape/.ndim/.dtype`, `getattr(x, "ndim", ...)`, `len(x)`,
      `isinstance(x, ...)`) are static under tracing and stay legal.
    - `len(...)`-derived slicing inside a jit body — `arr[:len(rows)]`
      compiles one executable per batch length; pad to the declared
      micro-batch bucket instead (runtime/ingest.py builders).
    - Python-scalar closure capture: a jit body capturing an enclosing
      function's loop variable or literal-scalar local bakes the value
      at trace time (stale after rebind; one executable per distinct
      value when it feeds shapes). Capturing plan-time config objects /
      enclosing parameters is the normal factory idiom and stays legal.

Taint propagation is positional and same-file only (conservative): the
entry body's parameters are traced; a call `self._helper(a, b)` taints
the helper's parameters that receive tainted arguments; functions passed
to `jax.vmap` / `shard_map` / `jax.lax.*` combinators are traced with
every parameter tainted.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import ImportMap, LintFile, Pass, Report, register

#: attribute/getattr reads that are static under tracing
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
#: calls whose result on a traced value is static under tracing
_STATIC_CALLS = {"len", "isinstance", "getattr", "type", "sorted", "list",
                 "range", "enumerate"}
#: combinators whose function argument is traced (all params tainted)
_TRACED_COMBINATORS = {"jax.vmap", "vmap", "shard_map", "jax.lax.scan",
                       "jax.lax.map", "jax.checkpoint", "functools.partial"}


def _site_scope() -> Tuple[str, ...]:
    return ("ekuiper_tpu/ops/**", "ekuiper_tpu/parallel/**")


class _ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.watch_prefix: Optional[str] = None
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        self.methods: Dict[str, ast.FunctionDef] = {}
        for child in node.body:
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    if (isinstance(t, ast.Name) and t.id == "watch_prefix"
                            and isinstance(child.value, ast.Constant)
                            and isinstance(child.value.value, str)):
                        self.watch_prefix = child.value.value
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child


def _classes(tree: ast.AST) -> Dict[str, _ClassInfo]:
    return {n.name: _ClassInfo(n)
            for n in ast.walk(tree) if isinstance(n, ast.ClassDef)}


def _resolve_prefix(cls: Optional[_ClassInfo],
                    classes: Dict[str, _ClassInfo]) -> Optional[str]:
    """watch_prefix of a class, chasing same-file bases (ShardedGroupBy
    overrides DeviceGroupBy's; BatchedGroupBy too)."""
    seen: Set[str] = set()
    while cls is not None:
        if cls.watch_prefix is not None:
            return cls.watch_prefix
        nxt = None
        for b in cls.bases:
            if b in classes and b not in seen:
                seen.add(b)
                nxt = classes[b]
                break
        cls = nxt
    return None


def _watched_jit_calls(tree: ast.AST, imports: ImportMap):
    """Yield (call_node, enclosing_class_name) for every watched_jit()
    or aot_jit() site (runtime/aotcache.py — same contract, AOT-cached
    dispatch)."""
    def walk(node, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
                continue
            if isinstance(child, ast.Call):
                target = imports.resolve_call(child.func)
                if target is not None and (
                        target == "watched_jit"
                        or target.endswith(".watched_jit")
                        or target == "aot_jit"
                        or target.endswith(".aot_jit")):
                    yield_list.append((child, cls_name))
            walk(child, cls_name)

    yield_list: List[Tuple[ast.Call, Optional[str]]] = []
    walk(tree, None)
    return yield_list


@register
class CertCoverage(Pass):
    name = "cert-coverage"
    description = ("every watched_jit site in ops//parallel/ must have a "
                   "jitcert certificate derivation")
    scope = _site_scope()

    def visit(self, f: LintFile, report: Report) -> None:
        try:
            from ekuiper_tpu.observability.jitcert import SITE_DERIVATIONS
        except Exception as exc:  # pragma: no cover - import env issue
            report.add_at(self.name, f.path, 1, 1,
                          f"cannot import jitcert derivations: {exc}")
            return
        imports = ImportMap(f.tree)
        classes = _classes(f.tree)
        for call, cls_name in _watched_jit_calls(f.tree, imports):
            op = self._op_name(call, cls_name, classes)
            if op is None:
                report.add(
                    self.name, f, call,
                    "watched_jit site's op name is not statically "
                    "resolvable — use a string literal or "
                    'self._watch_op("<suffix>") with a literal '
                    "watch_prefix so jitcert can bind a certificate")
                continue
            if isinstance(op, tuple):  # suffix with unresolved prefix
                suffix = op[1]
                if any(k.endswith(f".{suffix}")
                       for k in SITE_DERIVATIONS):
                    continue
                report.add(
                    self.name, f, call,
                    f"no jitcert derivation matches *.{suffix} — "
                    "register one in ekuiper_tpu/observability/"
                    "jitcert.py SITE_DERIVATIONS")
                continue
            if op not in SITE_DERIVATIONS:
                report.add(
                    self.name, f, call,
                    f"watched_jit site {op!r} has no certificate "
                    "derivation — register one in ekuiper_tpu/"
                    "observability/jitcert.py SITE_DERIVATIONS "
                    "(docs/STATIC_ANALYSIS.md § certifying a new site)")

    @staticmethod
    def _op_name(call: ast.Call, cls_name: Optional[str],
                 classes: Dict[str, _ClassInfo]):
        """The site's op: a str (fully resolved), (None, suffix) when
        only the suffix resolved, or None (unresolvable)."""
        op_kw = None
        for kw in call.keywords:
            if kw.arg == "op":
                op_kw = kw.value
        if op_kw is None and len(call.args) >= 2:
            op_kw = call.args[1]
        if op_kw is None:
            return None
        if isinstance(op_kw, ast.Constant) and isinstance(op_kw.value, str):
            return op_kw.value
        # self._watch_op("suffix") -> watch_prefix + "." + suffix
        if (isinstance(op_kw, ast.Call)
                and isinstance(op_kw.func, ast.Attribute)
                and op_kw.func.attr == "_watch_op"
                and op_kw.args
                and isinstance(op_kw.args[0], ast.Constant)
                and isinstance(op_kw.args[0].value, str)):
            suffix = op_kw.args[0].value
            prefix = _resolve_prefix(classes.get(cls_name or ""), classes)
            if prefix is not None:
                return f"{prefix}.{suffix}"
            return (None, suffix)
        return None


# ------------------------------------------------------------ sig-stability
class _FnAnalysis:
    __slots__ = ("fn", "cls_name", "tainted", "encl")

    def __init__(self, fn, cls_name, tainted, encl) -> None:
        self.fn = fn
        self.cls_name = cls_name
        self.tainted: Set[str] = tainted
        self.encl = encl  # enclosing FunctionDef for closures, or None


@register
class SigStability(Pass):
    name = "sig-stability"
    description = ("signature-unstable idioms inside jit-traced bodies "
                   "(traced-value branching, len()-derived slicing, "
                   "scalar closure capture)")
    scope = _site_scope()

    def visit(self, f: LintFile, report: Report) -> None:
        imports = ImportMap(f.tree)
        classes = _classes(f.tree)
        self._tree = f.tree
        # enclosing-function map for every FunctionDef/Lambda
        encl: Dict[ast.AST, Optional[ast.AST]] = {}
        self._map_enclosing(f.tree, None, encl)
        entries = self._entry_bodies(f.tree, imports, classes, encl)
        analyzed: Set[Tuple[int, frozenset]] = set()
        queue = list(entries)
        while queue:
            an = queue.pop()
            key = (id(an.fn), frozenset(an.tainted))
            if key in analyzed:
                continue
            analyzed.add(key)
            self._check_body(an, f, report, imports)
            queue.extend(self._expand_calls(an, classes, imports, encl))

    # ------------------------------------------------------- entry finding
    def _entry_bodies(self, tree, imports, classes, encl):
        out: List[_FnAnalysis] = []
        for call, cls_name in _watched_jit_calls(tree, imports):
            if not call.args:
                continue
            fn = self._resolve_fn(call.args[0], cls_name, classes, call,
                                  encl)
            if fn is None:
                continue
            params = self._params(fn)
            out.append(_FnAnalysis(fn, cls_name, set(params),
                                   encl.get(fn)))
        return out

    @staticmethod
    def _params(fn) -> List[str]:
        if isinstance(fn, ast.Lambda):
            args = fn.args
        else:
            args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args
                 + args.kwonlyargs]
        return [n for n in names if n != "self"]

    def _resolve_fn(self, expr, cls_name, classes, call, encl):
        """First arg of watched_jit -> a FunctionDef/Lambda in this file:
        self._x_impl (method), bare name (local def), or inline lambda."""
        if isinstance(expr, ast.Lambda):
            return expr
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls_name):
            cls = classes.get(cls_name)
            seen: Set[str] = set()
            while cls is not None:
                m = cls.methods.get(expr.attr)
                if m is not None:
                    return m
                nxt = None
                for b in cls.bases:
                    if b in classes and b not in seen:
                        seen.add(b)
                        nxt = classes[b]
                        break
                cls = nxt
            return None
        if isinstance(expr, ast.Name):
            # nearest enclosing scope holding a def of that name, then
            # the module's top level (module-scope jit sites)
            scope = encl.get(call)
            while scope is not None:
                for n in ast.walk(scope):
                    if (isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                            and n.name == expr.id):
                        return n
                scope = encl.get(scope)
            for n in ast.walk(getattr(self, "_tree", ast.Module(body=[],
                                                                type_ignores=[]))):
                if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n.name == expr.id):
                    return n
        return None

    def _map_enclosing(self, node, current, encl):
        for child in ast.iter_child_nodes(node):
            encl[child] = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                self._map_enclosing(child, child, encl)
            else:
                self._map_enclosing(child, current, encl)

    # ----------------------------------------------------------- expansion
    def _expand_calls(self, an, classes, imports, encl):
        """Same-file helpers receiving tainted values become analysis
        targets with positionally-tainted params; functions handed to
        vmap/shard_map trace with every param tainted."""
        out: List[_FnAnalysis] = []
        body = (an.fn.body if isinstance(an.fn.body, list)
                else [an.fn.body])
        for node in [n for stmt in body for n in ast.walk(stmt)]:
            if not isinstance(node, ast.Call):
                continue
            target = imports.resolve_call(node.func)
            if target in _TRACED_COMBINATORS or (
                    target is not None
                    and target.startswith("jax.lax.")):
                for arg in node.args:
                    fn = self._resolve_fn(arg, an.cls_name, classes,
                                          node, encl)
                    if fn is not None:
                        out.append(_FnAnalysis(
                            fn, an.cls_name, set(self._params(fn)),
                            encl.get(fn)))
                continue
            fn = None
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self" and an.cls_name):
                fn = self._resolve_fn(node.func, an.cls_name, classes,
                                      node, encl)
            elif isinstance(node.func, ast.Name):
                fn = self._resolve_fn(node.func, an.cls_name, classes,
                                      node, encl)
            if fn is None or fn is an.fn:
                continue
            params = self._params(fn)
            tainted: Set[str] = set()
            for i, arg in enumerate(node.args):
                if i < len(params) and self._is_tainted(arg, an.tainted):
                    tainted.add(params[i])
            for kw in node.keywords:
                if kw.arg in params and self._is_tainted(kw.value,
                                                         an.tainted):
                    tainted.add(kw.arg)
            if tainted:
                out.append(_FnAnalysis(fn, an.cls_name, tainted,
                                       encl.get(fn)))
        return out

    @staticmethod
    def _is_tainted(expr, tainted: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(expr))

    # -------------------------------------------------------------- checks
    def _check_body(self, an, f: LintFile, report: Report,
                    imports: ImportMap) -> None:
        fn = an.fn
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        # local taint: names assigned FROM tainted expressions inside the
        # body stay untracked (conservative: direct param uses only),
        # EXCEPT len()-derived names, which feed the slicing check
        len_names: Set[str] = set()
        for stmt in [n for s in body for n in ast.walk(s)]:
            if (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and imports.resolve_call(stmt.value.func) == "len"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        len_names.add(t.id)
        for node in [n for s in body for n in ast.walk(s)]:
            if isinstance(node, (ast.If, ast.While)):
                self._check_test(node.test, an, f, report)
            elif isinstance(node, ast.IfExp):
                self._check_test(node.test, an, f, report)
            elif isinstance(node, ast.Assert):
                self._check_test(node.test, an, f, report)
            elif isinstance(node, ast.Subscript):
                self._check_slice(node, an, f, report, imports,
                                  len_names)
        if an.encl is not None:
            self._check_closure(an, f, report)

    def _check_test(self, test, an, f, report) -> None:
        for name in self._unstable_names(test, an.tainted):
            report.add(
                self.name, f, test,
                f"jit body branches on traced value {name!r} — "
                "trace-time control flow compiles one executable per "
                "outcome (shape/structure tests are legal; use "
                "jnp.where/lax.cond for value-dependent paths)")
            return  # one finding per test

    @classmethod
    def _unstable_names(cls, test, tainted: Set[str]) -> List[str]:
        """Tainted Names in `test` that are not wrapped in a
        static-under-tracing form."""
        allowed: Set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
                for sub in ast.walk(node):
                    allowed.add(id(sub))
            elif isinstance(node, ast.Call):
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                if fname in _STATIC_CALLS:
                    for sub in ast.walk(node):
                        allowed.add(id(sub))
            elif isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
                for sub in ast.walk(node):
                    allowed.add(id(sub))
            elif isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops) and all(
                    isinstance(c, ast.Name) for c in node.comparators
                    ) and (
                    (isinstance(node.left, ast.Constant)
                     and isinstance(node.left.value, str))
                    or (isinstance(node.left, ast.Name)
                        and node.left.id not in tainted)):
                # `"key" in state` on a traced pytree tests STRUCTURE
                # (dict membership), which is static under tracing —
                # same class as `x is None`. Exempt only the narrow
                # form: string-constant or untainted-name KEY against a
                # bare-Name container (the groupby/tierstore state-dict
                # idiom). `traced_val in x`, `3 in traced_array`, and
                # membership on attribute/subscript containers all stay
                # flagged. Known residual: `i in traced_arr` with an
                # untainted scalar `i` and a bare-Name array passes —
                # no static signal separates a dict param from an array
                # param here.
                for sub in ast.walk(node):
                    allowed.add(id(sub))
        return [n.id for n in ast.walk(test)
                if isinstance(n, ast.Name) and n.id in tainted
                and id(n) not in allowed]

    def _check_slice(self, node: ast.Subscript, an, f, report,
                     imports, len_names: Set[str]) -> None:
        sl = node.slice
        bad = False
        for sub in ast.walk(sl):
            if (isinstance(sub, ast.Call)
                    and imports.resolve_call(sub.func) == "len"):
                bad = True
            elif isinstance(sub, ast.Name) and sub.id in len_names:
                bad = True
        if bad and self._is_tainted(node.value, an.tainted):
            report.add(
                self.name, f, node,
                "len()-derived slice of a traced value inside a jit "
                "body — one executable per batch length; pad to the "
                "declared micro-batch bucket instead "
                "(runtime/ingest.py pad_col_for_device)")

    def _check_closure(self, an, f, report) -> None:
        """Flag captures of enclosing-scope loop variables / literal
        scalars (baked at trace time)."""
        encl = an.encl
        local_binds: Set[str] = set(self._params(an.fn))
        body = (an.fn.body if isinstance(an.fn.body, list)
                else [an.fn.body])
        for node in [n for s in body for n in ast.walk(s)]:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.For)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            local_binds.add(sub.id)
        # suspicious enclosing bindings: loop targets + literal scalars,
        # collected from the enclosing function's OWN scope only — a
        # sibling nested function's loop variables/locals are a
        # different scope and must not poison this body's capture check
        # (ast.walk cannot prune, so walk with an explicit stack)
        suspicious: Dict[str, str] = {}
        stack = list(ast.iter_child_nodes(encl))
        own_scope: List[ast.AST] = []
        while stack:
            node = stack.pop()
            own_scope.append(node)
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))
        for node in own_scope:
            if isinstance(node, ast.For):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        suspicious[sub.id] = "loop variable"
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, (int, float, str, bool)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        suspicious[t.id] = "literal scalar"
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                suspicious[node.target.id] = "mutated scalar"
        for node in [n for s in body for n in ast.walk(s)]:
            if (isinstance(node, ast.Name) and node.id in suspicious
                    and node.id not in local_binds
                    and node.id not in an.tainted):
                report.add(
                    self.name, f, node,
                    f"jit body captures enclosing {suspicious[node.id]} "
                    f"{node.id!r} — the value bakes into the trace "
                    "(stale after rebind, re-specializes per value); "
                    "pass it as a kernel argument or bind it via a "
                    "default/functools.partial at definition time")
                return
