"""metric-hygiene — every kuiper_* literal must map to a documented family.

The static half of tools/check_metrics.py (which renders a synthetic
scrape and diffs it against docs/OBSERVABILITY.md at runtime): here the
SOURCE is swept instead, so a metric family added to an exporter but
not to the catalog fails even if no code path in the synthetic scrape
renders it yet. Dynamic family names built as f-strings
(`f"kuiper_op_{name}"`) are checked by prefix — some documented family
must extend the literal fragment.

Scope: ekuiper_tpu/observability/ — the only layer allowed to mint
metric families.
"""
from __future__ import annotations

import ast
import re
from typing import Set

from .. import LintFile, Pass, Report, register

FRAGMENT_RE = re.compile(r"kuiper_[a-z0-9_]*")
SERIES_SUFFIXES = ("_bucket", "_sum", "_count")


def _documented() -> Set[str]:
    # single source of truth shared with the runtime exposition lint
    import sys

    from .. import REPO_ROOT

    repo = str(REPO_ROOT)
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.check_metrics import documented_families

    return documented_families()


@register
class MetricHygiene(Pass):
    name = "metric-hygiene"
    description = ("every kuiper_* metric literal in the observability "
                   "layer must match a family documented in "
                   "docs/OBSERVABILITY.md")
    scope = ("ekuiper_tpu/observability/**",)

    def begin(self) -> None:
        self._docs: Set[str] = set()
        self._loaded = False

    def _families(self) -> Set[str]:
        if not self._loaded:
            self._docs = _documented()
            self._loaded = True
        return self._docs

    def visit(self, f: LintFile, report: Report) -> None:
        docs = self._families()
        if not docs:
            return  # no catalog (fixture trees): nothing to diff against
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for frag in FRAGMENT_RE.findall(node.value):
                if not self._fragment_ok(frag, node.value, docs):
                    report.add(
                        self.name, f, node,
                        f"metric literal {frag!r} has no documented "
                        "family in docs/OBSERVABILITY.md — document it "
                        "(and cover it in tools/check_metrics.py's "
                        "synthetic scrape) before shipping")

    @staticmethod
    def _fragment_ok(frag: str, whole: str, docs: Set[str]) -> bool:
        if frag in docs:
            return True
        # histogram series names roll up to their family
        for suf in SERIES_SUFFIXES:
            if frag.endswith(suf) and frag[: -len(suf)] in docs:
                return True
        # dynamic prefix (f"kuiper_op_{name}" -> fragment "kuiper_op_"):
        # legal when at least one documented family extends it
        if frag.endswith("_") and any(d.startswith(frag) for d in docs):
            return True
        return False
