"""CLI: `python -m tools.kuiperlint [paths...]` — exit 0 clean, 1 on
violations, 2 on usage/internal error (mirrors tools/check_metrics.py's
loud-failure contract so the tier-1 suite can gate on it)."""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import REPO_ROOT, all_passes, render_human, render_json, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.kuiperlint",
        description="repo-native invariant lint (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: ekuiper_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of passes to run")
    ap.add_argument("--root", default=None,
                    help="scope anchor directory (default: repo root)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the pass catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, p in sorted(all_passes().items()):
            print(f"{name:18s} {p.description}")
        return 0

    paths = args.paths or ["ekuiper_tpu"]
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    root = Path(args.root).resolve() if args.root else REPO_ROOT
    try:
        violations, n_files = run(paths, root=root, rules=rules)
    except ValueError as exc:
        print(f"kuiperlint: {exc}", file=sys.stderr)
        return 2
    if n_files == 0:
        print(f"kuiperlint: no python files under {' '.join(paths)}",
              file=sys.stderr)
        return 2
    print(render_json(violations, n_files) if args.as_json
          else render_human(violations, n_files))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
