"""kuiperlint — repo-native invariant-enforcing static analysis.

The engine's correctness contract (mockable clock discipline, exhaustive
jit attribution, lock ordering, no implicit device sync in hot paths,
donated-buffer hygiene, documented metrics) lives here as mechanical
AST passes instead of in reviewer memory — the TiLT argument applied to
tooling: invariants the codebase has already paid for once are checked
by the compiler layer forever after.

Usage (from the repo root):

    python -m tools.kuiperlint ekuiper_tpu/            # human output
    python -m tools.kuiperlint --json ekuiper_tpu/     # machine output
    python -m tools.kuiperlint --rules clock-discipline,lock-order src/

Suppression is per-line via an inline pragma that MUST carry a
justification (an unjustified pragma is itself a violation):

    t0 = time.monotonic()  # kuiperlint: ignore[clock-discipline]: real-thread deadline, not engine time

A pragma comment on its own line suppresses the next source line.
Rule catalog and how to add a pass: docs/STATIC_ANALYSIS.md.
"""
from __future__ import annotations

import ast
import fnmatch
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

#: pragma grammar — `# kuiperlint: ignore[rule1,rule2]: justification`
PRAGMA_RE = re.compile(
    r"#\s*kuiperlint:\s*ignore\[(?P<rules>[a-z0-9_,\-\s]*)\]"
    r"(?::\s*(?P<why>.*))?\s*$")

PRAGMA_RULE = "pragma-hygiene"  # violations about pragmas themselves


@dataclass
class Violation:
    rule: str
    path: str  # repo-root-relative posix path
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


@dataclass
class Pragma:
    line: int          # line the pragma comment sits on
    rules: Tuple[str, ...]
    justified: bool
    own_line: bool     # comment-only line -> also covers the next line


class LintFile:
    """One parsed source file handed to every pass."""

    def __init__(self, abspath: Path, relpath: str, source: str,
                 tree: ast.AST) -> None:
        self.abspath = abspath
        self.path = relpath  # posix, relative to the lint root
        self.source = source
        self.tree = tree
        self.pragmas: Dict[int, List[Pragma]] = {}
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.start[1], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError, IndentationError):
            comments = []
        # a comment is "own-line" when nothing but whitespace precedes it
        lines = self.source.splitlines()
        for lineno, col, text in comments:
            m = PRAGMA_RE.search(text)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            why = (m.group("why") or "").strip()
            own = lines[lineno - 1][:col].strip() == ""
            self.pragmas.setdefault(lineno, []).append(
                Pragma(lineno, rules, bool(why), own))

    def suppressed(self, rule: str, line: int) -> bool:
        """A justified pragma on the same line, or an own-line pragma on
        the line directly above, suppresses `rule` at `line`."""
        for p in self.pragmas.get(line, []):
            if rule in p.rules and p.justified:
                return True
        for p in self.pragmas.get(line - 1, []):
            if p.own_line and rule in p.rules and p.justified:
                return True
        return False


class Report:
    """Violation sink shared by all passes during one run."""

    def __init__(self) -> None:
        self.violations: List[Violation] = []
        self.files_seen = 0

    def add(self, rule: str, f: "LintFile", node, message: str) -> None:
        line = getattr(node, "lineno", 0) or 0
        col = (getattr(node, "col_offset", 0) or 0) + 1
        self.violations.append(Violation(rule, f.path, line, col, message))

    def add_at(self, rule: str, path: str, line: int, col: int,
               message: str) -> None:
        self.violations.append(Violation(rule, path, line, col, message))


class Pass:
    """Base class. Subclasses set `name`/`description`/`scope` and
    implement visit() (per file) and optionally finalize() (cross-file,
    after every file was visited — for graph passes)."""

    name: str = ""
    description: str = ""
    #: fnmatch globs (lint-root-relative posix paths) the pass applies to
    scope: Tuple[str, ...] = ("**",)
    #: globs exempted even when inside scope (per-path allowlist)
    allow: Tuple[str, ...] = ()

    def applies(self, relpath: str) -> bool:
        if not any(_match(relpath, g) for g in self.scope):
            return False
        return not any(_match(relpath, g) for g in self.allow)

    def begin(self) -> None:
        """Reset cross-file state (a registry pass instance is reused
        across runs in-process, e.g. from tests)."""

    def visit(self, f: LintFile, report: Report) -> None:
        raise NotImplementedError

    def finalize(self, report: Report) -> None:
        pass


def _match(relpath: str, glob: str) -> bool:
    if fnmatch.fnmatch(relpath, glob):
        return True
    # "pkg/sub/**" should also match files directly under deeper dirs the
    # way shell globstar does; fnmatch treats ** like * (no /), so try a
    # prefix interpretation too
    if glob.endswith("/**") and relpath.startswith(glob[:-2]):
        return True
    return False


_REGISTRY: Dict[str, Pass] = {}


def register(cls):
    """Class decorator: instantiate and add to the pass registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate kuiperlint pass {inst.name!r}")
    _REGISTRY[inst.name] = inst
    return cls


def all_passes() -> Dict[str, Pass]:
    from . import passes  # noqa: F401  (imports register every pass)

    return dict(_REGISTRY)


# --------------------------------------------------------------- import maps
class ImportMap:
    """Best-effort alias resolution: maps local names to dotted origins
    so `import time as _time; _time.sleep(...)` resolves to `time.sleep`
    and `from jax import jit; jit(...)` resolves to `jax.jit`."""

    def __init__(self, tree: ast.AST) -> None:
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.names:
                mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name)

    def resolve_call(self, func: ast.AST) -> Optional[str]:
        """Dotted name of a call target with the FIRST segment resolved
        through the import aliases; None for unresolvable shapes."""
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])


# ------------------------------------------------------------------ running
def collect_files(paths: Sequence[str], root: Path) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        pth = Path(p)
        if not pth.is_absolute():
            pth = root / pth
        if pth.is_dir():
            out.extend(sorted(
                f for f in pth.rglob("*.py")
                if "__pycache__" not in f.parts and ".git" not in f.parts))
        elif pth.suffix == ".py":
            out.append(pth)
    return out


def run(paths: Sequence[str], root: Optional[Path] = None,
        rules: Optional[Iterable[str]] = None) -> Tuple[List[Violation], int]:
    """Lint `paths` (files or directories). Returns (violations, n_files).

    `root` anchors pass scoping (pass scopes are root-relative globs) and
    defaults to the repo root; tests point it at fixture trees.
    """
    root = (root or REPO_ROOT).resolve()
    registry = all_passes()
    if rules is not None:
        want = set(rules)
        unknown = want - set(registry)
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        registry = {k: v for k, v in registry.items() if k in want}
    for p in registry.values():
        p.begin()

    report = Report()
    files: List[LintFile] = []
    for abspath in collect_files(paths, root):
        try:
            rel = abspath.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = abspath.as_posix()
        try:
            source = abspath.read_text()
            tree = ast.parse(source, filename=str(abspath))
        except (OSError, SyntaxError) as exc:
            report.files_seen += 1  # seen, just not analyzable
            report.add_at(PRAGMA_RULE, rel, getattr(exc, "lineno", 0) or 0, 1,
                          f"unparseable file: {exc}")
            continue
        f = LintFile(abspath, rel, source, tree)
        files.append(f)
        report.files_seen += 1
        # pragma hygiene is checked here so it runs even with --rules
        for plist in f.pragmas.values():
            for pr in plist:
                if not pr.rules:
                    report.add_at(PRAGMA_RULE, rel, pr.line, 1,
                                  "pragma names no rule: ignore[<rule>]")
                for r in pr.rules:
                    if r not in all_passes() and r != PRAGMA_RULE:
                        report.add_at(PRAGMA_RULE, rel, pr.line, 1,
                                      f"pragma names unknown rule {r!r}")
                if not pr.justified:
                    report.add_at(
                        PRAGMA_RULE, rel, pr.line, 1,
                        "suppression without justification — write "
                        "`# kuiperlint: ignore[rule]: <why>`")
        for p in registry.values():
            if p.applies(rel):
                p.visit(f, report)
    for p in registry.values():
        p.finalize(report)

    by_path = {f.path: f for f in files}
    kept = [v for v in report.violations
            if v.rule == PRAGMA_RULE
            or v.path not in by_path
            or not by_path[v.path].suppressed(v.rule, v.line)]
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return kept, report.files_seen


def render_human(violations: List[Violation], n_files: int) -> str:
    lines = [v.format() for v in violations]
    lines.append(
        f"kuiperlint: {len(violations)} violation(s) in {n_files} file(s)"
        if violations else
        f"kuiperlint: OK ({n_files} file(s), "
        f"{len(all_passes())} passes clean)")
    return "\n".join(lines)


def render_json(violations: List[Violation], n_files: int) -> str:
    return json.dumps({
        "files": n_files,
        "passes": sorted(all_passes()),
        "violations": [v.to_json() for v in violations],
        "ok": not violations,
    }, indent=2)
