#!/usr/bin/env python
"""probe_multichip — tier-1 smoke for multi-chip sharded serving
(parallel/sharded.py, docs/DISTRIBUTED.md).

Runs the full-pipe parity check on an 8-virtual-device CPU mesh (the
same `--xla_force_host_platform_device_count` recipe as
tests/conftest.py and __graft_entry__.dryrun_multichip) and asserts:

  1. planner selection: `shards=auto` under KUIPER_MESH plans the rule
     onto the sharded kernel, and explain() carries the "shards"
     section naming the mesh;
  2. full-pipe parity: the sharded plan's emitted windows (hopping
     panes, capacity growth mid-stream) are byte-identical to the
     single-chip plan on the same data;
  3. cross-mesh checkpoint restore: a snapshot taken on the 8-device
     mesh restores single-chip (8->1) and back onto the mesh (1->8)
     with KeyTable slots, pane cursor, and window output byte-identical;
  4. placement-aware admission: a rule the single-chip HBM budget would
     429 is ACCEPTED with a sharded placement when the mesh is up;
  5. jitcert: every traced sharded signature is inside its certificate
     (diff_live clean).

Run directly or through tools/ci_gate.py (gate name `probe_multichip`).
Exit 0 on success.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

SQL = ("SELECT deviceId, sum(v) AS s, count(*) AS c, min(v) AS mn "
       "FROM demo GROUP BY deviceId, HOPPINGWINDOW(ss, 4, 2)")


def _force_devices(n: int = 8) -> None:
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    _force_devices(8)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.parallel.mesh import make_mesh
    from ekuiper_tpu.planner.planner import (RuleDef, merged_options,
                                             mesh_request)
    from ekuiper_tpu.runtime.events import Trigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils import timex

    timex.set_mock_clock(0)
    problems = []
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    if len(jax.devices()) < 8:
        problems.append(f"only {len(jax.devices())} devices — the "
                        "virtual-device recipe did not engage")
        print(json.dumps({"ok": False, "problems": problems}))
        return 1

    # ---- 1. planner selection (shards=auto / KUIPER_MESH)
    os.environ["KUIPER_MESH"] = "2x4"
    try:
        rule = RuleDef(id="probe_mc", sql=SQL,
                       options={"planOptimizeStrategy": {"shards": "auto"}})
        req = mesh_request(merged_options(rule), plan)
        if req["mode"] != "sharded" or req["cfg"] != {"rows": 2, "keys": 4}:
            problems.append(f"planner did not select the mesh: {req}")
        off = RuleDef(id="probe_off", sql=SQL,
                      options={"planOptimizeStrategy": {"shards": "off"}})
        if mesh_request(merged_options(off), plan)["mode"] != "single-chip":
            problems.append("shards=off did not pin single-chip")
    finally:
        del os.environ["KUIPER_MESH"]

    # ---- 2. full-pipe parity: sharded vs single-chip fused node
    def mk(mesh):
        n = FusedWindowAggNode(
            "probe_mc", stmt.window, extract_kernel_plan(stmt),
            [d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128, prefinalize_lead_ms=0,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            emit_columnar=False, mesh=mesh)
        n.state = n.gb.init_state()
        out = []
        n.emit = lambda item, count=None, _o=out: _o.append(item)
        return n, out

    mesh = make_mesh(rows=2, keys=4)
    sharded, out_s = mk(mesh)
    plain, out_p = mk(None)
    if getattr(sharded.gb, "watch_prefix", "") != "sharded":
        problems.append("mesh node did not build a ShardedGroupBy")

    rng = np.random.default_rng(11)

    def batch(ids, vals):
        ids = np.array(ids, dtype=np.object_)
        return ColumnBatch(
            n=len(ids),
            columns={"deviceId": ids,
                     "v": np.asarray(vals, np.float64)},
            timestamps=np.zeros(len(ids), np.int64), emitter="demo")

    def feed(nodes, ids):
        vals = np.rint(rng.normal(50, 10, len(ids))).astype(np.float64)
        for n in nodes:
            n.process(batch(list(ids), vals))

    def boundary(nodes, ts):
        for n in nodes:
            n.on_trigger(Trigger(ts=ts))
            n._drain_async_emits()

    both = [sharded, plain]
    feed(both, [f"dev{i}" for i in range(40)])          # within capacity
    boundary(both, 2000)
    feed(both, [f"dev{i}" for i in range(40, 150)])     # forces a grow
    boundary(both, 4000)
    feed(both, [f"dev{i}" for i in range(0, 150, 3)])
    boundary(both, 6000)

    def flat(msgs):
        rows = {}
        for m in msgs:
            for r in (m if isinstance(m, list) else [m]):
                k = tuple(sorted(r.items()))
                rows[k] = rows.get(k, 0) + 1
        return rows

    if flat(out_s) != flat(out_p):
        diff = set(flat(out_s).items()) ^ set(flat(out_p).items())
        problems.append(f"sharded != single-chip windows: {list(diff)[:4]}")
    shard_rows = sharded.gb.shard_stats(sharded.state)
    if sum(s["rows"] for s in shard_rows) == 0:
        problems.append("per-shard row accounting recorded nothing")

    # ---- 3. cross-mesh checkpoint restore (8 -> 1 -> 8)
    snap8 = sharded.snapshot_state()
    single, out_1 = mk(None)
    single.restore_state(snap8)
    if single.kt.decode_all() != sharded.kt.decode_all():
        problems.append("8->1 restore changed the KeyTable slot order")
    if single.cur_pane != sharded.cur_pane:
        problems.append("8->1 restore changed the pane cursor")
    tail = [f"dev{i}" for i in range(10, 60)]
    vals = np.ones(len(tail), np.float64)
    for n in (single, sharded):
        n.process(batch(tail, vals))
    boundary([single, sharded], 8000)
    out_s_tail = flat(out_s[-1:])
    if flat(out_1) != out_s_tail:
        problems.append("8->1 restored windows diverged")
    snap1 = single.snapshot_state()
    remesh, out_8 = mk(make_mesh(rows=2, keys=4))
    remesh.restore_state(snap1)
    if remesh.kt.decode_all() != single.kt.decode_all():
        problems.append("1->8 restore changed the KeyTable slot order")
    for n in (remesh, single):
        n.process(batch(tail, vals))
    out_1.clear()
    boundary([remesh, single], 10000)
    if flat(out_8) != flat(out_1):
        problems.append("1->8 restored windows diverged")

    # ---- 4. placement-aware admission (per-chip ledger)
    from ekuiper_tpu.runtime import control
    from ekuiper_tpu.store import kv

    store = kv.get_store()
    # tierStore=off: the cold tier would otherwise absorb the footprint
    # (hot-set pricing) — this leg probes the PLACEMENT path
    fat = RuleDef(id="probe_fat", sql=SQL,
                  options={"key_slots": 262144, "sharedFold": False,
                           "tierStore": "off"})
    os.environ["KUIPER_HBM_BUDGET_MB"] = "8"
    ctl = control.install(lambda: [], start=False)
    try:
        single_chip = control.admit_rule(fat, store)
        if single_chip["decision"] != "reject":
            problems.append("single-chip HBM budget did not 429 the fat "
                            f"rule: {single_chip['decision']}")
        os.environ["KUIPER_MESH"] = "1x8"
        placed = control.admit_rule(fat, store)
        placement = (placed.get("price") or {}).get("placement") or {}
        if placed["decision"] != "accept" or \
                placement.get("mode") != "sharded":
            problems.append(
                "placement-aware admission did not accept the sharded "
                f"rule: {placed['decision']} / {placement}")
    finally:
        del os.environ["KUIPER_HBM_BUDGET_MB"]
        os.environ.pop("KUIPER_MESH", None)
        control.reset()

    # ---- 5. compile contracts
    d = jitcert.diff_live()
    if not d["clean"]:
        problems.append(
            "jitcert diff not clean: "
            + "; ".join(f"{u['op']}: {u['signature'][:80]}"
                        for u in d["uncertified"][:3]))

    report = {
        "ok": not problems,
        "problems": problems,
        "devices": len(jax.devices()),
        "mesh": getattr(sharded.gb, "mesh_tag", ""),
        "capacity": int(sharded.gb.capacity),
        "shard_rows": [s["rows"] for s in shard_rows],
        "jitcert_clean": d["clean"],
    }
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
