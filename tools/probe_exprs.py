#!/usr/bin/env python
"""probe_exprs — tier-1 smoke for the device-compiled expression IR.

Plans a rule whose WHERE + projection expressions span every operator
class the IR compiles (CASE, IN with string constants, dictionary-coded
string equality, temporal extraction on an int64 event-time column,
BETWEEN, NULL-propagating three-valued logic), then asserts:

  1. the rule takes the FUSED DEVICE path (device_path_eligible returns
     a kernel plan; no FilterNode / row-interpreter hop),
  2. the plan carries the expression-IR plumbing: int32 derived columns
     (__sd_*/__ts32_*), a per-column dtype map, and an IR hash for the
     prep-upload share keys,
  3. a real fold + finalize on CPU jax produces the row-interpreter's
     exact groups (WHERE parity, NULLs dropped),
  4. every traced signature is inside its jitcert certificate
     (diff_live clean) — the bounded-signature-family acceptance gate.

Run directly or through tools/ci_gate.py (gate name `probe_exprs`).
Exit 0 on success. docs/EXPRESSIONS.md documents the IR itself.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root


SQL = (
    "SELECT deviceId, count(*) AS c, "
    "sum(CASE WHEN status = 'ok' THEN v ELSE 0.0 END) AS s_ok, "
    "avg(v) FILTER (WHERE v BETWEEN 0 AND 100) AS a "
    "FROM s WHERE status IN ('ok', 'warn') AND hour(ets) < 23 "
    "AND NOT (v < 0) "
    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)"
)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ekuiper_tpu.data.batch import from_messages
    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan, \
        take_expr_fallbacks
    from ekuiper_tpu.ops.groupby import DeviceGroupBy
    from ekuiper_tpu.planner.planner import device_path_eligible
    from ekuiper_tpu.sql.eval import Evaluator
    from ekuiper_tpu.sql.expr_ir import materialize_derived
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils.config import RuleOptionConfig, get_config

    problems = []
    stmt = parse_select(SQL)
    opts = RuleOptionConfig(**{**get_config().rule.__dict__})
    plan = device_path_eligible(stmt, opts)
    notes = take_expr_fallbacks()
    if plan is None:
        problems.append(f"rule did not take the device path: {notes}")
    if plan is not None:
        derived = {d.kind for d in plan.derived}
        if "strdict" not in derived or "ts32" not in derived:
            problems.append(f"missing derived column kinds: {derived}")
        if not plan.expr_tag:
            problems.append("plan has no expression IR hash")
        if "int32" not in set(plan.col_dtypes.values()):
            problems.append(f"no int32 kernel columns: {plan.col_dtypes}")

    # ---- fold parity vs the row interpreter --------------------------
    if plan is not None:
        anchor = next(d.anchor for d in plan.derived if d.kind == "ts32")
        msgs = [
            {"deviceId": "a", "v": 1.0, "status": "ok",
             "ets": anchor + 3_600_000},
            {"deviceId": "a", "v": 2.0, "status": "warn",
             "ets": anchor + 3_600_000},
            {"deviceId": "b", "v": 3.0, "status": "err",
             "ets": anchor + 3_600_000},
            {"deviceId": "b", "v": 4.0, "status": "ok",
             "ets": anchor + 85_000_000},      # hour 23: dropped
            {"deviceId": "a", "v": None, "status": "ok", "ets": None},
            {"deviceId": "c", "v": 250.0, "status": "warn",
             "ets": anchor + 7_200_000},       # fails the agg FILTER
        ]
        batch, _ = from_messages(msgs, [0] * len(msgs), emitter="s")
        gb = DeviceGroupBy(plan, capacity=16, n_panes=1, micro_batch=8)
        state = gb.init_state()
        cols: dict = {}
        materialize_derived(plan.derived, cols, batch)
        for name in plan.columns:
            if name not in cols:
                cols[name] = np.asarray(batch.columns[name])
        valid = {n: batch.valid[n] for n in plan.columns
                 if n in batch.valid}
        keys = sorted({m["deviceId"] for m in msgs})
        slots = np.array([keys.index(m["deviceId"]) for m in msgs],
                         dtype=np.int32)
        state = gb.fold(state, cols, slots, valid, 0)
        outs, act = gb.finalize(state, len(keys))

        # reference: the row interpreter over the same WHERE
        ev = Evaluator()
        kept = [r for r in batch.to_tuples()
                if ev.eval_condition(stmt.condition, r)]
        ref_act = {k: sum(1 for r in kept
                          if r.value("deviceId")[0] == k) for k in keys}
        got_act = {k: int(act[i]) for i, k in enumerate(keys)}
        if got_act != ref_act:
            problems.append(f"WHERE parity: device act {got_act} != "
                            f"row-interpreter {ref_act}")
        # spot-check the CASE projection: key 'a' folds 1.0 (ok) + 0.0
        # (warn); the NULL-v row dropped by WHERE's NOT(v<0) null rule
        s_idx = next(i for i, s in enumerate(plan.specs)
                     if s.kind == "sum")
        if abs(float(outs[s_idx][keys.index("a")]) - 1.0) > 1e-6:
            problems.append(
                f"CASE sum for key a: {outs[s_idx][keys.index('a')]}"
                " != 1.0")

        d = jitcert.diff_live()
        if not d["clean"]:
            problems.append(f"jitcert diff not clean: "
                            f"{d['uncertified'][:4]}")

    report = {"ok": not problems, "problems": problems,
              "fallback_notes": notes}
    print(json.dumps(report, indent=2) if problems else
          "probe_exprs: OK — CASE+IN+string+temporal rule plans "
          "device-fused, fold parity holds, jitcert clean")
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
