#!/usr/bin/env python
"""probe_fleetobs — tier-1 smoke for the fleet observatory
(observability/meshwatch.py + timeline.py, docs/OBSERVABILITY.md).

Runs on the 8-virtual-device CPU mesh (same recipe as tests/conftest.py
and probe_multichip) and asserts BOTH signal directions:

  1. skewed workload: one hot key hogging a row shard drives
     `kuiper_mesh_skew_ratio` above the threshold, the health plane
     attributes the bottleneck to `shard_skew` naming the hot shard,
     and after `up_ticks` consecutive skewed observations the QoS
     controller raises ONE structured `rebalance_hint` flight event;
  2. uniform workload (negative control): skew stays under threshold,
     no `shard_skew` verdict, no hint — the signal must not cry wolf;
  3. collective split: the sharded fold sites carry a
     collective-vs-compute estimate bounded by sampled device time;
  4. durable timeline: snapshots + mirrored events land on disk,
     survive a hard kill (fresh Timeline over the same dir), replay
     through query filters, and byte-cap retention actually deletes;
  5. prometheus: all kuiper_mesh_* / kuiper_timeline_* families render.

Run directly or through tools/ci_gate.py (gate name `probe_fleetobs`).
Exit 0 on success.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

SQL = ("SELECT deviceId, sum(v) AS s, count(*) AS c "
       "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")


def _force_devices(n: int = 8) -> None:
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    _force_devices(8)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import types

    import numpy as np

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.observability import health, kernwatch, meshwatch
    from ekuiper_tpu.observability import timeline as tmod
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.parallel.mesh import make_mesh
    from ekuiper_tpu.runtime import control
    from ekuiper_tpu.runtime.events import recorder
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils import timex
    from ekuiper_tpu.utils.rulelog import set_rule_context

    clock = timex.set_mock_clock(0)
    problems = []
    if len(jax.devices()) < 8:
        print(json.dumps({"ok": False, "problems": [
            f"only {len(jax.devices())} devices — the virtual-device "
            "recipe did not engage"]}))
        return 1
    meshwatch.reset()
    recorder().clear()
    # sample EVERY kernel call: the probe feeds a couple of batches, the
    # default 1-in-N hot-path cadence would leave the split empty
    prior_sampling = kernwatch.set_sampling(hot=1, boundary=1)
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None

    def mk(rule_id):
        # rule context BEFORE construction: the shard registry label and
        # the kernwatch sample label must agree for the collective split
        set_rule_context(rule_id)
        try:
            n = FusedWindowAggNode(
                rule_id, stmt.window, extract_kernel_plan(stmt),
                [d.expr for d in stmt.dimensions],
                capacity=64, micro_batch=128, prefinalize_lead_ms=0,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=False, mesh=make_mesh(rows=2, keys=4))
            n.state = n.gb.init_state()
            n.emit = lambda item, count=None: None
        finally:
            set_rule_context(None)
        return n

    def feed(node, rule_id, ids):
        ids = np.array(ids, dtype=np.object_)
        b = ColumnBatch(
            n=len(ids),
            columns={"deviceId": ids,
                     "v": np.ones(len(ids), np.float64)},
            timestamps=np.zeros(len(ids), np.int64), emitter="demo")
        set_rule_context(rule_id)
        try:
            node.process(b)
        finally:
            set_rule_context(None)

    # ---- 1+2. skewed vs uniform workloads through real sharded kernels
    skew_node = mk("r_skew")
    uni_node = mk("r_uniform")
    # 80% of rows on ONE key -> one row shard runs hot
    feed(skew_node, "r_skew", ["hotdev"] * 800
         + [f"dev{i}" for i in range(200)])
    # uniform: 1000 rows over 200 keys spread across the hash space
    feed(uni_node, "r_uniform", [f"dev{i % 200}" for i in range(1000)])
    clock.advance(1000)

    # health + control over stub topos: meshwatch reads the shard
    # registry directly, so the verdict path only needs the rule ids
    stub = types.SimpleNamespace()
    triples = [("r_skew", stub, {}), ("r_uniform", stub, {})]
    hv = health.install(lambda: triples, start=False)
    ctl = control.install(lambda: triples, start=False,
                          verdicts_fn=lambda: hv.verdicts())
    try:
        for _ in range(ctl.up_ticks):
            hv.tick()
            ctl.tick()
            clock.advance(1000)
        verdicts = hv.verdicts()
        vs = verdicts.get("r_skew") or {}
        mesh_s = (vs.get("bottleneck") or {}).get("mesh") or {}
        if not mesh_s.get("skewed"):
            problems.append(f"skewed rule not flagged: {mesh_s}")
        if (vs.get("bottleneck") or {}).get("stage") != "shard_skew":
            problems.append("skewed rule verdict stage != shard_skew: "
                            f"{(vs.get('bottleneck') or {}).get('stage')}")
        ratio = meshwatch.rule_skew("r_skew").get("skew_ratio") or 0.0
        if ratio < meshwatch.skew_threshold():
            problems.append(f"skew_ratio {ratio:.2f} under threshold")
        vu = verdicts.get("r_uniform") or {}
        mesh_u = (vu.get("bottleneck") or {}).get("mesh") or {}
        if mesh_u.get("skewed"):
            problems.append(f"uniform rule falsely flagged: {mesh_u}")
        if (vu.get("bottleneck") or {}).get("stage") == "shard_skew":
            problems.append("uniform rule verdict stage is shard_skew")
        hints = recorder().events(kind="rebalance_hint")
        skew_hints = [e for e in hints if e.get("rule") == "r_skew"]
        if len(skew_hints) != 1:
            problems.append(f"expected exactly 1 rebalance_hint for "
                            f"r_skew, got {len(skew_hints)}")
        elif skew_hints[0].get("hot_shard") is None \
                or not skew_hints[0].get("skew_ratio"):
            problems.append(f"hint missing attribution: {skew_hints[0]}")
        if any(e.get("rule") == "r_uniform" for e in hints):
            problems.append("rebalance_hint raised for the uniform rule")
        md = ctl.diagnostics().get("mesh") or {}
        if md.get("rebalance_hints_total") != 1:
            problems.append(f"controller hint counter: {md}")
    finally:
        control.reset()
        health.reset()

    # ---- 3. collective-vs-compute split on the sharded fold sites
    split = meshwatch.collective_split()
    fold_sites = {k: v for k, v in split.items() if "fold" in k[0]}
    if not fold_sites:
        problems.append(f"no sharded fold sites in the split: "
                        f"{sorted(k[0] for k in split)}")
    for (op, label), v in fold_sites.items():
        if not (0.0 <= v["collective_us"] <= v["device_us"]):
            problems.append(f"collective estimate unbounded at {op}: {v}")
        if v["bytes_per_fold"] <= 0:
            problems.append(f"no collective payload priced at {op}")

    # ---- 4. durable timeline: snapshot/mirror, hard kill, retention
    tdir = tempfile.mkdtemp(prefix="fleetobs_tl_")
    try:
        beat = [0]

        def scrape():
            beat[0] += 1
            return (f"kuiper_probe_beat {beat[0]}\n"
                    'kuiper_probe_static{rule="r_skew"} 7\n')

        tl = tmod.Timeline(scrape, base_dir=tdir, interval_ms=0)
        tl.snapshot()
        tl.note_event({"kind": "rebalance_hint", "rule": "r_skew",
                       "ts_ms": timex.now_ms()})
        clock.advance(1000)
        tl.snapshot()
        tl.dying_gasp()
        # hard kill: a FRESH instance over the same dir must resume the
        # segment sequence and replay everything already on disk
        tl2 = tmod.Timeline(scrape, base_dir=tdir, interval_ms=0)
        q = tl2.query(family="kuiper_probe_beat")
        if q["returned"] < 2:
            problems.append(f"timeline replay after hard kill: {q}")
        qe = tl2.query(family="events", rule="r_skew")
        if not any(r["kind"] == "event" for r in q["records"]) and \
                not qe["returned"]:
            problems.append("mirrored event lost across hard kill")
        tl2.snapshot()  # must append past the old tail, not clobber it
        if tl2.query(family="kuiper_probe_beat")["returned"] < 3:
            problems.append("post-recovery snapshot did not append")
        # byte-cap retention: shrink the caps and write until the ring
        # must delete its oldest segments
        tl2.seg_bytes = 512
        tl2.max_bytes = 2048
        for _ in range(200):
            clock.advance(100)
            tl2.snapshot()
        st = tl2.stats()
        if st["bytes"] > tl2.max_bytes + tl2.seg_bytes:
            problems.append(f"retention over cap: {st}")
        if st["segments"] < 2:
            problems.append(f"rotation never split segments: {st}")
        if tl2.query(family="kuiper_probe_beat")["returned"] == 0:
            problems.append("retention deleted the live tail")
    finally:
        shutil.rmtree(tdir, ignore_errors=True)

    # ---- 5. the new families must render
    out, rendered = [], ""
    meshwatch.render_prometheus(out, lambda s: str(s))
    tmod.render_prometheus(out, lambda s: str(s))
    rendered = "\n".join(out)
    for fam in ("kuiper_mesh_skew_ratio", "kuiper_mesh_shard_rows_per_s",
                "kuiper_mesh_collective_ms", "kuiper_mesh_collective_share"):
        if fam not in rendered:
            problems.append(f"{fam} did not render")

    kernwatch.set_sampling(**prior_sampling)
    report = {
        "ok": not problems,
        "problems": problems,
        "devices": len(jax.devices()),
        "skew_ratio": round(
            meshwatch.rule_skew("r_skew").get("skew_ratio") or 0.0, 3),
        "uniform_ratio": round(
            meshwatch.rule_skew("r_uniform").get("skew_ratio") or 0.0, 3),
        "threshold": meshwatch.skew_threshold(),
        "fold_sites": sorted(k[0] for k in fold_sites),
    }
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
