"""Flagship benchmark: 10k-device tumbling-window GROUP BY on one TPU chip.

Reproduces the reference's select_aggr_rule.jmx scenario (TUMBLINGWINDOW avg
over an MQTT demo stream) at TPU scale: 10,000 devices, avg/count/min/max
aggregates, 10s window, measured through the real engine node (key encode +
device fold + window emit), not just the raw kernel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline = the reference's best published single-node throughput for its
streaming hot path (12k msg/s on a Raspberry Pi 3B+, README.md:98 — see
BASELINE.md; the reference publishes no TPU-class numbers).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

N_DEVICES = 10_000
BATCH_ROWS = 65_536
KEY_SLOTS = 16_384
WARMUP_BATCHES = 3
MEASURE_SECONDS = 10.0
MAX_SECONDS = 150.0  # run past MEASURE_SECONDS until >=50 emit samples
# ~0.9s windows: the fused node folds the first half on device, pre-issues
# the finalize at mid-window (~400ms runway for the tunnel round trip), and
# host-shadows the dying tail (ops/prefinalize.py). At the rule's real 10s
# cadence the same mechanism gives the device ~95% of rows; the compressed
# cadence here is only to collect >=50 latency samples.
WINDOW_EVERY_BATCHES = 96
PRE_ISSUE_AT = (48, 64, 80)  # retries are no-ops once a fetch lands
MIN_EMIT_SAMPLES = 50
BASELINE_MSG_S = 12_000.0

SQL = (
    "SELECT deviceId, avg(temperature) AS avg_t, count(*) AS cnt, "
    "min(temperature) AS min_t, max(temperature) AS max_t "
    "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
)


def bench_rule_group(batches, kt_slots) -> None:
    """256 homogeneous rules (per-rule thresholds) as ONE vmapped device
    program — the TPU answer to the reference's shared-source fan-out
    benchmark (300 rules x 500 msg/s = 150k rule-msg/s on 2 cores,
    README.md:144-156). Prints a stderr metric line; the headline JSON line
    stays the single-rule bench."""
    import jax
    from ekuiper_tpu.parallel.multirule import BatchedGroupBy, build_rule_batch
    from ekuiper_tpu.sql.parser import parse_select

    n_rules = 256
    stmts = [
        parse_select(
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
            f"FROM demo WHERE temperature > {10.0 + 0.1 * r} "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        for r in range(n_rules)
    ]
    spec = build_rule_batch([f"r{r}" for r in range(n_rules)], stmts)
    gb = BatchedGroupBy(spec, capacity=kt_slots, micro_batch=BATCH_ROWS)
    state = gb.init_state()
    from ekuiper_tpu.ops.keytable import KeyTable

    kt = KeyTable(kt_slots)
    cols = [{"temperature": b.columns["temperature"]} for b in batches]
    # warmup compile (one program for all 256 rules)
    slots, _ = kt.encode_column(batches[0].columns["deviceId"])
    state = gb.fold(state, dict(cols[0]), slots)
    gb.finalize(state, kt.n_keys)
    jax.block_until_ready(state)
    rows = 0
    n = 0
    t0 = time.time()
    while time.time() - t0 < 10.0:
        # full per-batch host path: key encode runs every batch (shared
        # across all 256 rules — that IS the group win)
        slots, _ = kt.encode_column(batches[n % 4].columns["deviceId"])
        state = gb.fold(state, dict(cols[n % 4]), slots)
        rows += BATCH_ROWS
        n += 1
    outs, act = gb.finalize(state, kt.n_keys)  # one transfer for all rules
    elapsed = time.time() - t0
    assert outs[1].shape[0] == n_rules and np.all(act[0] >= act[-1])
    rule_rows = rows * n_rules / elapsed
    print(
        f"# 256-rule group: {rows:,} rows x {n_rules} rules in {elapsed:.2f}s"
        f" = {rule_rows:,.0f} rule-rows/s through one vmapped program"
        f" (reference fan-out baseline: 150,000 rule-msg/s)",
        file=sys.stderr,
    )


def bench_event_time(batches, kt_slots) -> None:
    """Event-time device path: per-row pane routing + watermark-driven
    emission. Prints a stderr metric line."""
    import jax
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import Watermark
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "ev", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=kt_slots, micro_batch=BATCH_ROWS,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True, is_event_time=True, late_tolerance_ms=1000)
    from ekuiper_tpu.data.batch import ColumnBatch

    node.state = node.gb.init_state()
    emitted = []
    node.broadcast = lambda item: emitted.append(item)

    def stamped(i):  # event timestamps advance ~1s/batch -> window per ~10
        b = batches[i % 4]
        return ColumnBatch(n=b.n, columns=b.columns,
                           timestamps=np.full(b.n, i * 1000, dtype=np.int64),
                           emitter=b.emitter)

    node.process(stamped(0))
    node.on_watermark(Watermark(ts=0))
    jax.block_until_ready(node.state)
    rows = 0
    n = 1
    t0 = time.time()
    while time.time() - t0 < 10.0:
        node.process(stamped(n))
        node.on_watermark(Watermark(ts=n * 1000 - 1000))
        rows += BATCH_ROWS
        n += 1
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0
    n_windows = sum(1 for i in emitted if not isinstance(i, Watermark))
    print(
        f"# event-time device path: {rows:,} rows in {elapsed:.2f}s "
        f"({rows / elapsed:,.0f} rows/s), {n_windows} watermark-driven "
        f"window emits", file=sys.stderr,
    )


def main() -> None:
    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import PreTrigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.sql.parser import parse_select
    import jax

    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "bench rule must be device-eligible"
    direct = build_direct_emit(stmt, plan, ["deviceId"])
    assert direct is not None, "bench rule must take the direct-emit tail"

    node = FusedWindowAggNode(
        "bench", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=KEY_SLOTS, micro_batch=BATCH_ROWS, direct_emit=direct,
        emit_columnar=True,
    )
    node.state = node.gb.init_state()
    emitted = []
    node.broadcast = lambda item: emitted.append(item)  # capture emits

    rng = np.random.default_rng(0)
    device_ids = np.array([f"dev_{i}" for i in range(N_DEVICES)], dtype=np.object_)
    # a few distinct pre-built batches so host-side caching can't fake it
    batches = []
    for _ in range(4):
        idx = rng.integers(0, N_DEVICES, BATCH_ROWS)
        cols = {
            "deviceId": device_ids[idx],
            "temperature": rng.normal(20, 5, BATCH_ROWS).astype(np.float32),
        }
        batches.append(
            ColumnBatch(n=BATCH_ROWS, columns=cols,
                        timestamps=np.zeros(BATCH_ROWS, dtype=np.int64),
                        emitter="demo")
        )

    # warmup: compile fold + sync finalize + prefinalize components
    assert node._prefinalize_ok, "bench rule must take the latency-hiding emit"
    for i in range(WARMUP_BATCHES):
        node.process(batches[i % len(batches)])
    node._emit(WindowRange(0, 10_000))  # sync path (compiles finalize)
    node.on_pre_trigger(PreTrigger(ts=10_000))
    node.process(batches[3])
    node._emit(WindowRange(0, 10_000))  # merged path (compiles components)
    node.state = node.gb.reset_pane(node.state, 0)
    node.begin_window_backstop()  # first measured window is covered too
    jax.block_until_ready(node.state)

    # measured run: the window "closes" right after the last pre-boundary
    # batch is folded; emit latency = that point -> output messages emitted.
    # The device finalize was pre-issued PRE_LEAD_BATCHES earlier
    # (ops/prefinalize.py), so the round trip overlaps the stream.
    emit_latencies = []
    rows_done = 0
    n_batches = 0
    storm_windows = 0
    t0 = time.time()
    while (time.time() - t0 < MEASURE_SECONDS
           or len(emit_latencies) < MIN_EMIT_SAMPLES):
        if time.time() - t0 > MAX_SECONDS:
            break
        node.process(batches[n_batches % len(batches)])
        rows_done += BATCH_ROWS
        n_batches += 1
        m = n_batches % WINDOW_EVERY_BATCHES
        if m in PRE_ISSUE_AT:
            node.on_pre_trigger(PreTrigger(ts=0))
        elif m == 0:
            t_emit = time.time()
            node._emit(WindowRange(0, 10_000))
            emit_latencies.append((time.time() - t_emit) * 1000)
            node.state = node.gb.reset_pane(node.state, 0)
            node.begin_window_backstop()
            storm_windows += 1 if node._storm else 0
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0

    rows_per_sec = rows_done / elapsed
    p99 = float(np.percentile(emit_latencies, 99)) if emit_latencies else 0.0
    p50 = float(np.percentile(emit_latencies, 50)) if emit_latencies else 0.0

    # decompose emit latency: sync device finalize+transfer (what a naive
    # emit would pay, dominated by tunnel RTT) vs the merged path's pieces
    fin_ms, merge_ms, tail_ms = [], [], []
    for b in batches:  # repopulate: decomposition needs a live window
        node.process(b)
    outs, act = node.gb.finalize(node.state, node.kt.n_keys)
    active = np.nonzero(act > 0)[0]
    assert len(active) >= N_DEVICES * 0.99, "window must be populated for the split"
    for _ in range(5):
        t = time.time()
        outs, act = node.gb.finalize(node.state, node.kt.n_keys)
        fin_ms.append((time.time() - t) * 1000)
        pending = node.gb.prefinalize_begin(node.state)
        pending.get()
        t = time.time()
        node.gb.prefinalize_merge(pending, None, node.kt.n_keys)
        merge_ms.append((time.time() - t) * 1000)
        t = time.time()
        node._emit_direct(outs, active, WindowRange(0, 10_000))
        tail_ms.append((time.time() - t) * 1000)

    print(
        f"# {rows_done:,} rows in {elapsed:.2f}s over {n_batches} batches; "
        f"emit p50={p50:.1f}ms p99={p99:.1f}ms over {len(emit_latencies)} samples "
        f"(sync finalize/transfer p50={np.percentile(fin_ms, 50):.1f}ms, "
        f"prefinalize merge p50={np.percentile(merge_ms, 50):.1f}ms, "
        f"host tail p50={np.percentile(tail_ms, 50):.1f}ms; "
        f"storm windows={storm_windows}); "
        f"groups/window={N_DEVICES}; device={jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    bench_event_time(batches, KEY_SLOTS)
    bench_rule_group(batches, KEY_SLOTS)

    print(json.dumps({
        "metric": "tumbling_groupby_rows_per_sec_10k_devices",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_MSG_S, 2),
    }))


if __name__ == "__main__":
    main()
