"""Flagship benchmark: 10k-device tumbling-window GROUP BY on one TPU chip.

Reproduces the reference's select_aggr_rule.jmx scenario (TUMBLINGWINDOW avg
over an MQTT demo stream) at TPU scale: 10,000 devices, avg/count/min/max
aggregates, measured through the real engine node (key encode + device fold
+ window emit), not just the raw kernel.

Two phases, mirroring standard throughput-vs-latency methodology:

- Phase T (throughput): saturate the host→device link (on a tunneled chip
  the ~23MB/s upload channel is the ceiling, not the TPU). Every row folds
  on device; every window emits from a pre-issued DEVICE fetch the boundary
  waits for (no host backstop) — the reported rows/s therefore includes
  the full cost of device-served emission.
- Phase L (latency): pace ingest at the north-star load (>=1M rows/s,
  BASELINE.md) where the link has headroom, and measure emit latency over
  >=50 window boundaries. The pre-issued fetch lands before the boundary,
  so emits are device-served with p99 in single-digit ms; the per-window
  source tag (device/backstop/sync) is reported so a host-served emit can
  never masquerade as a device number (r02 post-mortem).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline = the reference's best published single-node throughput for its
streaming hot path (12k msg/s on a Raspberry Pi 3B+, README.md:98 — see
BASELINE.md; the reference publishes no TPU-class numbers).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_DEVICES = 10_000
BATCH_ROWS = 65_536
KEY_SLOTS = 16_384
WARMUP_BATCHES = 3
BASELINE_MSG_S = 12_000.0

# Total wall-clock budget for the WHOLE bench run. The driver wraps
# `python bench.py` in a hard 900s timeout; r05 died to it (rc=124, no
# artifact) because the full-pipe SUBPROCESS alone was allowed 900s. Every
# phase budget is now capped by the remaining global budget, and a global
# watchdog emits the final self-contained JSON just before the driver
# would kill us.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "870"))
_DEADLINE: list = []  # [epoch_seconds], set by main()


def _remaining_s() -> float:
    """Seconds left in the global budget (inf outside main())."""
    if not _DEADLINE:
        return float("inf")
    return _DEADLINE[0] - time.time()


def phase_budget(nominal_s: float, remaining_s=None,
                 reserve_s: float = 15.0,
                 later_floor_s: float = 0.0) -> float:
    """Wall-clock budget for one phase: its nominal allowance clamped so
    the phase can never spend past the global deadline minus a reserve
    for the final-JSON flush, minus the floors of every later phase
    (`later_floor_s`, see PHASE_FLOORS). THE invariants (unit-tested,
    tests/test_bench_budget.py — the r05 rc=124 post-mortem class of bug):
    for any sequence of phases each consuming at most its clamped budget,
    total spend stays within TOTAL_BUDGET_S; and when the roster's floors
    fit the budget, every phase is offered at least min(nominal, floor)
    seconds no matter how greedily earlier phases spent theirs."""
    rem = _remaining_s() if remaining_s is None else remaining_s
    return min(float(nominal_s), max(rem - reserve_s - later_floor_s, 0.0))


#: roster-ordered (tag, minimum useful seconds) per phase. phase_budget()
#: subtracts the floors of every LATER phase from the remaining global
#: budget before granting one, so a single slow phase can never starve
#: the rest of the roster out of the artifact (BENCH_r05's rc=124: the
#: full_pipe child alone was allowed the driver's whole 900s, so nothing
#: after it — or even the final JSON — ever ran). A floor is a guarantee
#: of OPPORTUNITY, not a spend: fast phases return their unused share to
#: the pool. Floors sum to well under TOTAL_BUDGET_S (asserted in
#: tests/test_bench_budget.py).
PHASE_FLOORS = (
    ("full-pipe", 110.0),
    ("full-pipe-contended", 90.0),
    ("hetero 256-rule", 90.0),
    ("phase_throughput", 60.0),
    ("phase_latency", 40.0),
    ("sliding", 50.0),
    ("heavy_hitters", 30.0),
    ("hll_1m", 60.0),
    ("event_time", 25.0),
    ("rule_group", 25.0),
    ("filter_heavy", 25.0),
    ("join_heavy", 15.0),
    ("multi_rule_shared", 30.0),
    ("multi_rule_shared_mixed", 25.0),
    ("key_cardinality", 45.0),
    ("multichip_full_pipe", 40.0),
    ("cold_start", 30.0),
    ("churn_soak", 45.0),
)


def later_floor(tag: str) -> float:
    """Sum of the floors of every phase AFTER `tag` in the roster (0.0
    for a tag not in the roster — ad-hoc phases get the plain greedy
    carve)."""
    names = [n for n, _ in PHASE_FLOORS]
    if tag not in names:
        return 0.0
    i = names.index(tag)
    return float(sum(f for _, f in PHASE_FLOORS[i + 1:]))

# Every phase records its key metrics here via record(); the final stdout
# JSON line carries the whole dict under "phases", so the driver artifact
# is self-contained even when its output tail is byte-truncated
# (VERDICT r4 weak #2: the 1M full-pipe claim was orphaned exactly that way)
RESULTS: dict = {}


def record(phase: str, **kv) -> None:
    d = {k: (round(v, 1) if isinstance(v, float) else v)
         for k, v in kv.items()}
    RESULTS[phase] = d
    # subprocess-isolated phases get their record lines re-parsed by the
    # parent (_run_isolated); plain stderr so humans can read them too
    print("#R " + json.dumps({phase: d}), file=sys.stderr, flush=True)


def _flush_record_dump() -> None:
    """One `#R ` line carrying EVERYTHING recorded so far — the dying
    gasp of a watchdog. Per-record lines already stream out as phases
    finish, but when a watchdog fires inside a subprocess-isolated phase
    the child's stdout JSON is discarded; this stderr line is what the
    parent's harvest (`_harvest_phase_stderr`) folds into the artifact's
    `phases` (the r05 class: a killed child left `parsed` null)."""
    try:
        print("#R " + json.dumps(dict(RESULTS)), file=sys.stderr,
              flush=True)
    except Exception:
        pass

def _block_marker(marker) -> None:
    """Pace the dispatch queue: wait for a buffer captured one mark ago.
    Capture sites take a tiny SLICE of the state (`state["act"][:1]`) —
    a fresh buffer nothing ever donates, whose computation completes no
    earlier than the state it was cut from — because the state array
    itself is donated to a later fold on backends that honor
    donate_argnums (CPU jax does): blocking the raw array raised
    INVALID_ARGUMENT and killed the sliding phase on every CPU round,
    and skipping deleted markers instead would silently disable pacing
    on exactly those backends. The deleted-buffer tolerance below is a
    last-resort guard for races, not the mechanism."""
    if marker is None:
        return
    import jax

    try:
        deleted = getattr(marker, "is_deleted", None)
        if deleted is not None and deleted():
            return
        jax.block_until_ready(marker)
    except Exception as exc:
        # ONLY the donation race between the check and the block is
        # benign; a real device fault must propagate (the marker is the
        # in-flight bound — swallowing it would let the loop dispatch
        # unboundedly and measure client RAM, not the pipeline)
        msg = str(exc).lower()
        if "deleted" not in msg and "donated" not in msg:
            raise


# Phase T: saturated link; long windows amortize the boundary's device wait.
# 20 windows -> >=20 device-served boundary samples (r03 recorded only 4,
# too thin for a latency claim)
T_WINDOW_BATCHES = 64
T_PRE_ISSUE_AT = (48,)
T_WINDOWS = 20
T_BLOCK_EVERY = 16  # bound the dispatch queue (client buffers uploads)

# Phase L: paced at north-star load
L_TARGET_ROWS_S = 1_500_000
L_WINDOW_BATCHES = 35  # ~1.5s windows at the paced rate
L_PRE_ISSUE_AT = (25, 30)  # ~440ms / ~220ms leads
L_MIN_SAMPLES = 50
L_MAX_SECONDS = 150.0

SQL = (
    "SELECT deviceId, avg(temperature) AS avg_t, count(*) AS cnt, "
    "min(temperature) AS min_t, max(temperature) AS max_t "
    "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
)


def bench_rule_group(batches, kt_slots) -> None:
    """256 homogeneous rules (per-rule thresholds) as ONE vmapped device
    program — the TPU answer to the reference's shared-source fan-out
    benchmark (300 rules x 500 msg/s = 150k rule-msg/s on 2 cores,
    README.md:144-156). Prints a stderr metric line; the headline JSON line
    stays the single-rule bench."""
    import jax
    from ekuiper_tpu.parallel.multirule import BatchedGroupBy, build_rule_batch
    from ekuiper_tpu.sql.parser import parse_select

    n_rules = 256
    stmts = [
        parse_select(
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
            f"FROM demo WHERE temperature > {10.0 + 0.1 * r} "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        for r in range(n_rules)
    ]
    spec = build_rule_batch([f"r{r}" for r in range(n_rules)], stmts)
    gb = BatchedGroupBy(spec, capacity=kt_slots, micro_batch=BATCH_ROWS)
    state = gb.init_state()
    from ekuiper_tpu.ops.keytable import KeyTable

    kt = KeyTable(kt_slots)
    cols = [{"temperature": b.columns["temperature"]} for b in batches]
    # warmup compile (one program for all 256 rules)
    slots, _ = kt.encode_column(batches[0].columns["deviceId"])
    state = gb.fold(state, dict(cols[0]), slots)
    gb.finalize(state, kt.n_keys)
    jax.block_until_ready(state)
    rows = 0
    n = 0
    t0 = time.time()
    while time.time() - t0 < 10.0:
        # full per-batch host path: key encode runs every batch (shared
        # across all 256 rules — that IS the group win)
        slots, _ = kt.encode_column(batches[n % 4].columns["deviceId"])
        state = gb.fold(state, dict(cols[n % 4]), slots)
        rows += BATCH_ROWS
        n += 1
    outs, act = gb.finalize(state, kt.n_keys)  # one transfer for all rules
    elapsed = time.time() - t0
    assert outs[1].shape[0] == n_rules and np.all(act[0] >= act[-1])
    rule_rows = rows * n_rules / elapsed
    print(
        f"# 256-rule group: {rows:,} rows x {n_rules} rules in {elapsed:.2f}s"
        f" = {rule_rows:,.0f} rule-rows/s through one vmapped program"
        f" (reference fan-out baseline: 150,000 rule-msg/s)",
        file=sys.stderr,
    )
    record("homogeneous_256_vmapped", rule_rows_per_sec=rule_rows)


def _delivery_latency_line(issue_ts, deliver_ts) -> str:
    """issue→delivered stats for FIFO-paired async emissions. A delivery
    can legitimately be skipped (no active keys / empty projection); a
    skip would silently shift every later pair, so pairs are only trusted
    when the counts match — otherwise the skew is reported, not hidden."""
    k = min(len(issue_ts), len(deliver_ts))
    if not k:
        return "no triggers fired"
    skipped = len(issue_ts) - len(deliver_ts)
    e2e_ms = [(deliver_ts[i] - issue_ts[i][0]) * 1000 for i in range(k)]
    line = (f"issue→delivered p50={np.percentile(e2e_ms, 50):.0f}ms "
            f"p99={np.percentile(e2e_ms, 99):.0f}ms")
    if skipped > 0:
        line += f" (UNPAIRED: {skipped} skipped deliveries, stats skewed)"
    return line


def bench_sliding_percentile(batches, kt_slots) -> None:
    """BASELINE config #3: SLIDINGWINDOW percentile_approx over 10k keys on
    the device path — saturated ingest with sparse trigger rows (OVER WHEN),
    each emitting the exact (t-L, t] window via pane merge + edge refolds.
    Prints a stderr metric line."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select
    from ekuiper_tpu.utils import timex

    sql = ("SELECT deviceId, percentile_approx(temperature, 0.99) AS p99, "
           "count(*) AS c FROM demo GROUP BY deviceId, "
           "SLIDINGWINDOW(ss, 10) OVER (WHEN temperature > 44.5)")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "sliding bench rule must be device-eligible"
    node = FusedWindowAggNode(
        "slide", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=kt_slots, micro_batch=BATCH_ROWS,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    emits = []
    deliver_ts = []
    node.broadcast = lambda item: (emits.append(item),
                                   deliver_ts.append(time.time()))
    issue_ts = []
    orig_emit = node._emit_sliding

    def timed_emit(t):
        t0 = time.time()
        orig_emit(t)
        issue_ts.append((t0, (time.time() - t0) * 1000))

    node._emit_sliding = timed_emit

    def stamped(i, spike=False):
        b = batches[i % len(batches)]
        cols = b.columns
        if spike:  # one trigger row (>44.5 threshold): alert-style cadence
            t = cols["temperature"].copy()
            t[0] = 99.0
            cols = {"deviceId": cols["deviceId"], "temperature": t}
        return ColumnBatch(
            n=b.n, columns=cols,
            timestamps=np.full(b.n, timex.now_ms(), dtype=np.int64),
            emitter=b.emitter)

    # implementation-agnostic warmup: the node warms ITS trigger path —
    # ring advance/flip/query (+ the components_dyn fallback) under
    # slidingImpl=daba, fold_masked (the mask-only edge refold) under
    # refold — so neither round profiles or warms a dead kernel
    node._warmup()
    node.process(stamped(0))  # warm (vector+scalar folds, trigger path)
    node._emit_sliding(timex.now_ms())  # warm emission path
    node._drain_async_emits()
    jax.block_until_ready(node.state)
    print(f"# sliding implementation: {node.sliding_impl}",
          file=sys.stderr)
    # the sliding phase is WHERE the 865ms stalls lived (BENCH_r04) — run
    # it with dense device-timing sampling so kernel_split can decompose
    # every trigger's emission path (slidingring.query/advance/flip +
    # components_dyn on the DABA rounds; fold_masked / finalize_dyn /
    # components on refold rounds) into dispatch / compile /
    # device-compute / transfer — proving the finalize_dyn stall is gone
    # on the DABA path, not renamed. The probe starts AFTER warmup so
    # steady-state numbers aren't polluted by warmup compiles, but
    # mid-segment compiles (a real stall component) are counted
    from ekuiper_tpu.observability import kernwatch

    prior_sampling = kernwatch.set_sampling(hot=8, boundary=1)
    try:
        kernel_split = _kernel_split_probe()
        emits.clear()
        deliver_ts.clear()
        issue_ts.clear()
        rows = 0
        n = 0
        marker = None
        t0 = time.time()
        while time.time() - t0 < 12.0:
            node.process(stamped(n, spike=(n % 40 == 39)))
            rows += BATCH_ROWS
            n += 1
            if n % T_BLOCK_EVERY == 0:
                _block_marker(marker)
                marker = node.state["act"][:1]  # non-donated slice
        node._drain_async_emits()
        jax.block_until_ready(node.state)
        elapsed = time.time() - t0
        # trigger emissions deliver via the emit worker: report BOTH the fold
        # stall (time the trigger spends in the fold stream — the dispatch) and
        # the issue->delivered latency the sink observes
        if issue_ts:
            stall_ms = [d for _, d in issue_ts]
            lat = (f"fold stall p50={np.percentile(stall_ms, 50):.1f}ms "
                   f"max={max(stall_ms):.0f}ms; "
                   + _delivery_latency_line(issue_ts, deliver_ts))
        else:
            lat = "no triggers fired"
        print(
            f"# sliding percentile (10s window, 10k keys, device path): "
            f"{rows:,} rows in {elapsed:.2f}s ({rows / elapsed:,.0f} rows/s), "
            f"{len(issue_ts)} trigger emissions, {lat}",
            file=sys.stderr,
        )
        k = min(len(issue_ts), len(deliver_ts))
        e2e = [(deliver_ts[i] - issue_ts[i][0]) * 1000 for i in range(k)]
        record("sliding_saturated", rows_per_sec=rows / elapsed,
               triggers=len(issue_ts),
               sliding_impl=node.sliding_impl,
               fold_stall_p50_ms=float(np.percentile(
                   [d for _, d in issue_ts], 50)) if issue_ts else None,
               fold_stall_max_ms=float(max(d for _, d in issue_ts))
               if issue_ts else None,
               deliver_p50_ms=float(np.percentile(e2e, 50)) if k else None,
               # HEADLINE (tools/benchdiff.py): trigger→sink emit tail —
               # a sliding-latency regression gates ci_gate every round
               emit_p99_ms=float(np.percentile(e2e, 99)) if k else None,
               kernel_split=kernel_split(),
               jitcert=_jitcert_fields())
        # paced segment (phase-L analogue): at sustainable load the delivery
        # latency is what a sink actually observes — the saturated segment
        # above queues the finalize behind ~16 in-flight fold dispatches
        kernel_split = _kernel_split_probe()  # fresh deltas for this segment
        emits.clear()
        deliver_ts.clear()
        issue_ts.clear()
        interval = BATCH_ROWS / 1_000_000  # pace at 1M rows/s
        rows = 0
        n = 0
        t0 = time.time()
        while time.time() - t0 < 8.0:
            target = t0 + n * interval
            delay = target - time.time()
            if delay > 0:
                time.sleep(delay)
            node.process(stamped(n, spike=(n % 5 == 4)))
            rows += BATCH_ROWS
            n += 1
        node._drain_async_emits()
        jax.block_until_ready(node.state)
        elapsed = time.time() - t0
        print(
            f"# sliding percentile paced (1.0M rows/s): {rows:,} rows in "
            f"{elapsed:.2f}s ({rows / elapsed:,.0f} rows/s), {len(issue_ts)} "
            f"trigger emissions, "
            f"{_delivery_latency_line(issue_ts, deliver_ts)}",
            file=sys.stderr,
        )
        k = min(len(issue_ts), len(deliver_ts))
        e2e = [(deliver_ts[i] - issue_ts[i][0]) * 1000 for i in range(k)]
        record("sliding_paced", rows_per_sec=rows / elapsed,
               triggers=len(issue_ts),
               sliding_impl=node.sliding_impl,
               fold_stall_p50_ms=float(np.percentile(
                   [d for _, d in issue_ts], 50)) if issue_ts else None,
               fold_stall_max_ms=float(max(d for _, d in issue_ts))
               if issue_ts else None,
               deliver_p50_ms=float(np.percentile(e2e, 50)) if k else None,
               # deliver_p99_ms keeps r01-r05 trajectory continuity and
               # stays report-only; emit_p99_ms is the SAME quantity under
               # the gated name (HEADLINE twin of sliding_saturated)
               deliver_p99_ms=float(np.percentile(e2e, 99)) if k else None,
               emit_p99_ms=float(np.percentile(e2e, 99)) if k else None,
               kernel_split=kernel_split(),
               jitcert=_jitcert_fields())
    finally:
        # dense sampling must not leak into later phases even if a
        # segment dies mid-run
        kernwatch.set_sampling(**prior_sampling)


def bench_hopping_heavy_hitters(batches, kt_slots) -> None:
    """BASELINE config #2: HOPPINGWINDOW GROUP BY device_id over 10k
    sensors with the count-min heavy-hitters UDF on the fused device path
    (linear group-testing sketch, device-side candidate recovery + top-k).
    Prints a stderr metric line."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    sql = ("SELECT deviceId, heavy_hitters(code, 3) AS top, count(*) AS c "
           "FROM demo GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "hh bench rule must be device-eligible"
    node = FusedWindowAggNode(
        "hh", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=kt_slots, micro_batch=BATCH_ROWS,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    emits = []  # (ColumnBatch, emit_info) from the async worker
    node.broadcast = lambda item: emits.append((item, node.last_emit_info))
    # skewed event codes: 3 heavy values + a 2000-distinct tail
    rng = np.random.default_rng(7)
    hh_batches = []
    for b in batches:
        p = rng.random(b.n)
        code = np.where(
            p < 0.35, 7, np.where(p < 0.55, 13, np.where(
                p < 0.70, 99, rng.integers(100, 2100, b.n)))).astype(np.int64)
        hh_batches.append(ColumnBatch(
            n=b.n, columns={"deviceId": b.columns["deviceId"], "code": code},
            timestamps=b.timestamps, emitter=b.emitter))

    def boundary(end_ms):
        # async hh boundary: dispatch + rotate, delivery on the worker
        t0 = time.time()
        node._emit_hh_async(WindowRange(end_ms - 10_000, end_ms))
        ms = (time.time() - t0) * 1000
        node.cur_pane = (node.cur_pane + 1) % node.n_panes
        node.state = node.gb.reset_pane(node.state, node.cur_pane)
        return ms

    node.process(hh_batches[0])  # warm fold
    boundary(5_000)  # warm compact hh finalize
    node._drain_async_emits()
    jax.block_until_ready(node.state)
    emits.clear()
    rows = 0
    n = 0
    emit_ms = []
    # paced at the north-star load: boundary fetches queue FIFO behind
    # in-flight folds, so emit latency is only meaningful when the link
    # has headroom (same methodology as phase L)
    interval = BATCH_ROWS / 1_100_000
    t0 = time.time()
    while time.time() - t0 < 10.0:
        target = t0 + n * interval
        delay = target - time.time()
        if delay > 0:
            time.sleep(delay)
        node.process(hh_batches[n % len(hh_batches)])
        rows += BATCH_ROWS
        n += 1
        if n % 16 == 0:  # one hop boundary per ~16 batches (~1s)
            emit_ms.append(boundary(5_000 * (n // 16 + 1)))
    node._drain_async_emits()
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0
    # sanity: the heaviest value must lead every emitted top list
    top_col = emits[0][0].columns["top"]
    assert top_col[0][0]["value"] == 7, f"bad top list: {top_col[0]}"
    deliv = [i["fetch_ms"] for _, i in emits if i]
    lat = (f"boundary dispatch p50={np.percentile(emit_ms, 50):.1f}ms, "
           f"issue→delivered p50={np.percentile(deliv, 50):.0f}ms"
           if emit_ms and deliv else "no boundaries")
    print(
        f"# hopping heavy-hitters (10s/5s, 10k keys, count-min device "
        f"sketch): {rows:,} rows in {elapsed:.2f}s "
        f"({rows / elapsed:,.0f} rows/s), {len(emits)} window emits, {lat}",
        file=sys.stderr,
    )
    record("hopping_heavy_hitters", rows_per_sec=rows / elapsed,
           emits=len(emits),
           dispatch_p50_ms=float(np.percentile(emit_ms, 50))
           if emit_ms else None,
           deliver_p50_ms=float(np.percentile(deliv, 50))
           if deliv else None)


def bench_countwindow_hll_1m(kt_slots) -> None:
    """BASELINE config #4: COUNTWINDOW HyperLogLog distinct-count with 1M-key
    GROUP BY cardinality — stresses KeyTable growth to >=1M slots, on-device
    state doubling, and the wide-register HLL fold at HBM scale.
    Prints a stderr metric line."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    n_keys_total = 1_000_000
    window_rows = 2_097_152  # 32 batches per count window
    sql = (f"SELECT deviceId, hll(uid) AS uniq FROM demo "
           f"GROUP BY deviceId, COUNTWINDOW({window_rows})")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "hll bench rule must be device-eligible"
    # pre-sized hash-slot table (SURVEY §7 hard-part c): growing 16k->1M
    # re-specializes the fold executable per doubling (~6 recompiles), so a
    # known-cardinality rule sizes up front; the grow path itself is covered
    # by tests (test_groupby.py grow + test_heavy_hitters device grows)
    node = FusedWindowAggNode(
        "hll1m", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=1 << 20, micro_batch=BATCH_ROWS,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    emits = []  # (ColumnBatch, emit_info) pairs from the async worker
    node.broadcast = lambda item: emits.append((item, node.last_emit_info))
    rng = np.random.default_rng(11)
    ids = np.array([f"dev_{i}" for i in range(n_keys_total)], dtype=np.object_)
    # one full count-window of DISTINCT batches (32 x 64k draws ≈ 878k
    # distinct keys of the 1M id space) — recycling fewer batches would cap
    # the key cardinality the bench claims to stress
    hll_batches = []
    for _ in range(window_rows // BATCH_ROWS):
        idx = rng.integers(0, n_keys_total, BATCH_ROWS)
        hll_batches.append(ColumnBatch(
            n=BATCH_ROWS,
            columns={"deviceId": ids[idx],
                     "uid": rng.integers(0, 5_000_000, BATCH_ROWS)},
            timestamps=np.zeros(BATCH_ROWS, dtype=np.int64), emitter="demo"))
    node.process(hll_batches[0])  # warm fold (1M-slot executable)
    node._emit(WindowRange(0, 0))  # warm finalize + emit tail executables
    node.state = node.gb.reset_pane(node.state, 0)
    node.kt.clear()
    node._rows_in_window = 0
    jax.block_until_ready(node.state)
    emits.clear()

    def run_windows(k: int):
        rows = n = 0
        marker = None
        want = len(emits) + k
        t0 = time.time()
        while time.time() - t0 < 60.0 and len(emits) < want:
            node.process(hll_batches[n % len(hll_batches)])
            rows += BATCH_ROWS
            n += 1
            if n % T_BLOCK_EVERY == 0:
                _block_marker(marker)
                marker = node.state["act"][:1]  # non-donated slice
        node._drain_async_emits()
        jax.block_until_ready(node.state)
        return rows, time.time() - t0

    # window 1: cold dictionary — every batch inserts new keys
    cold_rows, cold_s = run_windows(1)
    # windows 2-3: steady state — keys known, pure fold + async emit cadence
    warm_rows, warm_s = run_windows(2)
    state_gb = sum(
        np.prod(v.shape) * 4 for v in node.state.values()) / 1e9
    fetch_ms = [i["fetch_ms"] for _, i in emits if i]
    lat = (f"async emit issue→delivered p50={np.percentile(fetch_ms, 50):.0f}ms"
           if fetch_ms else "no window completed")
    # sanity on the last emit: ~full key coverage, sane per-key estimates
    if emits:
        uniq = emits[-1][0].columns["uniq"]
        assert len(uniq) > 800_000 and 0 < np.median(uniq) < 50, \
            f"bad hll emit: {len(uniq):,} groups, median {np.median(uniq)}"
    print(
        f"# countwindow hll @1M keys: steady {warm_rows:,} rows in "
        f"{warm_s:.2f}s ({warm_rows / max(warm_s, 1e-9):,.0f} rows/s; "
        f"cold-dictionary window {cold_rows / max(cold_s, 1e-9):,.0f} "
        f"rows/s), keys={node.kt.n_keys:,} in {node.gb.capacity:,} device "
        f"slots, state={state_gb:.2f}GB, {len(emits)} count-window "
        f"emits (device-async), {lat}",
        file=sys.stderr,
    )
    record("countwindow_hll_1m",
           steady_rows_per_sec=warm_rows / max(warm_s, 1e-9),
           cold_rows_per_sec=cold_rows / max(cold_s, 1e-9),
           keys=node.kt.n_keys, slots=node.gb.capacity,
           state_gb=round(state_gb, 2), emits=len(emits),
           deliver_p50_ms=float(np.percentile(fetch_ms, 50))
           if fetch_ms else None)

    # capacity headroom (VERDICT r4 weak #6): push past the pre-sized 1M
    # slots to ~1.5M-key cardinality — KeyTable doubles and the device
    # state grows MID-STREAM (one fold re-specialization at the new
    # capacity); the window must complete with no overflow and full key
    # coverage. Reported separately: the one-off grow compile is a
    # capacity event, not steady-state throughput.
    grow_ids = np.array(
        [f"dev_{i}" for i in range(1_500_000)], dtype=np.object_)
    slots_before = node.gb.capacity
    emits_before = len(emits)
    grow_batches = []
    # TWO full windows: async emit timing can leave a partial window open
    # entering this segment, so only the second window's emit is guaranteed
    # to cover a pure grow-space row range
    for _ in range(2 * (window_rows // BATCH_ROWS)):
        idx = rng.integers(0, 1_500_000, BATCH_ROWS)
        grow_batches.append(ColumnBatch(
            n=BATCH_ROWS,
            columns={"deviceId": grow_ids[idx],
                     "uid": rng.integers(0, 5_000_000, BATCH_ROWS)},
            timestamps=np.zeros(BATCH_ROWS, dtype=np.int64),
            emitter="demo"))
    t0 = time.time()
    for b in grow_batches:
        node.process(b)
    node._drain_async_emits()
    jax.block_until_ready(node.state)
    grow_s = time.time() - t0
    assert node.kt.n_keys > 1_100_000, \
        f"grow segment covered only {node.kt.n_keys:,} keys"
    assert node.gb.capacity > slots_before, "state never grew past 1M slots"
    assert node.kt.n_keys <= node.gb.capacity, "slot-table overflow"
    assert len(emits) > emits_before, "grow window never emitted"
    uniq = emits[-1][0].columns["uniq"]
    assert len(uniq) > 1_100_000, f"grow emit covered {len(uniq):,} groups"
    grow_rows = 2 * window_rows
    print(
        f"# hll capacity grow: {node.kt.n_keys:,} keys grew device slots "
        f"{slots_before:,} -> {node.gb.capacity:,} mid-stream; "
        f"{grow_rows:,} rows in {grow_s:.2f}s "
        f"({grow_rows / grow_s:,.0f} rows/s incl. the one-off grow "
        f"recompile), emit covered {len(uniq):,} groups",
        file=sys.stderr,
    )
    record("hll_capacity_grow", keys=node.kt.n_keys,
           slots=node.gb.capacity, slots_before=slots_before,
           rows_per_sec_incl_recompile=grow_rows / grow_s)


def bench_key_cardinality(kt_slots, budget_s: float = 240.0) -> None:
    """ISSUE 13 phase: distinct-key cardinality 1M -> 10M (attempted)
    under a FIXED HBM budget, with the tiered key state
    (ops/tierstore.py) absorbing the overflow — a hot core keeps its
    dense device slots while a marching cold tail demotes to the host
    arena and its slots recycle. Records rows/s, emit p99, spill/promote
    rates, and the device-slot ceiling per cardinality checkpoint, plus
    a sub-budget byte-parity segment vs the untiered path."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import Trigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    from ekuiper_tpu.ops.tierstore import env_hbm_budget_mb

    budget_mb = env_hbm_budget_mb() or 64.0
    sql = ("SELECT deviceId, sum(v) AS s, count(*) AS c FROM demo "
           "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None

    def mk(tier_mb, capacity):
        n = FusedWindowAggNode(
            "keycard", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=capacity, micro_batch=BATCH_ROWS,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            emit_columnar=True, prefinalize_lead_ms=0,
            tier_budget_mb=tier_mb, tier_scan_ms=1)
        n.state = n.gb.init_state()
        return n

    # ---- sub-budget byte-parity segment: tier ENGAGED but cardinality
    # below the hot target — emissions must be byte-identical to the
    # untiered path (acceptance gate)
    par_t, par_p = mk(0.01, 4096), mk(0.0, 4096)
    pe_t, pe_p = [], []
    par_t.broadcast = lambda item: pe_t.append(item)
    par_p.broadcast = lambda item: pe_p.append(item)
    rng = np.random.default_rng(13)
    par_ids = np.array([f"p{i}" for i in range(1000)], dtype=np.object_)
    for w in range(3):
        idx = rng.integers(0, 1000, 8192)
        vals = rng.normal(50, 10, 8192)
        for n in (par_t, par_p):
            n.process(ColumnBatch(
                n=8192, columns={"deviceId": par_ids[idx].copy(),
                                 "v": vals.copy()},
                timestamps=np.zeros(8192, dtype=np.int64),
                emitter="demo"))
            n.on_trigger(Trigger(ts=(w + 1) * 1000))
    for n in (par_t, par_p):
        n._drain_async_emits()

    def _rows(emits):
        out = []
        for cb in emits:
            cols = getattr(cb, "columns", None)
            if cols is None:
                continue
            out.append({k: np.asarray(v).tobytes()
                        if np.asarray(v).dtype != np.object_
                        else tuple(v) for k, v in sorted(cols.items())})
        return out

    parity = (par_t.tier is not None and _rows(pe_t) == _rows(pe_p))

    # ---- cardinality sweep under the fixed budget
    node = mk(budget_mb, 1 << 20)
    assert node.tier is not None, "tier must engage for the sweep"
    emits = []
    t_bound = [0.0]
    node.broadcast = lambda item: emits.append(
        (time.perf_counter() - t_bound[0]) * 1000.0)
    hot_n = 1 << 18
    fresh_per_batch = 2048
    hot_ids = np.array([f"hot_{i}" for i in range(hot_n)],
                       dtype=np.object_)
    targets = [1_000_000, 3_000_000, 10_000_000]
    checkpoints = {}
    fresh_cursor = 0
    rows = 0
    wn = 0
    t0 = time.time()
    deadline = t0 + budget_s
    seg_t0, seg_rows = t0, 0
    marker = None
    nb = 0
    while targets and time.time() < deadline:
        idx = rng.integers(0, hot_n, BATCH_ROWS - fresh_per_batch)
        fresh = np.array(
            [f"k{fresh_cursor + i}" for i in range(fresh_per_batch)],
            dtype=np.object_)
        fresh_cursor += fresh_per_batch
        ids = np.concatenate([hot_ids[idx], fresh])
        node.process(ColumnBatch(
            n=BATCH_ROWS,
            columns={"deviceId": ids,
                     "v": rng.normal(50, 10, BATCH_ROWS)},
            timestamps=np.zeros(BATCH_ROWS, dtype=np.int64),
            emitter="demo"))
        rows += BATCH_ROWS
        seg_rows += BATCH_ROWS
        nb += 1
        if nb % 4 == 0:
            wn += 1
            t_bound[0] = time.perf_counter()
            node.on_trigger(Trigger(ts=wn * 1000))
            _block_marker(marker)
            marker = node.state["act"][:1]
        total_distinct = hot_n + fresh_cursor
        if total_distinct >= targets[0]:
            node._drain_async_emits()
            jax.block_until_ready(node.state)
            seg_s = max(time.time() - seg_t0, 1e-9)
            t = node.tier
            checkpoints[str(targets[0])] = {
                "rows_per_sec": seg_rows / seg_s,
                "emit_p99_ms": (float(np.percentile(emits, 99))
                                if emits else None),
                "device_slots": node.gb.capacity,
                "resident_cold": len(t.store),
                "tier_host_mb": round(t.store.nbytes() / 2**20, 1),
                "demoted_total": t.demoted_total,
                "promoted_total": t.promoted_total,
                "spill_per_sec": round(t.demoted_total / seg_s, 1),
            }
            targets.pop(0)
            seg_t0, seg_rows = time.time(), 0
    node._drain_async_emits()
    jax.block_until_ready(node.state)
    total_s = time.time() - t0
    t = node.tier
    keys_reached = hot_n + fresh_cursor
    dev_state_mb = sum(
        int(getattr(a, "nbytes", 0) or 0)
        for a in node.state.values()) / 2**20
    print(
        f"# key_cardinality: {keys_reached:,} distinct keys attempted "
        f"({len(checkpoints)} checkpoints) under {budget_mb:.0f}MB budget "
        f"in {total_s:.1f}s — {rows / max(total_s, 1e-9):,.0f} rows/s, "
        f"device slots {node.gb.capacity:,} ({dev_state_mb:.1f}MB state), "
        f"{t.demoted_total:,} demoted / {t.promoted_total:,} promoted / "
        f"{t.recycled_total:,} recycled, cold-resident {len(t.store):,} "
        f"({t.store.nbytes() / 2**20:.1f}MB host), parity={parity}",
        file=sys.stderr,
    )
    record("key_cardinality",
           keys_reached=keys_reached,
           rows_per_sec=rows / max(total_s, 1e-9),
           emit_p99_ms=(float(np.percentile(emits, 99))
                        if emits else None),
           device_slots=node.gb.capacity,
           device_state_mb=round(dev_state_mb, 1),
           budget_mb=budget_mb,
           demoted_total=t.demoted_total,
           promoted_total=t.promoted_total,
           recycled_total=t.recycled_total,
           resident_cold=len(t.store),
           tier_host_mb=round(t.store.nbytes() / 2**20, 1),
           subbudget_parity=bool(parity),
           checkpoints=checkpoints)
    assert parity, "tiered emissions diverged from untiered at " \
                   "sub-budget cardinality"


def _harvest_phase_stderr(stderr, tag: str) -> bool:
    """Re-parse a phase subprocess's stderr: merge its `#R ` record lines
    into RESULTS (so PARTIAL progress survives a timeout/kill) and relay
    its human `# ` lines. Returns True when the phase's own metric line
    made it out."""
    if isinstance(stderr, bytes):
        stderr = stderr.decode(errors="replace")
    lines = (stderr or "").splitlines()
    for line in lines:
        if line.startswith("#R "):
            try:
                RESULTS.update(json.loads(line[3:]))
            except ValueError:
                pass
        elif line.startswith("# "):
            print(line, file=sys.stderr)
    return any(line.startswith(f"# {tag}") for line in lines)


def _run_isolated(func: str, tag: str, timeout: float = 900) -> None:
    """Run a bench phase in a subprocess: phases that open+close threaded
    topos against the tunneled TPU can intermittently crash native client
    teardown at exit — isolation keeps the headline bench process alive.

    The subprocess rides the same per-phase watchdog discipline as the
    in-process phases (r05 post-mortem: _full_pipe_main got the whole 900s
    driver budget, so the DRIVER timed out first and nothing was
    recorded): its timeout is capped by the remaining global budget, the
    child arms its own watchdog (BENCH_CHILD_BUDGET_S) so it dies with
    its partial records flushed, and a parent-side TimeoutExpired still
    harvests whatever `#R ` lines the child printed before the kill."""
    import subprocess

    timeout = phase_budget(timeout, reserve_s=20.0,
                           later_floor_s=later_floor(tag))
    if timeout < 30.0:
        print(f"# {tag}: skipped — {_remaining_s():.0f}s of global budget "
              "left", file=sys.stderr)
        RESULTS[f"{tag}_error"] = "skipped: global budget exhausted"
        return
    env = dict(os.environ)
    env["BENCH_CHILD_BUDGET_S"] = str(int(max(timeout - 15.0, 15.0)))
    try:
        r = subprocess.run(
            [sys.executable, "-c", f"import bench; bench.{func}()"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, timeout=timeout, text=True, env=env)
        if not _harvest_phase_stderr(r.stderr, tag):
            print(f"# {tag}: subprocess failed rc={r.returncode}",
                  file=sys.stderr)
            RESULTS.setdefault(f"{tag}_error", f"subprocess rc={r.returncode}")
    except subprocess.TimeoutExpired as exc:
        # partial per-phase records STILL land in the artifact
        _harvest_phase_stderr(exc.stderr, tag)
        print(f"# {tag}: subprocess timed out after {timeout:.0f}s "
              "(partial records harvested)", file=sys.stderr)
        RESULTS[f"{tag}_error"] = f"timeout after {timeout:.0f}s"
    except Exception as exc:
        print(f"# {tag}: {exc}", file=sys.stderr)
        RESULTS[f"{tag}_error"] = str(exc)


def bench_churn_soak() -> None:
    _run_isolated("_churn_soak_main", "churn_soak", timeout=600)


def _churn_soak_main() -> None:
    """Sustained-churn QoS soak (ISSUE 9): an in-process engine under
    rule create/update/delete churn, hot-key skew shifts, backpressure
    waves, and a mid-storm kill/restore — while the health plane +
    runtime/control.py close the loop. Green means: every dropped row
    carries a taxonomy reason, the breaching victim rule is shed by qos
    class while the healthy workload rules hold their emit p99, and
    admission rejections come back structured (reason + price).

    Runs on CPU jax (forced below): the phase measures the CONTROL
    plane, not device throughput, and the parent bench process may
    still own the TPU client."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    # fast control cadence: both intervals are read at module import,
    # which happens below — this subprocess is fresh
    # SUB-SECOND health cadence, below the 1s workload window: the burn
    # windows are sample-count-aware now (observability/health.py
    # _weighted_burn + observation-indexed decay), so a tick landing
    # between two window emissions holds its evidence instead of
    # decaying to zero and flapping the verdict — the 1500ms pin this
    # phase used to need is exactly the flap this soak now regresses
    os.environ.setdefault("KUIPER_HEALTH_INTERVAL_MS", "900")
    os.environ.setdefault("KUIPER_CONTROL_INTERVAL_MS", "500")
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET_S", "0") or 0)
    dog = PhaseWatchdog()
    if child_budget > 0:
        dog.arm("churn_soak_child", child_budget)
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.server.rest import RestApi
    from ekuiper_tpu.store import kv
    from tools.chaos import ChaosHarness

    mem.reset()
    api = RestApi(kv.get_store())
    # pool=2: the device-path rules ride POOLED sources so the storm
    # drives the decode pool + ingest ring end-to-end and the autosize
    # actuator has something real to resize (inline memory sources are
    # contractually never converted — the old soak could not see a
    # single autosize event)
    h = ChaosHarness(api, pool=2)
    h.ensure_stream()
    work = h.workload_rules(4, window_s=1, slo_p99_ms=5000)
    victim = h.victim_rule()
    ck = h.checkpoint_rule()
    # soak window: bounded by the child budget minus teardown headroom
    soak_s = 70.0
    if child_budget > 0:
        soak_s = min(soak_s, max(child_budget - 25.0, 20.0))
    t0 = time.time()
    deadline = t0 + soak_s
    kill_at = t0 + soak_s * 0.55
    next_wave = t0 + 10.0
    next_progress = t0 + 10.0
    hot, rows = 0, 0
    last_shift = t0
    recover_stats: dict = {}
    killed = False
    # fleet observatory duty cycle under churn: observe() + full-scrape
    # timeline snapshot at the production default 5s cadence inside the
    # soak, so observatory_overhead_pct is measured against a live
    # 25-rule fleet (the rules here are single-chip, so skew/collective
    # read ~0 — the leaves exist report-only for trajectory tracking)
    from ekuiper_tpu.observability import meshwatch as _meshwatch
    obs_s = 0.0
    next_obs = t0 + 5.0
    # offered load calibrated to keep the HEALTHY fleet comfortably
    # inside its SLO on one CPU: the soak demonstrates per-rule
    # isolation (victim shed, workload holds), not saturation collapse
    # — the waves are what push individual rules over
    while time.time() < deadline:
        h.churn_step(target_live=25)
        h.publish_skew(1000, hot_key=hot)
        rows += 1000
        now = time.time()
        if now - last_shift >= 7.0:
            # ONE discrete skew shift per interval — a per-iteration
            # modulo test would re-shift ~30x during each 7th second
            # and turn the hot key into uniform noise
            hot = (hot + 31) % 256
            last_shift = now
        if now >= next_wave:
            h.backpressure_wave(8_000)
            rows += 8_000
            next_wave = now + 10.0
        if not killed and now >= kill_at:
            # checkpoint, then crash — recovery must come from the
            # barrier snapshot, not a graceful stop-time save
            rs = api.rules.state(ck)
            if rs is not None and rs.topo is not None:
                rs.topo.trigger_checkpoint()
                time.sleep(0.5)
            running = h.hard_kill()
            recover_stats = h.recover(running)
            killed = True
        if now >= next_obs:
            # thread CPU time, not wall: on a saturated box a wall
            # clock mostly measures GIL contention with the workload,
            # not what the observatory itself costs
            ot = time.thread_time()
            _meshwatch.observe()
            if api.timeline is not None:
                api.timeline.snapshot()
            obs_s += time.thread_time() - ot
            next_obs = now + 5.0
        if now >= next_progress:
            # partial progress survives a watchdog/timeout kill as a
            # harvested `#R ` line (the r05 rc=124 class)
            s = h.summary()
            record("churn_soak_progress",
                   elapsed_s=now - t0, rows_published=rows,
                   created=s["churn"]["created"],
                   deleted=s["churn"]["deleted"],
                   live_rules=s["live_rules"],
                   shed_rows=sum(
                       int(v) for v in (s.get("shed_totals") or {})
                       .values()),
                   unexplained=len(s["unexplained_drops"]))
            next_progress = now + 10.0
        time.sleep(0.03)
    # structured-admission probe: under a tight fold budget a fat device
    # rule must come back 429 with reason + price, not an exception
    os.environ["KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S"] = "1"
    try:
        code, out = api.dispatch("POST", "/rules", {
            "id": "chaos_fat",
            "sql": ("SELECT deviceId, avg(v) AS a, min(v) AS mn, "
                    "max(v) AS mx FROM chaos GROUP BY deviceId, "
                    "TUMBLINGWINDOW(ss, 5)"),
            "actions": [{"nop": {}}],
            "options": {"sharedFold": False}}, {})
        adm = (out or {}).get("admission") or {}
        admission_structured = (code == 429 and bool(adm.get("reason"))
                               and "fold_us_per_s" in (adm.get("price")
                                                       or {}))
    finally:
        del os.environ["KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S"]
    elapsed = time.time() - t0
    # settle, then judge
    time.sleep(1.0)
    s = h.summary()
    p99 = h.e2e_p99_ms(work)
    victim_shed = sum(n for (rid, qos), n
                      in (api.qos_controller.shed_totals().items())
                      if rid == victim and qos == "low")
    soak_p99 = max(p99.values()) if p99 else float("nan")
    workload_ok = bool(p99) and all(v <= 5000.0 for v in p99.values())
    mrep = _meshwatch.observe()
    msplit = _meshwatch.collective_split()
    soak_skew = max((e["skew_ratio"] or 0.0 for e in mrep.values()),
                    default=0.0)
    mcoll = sorted(v["collective_us"] / 1000.0
                   for (op, _), v in msplit.items() if "fold" in str(op))
    print(f"# churn_soak: {rows:,} rows over {elapsed:.1f}s; "
          f"churn {s['churn']}; live={s['live_rules']}; "
          f"workload p99 {p99}; victim shed {victim_shed} rows; "
          f"shed totals {s.get('shed_totals')}; "
          f"victim health "
          f"{(api.health_evaluator.verdicts().get(victim) or {}).get('state')}; "
          f"admission {s.get('admission')}; "
          f"unexplained drops {s['unexplained_drops']}; "
          f"recover {recover_stats}", file=sys.stderr)
    record("churn_soak",
           soak_p99_ms=soak_p99,
           rows_published=rows,
           rules_created=s["churn"]["created"],
           rules_updated=s["churn"]["updated"],
           rules_deleted=s["churn"]["deleted"],
           admission_rejects=(s.get("admission") or {}).get("reject", 0),
           admission_queued=(s.get("admission") or {}).get("queue", 0),
           victim_shed_rows=victim_shed,
           victim_shed_ok=victim_shed > 0,
           workload_slo_ok=workload_ok,
           unexplained_drop_rules=len(s["unexplained_drops"]),
           zero_unexplained=not s["unexplained_drops"],
           admission_structured=admission_structured,
           skew_ratio=soak_skew,
           collective_ms_p50=(mcoll[len(mcoll) // 2] if mcoll else 0.0),
           observatory_overhead_pct=(100.0 * obs_s / elapsed
                                     if elapsed > 0 else 0.0),
           recovered=recover_stats.get("recovered", 0),
           recover_expected=recover_stats.get("expected", 0),
           pooled_sources=True,
           autosize_events=s.get("autosize_events", 0),
           # the actions themselves (node, grow/shrink, applied sizes):
           # the evidence the autosize path actually ran end-to-end
           autosize_actions=[
               {k: v for k, v in a.items() if k != "ts_ms"}
               for a in ((api.qos_controller.diagnostics()
                          .get("autosize") or {}).get("recent") or [])
           ][-8:],
           # churn keeps re-planning rules over the same certified
           # signature set: compile_total staying flat (vs rules_created
           # growing) is the AOT cache's zero-compile-churn claim
           compile_total=_compile_total(),
           aot=_aot_fields())
    dog.disarm()
    # daemon node threads + live jax state can segfault interpreter
    # teardown; the records are flushed — exit hard (kuiperdiag
    # --smoke precedent)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def bench_multichip_full_pipe() -> None:
    _run_isolated("_multichip_full_pipe_main", "multichip_full_pipe",
                  timeout=600)


def _multichip_full_pipe_main() -> None:
    """Multi-chip sharded serving phase (ISSUE 15): the saturated
    tumbling full pipe (json bytes → decode pool → fused window) run
    twice through the REAL planned topo — single-chip, then key-range
    sharded across an N-device mesh (`KUIPER_MESH`, planner
    `shards=auto`) — recording rows/s for both, the scaling ratio,
    per-shard fold rows, emit p99, a direct-kernel window-parity check,
    and jitcert.clean. `phases.multichip_full_pipe.rows_per_sec` gates
    in benchdiff's HEADLINE every round, replacing the dryrun.

    Devices: real chips when the host exposes >= BENCH_MULTICHIP_DEVICES
    of them; otherwise the CPU host-device emulation CI uses
    (`--xla_force_host_platform_device_count`). Near-linear scaling is a
    HARDWARE criterion — virtual CPU devices share the host's cores, so
    the CPU artifact records the ratio without judging it."""
    import json as _json

    n_dev = int(os.environ.get("BENCH_MULTICHIP_DEVICES", "8") or 8)
    if os.environ.get("KUIPER_BENCH_MULTICHIP_TPU", "0") != "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={n_dev}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax

    if os.environ.get("KUIPER_BENCH_MULTICHIP_TPU", "0") != "1":
        jax.config.update("jax_platforms", "cpu")
    n_dev = min(n_dev, len(jax.devices()))
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET_S", "0") or 0)
    dog = PhaseWatchdog()
    if child_budget > 0:
        dog.arm("multichip_child", child_budget)
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.observability import jitcert
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    on_tpu = os.environ.get("KUIPER_BENCH_MULTICHIP_TPU", "0") == "1"
    # CPU host-device emulation pays every shard's fold on the same
    # shared cores, so the full-size workload cannot finish two legs +
    # parity inside the phase floor (BENCH_r05: rc=124 with parsed null
    # — the child outlived the whole driver budget with nothing
    # recorded). Shrink rows, key universe, and per-fold state for the
    # emulated run; real chips keep the full-size workload.
    # (universe ~85% of the slot table so the key-range partition still
    # engages nearly every shard of the virtual mesh)
    key_universe = N_DEVICES if on_tpu else 3_500
    drain_rows = 2048 if on_tpu else 1024
    mb_rows = 16384 if on_tpu else 8192
    slots = 16384 if on_tpu else 4096
    rng = np.random.default_rng(29)
    drains = []
    for _ in range(8):
        drains.append([
            _json.dumps({
                "deviceId": f"dev_{rng.integers(0, key_universe)}",
                "temperature": round(float(rng.normal(20, 5)), 2),
            }).encode()
            for _ in range(drain_rows)
        ])

    seg_s = 8.0
    if child_budget > 0:
        seg_s = min(seg_s, max((child_budget - 60.0) / 2.0, 3.0))
    # per-leg deadline: each leg (plan + compile + warm + timed segment)
    # gets its share of the child budget; a leg that cannot start in
    # time is dropped with the partial record already emitted
    leg_deadline = (time.time() + child_budget - 20.0
                    if child_budget > 0 else float("inf"))

    def run_leg(shards: str, tag: str):
        """Plan + open one rule, saturate it for seg_s, return metrics."""
        mem.reset()
        store = kv.get_store()
        try:
            StreamProcessor(store).exec_stmt(
                'CREATE STREAM pipe_mc (deviceId STRING, temperature '
                'FLOAT) WITH (DATASOURCE="topic/pipe_mc", TYPE="memory", '
                'FORMAT="JSON")')
        except Exception:
            pass
        rule = RuleDef(
            id=f"mc_{tag}", sql=(
                "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
                "FROM pipe_mc GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)"),
            actions=[{"nop": {}}],
            options={"bufferLength": 64, "micro_batch_rows": mb_rows,
                     "micro_batch_linger_ms": 50, "key_slots": slots,
                     "decodePoolSize": 2, "ingestRingDepth": 2,
                     "sharedFold": False,
                     "planOptimizeStrategy": {"shards": shards}})
        topo = plan_rule(rule, store)
        fused = next(n for n in topo.ops
                     if type(n).__name__ == "FusedWindowAggNode")
        topo.open()
        src = (topo.sources[0] if topo.sources
               else topo._live_shared[0][0].source)
        # fleet observatory duty cycle rides the sharded leg: observe()
        # + timeline snapshot at a 1s cadence inside the timed segment,
        # so observatory_overhead is the measured fraction of fold wall
        # time the observatory costs (budget: <1%)
        fleetobs = None
        if shards != "off":
            import shutil as _shutil
            import tempfile as _tempfile

            from ekuiper_tpu.observability import meshwatch
            from ekuiper_tpu.observability import timeline as _tl_mod

            def _scrape() -> str:
                fam: list = []
                meshwatch.render_prometheus(fam, lambda s: s)
                return "\n".join(fam) + "\n"

            _tl_dir = _tempfile.mkdtemp(prefix="bench_mc_timeline_")
            fleetobs = (meshwatch,
                        _tl_mod.Timeline(scrape_fn=_scrape,
                                         base_dir=_tl_dir,
                                         interval_ms=0),
                        _tl_dir, _shutil)
            meshwatch.observe()  # baseline the skew window
        obs_s = 0.0
        try:
            # warm: compile the fold executables before the timed segment
            for d in drains:
                src.ingest(d)
            topo.wait_idle(30.0)
            topo.e2e_hist.snapshot_and_decay(0.0)
            rows = 0
            t0 = time.time()
            next_obs = t0 + 1.0
            n = 0
            while time.time() - t0 < seg_s:
                src.ingest(drains[n % len(drains)])
                rows += drain_rows
                n += 1
                if fleetobs is not None and time.time() >= next_obs:
                    # thread CPU time: wall would mostly count GIL
                    # waits behind the fold workers, not the observatory
                    ot = time.thread_time()
                    fleetobs[0].observe()
                    fleetobs[1].snapshot()
                    obs_s += time.thread_time() - ot
                    next_obs = time.time() + 1.0
                bp_deadline = time.time() + 60
                while fused.inq.qsize() > 8:
                    time.sleep(0.002)
                    if time.time() > bp_deadline:
                        raise RuntimeError(
                            "multichip: fused queue stuck >60s")
            topo.wait_idle(timeout=30.0)
            elapsed = time.time() - t0
            e2e = _e2e_fields(topo)
            shard_stats = (fused.gb.shard_stats(fused.state)
                           if hasattr(fused.gb, "shard_stats") else [])
            skew_ratio = 0.0
            coll_p50 = 0.0
            if fleetobs is not None:
                ot = time.thread_time()
                rep = fleetobs[0].observe()
                split = fleetobs[0].collective_split()
                fleetobs[1].snapshot()
                obs_s += time.thread_time() - ot
                skew_ratio = max(
                    (e["skew_ratio"] or 0.0 for e in rep.values()),
                    default=0.0)
                coll = sorted(v["collective_us"] / 1000.0
                              for (op, _), v in split.items()
                              if "fold" in str(op))
                if coll:
                    coll_p50 = coll[len(coll) // 2]
            return {
                "rows_per_sec": rows / elapsed,
                "rows": rows,
                "elapsed_s": elapsed,
                "shard_info": getattr(fused, "shard_info", {}),
                "per_shard_rows": [s["rows"] for s in shard_stats],
                "mesh": getattr(fused.gb, "mesh_tag", ""),
                "skew_ratio": skew_ratio,
                "collective_ms_p50": coll_p50,
                "observatory_overhead_pct": (100.0 * obs_s / elapsed
                                             if elapsed > 0 else 0.0),
                **e2e,
            }
        finally:
            topo.close()
            mem.reset()
            if fleetobs is not None:
                fleetobs[3].rmtree(fleetobs[2], ignore_errors=True)

    os.environ["KUIPER_MESH"] = f"1x{n_dev}"
    try:
        single = run_leg("off", "single")
        # partial record NOW: if the sharded leg dies to the watchdog or
        # the parent's kill, the artifact still carries the single-shard
        # leg instead of a bare timeout (the r05 parsed-null class)
        record("multichip_full_pipe",
               single_shard_rows_per_sec=single["rows_per_sec"],
               n_devices=n_dev, partial="single leg only")
        if time.time() + 25.0 > leg_deadline:
            print("# multichip_full_pipe: sharded leg dropped — "
                  "per-leg budget exhausted after the single leg",
                  file=sys.stderr)
            dog.disarm()
            sys.stderr.flush()
            os._exit(0)
        sharded = run_leg("auto", "sharded")
    finally:
        os.environ.pop("KUIPER_MESH", None)

    # direct-kernel window parity (byte-identical emitted groups):
    # the cheap in-process twin of tools/probe_multichip.py's full check
    parity_ok = True
    try:
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.ops.keytable import KeyTable
        from ekuiper_tpu.parallel.mesh import make_mesh
        from ekuiper_tpu.parallel.sharded import ShardedGroupBy
        from ekuiper_tpu.sql.parser import parse_select

        pstmt = parse_select(
            "SELECT deviceId, avg(v) AS a, count(*) AS c, min(v) AS mn "
            "FROM s GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)")
        pplan = extract_kernel_plan(pstmt)
        mesh = make_mesh(rows=1, keys=n_dev)
        sgb = ShardedGroupBy(pplan, mesh, capacity=256, micro_batch=512)
        ggb = DeviceGroupBy(extract_kernel_plan(pstmt), capacity=256,
                            micro_batch=512)
        kt = KeyTable(256)
        keys = np.array([f"d{rng.integers(200)}" for _ in range(5000)],
                        dtype=np.object_)
        vals = rng.normal(10, 3, 5000).astype(np.float32)
        slots, _ = kt.encode_column(keys)
        ss = sgb.fold(sgb.init_state(), {"v": vals}, slots)
        ds = ggb.fold(ggb.init_state(), {"v": vals}, slots)
        souts, sact = sgb.finalize(ss, kt.n_keys)
        douts, dact = ggb.finalize(ds, kt.n_keys)
        parity_ok = bool(np.array_equal(sact, dact) and all(
            np.allclose(souts[i], douts[i], rtol=1e-5, atol=1e-5,
                        equal_nan=True)
            for i in range(len(souts))))
    except Exception as exc:
        parity_ok = False
        print(f"# multichip parity check failed: {exc}", file=sys.stderr)

    scaling = (sharded["rows_per_sec"] / single["rows_per_sec"]
               if single["rows_per_sec"] else 0.0)
    print(
        f"# multichip_full_pipe ({n_dev} devices, mesh {sharded['mesh']}): "
        f"single {single['rows_per_sec']:,.0f} rows/s -> sharded "
        f"{sharded['rows_per_sec']:,.0f} rows/s ({scaling:.2f}x); "
        f"per-shard {sharded['per_shard_rows']}; emit p99 "
        f"{sharded['e2e_p99_ms']}ms; parity={'ok' if parity_ok else 'FAIL'}; "
        f"skew {sharded.get('skew_ratio', 0.0):.2f}; observatory "
        f"{sharded.get('observatory_overhead_pct', 0.0):.3f}%",
        file=sys.stderr,
    )
    record("multichip_full_pipe",
           rows_per_sec=sharded["rows_per_sec"],
           single_shard_rows_per_sec=single["rows_per_sec"],
           scaling_x=scaling,
           n_devices=n_dev,
           mesh=sharded["mesh"],
           per_shard_rows=sharded["per_shard_rows"],
           shard_info=sharded["shard_info"],
           skew_ratio=sharded.get("skew_ratio", 0.0),
           collective_ms_p50=sharded.get("collective_ms_p50", 0.0),
           observatory_overhead_pct=sharded.get(
               "observatory_overhead_pct", 0.0),
           parity_ok=parity_ok,
           platform=str(jax.devices()[0].platform),
           jitcert=_jitcert_fields(),
           emit_p99_ms=sharded["e2e_p99_ms"],
           e2e_p50_ms=sharded["e2e_p50_ms"])
    dog.disarm()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def bench_cold_start() -> None:
    _run_isolated("_cold_start_main", "cold_start", timeout=180)


def _cold_start_main() -> None:
    """Zero-compile serving phase (ISSUE 16): boot→first-emit and
    rule-create→first-emit for the SAME planned rule, cold (empty AOT
    executable cache — warmup lowers + compiles every fused-window
    executable) then warm (in-process restart against the disk cache the
    cold leg just baked — warmup is a deserialization sweep). The warm
    leg must show ZERO XLA traces and zero AOT misses: that pair is the
    cache's zero-compile-restart claim, and `speedup_first_fold_x` is
    its headline (seconds cold vs tens of ms warm).

    Runs on CPU jax in its own subprocess: the phase measures compile
    amortization, not device throughput."""
    import json as _json
    import shutil
    import tempfile

    os.environ["JAX_PLATFORMS"] = "cpu"
    cache_dir = tempfile.mkdtemp(prefix="bench-aot-")
    os.environ["KUIPER_AOT_CACHE_DIR"] = cache_dir
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET_S", "0") or 0)
    dog = PhaseWatchdog()
    if child_budget > 0:
        dog.arm("cold_start_child", child_budget)
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.observability import devwatch, jitcert
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule
    from ekuiper_tpu.runtime import aotcache
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    rng = np.random.default_rng(31)
    rows = [
        _json.dumps({
            "deviceId": f"dev_{rng.integers(0, 500)}",
            "temperature": round(float(rng.normal(20, 5)), 2),
        }).encode()
        for _ in range(2048)
    ]

    def leg(tag: str) -> dict:
        t_boot = time.time()
        mem.reset()
        store = kv.get_store()
        try:
            StreamProcessor(store).exec_stmt(
                'CREATE STREAM pipe_cs (deviceId STRING, temperature '
                'FLOAT) WITH (DATASOURCE="topic/pipe_cs", TYPE="memory", '
                'FORMAT="JSON")')
        except Exception:
            pass
        t_rule = time.time()
        # ONE rule id + no shared-fold grouping: the warm leg must plan
        # the byte-identical kernel config (a store still holding the
        # cold leg's rule would otherwise vmap-group the warm plan into
        # different state shapes, and nothing would hit the cache)
        rule = RuleDef(
            id="cs_restart",
            sql=("SELECT deviceId, avg(temperature) AS a, count(*) AS c "
                 "FROM pipe_cs GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)"),
            actions=[{"nop": {}}],
            options={"bufferLength": 64, "micro_batch_rows": 2048,
                     "micro_batch_linger_ms": 20, "key_slots": 1024,
                     "sharedFold": False})
        topo = plan_rule(rule, store)
        topo.open()  # <- warmup: compile sweep cold, cache probe warm
        src = (topo.sources[0] if topo.sources
               else topo._live_shared[0][0].source)
        try:
            src.ingest(rows)
            topo.wait_idle(60.0)
            t_fold = time.time()
            # first EMIT additionally waits for the 1s tumbling window
            # to close — the user-visible latency, window wait included
            emit_deadline = time.time() + 30.0
            while (topo.e2e_hist.count == 0
                   and time.time() < emit_deadline):
                time.sleep(0.01)
            t_emit = time.time()
            return {
                "boot_to_first_fold_ms": (t_fold - t_boot) * 1000.0,
                "rule_create_to_first_fold_ms":
                    (t_fold - t_rule) * 1000.0,
                "boot_to_first_emit_ms": (t_emit - t_boot) * 1000.0,
                "rule_create_to_first_emit_ms":
                    (t_emit - t_rule) * 1000.0,
                "emitted": bool(topo.e2e_hist.count > 0),
                "compile_total": _compile_total(),
                "aot": _aot_fields(),
            }
        finally:
            topo.close()
            mem.reset()

    try:
        cold = leg("cold")
        # partial record NOW so a watchdog kill still leaves the cold
        # numbers in the artifact (the r05 parsed-null class)
        record("cold_start", cold=cold, partial="cold leg only")
        # in-process restart: kernels + every registry die; only the
        # disk cache the cold leg baked survives — what a real process
        # restart on the same image sees
        devwatch.registry().clear()
        jitcert.reset()
        aotcache.reset()
        warm = leg("warm")
        zero_compile = (warm["compile_total"] == 0
                        and warm["aot"]["misses"] == 0)
        record("cold_start",
               cold=cold, warm=warm,
               zero_compile_restart=zero_compile,
               warm_disk_loads=warm["aot"]["disk_loads"],
               speedup_first_fold_x=round(
                   cold["rule_create_to_first_fold_ms"]
                   / max(warm["rule_create_to_first_fold_ms"], 1e-3), 1),
               jitcert=_jitcert_fields())
        print(
            "# cold_start: rule-create→first-fold "
            f"{cold['rule_create_to_first_fold_ms']:.0f}ms cold -> "
            f"{warm['rule_create_to_first_fold_ms']:.0f}ms warm; "
            f"first-emit {cold['rule_create_to_first_emit_ms']:.0f}ms "
            f"cold -> {warm['rule_create_to_first_emit_ms']:.0f}ms warm; "
            f"warm compiles {warm['compile_total']}, aot misses "
            f"{warm['aot']['misses']} (zero_compile_restart="
            f"{zero_compile})", file=sys.stderr)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        os.environ.pop("KUIPER_AOT_CACHE_DIR", None)
    dog.disarm()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def bench_full_pipe_ingest() -> None:
    _run_isolated("_full_pipe_main", "full-pipe")


def bench_full_pipe_contended() -> None:
    _run_isolated("_full_pipe_contended_main", "full-pipe-contended",
                  timeout=1200)


def bench_hetero_rules() -> None:
    _run_isolated("_hetero_main", "hetero 256-rule", timeout=1800)


def _hetero_main() -> None:
    """256 HETEROGENEOUS rules sharing one source on one chip (the
    reference's 300-rules-shared-stream benchmark, README.md:144-156, but
    with rules that do NOT all share a statement shape):

    - 4 rule FAMILIES with different aggregates/columns/comparators; rules
      within a family differ only in WHERE literals. Each family plans as
      ONE vmapped device program (plan_rule_group / parallel/multirule.py) —
      vmapped grouping applies WITHIN a family, never across families.
    - 4 fully-individual rules plan as their own fused nodes.
    - All 8 topologies ride ONE shared source+decode subtopo.

    Prints a stderr metric line with rule-rows/s and device state bytes."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule, plan_rule_group
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    mem.reset()
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM sensors (deviceId STRING, temperature FLOAT, '
        'pressure FLOAT, humidity FLOAT) '
        'WITH (DATASOURCE="topic/sensors", TYPE="memory", FORMAT="JSON")')
    families = [
        ("fa", "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
               "FROM sensors WHERE temperature > {x} "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 14.0, 0.05),
        ("fb", "SELECT deviceId, min(pressure) AS mn, max(pressure) AS mx "
               "FROM sensors WHERE pressure > {x} "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 0.4, 0.002),
        ("fc", "SELECT deviceId, sum(humidity) AS s, stddev(humidity) AS sd "
               "FROM sensors WHERE humidity > {x} "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 30.0, 0.1),
        ("fd", "SELECT deviceId, count(*) AS c, avg(pressure) AS ap "
               "FROM sensors WHERE temperature < {x} "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)", 26.0, 0.05),
    ]
    topos = []
    n_rules = 0
    for name, sql, base, step in families:
        rules = [
            RuleDef(id=f"{name}{i}", sql=sql.format(x=base + step * i),
                    actions=[{"nop": {}}],
                    options={"micro_batch_rows": 32768, "bufferLength": 96})
            for i in range(63)
        ]
        topos.append(plan_rule_group(name, rules, store))
        n_rules += 63
    singles = [
        "SELECT deviceId, stddev(temperature) AS sd, percentile_approx"
        "(temperature, 0.9) AS p90 FROM sensors "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        "SELECT deviceId, hll(humidity) AS u FROM sensors "
        "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)",
        "SELECT deviceId, max(temperature) AS m, count(*) AS c "
        "FROM sensors GROUP BY deviceId, COUNTWINDOW(262144)",
        "SELECT deviceId, avg(humidity) AS ah, min(temperature) AS mt "
        "FROM sensors GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)",
    ]
    for i, sql in enumerate(singles):
        topos.append(plan_rule(
            RuleDef(id=f"solo{i}", sql=sql, actions=[{"nop": {}}],
                    options={"micro_batch_rows": 32768, "bufferLength": 96}),
            store))
        n_rules += 1
    assert n_rules == 256
    for t in topos:
        t.open()
    try:
        import json as _json

        # ONE physical source is shared by all 8 topologies (subtopo pool)
        srcs = {id(t._live_shared[0][0]) for t in topos if t._live_shared}
        assert len(srcs) == 1, f"expected 1 shared subtopo, got {len(srcs)}"
        src = topos[0]._live_shared[0][0].source
        rng = np.random.default_rng(31)
        n_dev = 4096
        ids = np.array([f"dev_{i}" for i in range(n_dev)], dtype=np.object_)
        drains = []
        for _ in range(8):
            k = 16384
            # raw JSON bytes, like the reference's MQTT fan-out benchmark
            # (README.md:144-156 rides a real broker) — decoded once by the
            # shared pipeline's native decoder, then key-encoded + uploaded
            # once per batch for all 256 riders (SharedPrepCtx)
            drains.append([
                _json.dumps({"deviceId": d, "temperature": t, "pressure": p,
                             "humidity": h}).encode()
                for d, t, p, h in zip(
                    ids[rng.integers(0, n_dev, k)],
                    rng.normal(20, 5, k).round(2),
                    rng.random(k).round(3),
                    rng.normal(50, 15, k).round(2))
            ])
        deadline = time.time() + 900
        warm_ok = False
        for _ in range(2):  # two full-coverage rounds, flush inline
            for d in drains:
                src.ingest(d)
            warm_ok = False
            while time.time() < deadline:  # all 8 programs compile
                if all(t.wait_idle(5.0) for t in topos):
                    warm_ok = True
                    break
        if not warm_ok:
            print("# hetero warm-up INCOMPLETE — number includes compiles",
                  file=sys.stderr)
        fused = [n for t in topos for n in t.ops
                 if "Fused" in type(n).__name__]
        rows = 0
        n = 0
        stall = 0.0
        t0 = time.time()
        while time.time() - t0 < 20.0:
            src.ingest(drains[n % len(drains)])
            rows += len(drains[0])
            n += 1
            ts = time.time()
            # queue-depth-aware dispatch: boundary instants put ~256 rules'
            # finalize+reset work on the link at once — let queues absorb
            # the spike (depth << bufferLength so drop-oldest NEVER fires;
            # asserted below) and only stall when a node falls genuinely
            # behind for a sustained stretch
            bp_deadline = time.time() + 120
            while max(f.inq.qsize() for f in fused) > 48:
                time.sleep(0.002)
                if time.time() > bp_deadline:
                    raise RuntimeError(
                        "hetero: queues stuck >120s (device link wedged?) "
                        "— aborting phase")
            stall += time.time() - ts
        for t in topos:
            t.wait_idle(timeout=30.0)
        elapsed = time.time() - t0
        drop_nodes = [
            n_.name for t_ in topos
            for n_ in (t_.sources + t_.ops + t_.sinks)
            if "dropped oldest" in getattr(n_.stats, "last_exception", "")]
        assert not drop_nodes, \
            f"queue depth rode into drop-oldest on {drop_nodes} — stall% " \
            "would be fake; raise bufferLength or lower the threshold"
        state_mb = sum(
            float(np.prod(v.shape)) * 4 for f in fused
            for v in (f.state or {}).values()) / 1e6
        print(
            f"# hetero 256-rule fan-out (4 vmapped families x63 + 4 solo, "
            f"one shared source): {rows:,} rows x {n_rules} rules in "
            f"{elapsed:.2f}s = {rows * n_rules / elapsed:,.0f} rule-rows/s "
            f"({stall:.1f}s backpressure-stalled), device state "
            f"{state_mb:.0f}MB across {len(fused)} fused nodes "
            f"(reference fan-out baseline: 150,000 rule-msg/s)",
            file=sys.stderr,
        )
        record("hetero_256", rule_rows_per_sec=rows * n_rules / elapsed,
               stalled_s=stall, stalled_pct=100.0 * stall / elapsed,
               state_mb=state_mb)
    finally:
        for t in topos:
            t.close()
        mem.reset()


def _stage_summary(node) -> dict:
    """Per-stage StatManager timings for the bench artifact: the ingest
    pipeline balance (source decode/upload vs fused upload/fold) is an
    acceptance number, not just an operator dashboard."""
    out = {}
    for stage, st in node.stats.snapshot()["stage_timings"].items():
        calls = max(st["calls"], 1)
        out[stage] = {"calls": st["calls"], "rows": st["rows"],
                      "us_per_call": round(st["total_us"] / calls, 1)}
    return out


def _full_pipe_session(measure) -> None:
    """Shared full-pipe harness: raw JSON bytes → native columnar decode
    (jsoncol.cpp, shard-parallel on the decode pool) → fused device window,
    through the REAL planned topo (source node + decode pool + channels +
    fused node worker). Opens + warms the topo, then hands control to
    `measure(run_segment, src, dec)` where `run_segment(seconds)` returns
    (rows, bytes, elapsed) for one timed ingest segment."""
    import json as _json

    from ekuiper_tpu.io import memory as mem
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    # child-side watchdog (r05 fix): the parent kills us silently at its
    # subprocess timeout — die a little earlier WITH the partial records
    # and a final JSON flushed, so the artifact always carries this phase
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET_S", "0") or 0)
    dog = PhaseWatchdog()
    if child_budget > 0:
        dog.arm("full_pipe_child", child_budget)

    mem.reset()
    from ekuiper_tpu.io import fastjson

    fastjson.ensure_native(background=False)  # build the C decoder now
    store = kv.get_store()
    try:
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM pipe (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="topic/pipe", TYPE="memory", FORMAT="JSON")')
    except Exception:
        pass  # stream exists from a prior phase
    rule = RuleDef(
        id="pipe1", sql=(
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c "
            "FROM pipe GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        actions=[{"nop": {}}],
        # ingest-rate shapes: bigger micro-batches amortize per-item node
        # overhead and per-fold upload latency; key_slots pinned (= the
        # default) so the measured config is explicit about cardinality;
        # decode pool explicit so the measured ingest pipeline is too
        options={"bufferLength": 64, "micro_batch_rows": 32768,
                 "micro_batch_linger_ms": 50, "key_slots": 16384,
                 "decodePoolSize": 3, "ingestRingDepth": 3})
    topo = plan_rule(rule, store)
    fused = next(n for n in topo.ops
                 if type(n).__name__ == "FusedWindowAggNode")
    topo.open()
    # memory streams plan as a shared subtopo; the physical SourceNode
    # lives in the pool, resolved at open()
    src = (topo.sources[0] if topo.sources
           else topo._live_shared[0][0].source)
    try:
        # pregenerate raw JSON payload batches (768 msgs per broker drain)
        rng = np.random.default_rng(23)
        drain_rows = 3072
        drains = []
        for _ in range(12):
            drain = [
                _json.dumps({
                    "deviceId": f"dev_{rng.integers(0, N_DEVICES)}",
                    "temperature": round(float(rng.normal(20, 5)), 2),
                }).encode()
                for _ in range(drain_rows)
            ]
            drains.append(drain)
        n_bytes_per = sum(len(p) for p in drains[0])
        # warm: the node worker compiles fold/finalize/prefinalize
        # executables first (on a tunneled chip that is minutes, once).
        # Feed a full micro-batch so the flush happens INLINE in ingest —
        # rows sitting in the source's pending buffer would let wait_idle
        # return before the pipe ever ran (queues look empty), leaving
        # every compile inside the measured window. Two rounds: all 12
        # drains cover ~97% of the 10k keys, so steady-state capacity and
        # executables are reached before timing starts. The warm window is
        # capped HARD below the child budget (no floor that could swallow
        # it): the measured segment must start before the watchdog fires,
        # even if that means measuring with compiles still warm.
        warm_s = 600.0
        if child_budget > 0:
            warm_s = min(warm_s, max(child_budget - 45.0, 5.0))
        warm_deadline = time.time() + warm_s
        for _ in range(2):
            for d in drains:
                src.ingest(d)
            while time.time() < warm_deadline and not topo.wait_idle(5.0):
                pass

        from ekuiper_tpu.observability import devwatch, memwatch

        def run_segment(seconds: float):
            rows = 0
            byts = 0
            n = 0
            # warm-vs-cold attribution (BENCH_r06): a steady-state segment
            # must run on cached executables — compile_count says whether
            # this number paid XLA compiles mid-measurement
            compiles0 = devwatch.registry().totals()["compiles"]
            peak = 0
            t0 = time.time()
            while time.time() - t0 < seconds:
                src.ingest(drains[n % len(drains)])
                rows += drain_rows
                byts += n_bytes_per
                n += 1
                # registered-component HBM/host footprint, sampled per
                # drain (probe walk is a handful of attribute reads)
                b = memwatch.registry().total_bytes()
                if b > peak:
                    peak = b
                # backpressure: keep the fused node's input queue shallow so
                # drop-oldest never fires (dropped batches would fake the
                # rate). Deadline-bounded: a wedged device link must fail
                # the phase loudly, not hang into the subprocess timeout
                bp_deadline = time.time() + 120
                while fused.inq.qsize() > 8:
                    time.sleep(0.002)
                    if time.time() > bp_deadline:
                        raise RuntimeError(
                            "full-pipe: fused queue stuck >120s (device "
                            "link wedged?) — aborting phase")
            # drain: all queued batches consumed (state is owned by the
            # node's worker thread — donated buffers, don't touch it here)
            topo.wait_idle(timeout=30.0)
            b = memwatch.registry().total_bytes()
            run_segment.device_bytes_peak = max(peak, b)
            run_segment.compile_count = (
                devwatch.registry().totals()["compiles"] - compiles0)
            return rows, byts, time.time() - t0

        run_segment.device_bytes_peak = 0
        run_segment.compile_count = 0

        dec = ("native" if src._fast_spec is not None
               and fastjson._load() is not None else "python")
        measure(run_segment, src, dec, fused, topo)
    finally:
        dog.disarm()
        topo.close()
        mem.reset()


def _devwatch_overhead(fused) -> dict:
    """Measured cost of the compile-watcher wrapper (observability/
    devwatch.py) on the CACHE-HIT path — the acceptance number behind
    'instrumentation ≤1% of fold time'. Each watched call adds exactly:
    one rule-context check, one flag write, one perf_counter read and two
    counter bumps; measured here as (watched − raw) jit dispatch time on
    an identity kernel, scaled against the fused fold stage."""
    import jax

    from ekuiper_tpu.observability.devwatch import watched_jit

    x = np.zeros(8, dtype=np.float32)
    raw = jax.jit(lambda v: v)
    watched = watched_jit(lambda v: v, op="bench.overhead_probe")
    raw(x)
    watched(x)  # both compiled before timing
    n = 3000

    def per_call_us(fn):
        t0 = time.perf_counter()
        for _ in range(n):
            fn(x)
        return (time.perf_counter() - t0) * 1e6 / n

    raw_us = per_call_us(raw)
    watched_us = per_call_us(watched)
    per_call = max(watched_us - raw_us, 0.0)
    st = fused.stats.snapshot()["stage_timings"].get("fold")
    fold_us = (st["total_us"] / max(st["calls"], 1)) if st else 0.0
    pct = (100.0 * per_call / fold_us) if fold_us else None
    return {"wrapper_us_per_call": round(per_call, 3),
            "fold_us_per_call": round(fold_us, 1),
            "pct_of_fold": round(pct, 3) if pct is not None else None}


def _kernwatch_overhead(fused) -> dict:
    """Measured cost of the kernel observatory (observability/
    kernwatch.py) against the fused fold — the acceptance number behind
    'device-time sampling ≤1% of fold', same bar as devwatch_overhead.
    Every watched call pays one cadence check (`KernelRecord.tick`);
    every Nth call additionally pays a device sync (`block_until_ready`
    on the outputs) plus the dispatch/device split math. Amortized
    per-call cost at the hot cadence = tick + sample / N."""
    import jax

    from ekuiper_tpu.observability import kernwatch
    from ekuiper_tpu.observability.kernwatch import KernelRecord

    rec = KernelRecord("bench.kern_probe")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        rec.tick()
    tick_us = (time.perf_counter() - t0) * 1e6 / n
    # sample cost = (dispatch + block + split math) − bare dispatch, on a
    # compiled identity kernel: what a sampled call pays BEYOND the call
    x = np.zeros(8, dtype=np.float32)
    f = jax.jit(lambda v: v)
    jax.block_until_ready(f(x))
    m = 500
    t0 = time.perf_counter()
    for _ in range(m):
        f(x)
    bare_us = (time.perf_counter() - t0) * 1e6 / m
    t0 = time.perf_counter()
    for _ in range(m):
        ta = time.perf_counter()
        out = f(x)
        tb = time.perf_counter()
        rec.sample(out, ta, tb, (x,), {})
    sample_us = max((time.perf_counter() - t0) * 1e6 / m - bare_us, 0.0)
    # cadence 0 = hot sampling disabled: only the tick cost remains
    every = kernwatch.DEFAULT_SAMPLING["hot"]
    per_call = tick_us + (sample_us / every if every > 0 else 0.0)
    st = fused.stats.snapshot()["stage_timings"].get("fold")
    fold_us = (st["total_us"] / max(st["calls"], 1)) if st else 0.0
    pct = (100.0 * per_call / fold_us) if fold_us else None
    return {"tick_us": round(tick_us, 3),
            "sample_us": round(sample_us, 1),
            "sample_every": every,
            "per_call_us": round(per_call, 3),
            "fold_us_per_call": round(fold_us, 1),
            "pct_of_fold": round(pct, 3) if pct is not None else None}


def _kernel_fields() -> dict:
    """The kernel observatory's per-kernel device-time summary for the
    bench artifact (observability/kernwatch.py): top sites by sampled
    device time with FLOPs/bytes cost and roofline utilization — the
    numbers a ROADMAP re-anchor can cite for headroom claims."""
    from ekuiper_tpu.observability import kernwatch

    return kernwatch.bench_summary()


def _jitcert_fields() -> dict:
    """The compile-contract verdict for the phase (observability/
    jitcert.py): every devwatch-observed signature must sit inside the
    registered certificates. `clean=False` names the escapees — the
    acceptance gate for new jit sites (ISSUE 10) is zero observed
    signatures outside the certified set on full_pipe and
    multi_rule_shared."""
    from ekuiper_tpu.observability import jitcert

    d = jitcert.diff_live()
    return {
        "clean": d["clean"],
        "observed_signatures": d["observed_signatures"],
        "certified_signatures": d["certified_signatures"],
        "sites_observed": d["sites_observed"],
        "sites_open": d["sites_open"],
        "uncertified": [
            {"op": u["op"], "rule": u["rule"],
             "signature": u["signature"][:300]}
            for u in d["uncertified"][:16]],
    }


def _kernel_split_probe():
    """Device-time decomposition over the jit registry: returns
    `finish() -> dict` computing per-op deltas of sampled dispatch /
    device / transfer time plus devwatch compile time since the probe
    started — the sliding phase's answer to WHERE its trigger stalls go
    (the 865ms fold stalls of BENCH_r04 were one opaque host number)."""
    from ekuiper_tpu.observability import devwatch, kernwatch

    def totals():
        t = {}
        for w in devwatch.registry().watches():
            k = w.kern
            t[w.op] = (k.samples, k.dispatch_us, k.device_us,
                       k.transfer_us, w.compile_hist.sum, w.traces)
        return t

    before = totals()

    def finish(top: int = 8) -> dict:
        after = totals()
        ops = {}
        agg = {"samples": 0, "dispatch_us": 0.0, "device_us": 0.0,
               "transfer_us": 0.0, "compile_us": 0.0, "compiles": 0}
        for op, a in after.items():
            b = before.get(op, (0, 0.0, 0.0, 0.0, 0, 0))
            samples, disp, dev, xfer, comp_us, traces = (
                x - y for x, y in zip(a, b))
            if samples <= 0 and traces <= 0:
                continue
            agg["samples"] += samples
            agg["dispatch_us"] += disp
            agg["device_us"] += dev
            agg["transfer_us"] += xfer
            agg["compile_us"] += comp_us
            agg["compiles"] += traces
            ops[op] = {"samples": samples,
                       "dispatch_ms": round(disp / 1e3, 2),
                       "device_ms": round(dev / 1e3, 2),
                       "transfer_est_ms": round(xfer / 1e3, 2),
                       **({"compile_ms": round(comp_us / 1e3, 1),
                           "compiles": traces} if traces else {})}
        hot = sorted(ops, key=lambda o: -ops[o]["device_ms"])[:top]
        return {
            "device": kernwatch.device_spec().get("kind"),
            "sampling": dict(kernwatch.DEFAULT_SAMPLING),
            "samples": agg["samples"],
            "dispatch_ms": round(agg["dispatch_us"] / 1e3, 2),
            "compile_ms": round(agg["compile_us"] / 1e3, 1),
            "device_compute_ms": round(
                (agg["device_us"] - agg["transfer_us"]) / 1e3, 2),
            "transfer_est_ms": round(agg["transfer_us"] / 1e3, 2),
            "compiles": agg["compiles"],
            "ops": {o: ops[o] for o in hot},
        }

    return finish


def _hist_overhead(fused) -> dict:
    """Measured cost of the histogram hot path against the fused fold —
    the acceptance number behind 'histograms add <1% to the fold'. The
    fold path gained exactly: one queue-wait record + one process-latency
    record per dispatched batch (observability/histogram.py O(1) record),
    so overhead = 2 x record cost / per-batch fold time."""
    from ekuiper_tpu.observability.histogram import LatencyHistogram

    h = LatencyHistogram()
    n = 100_000
    t0 = time.perf_counter()
    for i in range(n):
        h.record(i & 0xFFFFF)
    per_record_us = (time.perf_counter() - t0) * 1e6 / n
    st = fused.stats.snapshot()["stage_timings"].get("fold")
    fold_us = (st["total_us"] / max(st["calls"], 1)) if st else 0.0
    pct = (100.0 * 2 * per_record_us / fold_us) if fold_us else None
    return {"record_us": round(per_record_us, 3),
            "fold_us_per_call": round(fold_us, 1),
            "pct_of_fold": round(pct, 3) if pct is not None else None}


def _compile_total() -> int:
    """Engine-wide XLA trace count (devwatch): the number the AOT cache
    exists to hold flat across rule churn and restarts."""
    from ekuiper_tpu.observability import devwatch

    return int(devwatch.registry().totals()["compiles"])


def _aot_fields() -> dict:
    """AOT executable-cache counters for the artifact (runtime/
    aotcache.py): hits serve from prebuilt executables, misses paid a
    serve-path lower+compile, disk_loads deserialized a baked entry."""
    from ekuiper_tpu.runtime import aotcache

    s = aotcache.stats().snapshot()
    return {"hits": s["hits"], "misses": s["misses"],
            "serve_misses": s["serve_misses"],
            "disk_loads": s["disk_loads"], "builds": s["builds"],
            "build_seconds": s["build_seconds"],
            "executables": s["executables"]}


def _e2e_fields(topo) -> dict:
    """SLO fields for the artifact: the rule's ingest→emit distribution
    (runtime/topo.py e2e_hist, fed by the sink) as p50/p99 ms."""
    h = topo.e2e_hist
    if h.count == 0:
        return {"e2e_p50_ms": None, "e2e_p99_ms": None, "e2e_samples": 0}
    return {"e2e_p50_ms": float(h.percentile(50)),
            "e2e_p99_ms": float(h.percentile(99)),
            "e2e_samples": h.count}


class _HealthTopoShim:
    """Just enough Topo surface for the health evaluator when a bench
    phase drives nodes directly (no planned Topo): all_nodes + no shared
    list, no e2e histogram (the evaluator skips absent surfaces)."""

    def __init__(self, nodes):
        self._nodes = nodes

    def all_nodes(self):
        return self._nodes

    def live_shared(self):
        return []


def _health_fields(topo, fused, elapsed_s, rule_id="pipe1") -> dict:
    """Final health verdict + peak burn rate + measured evaluator
    overhead (observability/health.py) for a bench phase. Same
    methodology as devwatch_overhead — measured cost scaled against the
    fold stage: the evaluator ticks once per DEFAULT_INTERVAL_MS, so
    overhead = mean tick cost x the ticks this segment would have seen
    at the default cadence, over the fold time the segment actually
    spent (acceptance target <1% of fold)."""
    from ekuiper_tpu.observability import health

    ev = health.HealthEvaluator(lambda: [(rule_id, topo, {})])
    # seed tick: first delta is the whole segment, so ITS verdict carries
    # the segment-wide burn/bottleneck/watermark attribution; later ticks
    # see empty deltas (traffic stopped) and only advance the FSM
    ev.tick()
    seed = ev.verdicts().get(rule_id) or {}
    tick_us = []  # warm ticks only — the seed paid the lazy imports
    for _ in range(5):
        ev.tick()
        tick_us.append(ev.last_tick_us)
    v = ev.verdicts().get(rule_id) or seed
    mean_us = sum(tick_us) / len(tick_us)
    st = (fused.stats.snapshot()["stage_timings"].get("fold")
          if fused is not None else None)
    fold_us = st["total_us"] if st else 0
    ticks = max(elapsed_s * 1000.0 / health.DEFAULT_INTERVAL_MS, 1.0)
    pct = (100.0 * mean_us * ticks / fold_us) if fold_us else None
    burn = seed.get("burn_rate") or {}
    return {
        "health_verdict": v.get("state"),
        "peak_burn_rate": ev.peak_burn(rule_id),
        "burn_rate_fast": burn.get("fast"),
        "burn_rate_slow": burn.get("slow"),
        "bottleneck_stage": (seed.get("bottleneck") or {}).get("stage"),
        "watermark_lag_ms": (seed.get("watermark") or {}).get("lag_ms"),
        "health_overhead": {
            "tick_us": round(mean_us, 1),
            "interval_ms": health.DEFAULT_INTERVAL_MS,
            "pct_of_fold": round(pct, 3) if pct is not None else None,
        },
    }


def _full_pipe_main() -> None:
    """Full-pipe ingest throughput (the reference measures through its
    MQTT+decode pipeline, README.md:98; kernel-fed numbers skip ingest,
    this line does not). Prints a stderr metric line."""

    def measure(run_segment, src, dec, fused, topo):
        # warm-up emissions (jit-stall dwells) must not pollute the SLO
        # fields: the measured segment starts from an empty distribution
        topo.e2e_hist.snapshot_and_decay(0.0)
        rows, byts, elapsed = run_segment(10.0)
        e2e = _e2e_fields(topo)
        print(
            f"# full-pipe ingest (json bytes → decode[{dec}] → coerce → "
            f"fused window, real topo): {rows:,} rows / {byts / 1e6:.0f}MB "
            f"in {elapsed:.2f}s ({rows / elapsed:,.0f} rows/s, "
            f"{byts / elapsed / 1e6:.1f}MB/s bytes-in); ingest→emit "
            f"p50={e2e['e2e_p50_ms']}ms p99={e2e['e2e_p99_ms']}ms over "
            f"{e2e['e2e_samples']} window emits",
            file=sys.stderr,
        )
        prep = src.prep_ctx
        record("full_pipe", rows_per_sec=rows / elapsed,
               mb_per_sec=byts / elapsed / 1e6, decoder=dec,
               pool=src.decode_pool_size, shards=src._decode_shards,
               prep_batches=(prep.n_precomputed if prep else 0),
               hist_overhead=_hist_overhead(fused),
               devwatch_overhead=_devwatch_overhead(fused),
               kernwatch_overhead=_kernwatch_overhead(fused),
               kernels=_kernel_fields(),
               jitcert=_jitcert_fields(),
               compile_count=run_segment.compile_count,
               device_bytes_peak=run_segment.device_bytes_peak,
               stages={"source": _stage_summary(src),
                       "fused": _stage_summary(fused)},
               **e2e, **_health_fields(topo, fused, elapsed))

    _full_pipe_session(measure)


def _burn_cpu(stop_path: str) -> None:
    """Background CPU load for the contention phase: spin until the stop
    file appears. A subprocess, not a thread — the point is stealing CPU
    from the engine the way a co-tenant process would, not GIL contention."""
    import os as _os

    x = 1.0
    while not _os.path.exists(stop_path):
        for _ in range(100_000):
            x = x * 1.0000001 + 1e-9
    _ = x


def _full_pipe_contended_main() -> None:
    """Full-pipe ingest under concurrent CPU load (VERDICT r5 weak #3:
    1.14M rows/s idle collapsed to 554k under load — the decode was
    GIL-bound on one thread). Measures an idle segment, then the same
    segment with cpu_count/2 busy subprocesses, and records both plus the
    degradation — the number that must stop halving under load."""
    import multiprocessing
    import os as _os
    import tempfile

    def measure(run_segment, src, dec, fused, topo):
        rows, byts, elapsed = run_segment(10.0)
        idle = rows / elapsed
        n_burn = max(2, (_os.cpu_count() or 4) // 2)
        stop_path = tempfile.mktemp(prefix="ek_burn_stop_")
        burners = [
            multiprocessing.Process(target=_burn_cpu, args=(stop_path,),
                                    daemon=True)
            for _ in range(n_burn)
        ]
        for b in burners:
            b.start()
        try:
            time.sleep(0.5)  # burners reach steady spin before the segment
            # e2e fields report the LOADED segment only (the phase's claim)
            topo.e2e_hist.snapshot_and_decay(0.0)
            rows, byts, elapsed = run_segment(10.0)
        finally:
            with open(stop_path, "w"):
                pass
            for b in burners:
                b.join(timeout=5)
                if b.is_alive():
                    b.terminate()
            _os.unlink(stop_path)
        loaded = rows / elapsed
        degr = 100.0 * (1.0 - loaded / idle) if idle else 0.0
        print(
            f"# full-pipe-contended ingest (decode[{dec}], {n_burn} cpu "
            f"burners): idle {idle:,.0f} rows/s → loaded {loaded:,.0f} "
            f"rows/s ({degr:.0f}% degradation)",
            file=sys.stderr,
        )
        prep = src.prep_ctx
        record("full_pipe_contended", idle_rows_per_sec=idle,
               loaded_rows_per_sec=loaded, degradation_pct=degr,
               burners=n_burn, decoder=dec,
               pool=src.decode_pool_size, shards=src._decode_shards,
               prep_batches=(prep.n_precomputed if prep else 0),
               kernels=_kernel_fields(),
               jitcert=_jitcert_fields(),
               compile_count=run_segment.compile_count,
               device_bytes_peak=run_segment.device_bytes_peak,
               stages={"source": _stage_summary(src),
                       "fused": _stage_summary(fused)},
               **_e2e_fields(topo),
               **_health_fields(topo, fused, elapsed))

    _full_pipe_session(measure)


def bench_multi_rule_shared(batches, kt_slots) -> None:
    """ISSUE 4 acceptance phase: 8 correlated rules, one stream, 10k keys —
    shared pane fold (one device fold per batch + per-rule pane combine)
    vs 8 independent folds. Records aggregate rule-rows/s for both plans,
    the fold-dedup ratio, and a deterministic byte-parity check of the
    emitted windows (integer-valued measurements so pane-sum association
    is exact — docs/SHARING.md)."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.ops.panestore import pane_gcd, union_plan
    from ekuiper_tpu.runtime.events import Trigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.runtime.nodes_sharedfold import (
        MemberSpec, SharedEmitNode, SharedFoldNode)
    from ekuiper_tpu.sql import ast
    from ekuiper_tpu.sql.parser import parse_select

    n_rules = 8
    sqls = [
        "SELECT deviceId, avg(temperature) AS a, count(*) AS c FROM demo "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        "SELECT deviceId, min(temperature) AS mn, max(temperature) AS mx "
        "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        "SELECT deviceId, sum(temperature) AS s FROM demo "
        "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)",
        "SELECT deviceId, count(*) AS c, max(temperature) AS mx FROM demo "
        "GROUP BY deviceId, HOPPINGWINDOW(ss, 20, 5)",
        "SELECT deviceId, avg(temperature) AS a, min(temperature) AS mn "
        "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 20)",
        "SELECT deviceId, avg(temperature) AS a, count(*) AS c FROM demo "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 15)",
        "SELECT deviceId, sum(temperature) AS s, count(*) AS c FROM demo "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)",
        "SELECT deviceId, avg(temperature) AS a FROM demo "
        "GROUP BY deviceId, HOPPINGWINDOW(ss, 15, 5)",
    ]
    stmts = [parse_select(s) for s in sqls]
    plans = [extract_kernel_plan(s) for s in stmts]
    assert all(p is not None for p in plans)
    union, _ = union_plan(plans)
    windows = []
    for s in stmts:
        w = s.window
        windows += [w.length_ms(), w.interval_ms() or w.length_ms()]
    pane = pane_gcd(windows)
    max_span = max(s.window.length_ms() // pane for s in stmts)

    # integer-valued temperatures: pane-sum association is exact, so the
    # shared-vs-private comparison below is BYTE-identical, not approximate
    int_batches = [
        ColumnBatch(n=b.n,
                    columns={"deviceId": b.columns["deviceId"],
                             "temperature": np.rint(
                                 b.columns["temperature"]).astype(
                                     np.float32)},
                    timestamps=b.timestamps, emitter=b.emitter)
        for b in batches
    ]

    def mk_shared():
        node = SharedFoldNode(
            "bench", "shared_fold[demo]", union, pane, max_span + 2,
            subtopo_ref=None, capacity=kt_slots, micro_batch=BATCH_ROWS)
        node._cur_bucket = 0
        entries = []
        for i, (stmt, plan) in enumerate(zip(stmts, plans)):
            w = stmt.window
            spec = MemberSpec(
                rule_id=f"r{i}", length_ms=w.length_ms(),
                interval_ms=w.interval_ms() or w.length_ms(), plan=plan,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                dims=["deviceId"], emit_columnar=True)
            e = SharedEmitNode(f"r{i}_emit", buffer_length=4096)
            node.attach_rule(spec, e, None)
            entries.append(e)
        return node, entries

    def mk_private():
        nodes, caps = [], []
        for stmt, plan in zip(stmts, plans):
            n = FusedWindowAggNode(
                "priv", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=kt_slots, micro_batch=BATCH_ROWS,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, prefinalize_lead_ms=0)
            n.state = n.gb.init_state()
            got = []
            n.broadcast = lambda item, g=got: g.append(item)
            nodes.append(n)
            caps.append(got)
        return nodes, caps

    def private_boundary(p, end):
        iv = p.interval_ms or p.length_ms
        if end % iv:
            return
        p._emit(WindowRange(end - p.length_ms, end))
        if p.wt == ast.WindowType.TUMBLING_WINDOW:
            p.state = p.gb.reset_pane(p.state, 0)
        else:
            p.cur_pane = (p.cur_pane + 1) % p.n_panes
            p.state = p.gb.reset_pane(p.state, p.cur_pane)

    # ---- parity: identical batches + boundaries through both plans ----
    shared, entries = mk_shared()
    privs, caps = mk_private()
    for end_i in range(1, 5):
        end = end_i * pane
        shared.process(int_batches[end_i % len(int_batches)])
        for p in privs:
            p.process(int_batches[end_i % len(int_batches)])
        shared.on_trigger(Trigger(ts=end))
        for p in privs:
            private_boundary(p, end)
    jax.block_until_ready(shared.store.state)
    n_windows = 0
    for i, e in enumerate(entries):
        got = []
        while not e.inq.empty():
            item = e.inq.get_nowait()
            if isinstance(item, ColumnBatch):
                got.append(item)
        ref = [x for x in caps[i] if isinstance(x, ColumnBatch)]
        assert len(got) == len(ref), f"rule {i}: {len(got)} vs {len(ref)}"
        for a, b in zip(got, ref):
            for c in a.columns:
                assert np.array_equal(a.columns[c], b.columns[c]), \
                    f"rule {i} col {c} diverged"
        n_windows += len(got)
    parity_windows = n_windows

    # ---- throughput: aggregate rule-rows/s shared vs independent ----
    def run(fold_fn, boundary_fn, state_ref, seconds=6.0):
        rows = 0
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            fold_fn(int_batches[n % len(int_batches)])
            rows += BATCH_ROWS
            n += 1
            if n % T_BLOCK_EVERY == 0:
                # bound the dispatch queue: block on the CURRENT state
                # before the boundary donates it (a held older marker
                # would reference donated buffers). Same pipeline bubble
                # for both arms — the comparison stays fair.
                jax.block_until_ready(state_ref()["act"])
            if n % 16 == 0:
                boundary_fn((n // 16) * pane)
        jax.block_until_ready(state_ref())
        return rows, time.time() - t0

    shared, entries = mk_shared()
    shared.process(int_batches[0])
    shared.on_trigger(Trigger(ts=pane))  # warm fold + combine
    jax.block_until_ready(shared.store.state)
    for e in entries:
        while not e.inq.empty():
            e.inq.get_nowait()
    shared.folds_did = shared.folds_would = 0
    s_rows, s_el = run(shared.process,
                       lambda end: shared.on_trigger(Trigger(ts=end)),
                       lambda: shared.store.state)
    dedup = shared.fold_dedup_ratio()

    privs, caps = mk_private()
    for p in privs:
        p.process(int_batches[0])
        private_boundary(p, p.interval_ms or p.length_ms)
    jax.block_until_ready(privs[0].state)

    def priv_fold(b):
        for p in privs:
            p.process(b)

    def priv_boundary(end):
        for p in privs:
            private_boundary(p, end)

    p_rows, p_el = run(priv_fold, priv_boundary, lambda: privs[0].state)
    shared_agg = s_rows * n_rules / s_el
    priv_agg = p_rows * n_rules / p_el
    speedup = shared_agg / max(priv_agg, 1e-9)
    print(
        f"# multi-rule shared fold ({n_rules} correlated rules, "
        f"{N_DEVICES} keys, pane {pane}ms x {max_span + 2} panes): shared "
        f"{shared_agg:,.0f} rule-rows/s vs independent {priv_agg:,.0f} "
        f"rule-rows/s = {speedup:.1f}x; fold-dedup ratio {dedup:.3f}; "
        f"parity: {parity_windows} windows byte-identical",
        file=sys.stderr,
    )
    record("multi_rule_shared",
           shared_rule_rows_per_sec=shared_agg,
           independent_rule_rows_per_sec=priv_agg,
           speedup=speedup, fold_dedup_ratio=dedup,
           parity_windows=parity_windows, n_rules=n_rules,
           pane_ms=pane,
           jitcert=_jitcert_fields(),
           **_health_fields(
               _HealthTopoShim(shared.pipeline_nodes() + entries),
               shared, s_el, rule_id="r0"))


def bench_join_heavy(kt_slots) -> None:
    """ISSUE 19 acceptance phase: interval stream-stream join through
    the device join ring (ops/joinring.py). Two legs:

    - columnar throughput: 2048-rows-per-side windows through the
      certified match kernel (key equality + event-time band + residual)
      — rows/s counts both sides, acceptance floor 500k rows/s on the
      CPU smoke;
    - emission tail: full DeviceJoinNode._join_step windows (mask +
      host-order emission reconstruction) at 256 rows/side — the
      per-window latency p99 is the join analogue of the emit p99.

    Every window must take the device mask: a single runtime fallback
    (fallback_windows_total != 0) fails the phase."""
    import jax

    from ekuiper_tpu.data.rows import JoinTuple, Tuple
    from ekuiper_tpu.ops.joinring import SideBatch
    from ekuiper_tpu.planner import relational
    from ekuiper_tpu.runtime.nodes_relational import DeviceJoinNode
    from ekuiper_tpu.sql.parser import parse_select

    sql = ("SELECT l.v, r.w FROM l INNER JOIN r ON l.k = r.k "
           "AND l.ts - r.ts >= -5000 AND l.ts - r.ts <= 5000 "
           "AND l.v > r.w GROUP BY TUMBLINGWINDOW(ss, 10)")
    stmt = parse_select(sql)
    lowering = relational.lower_join(stmt, stmt.joins)
    ring = lowering.build_ring(capacity=kt_slots)
    rng = np.random.default_rng(19)
    n_keys = 512

    def side(n, left):
        b = SideBatch(n=n)
        b.key_cols.append([f"k{i}" for i in rng.integers(0, n_keys, n)])
        b.band = rng.integers(0, 60_000, n).tolist()
        col = "__jl_v" if left else "__jr_w"
        b.cols[col] = rng.uniform(0.0, 100.0, n).tolist()
        return b

    per_side = 2048
    windows = [(side(per_side, True), side(per_side, False))
               for _ in range(4)]
    mask = ring.match(*windows[0])  # warm: compile the (2048, 2048) pad
    matches = 0
    rows = 0
    n = 0
    t0 = time.time()
    while time.time() - t0 < 6.0:
        left, right = windows[n % len(windows)]
        mask = ring.match(left, right)
        rows += left.n + right.n
        n += 1
    matches = int(mask.sum())
    elapsed = time.time() - t0
    rows_per_sec = rows / elapsed

    # emission-order reconstruction leg: host rows through the full node
    node = DeviceJoinNode("join", stmt.joins, left_name="l",
                          lowering=lowering)
    node.ring = ring

    def mk_rows(n, left):
        out = []
        for i in range(n):
            ts = int(rng.integers(0, 60_000))
            msg = {"k": f"k{int(rng.integers(0, n_keys))}", "ts": ts}
            if left:
                msg["v"] = float(rng.uniform(0.0, 100.0))
            else:
                msg["w"] = float(rng.uniform(0.0, 100.0))
            out.append(Tuple(emitter="l" if left else "r", message=msg,
                             timestamp=ts))
        return out

    lat_ms = []
    emitted = 0
    for _ in range(40):
        left = [JoinTuple(tuples=[t]) for t in mk_rows(256, True)]
        right = mk_rows(256, False)
        w0 = time.perf_counter()
        out = node._join_step(left, right, stmt.joins[0])
        lat_ms.append((time.perf_counter() - w0) * 1e3)
        emitted += len(out)
    p99 = float(np.percentile(lat_ms, 99))
    fallbacks = int(ring.fallback_windows_total)
    print(f"# join_heavy: match {rows_per_sec:,.0f} rows/s "
          f"({matches:,} pairs/window at {per_side}/side), emission "
          f"window p99 {p99:.1f}ms ({emitted:,} tuples over 40 windows), "
          f"fallback windows {fallbacks} (must be 0); device="
          f"{jax.devices()[0].device_kind}", file=sys.stderr)
    record("join_heavy", rows_per_sec=rows_per_sec,
           emit_p99_ms=p99, matches_per_window=matches,
           emitted_tuples=emitted, fallback_windows=fallbacks)
    assert fallbacks == 0, \
        f"join_heavy: {fallbacks} windows fell back to the host loop"


def bench_filter_heavy(batches, kt_slots) -> None:
    """ISSUE 12 acceptance phase: a rule with a non-trivial WHERE
    (string-dict IN + numeric predicate) and a CASE agg projection at
    10k keys, fully device-compiled by the expression IR
    (sql/expr_ir.py) — vs the same aggregates with NO WHERE. Acceptance:
    the compiled-WHERE rule runs fold-limited (within 15% of the
    no-WHERE tumbling throughput) with zero FilterNode / row-interpreter
    samples in kernel_split (the plan IS the fused kernel; there is no
    filter hop to sample)."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    sql_where = (
        "SELECT deviceId, count(*) AS c, "
        "sum(CASE WHEN status = 'ok' THEN temperature ELSE 0.0 END) AS s, "
        "avg(temperature) AS a FROM demo "
        "WHERE status IN ('ok', 'warn') AND temperature > 15 "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
    sql_plain = (
        "SELECT deviceId, count(*) AS c, sum(temperature) AS s, "
        "avg(temperature) AS a FROM demo "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")

    # status column riding the shared bench batches: ~70% pass the IN
    rng = np.random.default_rng(12)
    statuses = np.array(["ok", "warn", "err"], dtype=np.object_)
    f_batches = []
    for b in batches:
        st = statuses[rng.integers(0, 3, b.n)]
        f_batches.append(ColumnBatch(
            n=b.n, columns={**b.columns, "status": st},
            timestamps=b.timestamps, emitter=b.emitter))

    def mk(sql):
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)
        assert plan is not None, f"not device-eligible: {sql}"
        node = FusedWindowAggNode(
            "fh", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=kt_slots, micro_batch=BATCH_ROWS,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            emit_columnar=True, prefinalize_lead_ms=0)
        node.state = node.gb.init_state()
        node.broadcast = lambda item: None
        return node, plan

    node_w, plan_w = mk(sql_where)
    assert plan_w.filter is not None and plan_w.derived, \
        "WHERE must compile into the fused kernel (expression IR)"
    node_p, _ = mk(sql_plain)

    def run(node, seconds=6.0):
        # warm
        node.process(f_batches[0])
        node._emit(WindowRange(0, 10_000))
        node.state = node.gb.reset_pane(node.state, 0)
        jax.block_until_ready(node.state)
        split = _kernel_split_probe()
        rows = 0
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            node.process(f_batches[n % len(f_batches)])
            rows += BATCH_ROWS
            n += 1
            if n % T_BLOCK_EVERY == 0:
                jax.block_until_ready(node.state["act"])
            if n % 16 == 0:
                node._emit(WindowRange(0, (n // 16) * 10_000))
                node.state = node.gb.reset_pane(node.state, 0)
        jax.block_until_ready(node.state)
        return rows / (time.time() - t0), split()

    w_rows, w_split = run(node_w)
    p_rows, _ = run(node_p)
    ratio = w_rows / max(p_rows, 1e-9)
    # device-path contract: every sampled op is a fused-kernel site —
    # a FilterNode hop or row-interpreter loop has no jit site and would
    # show up as a throughput collapse (the ratio floor), never here
    host_ops = [op for op in w_split.get("ops", {})
                if not op.startswith(("groupby.", "sharded.",
                                      "slidingring.", "multirule.",
                                      "sketch."))]
    print(
        f"# filter_heavy: compiled WHERE+CASE {w_rows:,.0f} rows/s vs "
        f"no-WHERE {p_rows:,.0f} rows/s = {ratio:.3f}x "
        f"(fold-limited target >= 0.85); kernel_split ops "
        f"{sorted(w_split.get('ops', {}))}; device="
        f"{jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    record("filter_heavy",
           rows_per_sec=w_rows,
           nowhere_rows_per_sec=p_rows,
           where_throughput_ratio=ratio,
           fold_limited=ratio >= 0.85,
           derived_cols=len(plan_w.derived),
           host_expr_ops=host_ops,
           kernel_split=w_split,
           jitcert=_jitcert_fields())


def bench_multi_rule_shared_mixed(batches, kt_slots) -> None:
    """Mixed-WHERE twin of multi_rule_shared: 6 rules, same stream /
    GROUP BY / window grid, WHERE clauses all DIFFERENT — the shape that
    planned 6 private folds before predicate lifting. Records the
    predicate-lifted fold-dedup ratio and byte-parity of every member's
    emissions vs its private plan."""
    import jax

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan, lift_predicate
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.ops.panestore import union_plan
    from ekuiper_tpu.runtime.events import Trigger
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.runtime.nodes_sharedfold import (
        MemberSpec, SharedEmitNode, SharedFoldNode)
    from ekuiper_tpu.sql.parser import parse_select

    sqls = [
        "SELECT deviceId, count(*) AS c, sum(temperature) AS s FROM demo "
        f"WHERE temperature > {t} GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        for t in (10, 15, 20, 25)
    ] + [
        "SELECT deviceId, count(*) AS c, max(temperature) AS mx FROM demo "
        "WHERE status = 'ok' GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        "SELECT deviceId, count(*) AS c FROM demo "
        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
    ]
    stmts = [parse_select(s) for s in sqls]
    plans = [extract_kernel_plan(s) for s in stmts]
    assert all(p is not None for p in plans)
    lifted = [lift_predicate(p, s.condition)
              for p, s in zip(plans, stmts)]
    union, _ = union_plan(lifted)
    n_rules = len(sqls)

    rng = np.random.default_rng(13)
    statuses = np.array(["ok", "warn", "err"], dtype=np.object_)
    int_batches = []
    for b in batches:
        st = statuses[rng.integers(0, 3, b.n)]
        int_batches.append(ColumnBatch(
            n=b.n,
            columns={"deviceId": b.columns["deviceId"],
                     "temperature": np.rint(
                         b.columns["temperature"]).astype(np.float32),
                     "status": st},
            timestamps=b.timestamps, emitter=b.emitter))

    def mk_shared():
        node = SharedFoldNode(
            "bench_mixed", "shared_fold[demo:mixed]", union, 10_000, 3,
            subtopo_ref=None, capacity=kt_slots, micro_batch=BATCH_ROWS)
        node._cur_bucket = 0
        entries = []
        for i, (stmt, plan, lp) in enumerate(zip(stmts, plans, lifted)):
            spec = MemberSpec(
                rule_id=f"m{i}", length_ms=10_000, interval_ms=10_000,
                plan=lp, dims=["deviceId"],
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, act_idx=lp.act_idx)
            e = SharedEmitNode(f"m{i}_emit", buffer_length=4096)
            node.attach_rule(spec, e, None)
            entries.append(e)
        return node, entries

    def mk_private():
        nodes, caps = [], []
        for stmt, plan in zip(stmts, plans):
            n = FusedWindowAggNode(
                "privm", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=kt_slots, micro_batch=BATCH_ROWS,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, prefinalize_lead_ms=0)
            n.state = n.gb.init_state()
            got = []
            n.broadcast = lambda item, g=got: g.append(item)
            nodes.append(n)
            caps.append(got)
        return nodes, caps

    # ---- byte parity: same batches + boundaries through both plans ----
    shared, entries = mk_shared()
    privs, caps = mk_private()
    for end_i in range(1, 4):
        end = end_i * 10_000
        shared.process(int_batches[end_i % len(int_batches)])
        for p in privs:
            p.process(int_batches[end_i % len(int_batches)])
        shared.on_trigger(Trigger(ts=end))
        for p in privs:
            p._emit(WindowRange(end - 10_000, end))
            p.state = p.gb.reset_pane(p.state, 0)
    jax.block_until_ready(shared.store.state)
    parity_windows = 0
    for i, e in enumerate(entries):
        got = []
        while not e.inq.empty():
            item = e.inq.get_nowait()
            if isinstance(item, ColumnBatch):
                got.append(item)
        ref = [x for x in caps[i] if isinstance(x, ColumnBatch)]
        assert len(got) == len(ref), f"rule {i}: {len(got)} vs {len(ref)}"
        for a, b in zip(got, ref):
            for c in a.columns:
                assert np.array_equal(a.columns[c], b.columns[c]), \
                    f"mixed rule {i} col {c} diverged"
        parity_windows += len(got)

    # ---- throughput + dedup: shared (lifted) vs 6 private folds ----
    def run(fold_fn, boundary_fn, state_ref, seconds=5.0):
        rows = 0
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            fold_fn(int_batches[n % len(int_batches)])
            rows += BATCH_ROWS
            n += 1
            if n % T_BLOCK_EVERY == 0:
                jax.block_until_ready(state_ref()["act"])
            if n % 16 == 0:
                boundary_fn((n // 16) * 10_000)
        jax.block_until_ready(state_ref())
        return rows, time.time() - t0

    shared, entries = mk_shared()
    shared.process(int_batches[0])
    shared.on_trigger(Trigger(ts=10_000))
    jax.block_until_ready(shared.store.state)
    for e in entries:
        while not e.inq.empty():
            e.inq.get_nowait()
    shared.folds_did = shared.folds_would = 0
    s_rows, s_el = run(shared.process,
                       lambda end: shared.on_trigger(Trigger(ts=end)),
                       lambda: shared.store.state)
    dedup = shared.fold_dedup_ratio()

    privs, caps = mk_private()
    for p in privs:
        p.process(int_batches[0])
        p._emit(WindowRange(0, 10_000))
        p.state = p.gb.reset_pane(p.state, 0)
    jax.block_until_ready(privs[0].state)

    def priv_fold(b):
        for p in privs:
            p.process(b)

    def priv_boundary(end):
        for p in privs:
            p._emit(WindowRange(end - 10_000, end))
            p.state = p.gb.reset_pane(p.state, 0)

    p_rows, p_el = run(priv_fold, priv_boundary, lambda: privs[0].state)
    shared_agg = s_rows * n_rules / s_el
    priv_agg = p_rows * n_rules / p_el
    speedup = shared_agg / max(priv_agg, 1e-9)
    # identical-WHERE-only baseline: these 6 mixed-WHERE rules shared
    # NOTHING before predicate lifting (6 distinct store keys) — the
    # lifted dedup ratio improves on a flat 0.0
    print(
        f"# multi-rule shared MIXED-WHERE ({n_rules} rules, predicate-"
        f"lifted): shared {shared_agg:,.0f} rule-rows/s vs independent "
        f"{priv_agg:,.0f} rule-rows/s = {speedup:.1f}x; lifted fold-dedup "
        f"ratio {dedup:.3f} (identical-WHERE-only baseline: 0.000); "
        f"union specs {len(union.specs)}; parity: {parity_windows} "
        "windows byte-identical",
        file=sys.stderr,
    )
    record("multi_rule_shared_mixed",
           shared_rule_rows_per_sec=shared_agg,
           independent_rule_rows_per_sec=priv_agg,
           speedup=speedup,
           mixed_where_dedup_ratio=dedup,
           identical_where_baseline_dedup=0.0,
           union_specs=len(union.specs),
           parity_windows=parity_windows, n_rules=n_rules,
           jitcert=_jitcert_fields())


def bench_event_time(batches, kt_slots) -> None:
    """Event-time device path: per-row pane routing + watermark-driven
    emission. Prints a stderr metric line."""
    import jax
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import Watermark
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "ev", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=kt_slots, micro_batch=BATCH_ROWS,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True, is_event_time=True, late_tolerance_ms=1000)
    from ekuiper_tpu.data.batch import ColumnBatch

    node.state = node.gb.init_state()
    emitted = []
    node.broadcast = lambda item: emitted.append(item)

    def stamped(i):  # event timestamps advance ~1s/batch -> window per ~10
        b = batches[i % 4]
        return ColumnBatch(n=b.n, columns=b.columns,
                           timestamps=np.full(b.n, i * 1000, dtype=np.int64),
                           emitter=b.emitter)

    node.process(stamped(0))
    node.on_watermark(Watermark(ts=0))
    jax.block_until_ready(node.state)
    n = 1
    t0 = time.time()
    while time.time() - t0 < 3.0:  # untimed warm: steady link + executables
        node.process(stamped(n))
        node.on_watermark(Watermark(ts=n * 1000 - 1000))
        n += 1
    jax.block_until_ready(node.state)
    emitted.clear()
    rows = 0
    t0 = time.time()
    while time.time() - t0 < 10.0:
        node.process(stamped(n))
        node.on_watermark(Watermark(ts=n * 1000 - 1000))
        rows += BATCH_ROWS
        n += 1
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0
    n_windows = sum(1 for i in emitted if not isinstance(i, Watermark))
    print(
        f"# event-time device path: {rows:,} rows in {elapsed:.2f}s "
        f"({rows / elapsed:,.0f} rows/s), {n_windows} watermark-driven "
        f"window emits", file=sys.stderr,
    )
    record("event_time", rows_per_sec=rows / elapsed, windows=n_windows)


def make_node(backstop: bool):
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "bench rule must be device-eligible"
    direct = build_direct_emit(stmt, plan, ["deviceId"])
    assert direct is not None, "bench rule must take the direct-emit tail"
    node = FusedWindowAggNode(
        "bench", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=KEY_SLOTS, micro_batch=BATCH_ROWS, direct_emit=direct,
        emit_columnar=True, prefinalize_backstop=backstop,
    )
    node.state = node.gb.init_state()
    node.broadcast = lambda item: None
    return node


def make_batches():
    from ekuiper_tpu.data.batch import ColumnBatch

    rng = np.random.default_rng(0)
    device_ids = np.array(
        [f"dev_{i}" for i in range(N_DEVICES)], dtype=np.object_)
    # a few distinct pre-built batches so host-side caching can't fake it
    batches = []
    for _ in range(4):
        idx = rng.integers(0, N_DEVICES, BATCH_ROWS)
        cols = {
            "deviceId": device_ids[idx],
            "temperature": rng.normal(20, 5, BATCH_ROWS).astype(np.float32),
        }
        batches.append(
            ColumnBatch(n=BATCH_ROWS, columns=cols,
                        timestamps=np.zeros(BATCH_ROWS, dtype=np.int64),
                        emitter="demo")
        )
    return batches


def warmup(node, batches) -> None:
    """Compile fold + sync finalize + components before measuring."""
    import jax

    from ekuiper_tpu.data.rows import WindowRange
    from ekuiper_tpu.runtime.events import PreTrigger

    assert node._prefinalize_ok, "bench rule must take the latency-hiding emit"
    for i in range(WARMUP_BATCHES):
        node.process(batches[i % len(batches)])
    node._emit(WindowRange(0, 10_000))  # sync path (compiles finalize)
    node.on_pre_trigger(PreTrigger(ts=10_000))
    node.process(batches[3])
    node._emit(WindowRange(0, 10_000))  # merged path (compiles components)
    node.state = node.gb.reset_pane(node.state, 0)
    node.begin_window_backstop()
    jax.block_until_ready(node.state)


class WindowStats:
    """Per-boundary bookkeeping shared by both phases."""

    def __init__(self) -> None:
        self.latencies: list = []
        self.device_latencies: list = []
        self.fetch_ms: list = []
        self.sources = {"device": 0, "backstop": 0, "sync": 0}
        self.storms = 0

    def boundary(self, node, emit_fn) -> None:
        from ekuiper_tpu.data.rows import WindowRange

        t = time.time()
        emit_fn(WindowRange(0, 10_000))
        lat = (time.time() - t) * 1000
        self.latencies.append(lat)
        node.state = node.gb.reset_pane(node.state, 0)
        node.begin_window_backstop()
        self.storms += 1 if node._storm else 0
        info = node.last_emit_info
        if info is None:  # empty window: no emit, no source to attribute
            return
        self.sources[info.get("source", "sync")] += 1
        if info.get("source") == "device":
            self.device_latencies.append(lat)
            self.fetch_ms.append(info.get("fetch_ms", -1.0))

    def line(self) -> str:
        def pct(xs, q):
            return float(np.percentile(xs, q)) if xs else float("nan")

        s = self.sources
        return (
            f"emit p50={pct(self.latencies, 50):.1f}ms "
            f"p99={pct(self.latencies, 99):.1f}ms over "
            f"{len(self.latencies)} samples; sources device/backstop/sync="
            f"{s['device']}/{s['backstop']}/{s['sync']}; "
            f"device-served p50={pct(self.device_latencies, 50):.1f}ms "
            f"p99={pct(self.device_latencies, 99):.1f}ms "
            f"(fetch issue→landed p50={pct(self.fetch_ms, 50):.0f}ms); "
            f"storm windows={self.storms}"
        )


def phase_throughput(batches) -> float:
    """Saturate the ingest path; boundaries WAIT on the pre-issued device
    fetch (no backstop), so throughput includes device-served emission."""
    import jax

    from ekuiper_tpu.runtime.events import PreTrigger

    node = make_node(backstop=False)
    warmup(node, batches)
    stats = WindowStats()
    rows = 0
    n = 0
    marker = None
    t0 = time.time()
    while len(stats.latencies) < T_WINDOWS:
        node.process(batches[n % len(batches)])
        rows += BATCH_ROWS
        n += 1
        if n % T_BLOCK_EVERY == 0:
            # bound the dispatch queue WITHOUT stalling the pipeline: wait
            # for the state as of one mark AGO (usually already done), so
            # at most ~2*T_BLOCK_EVERY batches are ever in flight. An
            # unbounded loop would measure client RAM, not the pipeline.
            _block_marker(marker)
            marker = node.state["act"][:1]  # non-donated slice
        m = n % T_WINDOW_BATCHES
        if m in T_PRE_ISSUE_AT:
            node.on_pre_trigger(PreTrigger(ts=0))
        elif m == 0:
            stats.boundary(node, node._emit)
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0
    rows_per_sec = rows / elapsed
    print(
        f"# phase T (saturated): {rows:,} rows in {elapsed:.2f}s "
        f"({rows_per_sec:,.0f} rows/s); {stats.line()}; "
        f"groups/window={N_DEVICES}; device={jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    assert stats.sources["device"] == len(stats.latencies), \
        "phase T emits must all be device-served"
    record("tumbling_saturated", rows_per_sec=rows_per_sec,
           emit_p50_ms=float(np.percentile(stats.latencies, 50)),
           emit_p99_ms=float(np.percentile(stats.latencies, 99)),
           windows=len(stats.latencies), storms=stats.storms)
    return rows_per_sec


def phase_latency(batches) -> None:
    """Pace ingest at the north-star load and measure boundary latency."""
    import jax

    from ekuiper_tpu.runtime.events import PreTrigger

    node = make_node(backstop=True)
    warmup(node, batches)
    stats = WindowStats()
    interval = BATCH_ROWS / L_TARGET_ROWS_S
    rows = 0
    n = 0
    t0 = time.time()
    while (len(stats.latencies) < L_MIN_SAMPLES
           and time.time() - t0 < L_MAX_SECONDS):
        target = t0 + n * interval
        delay = target - time.time()
        if delay > 0:
            time.sleep(delay)
        node.process(batches[n % len(batches)])
        rows += BATCH_ROWS
        n += 1
        m = n % L_WINDOW_BATCHES
        if m in L_PRE_ISSUE_AT:
            node.on_pre_trigger(PreTrigger(ts=0))
        elif m == 0:
            stats.boundary(node, node._emit)
    jax.block_until_ready(node.state)
    elapsed = time.time() - t0
    print(
        f"# phase L (paced {L_TARGET_ROWS_S / 1e6:.1f}M rows/s): "
        f"{rows:,} rows in {elapsed:.2f}s "
        f"({rows / elapsed:,.0f} rows/s achieved); {stats.line()}",
        file=sys.stderr,
    )
    record("tumbling_paced", rows_per_sec=rows / elapsed,
           emit_p50_ms=float(np.percentile(stats.latencies, 50))
           if stats.latencies else None,
           emit_p99_ms=float(np.percentile(stats.latencies, 99))
           if stats.latencies else None,
           device_served=stats.sources["device"],
           backstop_served=stats.sources["backstop"],
           storms=stats.storms)


def _final_json(rows_per_sec: float = 0.0, error: str = "") -> None:
    """The self-contained artifact line: the LAST stdout line carries every
    recorded phase metric under "phases", so the driver's record survives
    any tail truncation AND any mid-run death (the watchdog prints this
    before force-exiting)."""
    out = {
        "metric": "tumbling_groupby_rows_per_sec_10k_devices",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_MSG_S, 2),
        # shallow copy: the watchdog dumps this from a timer thread while
        # the main thread may still be record()-ing
        "phases": dict(RESULTS),
    }
    if error:
        out["error"] = error
    print(json.dumps(out), flush=True)


def preflight(timeout: float = 120.0) -> bool:
    """TPU tunnel probe (tools/check_tpu.py, subprocess-isolated) BEFORE
    any phase runs: a dead tunnel hangs the first in-process jax call
    forever (VERDICT r5: BENCH_r05 was rc=124 with parsed null for exactly
    this), so the bench must find out while it can still bail."""
    import subprocess

    root = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "check_tpu.py"),
             "--timeout", str(timeout)],
            capture_output=True, text=True, timeout=timeout + 60)
        ok = r.returncode == 0
        for line in r.stdout.splitlines():
            print(f"# preflight: {line}", file=sys.stderr)
        detail = (r.stdout.strip().splitlines()
                  or r.stderr.strip().splitlines() or ["no output"])[-1]
    except Exception as exc:
        ok, detail = False, str(exc)
    record("preflight", ok=ok, detail=detail[-200:])
    return ok


class PhaseWatchdog:
    """Hard wall-clock bound around each in-process phase. A wedged device
    call (dead tunnel mid-run) cannot be interrupted from Python, so on
    expiry the watchdog prints the final self-contained JSON — everything
    recorded so far — and force-exits with rc=3 instead of letting the
    driver's global timeout produce rc=124 with no artifact."""

    def __init__(self) -> None:
        self._timer = None

    def arm(self, phase: str, seconds: float) -> None:
        import threading

        self.disarm()
        self._timer = threading.Timer(seconds, self._fire, (phase, seconds))
        self._timer.daemon = True
        self._timer.start()

    def disarm(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _fire(self, phase: str, seconds: float) -> None:
        # exception-safe: os._exit MUST run even if the artifact dump
        # races a record() on the wedged main thread — dying here would
        # recreate the rc=124-no-artifact failure this class prevents
        try:
            RESULTS[f"{phase}_error"] = f"watchdog: exceeded {seconds:.0f}s"
            print(f"# WATCHDOG: {phase} exceeded {seconds:.0f}s — emitting "
                  "final JSON and exiting", file=sys.stderr, flush=True)
            _flush_record_dump()
            _final_json(error=f"{phase} exceeded {seconds:.0f}s watchdog")
        except BaseException:
            pass
        finally:
            os._exit(3)


def main() -> None:
    # global budget: the driver hard-kills `python bench.py` (rc=124, no
    # artifact) — phase budgets are carved from TOTAL_BUDGET_S and a
    # last-resort watchdog emits the final JSON with whatever was recorded
    # just before that outer timeout would hit
    _DEADLINE.clear()
    _DEADLINE.append(time.time() + TOTAL_BUDGET_S)
    global_dog = PhaseWatchdog()
    global_dog.arm("total_budget", TOTAL_BUDGET_S - 10.0)
    # tunnel health gate: a dead tunnel short-circuits to a self-contained
    # failure artifact instead of burning subprocess timeouts and hanging
    # at first in-process jax use
    if not preflight():
        print("# TPU preflight failed — skipping all phases",
              file=sys.stderr)
        _final_json(error="tpu preflight failed")
        return
    # subprocess-isolated phases FIRST: they need the chip to themselves —
    # once this process initializes its own TPU client (first jax use), a
    # concurrent child client is starved to ~1% of its standalone rate
    bench_full_pipe_ingest()
    bench_full_pipe_contended()
    bench_hetero_rules()
    batches = make_batches()
    # one phase failing must not orphan the headline + phases JSON — the
    # driver records the LAST stdout line; log the failure and keep going.
    # The watchdog bounds each phase: a mid-run tunnel death prints the
    # artifact with whatever was recorded and exits rc=3.
    rows_per_sec = 0.0
    dog = PhaseWatchdog()
    for name, budget_s, fn in (
        ("phase_throughput", 900.0, lambda: phase_throughput(batches)),
        ("phase_latency", 600.0, lambda: phase_latency(batches)),
        ("sliding", 600.0,
         lambda: bench_sliding_percentile(batches, KEY_SLOTS)),
        ("heavy_hitters", 600.0,
         lambda: bench_hopping_heavy_hitters(batches, KEY_SLOTS)),
        ("hll_1m", 900.0, lambda: bench_countwindow_hll_1m(KEY_SLOTS)),
        ("event_time", 600.0, lambda: bench_event_time(batches, KEY_SLOTS)),
        ("rule_group", 600.0, lambda: bench_rule_group(batches, KEY_SLOTS)),
        ("filter_heavy", 600.0,
         lambda: bench_filter_heavy(batches, KEY_SLOTS)),
        ("join_heavy", 600.0, lambda: bench_join_heavy(KEY_SLOTS)),
        ("multi_rule_shared", 600.0,
         lambda: bench_multi_rule_shared(batches, KEY_SLOTS)),
        ("multi_rule_shared_mixed", 600.0,
         lambda: bench_multi_rule_shared_mixed(batches, KEY_SLOTS)),
        ("key_cardinality", 600.0,
         lambda: bench_key_cardinality(
             KEY_SLOTS,
             budget_s=max(phase_budget(
                 240.0, later_floor_s=later_floor("key_cardinality"))
                 - 30.0, 30.0))),
    ):
        budget_s = phase_budget(budget_s, later_floor_s=later_floor(name))
        if budget_s < 20.0:
            print(f"# {name}: skipped — global budget exhausted",
                  file=sys.stderr)
            RESULTS[f"{name}_error"] = "skipped: global budget exhausted"
            continue
        dog.arm(name, budget_s)
        try:
            out = fn()
            if name == "phase_throughput":
                rows_per_sec = out
        except Exception as exc:
            print(f"# {name} FAILED: {exc}", file=sys.stderr)
            RESULTS[f"{name}_error"] = str(exc)
        finally:
            dog.disarm()

    # subprocess phases with their own (virtual) device fleets run after
    # the in-process chip phases: multichip forces CPU host-device
    # emulation unless KUIPER_BENCH_MULTICHIP_TPU=1 points it at real
    # chips, so it never contends with the parent's TPU client
    bench_multichip_full_pipe()
    # cold vs warm boot on CPU jax in its own subprocess: the AOT
    # executable cache's zero-compile-restart claim, measured
    bench_cold_start()
    # the churn soak runs LAST (its floor is reserved by every earlier
    # phase): it needs no chip to itself — it measures the QoS control
    # plane on CPU jax in its own subprocess
    bench_churn_soak()

    global_dog.disarm()
    _final_json(rows_per_sec)


if __name__ == "__main__":
    main()
