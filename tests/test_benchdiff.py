"""Bench trajectory gate (tools/benchdiff.py): metric flattening,
direction-aware noise tolerance, headline gating, failed-round (r05
class) detection — plus the --smoke subprocess self-test wired into
tier-1 like kuiperdiag."""
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.benchdiff import (  # noqa: E402
    classify, compare, flatten, gate, round_ok)


def art(value=2_800_000, phases=None, rc=0, parsed=True):
    return {"n": 1, "cmd": "bench", "rc": rc, "tail": "",
            "parsed": ({"metric": "t", "value": value, "unit": "rows/s",
                        "phases": phases or {}} if parsed else None)}


class TestFlatten:
    def test_headline_and_phase_leaves(self):
        flat = flatten(art(123.0, {
            "full_pipe": {"rows_per_sec": 1e6, "e2e_p99_ms": 4.0,
                          "decoder": "native", "pool": 3,
                          "stages": {"fused": {"fold": {
                              "us_per_call": 60.0}}}}}))
        assert flat["headline.value"] == 123.0
        assert flat["phases.full_pipe.rows_per_sec"] == 1e6
        assert flat["phases.full_pipe.e2e_p99_ms"] == 4.0
        # nested leaves flatten through dicts
        assert ("phases.full_pipe.stages.fused.fold.us_per_call" in flat)
        # config echoes / strings are context, not compared metrics
        assert "phases.full_pipe.decoder" not in flat
        assert "phases.full_pipe.pool" not in flat

    def test_booleans_and_nan_excluded(self):
        flat = flatten(art(1.0, {
            "p": {"ok_per_sec": True, "bad_ms": float("nan")}}))
        assert "phases.p.ok_per_sec" not in flat
        assert "phases.p.bad_ms" not in flat

    def test_classify_directions(self):
        assert classify("headline.value") == "higher"
        assert classify("phases.full_pipe.rows_per_sec") == "higher"
        assert classify("phases.x.dedup_ratio") == "higher"
        assert classify("phases.x.e2e_p99_ms") == "lower"
        assert classify("phases.x.degradation_pct") == "lower"
        assert classify("phases.x.triggers") is None


class TestRoundOk:
    def test_parsed_null_is_the_r05_class(self):
        ok, reason = round_ok(art(rc=124, parsed=False))
        assert not ok
        assert "rc=124" in reason

    def test_watchdog_exit_with_artifact_is_usable(self):
        # bench's own watchdogs exit rc=3 WITH a final JSON — usable
        ok, _ = round_ok(art(rc=3))
        assert ok


class TestCompareAndGate:
    def test_within_tolerance_is_ok(self):
        cmp = compare([("a", art(1000.0)), ("b", art(950.0))])
        assert gate(cmp) == 0
        assert not cmp["regressions"]

    def test_headline_regression_gates(self):
        cmp = compare([("a", art(1000.0)), ("b", art(500.0))])
        assert gate(cmp) == 1
        assert cmp["headline_regressions"][0]["metric"] == "headline.value"
        assert cmp["headline_regressions"][0]["delta_pct"] == -50.0

    def test_latency_direction_inverted(self):
        base = art(phases={"full_pipe": {"e2e_p99_ms": 4.0}})
        worse = art(phases={"full_pipe": {"e2e_p99_ms": 20.0}})
        better = art(phases={"full_pipe": {"e2e_p99_ms": 1.0}})
        assert gate(compare([("a", base), ("b", worse)])) == 1  # headline
        cmp = compare([("a", base), ("b", better)])
        assert gate(cmp) == 0
        row = next(r for r in cmp["rows"]
                   if r["metric"] == "phases.full_pipe.e2e_p99_ms")
        assert row["status"] == "improved"

    def test_non_headline_regression_reports_but_passes(self):
        base = art(phases={"sliding_paced": {"deliver_p99_ms": 100.0}})
        slow = art(phases={"sliding_paced": {"deliver_p99_ms": 400.0}})
        cmp = compare([("a", base), ("b", slow)])
        assert gate(cmp) == 0
        assert [r["metric"] for r in cmp["regressions"]] == \
            ["phases.sliding_paced.deliver_p99_ms"]

    def test_custom_tolerance(self):
        cmp = compare([("a", art(1000.0)), ("b", art(870.0))],
                      tolerance=0.10)
        # headline keeps its OWN tolerance (10%): -13% gates
        assert gate(cmp) == 1

    def test_baseline_skips_rounds_missing_the_metric(self):
        """An r05-shaped hole (round with no phases) must not erase the
        baseline for phase metrics."""
        base = art(phases={"full_pipe": {"rows_per_sec": 1e6}})
        hole = art()  # headline only
        cand = art(phases={"full_pipe": {"rows_per_sec": 0.4e6}})
        cmp = compare([("r1", base), ("r2", hole), ("r3", cand)])
        row = next(r for r in cmp["rows"]
                   if r["metric"] == "phases.full_pipe.rows_per_sec")
        assert row["baseline_round"] == "r1"
        assert gate(cmp) == 1

    def test_new_and_dropped_metrics_never_gate(self):
        base = art(phases={"old_phase": {"rows_per_sec": 1e6}})
        cand = art(phases={"new_phase": {"rows_per_sec": 1.0}})
        cmp = compare([("a", base), ("b", cand)])
        assert gate(cmp) == 0
        statuses = {r["metric"]: r["status"] for r in cmp["rows"]}
        assert statuses["phases.old_phase.rows_per_sec"] == "dropped"
        assert statuses["phases.new_phase.rows_per_sec"] == "new"

    def test_vanished_headline_metric_gates(self):
        """A partially-dead bench — full_pipe child died, tumbling
        headline survived — must fail the gate: a HEADLINE metric
        present in the baseline but missing from the candidate is a
        regression, not a 'dropped' footnote."""
        base = art(phases={"full_pipe": {"rows_per_sec": 1e6,
                                         "e2e_p99_ms": 4.0}})
        cand = art()  # parsed fine, but no full_pipe phase at all
        cmp = compare([("a", base), ("b", cand)])
        assert cmp["candidate_ok"]
        assert gate(cmp) == 1
        gated = {r["metric"] for r in cmp["headline_regressions"]}
        assert gated == {"phases.full_pipe.rows_per_sec",
                         "phases.full_pipe.e2e_p99_ms"}
        # non-headline metrics vanishing still never gate (other test)

    def test_zero_baseline_still_flags(self):
        """0ms -> 500ms must flag (no ratio exists; it must not divide
        to 'ok'), and 0 -> 0 stays ok; a higher-better metric appearing
        from zero is an improvement."""
        base = art(phases={"s": {"fold_stall_p50_ms": 0.0,
                                 "extra_per_sec": 0.0}})
        cand = art(phases={"s": {"fold_stall_p50_ms": 500.0,
                                 "extra_per_sec": 10.0}})
        cmp = compare([("a", base), ("b", cand)])
        statuses = {r["metric"]: r["status"] for r in cmp["rows"]}
        assert statuses["phases.s.fold_stall_p50_ms"] == "REGRESSION"
        assert statuses["phases.s.extra_per_sec"] == "improved"
        assert gate(cmp) == 0  # neither is a headline metric
        same = compare([("a", base), ("b", base)])
        assert all(r["status"] == "ok" for r in same["rows"]
                   if r["metric"].startswith("phases.s."))

    def test_failed_candidate_gates(self):
        cmp = compare([("a", art()), ("b", art(rc=124, parsed=False))])
        assert not cmp["candidate_ok"]
        assert gate(cmp) == 1


class TestSmoke:
    def test_smoke_cli(self):
        """tools/benchdiff.py --smoke exits 0 (tier-1, like
        kuiperdiag --smoke / check_metrics)."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "benchdiff.py"),
             "--smoke"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, (
            f"benchdiff --smoke FAILED:\n{proc.stdout}\n{proc.stderr}")
        assert "OK" in proc.stdout
