"""End-to-end rule tests — modeled on the reference's topotest harness
(internal/topo/topotest/mock_topo.go DoRuleTest): build a real topo with a
memory source fed canned tuples, drive the mock clock, assert sink results.
"""
import time

import pytest

from ekuiper_tpu.io import memory as mem
from ekuiper_tpu.planner.planner import RuleDef, explain, plan_rule
from ekuiper_tpu.server.processors import RuleProcessor, StreamProcessor
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils import timex


@pytest.fixture(autouse=True)
def clean_pubsub():
    mem.reset()
    yield
    mem.reset()


def wait_results(sink_node, n=1, timeout=5.0):
    """Poll the sink until n results arrive (real-time wait, data-driven)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(sink_node.results) >= n:
            return list(sink_node.results)
        time.sleep(0.01)
    return list(sink_node.results)


def make_rule(sql, rule_id="r1", options=None, actions=None):
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT, ok BOOLEAN) '
        'WITH (DATASOURCE="topic/demo", TYPE="memory", FORMAT="JSON")'
    )
    rule = RuleDef(
        id=rule_id, sql=sql,
        actions=actions or [{"memory": {"topic": "res/" + rule_id}}],
        options=options or {},
    )
    topo = plan_rule(rule, store)
    return topo


def feed(rows, topic="topic/demo"):
    for row in rows:
        mem.publish(topic, row)


class TestScan:
    """Windowless passthrough rules."""

    def test_filter_project(self, mock_clock):
        topo = make_rule(
            "SELECT deviceId, temperature FROM demo WHERE temperature > 25"
        )
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([
                {"deviceId": "a", "temperature": 20.0},
                {"deviceId": "b", "temperature": 30.0},
                {"deviceId": "c", "temperature": 26.5},
            ])
            mock_clock.advance(20)  # linger flush
            results = wait_results(sink, 1)
            # one micro-batch in -> one result message (a list); sendSingle
            # splits when configured, matching reference semantics
            assert results[0] == [
                {"deviceId": "b", "temperature": 30.0},
                {"deviceId": "c", "temperature": 26.5},
            ]
        finally:
            topo.close()

    def test_expression_projection(self, mock_clock):
        topo = make_rule(
            "SELECT upper(deviceId) AS dev, temperature * 2 AS t2 FROM demo"
        )
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([{"deviceId": "a", "temperature": 3.0}])
            mock_clock.advance(20)
            results = wait_results(sink, 1)
            assert results[0] == {"dev": "A", "t2": 6.0}
        finally:
            topo.close()


class TestFusedTumbling:
    """The flagship device path: tumbling GROUP BY avg."""

    def test_tumbling_group_by(self, mock_clock):
        topo = make_rule(
            "SELECT deviceId, avg(temperature) AS avg_t, count(*) AS cnt "
            "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        # confirm the device path was chosen
        assert any(n.name == "window_agg" for n in topo.ops)
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([
                {"deviceId": "a", "temperature": 10.0},
                {"deviceId": "a", "temperature": 20.0},
                {"deviceId": "b", "temperature": 30.0},
            ])
            mock_clock.advance(20)  # flush micro-batch (linger)
            topo.wait_idle()  # deterministic: all in-flight batches folded
            mock_clock.advance(10_000)  # window fires
            results = wait_results(sink, 1)
            assert len(results) == 1
            got = {r["deviceId"]: r for r in results[0]}
            assert got["a"]["avg_t"] == 15.0 and got["a"]["cnt"] == 2
            assert got["b"]["avg_t"] == 30.0 and got["b"]["cnt"] == 1
            # next window: only new data
            feed([{"deviceId": "a", "temperature": 50.0}])
            mock_clock.advance(20)
            topo.wait_idle()
            mock_clock.advance(10_000)
            results = wait_results(sink, 2)
            got2 = {r["deviceId"]: r for r in results[1]} if isinstance(results[1], list) else {results[1]["deviceId"]: results[1]}
            assert got2["a"]["avg_t"] == 50.0 and got2["a"]["cnt"] == 1
            assert "b" not in got2  # b inactive in window 2
        finally:
            topo.close()

    def test_string_case_where_stays_device_fused(self, mock_clock):
        """Expression-IR WHERE (string-dict IN + CASE) keeps the rule on
        the fused device path — no FilterNode hop — with row-interpreter
        result parity."""
        topo = make_rule(
            "SELECT deviceId, count(*) AS cnt, sum(temperature) AS s "
            "FROM demo WHERE deviceId IN ('a', 'b') AND "
            "CASE WHEN temperature > 25 THEN 1 ELSE 0 END = 1 "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        # the WHERE compiled into the kernel: fused node, no filter hop
        assert any(n.name == "window_agg" for n in topo.ops)
        assert not any(n.name == "filter" for n in topo.ops)
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([
                {"deviceId": "a", "temperature": 30.0},   # kept
                {"deviceId": "a", "temperature": 20.0},   # CASE=0
                {"deviceId": "b", "temperature": 40.0},   # kept
                {"deviceId": "c", "temperature": 50.0},   # not IN
                {"deviceId": None, "temperature": 99.0},  # NULL drops
            ])
            mock_clock.advance(20)
            topo.wait_idle()
            mock_clock.advance(10_000)
            results = wait_results(sink, 1)
            got = {r["deviceId"]: r for r in results[0]}
            assert set(got) == {"a", "b"}
            assert got["a"]["cnt"] == 1 and got["a"]["s"] == 30.0
            assert got["b"]["cnt"] == 1 and got["b"]["s"] == 40.0
        finally:
            topo.close()

    def test_having_on_device_path(self, mock_clock):
        topo = make_rule(
            "SELECT deviceId, avg(temperature) AS t FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10) HAVING avg(temperature) > 20"
        )
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([
                {"deviceId": "cold", "temperature": 10.0},
                {"deviceId": "hot", "temperature": 30.0},
            ])
            mock_clock.advance(20)
            topo.wait_idle()
            mock_clock.advance(10_000)
            results = wait_results(sink, 1)
            assert len(results) == 1
            only = results[0] if isinstance(results[0], dict) else results[0][0]
            assert only["deviceId"] == "hot"
        finally:
            topo.close()

    def test_explain_paths(self):
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="t", TYPE="memory")'
        )
        device = explain(RuleDef(id="x", sql=(
            "SELECT avg(temperature) FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )), store)
        assert device["path"] == "device-fused"
        host = explain(RuleDef(id="y", sql=(
            "SELECT collect(deviceId) FROM demo GROUP BY SLIDINGWINDOW(ss, 10)"
        )), store)
        assert host["path"] == "host"


class TestHostWindows:
    def test_count_window_host_agg(self, mock_clock):
        # collect() is not device-eligible -> host path with COUNTWINDOW
        topo = make_rule(
            "SELECT collect(temperature) AS temps FROM demo GROUP BY COUNTWINDOW(3)"
        )
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([{"deviceId": "a", "temperature": float(i)} for i in range(3)])
            mock_clock.advance(20)
            results = wait_results(sink, 1)
            assert results[0] == {"temps": [0.0, 1.0, 2.0]}
        finally:
            topo.close()

    def test_tumbling_host_path_when_disabled(self, mock_clock):
        topo = make_rule(
            "SELECT deviceId, avg(temperature) AS t FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            options={"use_device_kernel": False},
        )
        assert any(n.name == "window" for n in topo.ops)
        sink = topo.sinks[0]
        topo.open()
        try:
            feed([
                {"deviceId": "a", "temperature": 10.0},
                {"deviceId": "a", "temperature": 30.0},
            ])
            mock_clock.advance(20)
            topo.wait_idle()
            mock_clock.advance(10_000)
            results = wait_results(sink, 1)
            row = results[0] if isinstance(results[0], dict) else results[0][0]
            assert row == {"deviceId": "a", "t": 20.0}
        finally:
            topo.close()


class TestRuleFSM:
    def test_start_stop_status(self, mock_clock):
        from ekuiper_tpu.runtime.rule import RuleState, RunState

        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo () WITH (DATASOURCE="t/d", TYPE="memory")'
        )
        rule = RuleProcessor(store).create({
            "id": "fsm1",
            "sql": "SELECT * FROM demo",
            "actions": [{"nop": {}}],
        })
        rs = RuleState(rule, store)
        rs.start()
        deadline = time.time() + 5
        while rs.state != RunState.RUNNING and time.time() < deadline:
            time.sleep(0.01)
        assert rs.state == RunState.RUNNING
        status = rs.status()
        assert status["status"] == "running"
        rs.stop()
        deadline = time.time() + 5
        while rs.state != RunState.STOPPED and time.time() < deadline:
            time.sleep(0.01)
        assert rs.state == RunState.STOPPED


class TestRuleOptions:
    def test_duration_options_coerced_and_validated(self):
        """Rule options accept int ms (reference form: rules/overview.md
        checkpointInterval int) or Go-style duration strings; bad values
        fail at plan time with PlanError, not at topo.open."""
        from ekuiper_tpu.planner.planner import merged_options
        from ekuiper_tpu.utils.infra import PlanError

        def opts(**o):
            return merged_options(RuleDef(id="x", sql="", actions=[], options=o))

        assert opts(checkpointInterval=5000).checkpoint_interval_ms == 5000
        assert opts(checkpointInterval="1s").checkpoint_interval_ms == 1000
        assert opts(lateTolerance="500ms").late_tolerance_ms == 500
        assert opts(qos="2").qos == 2
        with pytest.raises(PlanError, match="checkpointInterval"):
            opts(checkpointInterval="one second")
        with pytest.raises(PlanError, match="qos"):
            opts(qos="high")
        assert opts(sendError="false").send_error is False
        assert opts(sendError="true").send_error is True
        assert opts(sendError=False).send_error is False
        with pytest.raises(PlanError, match="sendError"):
            opts(sendError="maybe")
