"""Predicate lifting (ops/aggspec.py lift_predicate +
planner/sharing.py): rules that differ only in WHERE share ONE pooled
pane fold — each member's predicate becomes per-spec device FILTER
masks plus a private activity spec. Byte parity: every member's emitted
windows must be bit-identical to its private (unshared) plan's."""
import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.data.rows import WindowRange
from ekuiper_tpu.ops.aggspec import extract_kernel_plan, lift_predicate
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.ops.panestore import pane_gcd, union_plan
from ekuiper_tpu.runtime.events import Trigger
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.runtime.nodes_sharedfold import (
    MemberSpec, SharedEmitNode, SharedFoldNode,
)
from ekuiper_tpu.sql import ast
from ekuiper_tpu.sql.parser import parse_select

#: four rules over one stream, same GROUP BY + window grid, WHEREs all
#: different (numeric, string-dict, CASE-bearing, none) — the shape that
#: planned four PRIVATE folds before predicate lifting
SQLS = [
    "SELECT deviceId, count(*) AS c, sum(temperature) AS s FROM demo "
    "WHERE temperature > 20 GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
    "SELECT deviceId, count(*) AS c, sum(temperature) AS s FROM demo "
    "WHERE temperature > 30 GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
    "SELECT deviceId, count(*) AS c, min(temperature) AS mn FROM demo "
    "WHERE status = 'ok' AND temperature <= 40 "
    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
    "SELECT deviceId, count(*) AS c FROM demo "
    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
]


def _batch(rng, n=120, t0=0):
    ids = np.array([f"d{rng.integers(0, 6)}" for _ in range(n)],
                   dtype=np.object_)
    temp = np.rint(rng.normal(25, 12, n)).astype(np.float32)
    status = np.array([("ok", "warn", "err")[rng.integers(0, 3)]
                       for _ in range(n)], dtype=np.object_)
    # a few NULLs: predicate masks must drop them, not fold them
    for i in rng.integers(0, n, 5):
        status[i] = None
    ts = np.full(n, t0, dtype=np.int64)
    return ColumnBatch(n=n, columns={"deviceId": ids, "temperature": temp,
                                     "status": status},
                       timestamps=ts, emitter="demo")


def _copy(b):
    return ColumnBatch(n=b.n, columns=b.columns, valid=b.valid,
                       timestamps=b.timestamps, emitter=b.emitter)


def _drain(entry):
    out = []
    while not entry.inq.empty():
        item = entry.inq.get_nowait()
        if isinstance(item, ColumnBatch):
            out.append(item)
    return out


class TestLiftPlan:
    def test_lift_shape(self):
        stmt = parse_select(SQLS[0])
        plan = extract_kernel_plan(stmt)
        lifted = lift_predicate(plan, stmt.condition)
        assert lifted.filter is None
        assert len(lifted.specs) == len(plan.specs) + 1
        assert lifted.act_idx == len(plan.specs)
        # every original spec now carries the predicate as FILTER
        for s in lifted.specs:
            assert s.filter is not None
        # spec order preserved: direct-emit indices stay valid
        assert [s.kind for s in lifted.specs[:-1]] == \
            [s.kind for s in plan.specs]

    def test_no_predicate_is_identity(self):
        stmt = parse_select(SQLS[3])
        plan = extract_kernel_plan(stmt)
        assert lift_predicate(plan, stmt.condition) is plan

    def test_union_dedups_identical_where_only(self):
        stmts = [parse_select(s) for s in (SQLS[0], SQLS[0], SQLS[1])]
        lifted = [lift_predicate(extract_kernel_plan(s), s.condition)
                  for s in stmts]
        union, maps = union_plan(lifted)
        # rules 0 and 1 (identical WHERE) dedup completely; rule 2 adds
        # its own masked specs. Within one rule the synthetic activity
        # spec aliases its own `count(*) FILTER(pred)` spec (same call
        # key), so each rule contributes 2 distinct columns, not 3.
        assert len(union.specs) == 4
        assert maps[0] == maps[1]
        assert maps[2] != maps[0]


class TestByteParity:
    def test_mixed_where_shared_equals_private(self):
        stmts = [parse_select(s) for s in SQLS]
        plans = [extract_kernel_plan(s) for s in stmts]
        assert all(p is not None for p in plans)
        lifted = [lift_predicate(p, s.condition)
                  for p, s in zip(plans, stmts)]
        union, _ = union_plan(lifted)
        assert union.filter is None
        pane = pane_gcd([10_000])
        store = SharedFoldNode("k", "sf_lift", union, pane, 3,
                               subtopo_ref=None, capacity=64,
                               micro_batch=256)
        store._cur_bucket = 0
        entries = []
        for i, (stmt, plan, lp) in enumerate(zip(stmts, plans, lifted)):
            spec = MemberSpec(
                rule_id=f"r{i}", length_ms=10_000, interval_ms=10_000,
                plan=lp, dims=["deviceId"],
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, act_idx=lp.act_idx)
            e = SharedEmitNode(f"r{i}_emit")
            assert store.attach_rule(spec, e, None)
            entries.append(e)

        privs = []
        for stmt, plan in zip(stmts, plans):
            n = FusedWindowAggNode(
                "priv", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=64, micro_batch=256,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, prefinalize_lead_ms=0)
            n.state = n.gb.init_state()
            got = []
            n.broadcast = lambda item, g=got: g.append(item)
            privs.append((n, got))

        rng = np.random.default_rng(11)
        for end in (10_000, 20_000, 30_000):
            for _ in range(3):
                b = _batch(rng, t0=end - 5_000)
                store.process(b)
                for p, _g in privs:
                    p.process(_copy(b))
            store.on_trigger(Trigger(ts=end))
            for p, _g in privs:
                p._emit(WindowRange(end - 10_000, end))
                p.state = p.gb.reset_pane(p.state, 0)

        total = 0
        for i, e in enumerate(entries):
            shared = _drain(e)
            priv = [x for x in privs[i][1] if isinstance(x, ColumnBatch)]
            assert shared, f"rule {i} emitted nothing"
            assert len(shared) == len(priv), i
            for s, p in zip(shared, priv):
                assert set(s.columns) == set(p.columns), i
                for c in s.columns:
                    assert s.columns[c].dtype == p.columns[c].dtype, (i, c)
                    assert np.array_equal(s.columns[c], p.columns[c]), \
                        (i, c, s.columns[c], p.columns[c])
                total += s.n
        assert total > 0
        # dedup accounting: one fold per batch serves 4 members
        assert store.folds_did == 9
        assert store.fold_dedup_ratio() == pytest.approx(0.75)

    def test_member_activity_excludes_fully_filtered_groups(self):
        """A key whose rows ALL fail one member's predicate must not
        emit a group for that member (the lifted activity spec), while
        a no-predicate peer still sees it."""
        sql_hot = ("SELECT deviceId, count(*) AS c FROM demo "
                   "WHERE temperature > 100 "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        sql_all = ("SELECT deviceId, count(*) AS c FROM demo "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        stmts = [parse_select(sql_hot), parse_select(sql_all)]
        plans = [extract_kernel_plan(s) for s in stmts]
        lifted = [lift_predicate(p, s.condition)
                  for p, s in zip(plans, stmts)]
        union, _ = union_plan(lifted)
        store = SharedFoldNode("k2", "sf_act", union, 10_000, 3,
                               subtopo_ref=None, capacity=16,
                               micro_batch=64)
        store._cur_bucket = 0
        entries = []
        for i, (stmt, plan, lp) in enumerate(zip(stmts, plans, lifted)):
            spec = MemberSpec(
                rule_id=f"r{i}", length_ms=10_000, interval_ms=10_000,
                plan=lp, dims=["deviceId"],
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=True, act_idx=lp.act_idx)
            e = SharedEmitNode(f"r{i}_e")
            store.attach_rule(spec, e, None)
            entries.append(e)
        cold = ColumnBatch(
            n=4,
            columns={"deviceId": np.array(["cold"] * 4, dtype=np.object_),
                     "temperature": np.array([1., 2., 3., 4.],
                                             dtype=np.float32)},
            timestamps=np.zeros(4, dtype=np.int64), emitter="demo")
        hot = ColumnBatch(
            n=2,
            columns={"deviceId": np.array(["hot"] * 2, dtype=np.object_),
                     "temperature": np.array([150., 200.],
                                             dtype=np.float32)},
            timestamps=np.zeros(2, dtype=np.int64), emitter="demo")
        store.process(cold)
        store.process(hot)
        store.on_trigger(Trigger(ts=10_000))
        got_hot = _drain(entries[0])
        got_all = _drain(entries[1])
        assert len(got_hot) == 1 and got_hot[0].n == 1
        assert got_hot[0].columns["deviceId"].tolist() == ["hot"]
        assert got_all[0].n == 2  # the unfiltered peer sees both keys


class TestLiftGuards:
    def test_uncompilable_conjunction_stays_private(self):
        """Pieces that compile separately but conflict when conjoined
        (WHERE types the column temporal, FILTER arithmetic types it
        numeric) must return None — the caller keeps a private fold —
        never raise out of rule planning."""
        stmt = parse_select(
            "SELECT deviceId, sum(temperature) FILTER (WHERE ts % 2 = 0)"
            " AS s FROM demo WHERE ts > 1700000000000 "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(stmt)
        assert plan is not None
        assert lift_predicate(plan, stmt.condition) is None

    def test_lift_reuses_plan_dictionaries(self):
        """The lifted filters must resolve to the SAME __sd_* columns
        the plan's arg closures already reference — one host encode,
        one upload per raw column."""
        stmt = parse_select(
            "SELECT deviceId, sum(CASE WHEN status = 'warn' THEN "
            "temperature ELSE 0.0 END) AS s FROM demo "
            "WHERE status = 'ok' GROUP BY deviceId, "
            "TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(stmt)
        lifted = lift_predicate(plan, stmt.condition)
        sd = [d for d in lifted.derived if d.kind == "strdict"]
        assert len(sd) == 1
        assert set(sd[0].values) == {"ok", "warn"}

    def test_temporal_value_never_escapes_as_number(self):
        """A CASE yielding the raw (anchor-rebased) event-time column
        must NOT device-compile — letting it out would emit epoch-ms
        minus the plan anchor."""
        from ekuiper_tpu.ops.aggspec import take_expr_fallbacks

        stmt = parse_select(
            "SELECT deviceId, max(CASE WHEN hour(ts) < 23 THEN ts "
            "ELSE 0 END) AS m FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        assert extract_kernel_plan(stmt) is None
        assert any(n["reason"] == "temporal-value"
                   for n in take_expr_fallbacks())
