"""Event-time windows on the device kernel (per-row pane routing +
watermark-driven emission) — output parity with the host window path."""
import time

import numpy as np
import pytest

from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _mk_stream(store):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM ed (deviceId STRING, temperature FLOAT, ts BIGINT) '
        'WITH (DATASOURCE="ev/d", TYPE="memory", FORMAT="JSON", '
        'TIMESTAMP="ts")')


def _run_rule(store, mock_clock, sql, rows, options, rule_id, wm_rows=None):
    topo = plan_rule(RuleDef(
        id=rule_id, sql=sql,
        actions=[{"memory": {"topic": f"ev/{rule_id}"}}],
        options=options), store)
    got = []
    mem.subscribe(f"ev/{rule_id}", lambda t, p: got.append(p))
    topo.open()
    try:
        for r in rows:
            mem.publish("ev/d", r)
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        for r in (wm_rows or []):  # watermark pushers
            mem.publish("ev/d", r)
            mock_clock.advance(20)
            assert topo.wait_idle(10)
        deadline = time.time() + 6
        while time.time() < deadline and not got:
            time.sleep(0.02)
        time.sleep(0.2)
    finally:
        topo.close()
    out = []
    for p in got:
        out.extend(p if isinstance(p, list) else [p])
    return out, topo


SQL = ("SELECT deviceId, count(*) AS c, avg(temperature) AS a, "
       "min(temperature) AS mn FROM ed "
       "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
SQL_HOP = ("SELECT deviceId, count(*) AS c, avg(temperature) AS a FROM ed "
           "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)")

ROWS = [
    {"deviceId": "a", "temperature": 10.0, "ts": 1_000},
    {"deviceId": "a", "temperature": 20.0, "ts": 6_000},
    {"deviceId": "b", "temperature": 5.0, "ts": 9_000},
    {"deviceId": "a", "temperature": 30.0, "ts": 12_000},
    {"deviceId": "b", "temperature": 7.0, "ts": 15_000},
]
PUSHER = [{"deviceId": "z", "temperature": 0.0, "ts": 40_000}]


def _norm(msgs):
    def r2(x):
        return None if x is None else round(x, 4)

    out = {}
    for m in msgs:
        if m["deviceId"] == "z":
            continue
        key = (m["deviceId"], m.get("window_end") or 0)
        out.setdefault(key, []).append(
            tuple(sorted((k, r2(v) if isinstance(v, float) else v)
                         for k, v in m.items() if k != "deviceId")))
    return out


class TestEventTimeFusedParity:
    def _both(self, mock_clock, sql):
        store = kv.get_store()
        _mk_stream(store)
        fused_msgs, fused_topo = _run_rule(
            store, mock_clock, sql, ROWS,
            {"isEventTime": True, "lateTolerance": 1000}, "ef",
            wm_rows=PUSHER)
        assert any(isinstance(n, FusedWindowAggNode) for n in fused_topo.ops), \
            "event-time rule did not take the device path"
        host_msgs, host_topo = _run_rule(
            store, mock_clock, sql, ROWS,
            {"isEventTime": True, "lateTolerance": 1000,
             "use_device_kernel": False}, "eh",
            wm_rows=PUSHER)
        assert not any(isinstance(n, FusedWindowAggNode)
                       for n in host_topo.ops)
        return fused_msgs, host_msgs

    def test_tumbling(self, mock_clock):
        fused, host = self._both(mock_clock, SQL)
        fa = {(m["deviceId"]): (m["c"], round(m["a"], 4), m["mn"])
              for m in fused if m["deviceId"] != "z"}
        ha = {}
        for m in host:
            if m["deviceId"] != "z":
                ha.setdefault(m["deviceId"], []).append(
                    (m["c"], round(m["a"], 4), m["mn"]))
        # every fused (device, window) result appears in the host output
        for m in fused:
            if m["deviceId"] == "z":
                continue
            assert (m["c"], round(m["a"], 4), m["mn"]) in \
                ha.get(m["deviceId"], []), (m, host)
        # same total group-windows emitted
        n_f = sum(1 for m in fused if m["deviceId"] != "z")
        n_h = sum(1 for m in host if m["deviceId"] != "z")
        assert n_f == n_h, (fused, host)

    def test_hopping(self, mock_clock):
        fused, host = self._both(mock_clock, SQL_HOP)

        def collect(msgs):
            out = {}
            for m in msgs:
                if m["deviceId"] == "z":
                    continue
                out.setdefault(m["deviceId"], []).append(
                    (m["c"], round(m["a"], 4)))
            return {k: sorted(v) for k, v in out.items()}

        assert collect(fused) == collect(host), (fused, host)


SQL_SESS = ("SELECT deviceId, count(*) AS c, avg(temperature) AS a FROM ed "
            "GROUP BY deviceId, SESSIONWINDOW(ss, 30, 5)")
# session 1: ts 1000..4000 (incl. an out-of-order 2500); session 2 opens at
# 12_000 (gap 8s > 5s); both close when the watermark passes last+gap
SESS_ROWS = [
    {"deviceId": "a", "temperature": 10.0, "ts": 1_000},
    {"deviceId": "a", "temperature": 20.0, "ts": 4_000},
    {"deviceId": "b", "temperature": 6.0, "ts": 2_500},  # out of order
    {"deviceId": "a", "temperature": 30.0, "ts": 12_000},
    {"deviceId": "b", "temperature": 8.0, "ts": 13_000},
]


class TestEventTimeSessionParity:
    def test_eligibility(self):
        from ekuiper_tpu.planner.planner import device_path_eligible
        from ekuiper_tpu.sql.parser import parse_select
        from ekuiper_tpu.utils.config import RuleOptionConfig

        stmt = parse_select(SQL_SESS)
        opts = RuleOptionConfig()
        opts.is_event_time = True
        assert device_path_eligible(stmt, opts) is not None
        opts.plan_optimize_strategy = {"mesh": {"rows": 2, "keys": 4}}
        # mesh OK since round 5: session split is host-side, folds shard
        assert device_path_eligible(stmt, opts) is not None

    def test_session_parity(self, mock_clock):
        fused, host = self._run_both(mock_clock)

        def collect(msgs):
            out = {}
            for m in msgs:
                if m["deviceId"] == "z":
                    continue
                out.setdefault(m["deviceId"], []).append(
                    (m["c"], round(m["a"], 4)))
            return {k: sorted(v) for k, v in out.items()}

        assert collect(fused) == collect(host), (fused, host)
        # exact structure: session 1 = a:{10,20}, b:{6}; session 2 = a:{30},
        # b:{8} — the out-of-order b row lands in session 1
        assert collect(fused) == {"a": sorted([(2, 15.0), (1, 30.0)]),
                                  "b": sorted([(1, 6.0), (1, 8.0)])}

    def _run_both(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        fused_msgs, fused_topo = _run_rule(
            store, mock_clock, SQL_SESS, SESS_ROWS,
            {"isEventTime": True, "lateTolerance": 1000}, "sf",
            wm_rows=PUSHER)
        assert any(isinstance(n, FusedWindowAggNode)
                   for n in fused_topo.ops), \
            "event-time session rule did not take the device path"
        host_msgs, host_topo = _run_rule(
            store, mock_clock, SQL_SESS, SESS_ROWS,
            {"isEventTime": True, "lateTolerance": 1000,
             "use_device_kernel": False}, "sh",
            wm_rows=PUSHER)
        assert not any(isinstance(n, FusedWindowAggNode)
                       for n in host_topo.ops)
        return fused_msgs, host_msgs

    def test_incomplete_session_waits_for_watermark(self, mock_clock):
        """A session whose gap has not yet been passed by the watermark
        must NOT emit (host-path parity: last + gap <= wm)."""
        store = kv.get_store()
        _mk_stream(store)
        rows = [{"deviceId": "a", "temperature": 10.0, "ts": 1_000}]
        # watermark pusher at 5_500: with lateTolerance 1000 the watermark
        # is ~4_500 < last(1_000) + gap(5_000) -> session stays open
        msgs, topo = _run_rule(
            store, mock_clock, SQL_SESS, rows,
            {"isEventTime": True, "lateTolerance": 1000}, "sw",
            wm_rows=[{"deviceId": "z", "temperature": 0.0, "ts": 5_500}])
        open_msgs = [m for m in msgs if m["deviceId"] == "a"]
        # the EOF flush at close() emits the buffered session — but only
        # ONE emission total and only at close, never at the watermark
        assert len(open_msgs) <= 1

    def test_checkpoint_roundtrip_buffers(self, mock_clock):
        """Buffered (unclosed) session rows survive snapshot/restore."""
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.events import Watermark
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.sql.parser import parse_select
        import json

        stmt = parse_select(SQL_SESS)
        plan = extract_kernel_plan(stmt)

        def mknode(name):
            n = FusedWindowAggNode(
                name, stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions], capacity=64,
                micro_batch=64, is_event_time=True,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
            n.state = n.gb.init_state()
            got = []
            n.broadcast = lambda item: got.append(item)
            return n, got

        node, got = mknode("s1")
        b = ColumnBatch(
            n=2,
            columns={"deviceId": np.array(["a", "a"], dtype=np.object_),
                     "temperature": np.array([10.0, 20.0],
                                             dtype=np.float32)},
            timestamps=np.array([1_000, 3_000], dtype=np.int64),
            emitter="ed")
        node.process(b)
        snap = json.loads(json.dumps(node.snapshot_state()))
        node2, got2 = mknode("s2")
        node2.restore_state(snap)
        node2.on_watermark(Watermark(ts=60_000))
        node2._drain_async_emits()
        msgs = []
        for item in got2:
            if isinstance(item, ColumnBatch):
                msgs.extend(item.to_messages())
            elif isinstance(item, list):
                msgs.extend(item)
            elif hasattr(item, "groups"):
                continue
        assert any(m.get("c") == 2 and m.get("a") == 15.0 for m in msgs), \
            (msgs, got2)


class TestEventTimeFusedMechanics:
    def test_late_rows_dropped_after_emit(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        rows = [
            {"deviceId": "a", "temperature": 1.0, "ts": 1_000},
        ]
        topo = plan_rule(RuleDef(
            id="lt1", sql=SQL, actions=[{"memory": {"topic": "ev/lt1"}}],
            options={"isEventTime": True, "lateTolerance": 0}), store)
        got = []
        mem.subscribe("ev/lt1", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("ev/d", rows[0])
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            # push watermark past window 1 -> emit (a,c=1)
            mem.publish("ev/d", {"deviceId": "z", "temperature": 0.0,
                                 "ts": 25_000})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            # a very late row for the emitted window must be dropped by the
            # watermark node / kernel, not corrupt a recycled pane
            mem.publish("ev/d", {"deviceId": "a", "temperature": 99.0,
                                 "ts": 1_500})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mem.publish("ev/d", {"deviceId": "z", "temperature": 0.0,
                                 "ts": 60_000})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            deadline = time.time() + 6
            while time.time() < deadline and not got:
                time.sleep(0.02)
            time.sleep(0.2)
        finally:
            topo.close()
        msgs = []
        for p in got:
            msgs.extend(p if isinstance(p, list) else [p])
        a_msgs = [m for m in msgs if m["deviceId"] == "a"]
        assert a_msgs == [{"deviceId": "a", "c": 1, "a": 1.0, "mn": 1.0}], msgs

    def test_pane_overflow_forces_emission(self, mock_clock):
        """A burst spanning more buckets than panes must force-emit the
        oldest windows rather than corrupt recycled panes."""
        from ekuiper_tpu.data.batch import from_tuples
        from ekuiper_tpu.data.rows import Tuple
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.sql.parser import parse_select

        stmt = parse_select(SQL.replace("FROM ed", "FROM s"))
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "t", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=32, is_event_time=True,
            late_tolerance_ms=0)
        node.state = node.gb.init_state()
        emitted = []
        node.broadcast = lambda item: emitted.append(item)
        # n_panes buckets + 3 more in one stream of batches
        n = node.n_panes + 3
        rows = [Tuple(emitter="s",
                      message={"deviceId": "d", "temperature": float(i)},
                      timestamp=i * 10_000 + 500)
                for i in range(n)]
        node.process(from_tuples(rows, emitter="s"))
        # forced emissions happened for the overflowed buckets
        assert len(emitted) >= 3
        assert node._next_emit_bucket > 0

    def test_time_gap_skips_empty_windows(self, mock_clock):
        """An overnight gap (or outlier timestamp) must fast-forward, not
        emit one device round trip per empty bucket."""
        from ekuiper_tpu.data.batch import from_tuples
        from ekuiper_tpu.data.rows import Tuple
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.runtime.events import Watermark
        from ekuiper_tpu.sql.parser import parse_select

        stmt = parse_select(SQL.replace("FROM ed", "FROM s"))
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "t", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=32, is_event_time=True,
            late_tolerance_ms=0)
        node.state = node.gb.init_state()
        emitted = []
        node.broadcast = lambda item: emitted.append(item)
        calls = {"n": 0}
        orig = node.gb.finalize

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        node.gb.finalize = counting
        mk = lambda ts: from_tuples([Tuple(
            emitter="s", message={"deviceId": "d", "temperature": 1.0},
            timestamp=ts)], emitter="s")
        node.process(mk(1_000))
        node.on_watermark(Watermark(ts=15_000))       # emits window 1
        # 100k buckets later (11+ days at 10s buckets)
        node.process(mk(1_000_000_000))
        node.on_watermark(Watermark(ts=1_000_020_000))
        data_windows = [i for i in emitted if not isinstance(i, Watermark)]
        assert len(data_windows) == 2
        assert calls["n"] <= 4, calls  # no per-empty-bucket device calls


class TestDivisibilityGate:
    def test_event_hopping_non_divisible_raises_on_node(self):
        """HOPPINGWINDOW(ss,25,10) under event time: flooring the pane span
        would silently aggregate only 20s of a declared 25s window — direct
        node construction must fail loudly (the planner routes these shapes
        to the exact host path)."""
        import pytest

        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
        from ekuiper_tpu.sql.parser import parse_select

        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM s "
            "GROUP BY deviceId, HOPPINGWINDOW(ss, 25, 10)")
        plan = extract_kernel_plan(stmt)
        with pytest.raises(ValueError, match="not a multiple"):
            FusedWindowAggNode(
                "bad", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=64, micro_batch=32, is_event_time=True)

    def test_planner_routes_non_divisible_to_host(self):
        from ekuiper_tpu.planner.planner import device_path_eligible
        from ekuiper_tpu.sql.parser import parse_select
        from ekuiper_tpu.utils.config import RuleOptionConfig

        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM s "
            "GROUP BY deviceId, HOPPINGWINDOW(ss, 25, 10)")
        opts = RuleOptionConfig(is_event_time=True)
        assert device_path_eligible(stmt, opts) is None


class TestEventTimeCountParity:
    """Event-time COUNT windows on the device path: the watermark node
    late-drops + orders, then counting folds exactly like processing time
    (host oracle: nodes_window.py _ingest_row COUNT branch)."""

    def test_eligibility(self):
        from ekuiper_tpu.planner.planner import device_path_eligible
        from ekuiper_tpu.sql.parser import parse_select
        from ekuiper_tpu.utils.config import RuleOptionConfig

        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM ed "
            "GROUP BY deviceId, COUNTWINDOW(4)")
        assert device_path_eligible(
            stmt, RuleOptionConfig(is_event_time=True)) is not None
        # overlapping count windows still buffer on the host
        stmt2 = parse_select(
            "SELECT deviceId, count(*) AS c FROM ed "
            "GROUP BY deviceId, COUNTWINDOW(4, 2)")
        assert device_path_eligible(
            stmt2, RuleOptionConfig(is_event_time=True)) is None

    def test_parity_with_host(self, mock_clock):
        sql = ("SELECT deviceId, count(*) AS c, avg(temperature) AS a "
               "FROM ed GROUP BY deviceId, COUNTWINDOW(4)")
        mem.reset()
        store = kv.get_store()
        _mk_stream(store)
        fused_msgs, fused_topo = _run_rule(
            store, mock_clock, sql, ROWS + PUSHER,
            {"isEventTime": True, "lateTolerance": 1000}, "ecf")
        assert any(isinstance(n, FusedWindowAggNode)
                   for n in fused_topo.ops), \
            "event-time count rule did not take the device path"
        host_msgs, host_topo = _run_rule(
            store, mock_clock, sql, ROWS + PUSHER,
            {"isEventTime": True, "lateTolerance": 1000,
             "use_device_kernel": False}, "ech")
        assert not any(isinstance(n, FusedWindowAggNode)
                       for n in host_topo.ops)

        def norm(msgs):
            return sorted(
                (m["deviceId"], m["c"], round(m["a"], 4)) for m in msgs)

        assert fused_msgs and norm(fused_msgs) == norm(host_msgs)


class TestEventTimeStateParity:
    """Event-time STATE windows on the device path — watermark-ordered rows
    toggle begin/emit exactly like the host path's condition scan."""

    def test_parity_with_host(self, mock_clock):
        sql = ("SELECT deviceId, count(*) AS c, avg(temperature) AS a "
               "FROM ed GROUP BY deviceId, "
               "STATEWINDOW(temperature > 25, temperature < 8)")
        rows = [
            {"deviceId": "a", "temperature": 30.0, "ts": 1_000},  # begin
            {"deviceId": "a", "temperature": 15.0, "ts": 2_000},
            {"deviceId": "b", "temperature": 5.0, "ts": 3_000},   # emit
            {"deviceId": "a", "temperature": 40.0, "ts": 4_000},  # begin
            {"deviceId": "b", "temperature": 2.0, "ts": 5_000},   # emit
        ]
        mem.reset()
        store = kv.get_store()
        _mk_stream(store)
        fused_msgs, fused_topo = _run_rule(
            store, mock_clock, sql, rows,
            {"isEventTime": True, "lateTolerance": 1000}, "esf")
        assert any(isinstance(n, FusedWindowAggNode)
                   for n in fused_topo.ops), \
            "event-time state rule did not take the device path"
        host_msgs, host_topo = _run_rule(
            store, mock_clock, sql, rows,
            {"isEventTime": True, "lateTolerance": 1000,
             "use_device_kernel": False}, "esh")
        assert not any(isinstance(n, FusedWindowAggNode)
                       for n in host_topo.ops)

        def norm(msgs):
            return sorted(
                (m["deviceId"], m["c"], round(m["a"], 4)) for m in msgs)

        assert fused_msgs and norm(fused_msgs) == norm(host_msgs)


def test_event_time_state_open_span_flushes_at_eof():
    """An open (never-closed) event-time STATE window must flush at EOF,
    matching the host path's buffer flush (review finding r5)."""
    import numpy as np

    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.events import EOF
    from ekuiper_tpu.sql.parser import parse_select

    sql = ("SELECT deviceId, count(*) AS c, avg(v) AS a FROM s "
           "GROUP BY deviceId, STATEWINDOW(st = 1, st = 0)")
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "eof_st", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=16, micro_batch=32, is_event_time=True,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    node.process(ColumnBatch(
        n=2,
        columns={"deviceId": np.array(["a", "a"], dtype=np.object_),
                 "v": np.asarray([1.0, 2.0], np.float32),
                 "st": np.asarray([1, 5], np.int64)},
        timestamps=np.asarray([1000, 2000], np.int64), emitter="s"))
    node.on_eof(EOF(source_id="s"))
    msgs = [m for item in got if isinstance(item, list) for m in item]
    assert msgs and msgs[0]["c"] == 2 and abs(msgs[0]["a"] - 1.5) < 1e-6, got
