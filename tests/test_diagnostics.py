"""Flight recorder, drop taxonomy, /diagnostics/* endpoints, and the
kuiperdiag support bundle — all mock-clock, CPU, tier-1."""
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from ekuiper_tpu.runtime.events import FlightRecorder, recorder
from ekuiper_tpu.runtime.node import Node
from ekuiper_tpu.utils.metrics import StatManager

REPO = Path(__file__).resolve().parent.parent


class TestFlightRecorder:
    def test_ring_bounds_and_eviction_order(self):
        fr = FlightRecorder(capacity=4)
        for i in range(7):
            fr.record("k", rule="r", i=i)
        evs = fr.events()
        # oldest evicted first; the survivors keep arrival order
        assert [e["i"] for e in evs] == [3, 4, 5, 6]
        assert [e["seq"] for e in evs] == [4, 5, 6, 7]
        assert fr.total_recorded == 7
        assert fr.capacity == 4

    def test_filters_and_limit(self):
        fr = FlightRecorder(capacity=16)
        fr.record("a", rule="r1", x=1)
        fr.record("b", rule="r1", x=2)
        fr.record("a", rule="r2", x=3)
        assert [e["x"] for e in fr.events(kind="a")] == [1, 3]
        assert [e["x"] for e in fr.events(rule="r1")] == [1, 2]
        assert [e["x"] for e in fr.events(kind="a", rule="r2")] == [3]
        # limit keeps the NEWEST n after filtering
        assert [e["x"] for e in fr.events(limit=2)] == [2, 3]
        assert fr.events(kind="zzz") == []

    def test_mock_clock_timestamps(self, mock_clock):
        fr = FlightRecorder()
        fr.record("t")
        mock_clock.advance(1234)
        fr.record("t")
        ts = [e["ts_ms"] for e in fr.events()]
        assert ts[1] - ts[0] == 1234

    def test_diagnostics_shape(self):
        fr = FlightRecorder(capacity=8)
        fr.record("k", rule="r")
        d = fr.diagnostics()
        assert d["capacity"] == 8
        assert d["total_recorded"] == 1
        assert d["returned"] == 1
        assert d["events"][0]["kind"] == "k"
        # must be one self-contained json document (REST serves verbatim)
        json.dumps(d)


class TestDropTaxonomy:
    def test_counts_by_reason_and_exceptions_untouched(self):
        sm = StatManager("op", "n1")
        sm.inc_dropped("buffer_full")
        sm.inc_dropped("buffer_full", n=3)
        sm.inc_dropped("decode_error")
        snap = sm.snapshot()
        assert snap["dropped_total"] == {"buffer_full": 4,
                                         "decode_error": 1}
        assert snap["exceptions_total"] == 0
        assert snap["last_exception"] == ""

    def test_drop_burst_events_at_decades(self):
        sm = StatManager("op", "n2")
        sm.rule_id = "rb"
        sm.inc_dropped("buffer_full")  # 1st drop -> threshold-1 event
        assert len(recorder().events(kind="drop_burst")) == 1
        for _ in range(8):
            sm.inc_dropped("buffer_full")  # 2..9: quiet
        assert len(recorder().events(kind="drop_burst")) == 1
        sm.inc_dropped("buffer_full")  # 10th -> threshold-10 event
        evs = recorder().events(kind="drop_burst")
        assert len(evs) == 2
        assert evs[-1]["threshold"] == 10
        assert evs[-1]["total"] == 10
        assert evs[-1]["rule"] == "rb"
        assert evs[-1]["node"] == "n2"
        # a bulk increment that jumps decades fires ONE event (highest)
        sm.inc_dropped("buffer_full", n=500)
        evs = recorder().events(kind="drop_burst")
        assert len(evs) == 3
        assert evs[-1]["threshold"] == 100

    def test_node_buffer_full_reclassified(self):
        """Satellite: drop-oldest is a drop, not an exception — and the
        reference drop-oldest semantics are unchanged (newest kept)."""
        n = Node("bf", buffer_length=2)
        n.put("a")
        n.put("b")
        n.put("c")  # full -> drops "a"
        n.put("d")  # full -> drops "b"
        assert n.stats.dropped == {"buffer_full": 2}
        assert n.stats.exceptions == 0
        held = [n.inq.get_nowait() for _ in range(2)]
        assert held == ["c", "d"]
        evs = recorder().events(kind="drop_burst")
        assert evs and evs[0]["reason"] == "buffer_full"

    def test_watermark_late_drop_is_stale_watermark(self):
        from ekuiper_tpu.runtime.nodes_window import WatermarkNode

        wm = WatermarkNode("wm", late_tolerance_ms=0)
        got = []
        wm.broadcast = lambda item: got.append(item)
        from ekuiper_tpu.data.batch import ColumnBatch

        def b(ts_list):
            k = len(ts_list)
            return ColumnBatch(
                n=k, columns={"v": np.ones(k, dtype=np.float32)},
                timestamps=np.asarray(ts_list, dtype=np.int64),
                emitter="s")

        wm.process(b([5_000]))
        wm.process(b([1_000]))  # behind the watermark -> dropped
        assert wm.stats.dropped.get("stale_watermark") == 1
        assert wm.stats.exceptions == 0

    def test_status_json_carries_drop_map(self):
        from ekuiper_tpu.runtime.topo import Topo

        topo = Topo("rd")
        node = Node("n", op_type="op")
        topo.add_op(node)
        assert node.stats.rule_id == "rd"
        node.stats.inc_dropped("pane_recycle", n=2)
        st = topo.status()
        assert st["op_n_0_dropped_total"] == {"pane_recycle": 2}


class TestDiagnosticsEndpoints:
    @pytest.fixture
    def api(self):
        from ekuiper_tpu.server.rest import RestApi
        from ekuiper_tpu.store import kv

        return RestApi(kv.get_store())

    def test_events_endpoint_filters(self, api):
        recorder().record("compile_storm", rule="r1", op="o")
        recorder().record("drop_burst", rule="r2", reason="buffer_full")
        code, out = api.dispatch("GET", "/diagnostics/events", None, {})
        assert code == 200 and out["returned"] == 2
        code, out = api.dispatch("GET", "/diagnostics/events", None,
                                 {"kind": "compile_storm"})
        assert code == 200 and out["returned"] == 1
        assert out["events"][0]["rule"] == "r1"
        code, out = api.dispatch("GET", "/diagnostics/events", None,
                                 {"limit": "1"})
        assert out["returned"] == 1
        assert out["events"][0]["kind"] == "drop_burst"
        code, out = api.dispatch("GET", "/diagnostics/events", None,
                                 {"limit": "bogus"})
        assert code == 400

    def test_memory_endpoint(self, api):
        from ekuiper_tpu.observability import memwatch

        class Owner:
            pass

        owner = Owner()
        memwatch.register("test_component", owner, lambda o: 12345,
                          rule="rm")
        try:
            code, out = api.dispatch("GET", "/diagnostics/memory", None, {})
            assert code == 200
            rows = [r for r in out["components"]
                    if r["component"] == "test_component"]
            assert rows == [{"component": "test_component", "rule": "rm",
                             "bytes": 12345}]
            assert out["registered_bytes_total"] >= 12345
            assert "live_bytes" in out["jax"]
            json.dumps(out)
        finally:
            memwatch.registry().clear()

    def test_memory_probe_dies_with_owner(self):
        from ekuiper_tpu.observability import memwatch

        class Owner:
            pass

        owner = Owner()
        memwatch.register("ephemeral", owner, lambda o: 1, rule="x")
        assert any(r["component"] == "ephemeral"
                   for r in memwatch.registry().snapshot())
        del owner
        import gc

        gc.collect()
        assert not any(r["component"] == "ephemeral"
                       for r in memwatch.registry().snapshot())

    def test_xla_endpoint(self, api):
        from ekuiper_tpu.observability import devwatch

        w = devwatch.registry().register("diag.fold", "rx")
        w.calls = 2
        w.on_compile(1_000.0, (), {})
        code, out = api.dispatch("GET", "/diagnostics/xla", None, {})
        assert code == 200
        assert out["totals"]["compiles"] >= 1
        site = next(s for s in out["sites"] if s["op"] == "diag.fold")
        assert site["compiles"] == 1 and site["cache_hits"] == 1
        json.dumps(out)

    def test_prometheus_scrape_has_new_families(self, api):
        recorder().record("x")
        code, out = api.dispatch("GET", "/metrics", None, {})
        assert code == 200
        text = str(out)
        assert "# TYPE kuiper_device_bytes gauge" in text
        assert 'component="jax_live_arrays"' in text
        assert "# TYPE kuiper_node_dropped_total counter" in text
        assert "# TYPE kuiper_xla_compile_total counter" in text


class TestRuleLifecycleEvents:
    def test_rule_state_transitions_recorded(self):
        """An end-to-end rule start/stop leaves a replayable rule_state
        trail in the recorder."""
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.server.rule_manager import RuleRegistry
        from ekuiper_tpu.store import kv

        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM fr_s (deviceId STRING, v FLOAT) WITH '
            '(DATASOURCE="topic/fr", TYPE="memory", FORMAT="JSON")')
        reg = RuleRegistry(store)
        rid = reg.create({
            "id": "fr_rule",
            "sql": "SELECT deviceId, count(*) AS c FROM fr_s "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "actions": [{"nop": {}}]})
        try:
            import time

            deadline = time.time() + 10
            while time.time() < deadline:
                states = [e["state"] for e in recorder().events(
                    kind="rule_state", rule=rid)]
                if "running" in states:
                    break
                time.sleep(0.02)
            states = [e["state"] for e in recorder().events(
                kind="rule_state", rule=rid)]
            assert "starting" in states and "running" in states
        finally:
            reg.delete(rid)
        deadline = __import__("time").time() + 10
        while __import__("time").time() < deadline:
            states = [e["state"] for e in recorder().events(
                kind="rule_state", rule=rid)]
            if "stopped" in states:
                break
            __import__("time").sleep(0.02)
        assert "stopped" in states


class TestKuiperdiag:
    def test_smoke_bundle(self):
        """tools/kuiperdiag.py --smoke: boots an in-process engine, emits
        a self-contained JSON bundle, validates its shape (tier-1, like
        check_metrics/check_native)."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "kuiperdiag.py"),
             "--smoke"],
            capture_output=True, text=True, timeout=240,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, (
            f"kuiperdiag --smoke FAILED:\n{proc.stdout}\n{proc.stderr}")
        assert "OK" in proc.stdout

    def test_collect_degrades_per_section(self):
        """A half-dead engine still yields a bundle: failing sections
        carry {"error": ...} instead of killing the collection."""
        sys.path.insert(0, str(REPO))
        from tools.kuiperdiag import REQUIRED_SECTIONS, collect

        def flaky_fetch(path):
            if path.startswith("/diagnostics/memory"):
                raise RuntimeError("boom")
            if path == "/rules":
                return 200, [{"id": "r1"}]
            if path.startswith("/rules/r1/status"):
                return 500, {"error": "dead"}
            return 200, {"ok": path}

        bundle = collect(flaky_fetch)
        assert bundle["memory"] == {"error": "boom"}
        assert bundle["rule_details"]["r1"]["status"]["error"].startswith(
            "status 500")
        for k in REQUIRED_SECTIONS:
            assert k in bundle
        json.dumps(bundle)
