"""Sharded ingest pipeline (native shard parse + decode pool + ring):
shard-boundary correctness of the C decoder, pool ordering/backpressure,
and decode-pool determinism vs the single-thread source path.
"""
import json
import threading
import time

import numpy as np
import pytest

from ekuiper_tpu.data.types import DataType, Field, Schema
from ekuiper_tpu.io import fastjson
from ekuiper_tpu.io.converters import JsonConverter
from ekuiper_tpu.runtime.ingest import DecodePool
from ekuiper_tpu.runtime.nodes_source import SourceNode

SCHEMA = Schema(fields=[
    Field("deviceId", DataType.STRING),
    Field("temperature", DataType.FLOAT),
    Field("count", DataType.BIGINT),
    Field("ok", DataType.BOOLEAN),
])


@pytest.fixture(scope="module")
def native():
    fastjson.ensure_native(background=False)
    mod = fastjson._load()
    if mod is None:
        pytest.skip("native decoder unavailable (no toolchain)")
    return mod


def mixed_payloads(n=4000, seed=3):
    """string/float/bool/null/missing fixtures spread across any shard cut."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        m = {"deviceId": f"dev_{int(rng.integers(0, 97))}"}
        if i % 3 != 0:
            m["temperature"] = round(float(rng.normal(20, 5)), 3)
        if i % 4 != 0:
            m["count"] = int(rng.integers(-5000, 5000))
        if i % 5 == 0:
            m["ok"] = bool(i % 2)
        if i % 11 == 0:
            m["deviceId"] = None  # null string -> invalid, row stays good
        out.append(json.dumps(m).encode())
    return out


class TestShardBoundaries:
    def test_parity_across_shard_counts(self, native):
        spec = fastjson.schema_field_spec(SCHEMA)
        payloads = mixed_payloads()
        ref = fastjson.decode_columns(payloads, spec, shards=1)
        for shards in (2, 3, 5, 8):
            got = fastjson.decode_columns(payloads, spec, shards=shards)
            for k in ref[0]:
                if ref[0][k].dtype == object:
                    assert got[0][k].tolist() == ref[0][k].tolist(), k
                else:
                    np.testing.assert_array_equal(got[0][k], ref[0][k], k)
                np.testing.assert_array_equal(got[1][k], ref[1][k], k)
            np.testing.assert_array_equal(got[2], ref[2])

    def test_interning_shared_across_shards(self, native):
        # the same device id decoded by different shards must still intern
        # to ONE object (the intern pass is a single GIL'd merge)
        payloads = [b'{"deviceId": "only_one"}'] * 2048
        spec = fastjson.schema_field_spec(SCHEMA)
        cols, _, _ = fastjson.decode_columns(payloads, spec, shards=4)
        first = cols["deviceId"][0]
        assert all(v is first for v in cols["deviceId"])

    def test_int64_overflow_in_any_shard_falls_back(self, native):
        spec = fastjson.schema_field_spec(SCHEMA)
        good = [b'{"count": 1}'] * 1500
        big = b'{"count": 99999999999999999999999}'
        for pos in (0, 700, 1499):  # first, middle, last shard
            payloads = list(good)
            payloads[pos] = big
            assert fastjson.decode_columns(payloads, spec, shards=3) is None

    def test_malformed_payload_isolated_per_shard(self, native):
        spec = fastjson.schema_field_spec(SCHEMA)
        payloads = mixed_payloads(3000)
        bad_at = [5, 777, 1500, 1501, 2999]
        for i in bad_at:
            payloads[i] = b"not json at all"
        cols, valid, bad = fastjson.decode_columns(payloads, spec, shards=4)
        assert sorted(np.nonzero(bad)[0].tolist()) == bad_at
        # neighbors of bad rows decode normally
        ref = fastjson.decode_columns(payloads, spec, shards=1)
        np.testing.assert_array_equal(bad, ref[2])
        np.testing.assert_array_equal(cols["count"], ref[0]["count"])

    def test_shard_count_clamped_for_tiny_batches(self, native):
        # far fewer rows than shards*256: must still decode correctly
        spec = fastjson.schema_field_spec(SCHEMA)
        cols, valid, bad = fastjson.decode_columns(
            [b'{"count": 7}'] * 10, spec, shards=8)
        assert cols["count"].tolist() == [7] * 10
        assert not bad.any()


class TestDecodePool:
    def test_ordered_emission_under_reordered_completion(self):
        # job 0 decodes SLOWEST; emission must still be 0, 1, 2, ...
        done = []
        delays = {0: 0.15, 1: 0.0, 2: 0.05, 3: 0.0}

        def decode(job):
            time.sleep(delays.get(job, 0))
            return job

        pool = DecodePool(4, 8, decode, done.append, name="t")
        for i in range(8):
            pool.submit(i)
        assert pool.drain(timeout=5)
        assert done == list(range(8))
        pool.close()

    def test_none_results_skip_emit_but_keep_order(self):
        done = []
        pool = DecodePool(2, 4, lambda j: None if j % 2 else j,
                          done.append, name="t")
        for i in range(6):
            pool.submit(i)
        assert pool.drain(timeout=5)
        assert done == [0, 2, 4]
        pool.close()

    def test_ring_depth_backpressures_submit(self):
        gate = threading.Event()
        done = []

        def decode(job):
            gate.wait(timeout=5)
            return job

        pool = DecodePool(1, 2, decode, done.append, name="t")
        pool.submit(0)
        pool.submit(1)  # ring full: 2 in flight
        t0 = time.monotonic()
        blocker = threading.Thread(target=pool.submit, args=(2,))
        blocker.start()
        time.sleep(0.1)
        assert blocker.is_alive()  # submit is blocked on the full ring
        gate.set()
        blocker.join(timeout=5)
        assert not blocker.is_alive()
        assert pool.drain(timeout=5)
        assert done == [0, 1, 2]
        assert time.monotonic() - t0 < 5
        pool.close()

    def test_decode_error_skips_job(self):
        done = []

        def decode(job):
            if job == 1:
                raise ValueError("boom")
            return job

        pool = DecodePool(2, 4, decode, done.append, name="t")
        for i in range(4):
            pool.submit(i)
        assert pool.drain(timeout=5)
        assert done == [0, 2, 3]
        pool.close()

    def test_submit_after_close_raises(self):
        pool = DecodePool(1, 2, lambda j: j, lambda r: None, name="t")
        pool.close()
        with pytest.raises(RuntimeError):
            pool.submit(0)


def make_source(pool_size, native_ok=True, micro_batch_rows=512):
    src = SourceNode(
        "s", connector=type("C", (), {
            "open": lambda self, cb: None,
            "close": lambda self: None})(),
        schema=SCHEMA, converter=JsonConverter(),
        micro_batch_rows=micro_batch_rows,
        decode_pool_size=pool_size, decode_shards=0, ring_depth=2)
    got = []
    src.broadcast = lambda item: got.append(item)
    return src, got


class TestSourceDeterminism:
    def test_pool_path_matches_inline_path(self, native):
        payloads = mixed_payloads(2100, seed=9)
        outs = []
        for pool_size in (0, 3):
            src, got = make_source(pool_size)
            # several drains -> several flush jobs through the ring
            for i in range(0, len(payloads), 300):
                src.ingest(payloads[i:i + 300])
            src._flush()  # final=True drains the pool
            src.on_close()
            outs.append(got)
        inline, pooled = outs
        assert [b.n for b in inline] == [b.n for b in pooled]
        for bi, bp in zip(inline, pooled):
            for k in bi.columns:
                if bi.columns[k].dtype == object:
                    assert bi.columns[k].tolist() == bp.columns[k].tolist()
                else:
                    np.testing.assert_array_equal(
                        bi.columns[k], bp.columns[k])
            np.testing.assert_array_equal(bi.timestamps, bp.timestamps)

    def test_pool_source_records_decode_stage(self, native):
        src, got = make_source(2)
        src.ingest([json.dumps({"count": i}).encode() for i in range(600)])
        src._flush()
        src.on_close()
        stages = src.stats.snapshot()["stage_timings"]
        assert "decode" in stages
        assert stages["decode"]["calls"] >= 1
        assert stages["decode"]["rows"] == 600

    def test_eof_never_precedes_pooled_batches(self, native):
        from ekuiper_tpu.runtime.events import EOF

        src, got = make_source(2)
        src.ingest([json.dumps({"count": i}).encode() for i in range(900)])
        src.on_eof(EOF(source_id="s"))
        kinds = [type(x).__name__ for x in got]
        assert kinds[-1] == "EOF"
        assert sum(1 for x in got if not isinstance(x, EOF)) >= 1
        total = sum(b.n for b in got if hasattr(b, "n"))
        assert total == 900
        src.on_close()

    def test_eof_drains_ring_even_with_empty_pending(self, native):
        """Exactly micro_batch_rows rows: the threshold flush submits the
        job and empties pending, so the EOF-time _flush sees nothing
        pending — it must STILL drain the ring or EOF overtakes the batch
        (review regression: got order was ['EOF', 'ColumnBatch'])."""
        from ekuiper_tpu.runtime.events import EOF

        # slow decode so the job is reliably still in flight at EOF time
        src, got = make_source(1, micro_batch_rows=512)
        inner = src._decode_job

        def slow(job):
            time.sleep(0.1)
            return inner(job)

        src._ensure_pool()._decode = slow
        src.ingest([json.dumps({"count": i}).encode() for i in range(512)])
        src.on_eof(EOF(source_id="s"))
        kinds = [type(x).__name__ for x in got]
        assert kinds == ["ColumnBatch", "EOF"]
        assert got[0].n == 512
        src.on_close()

    def test_barrier_drains_pending_and_ring(self, native):
        """A checkpoint barrier must not pass rows still buffered or
        decoding: the connector offset already covers them, so rows
        emitted after the barrier would be lost on restore (behind the
        offset, outside the snapshot)."""
        from ekuiper_tpu.runtime.events import Barrier

        src, got = make_source(1, micro_batch_rows=512)
        inner = src._decode_job

        def slow(job):
            time.sleep(0.1)
            return inner(job)

        src._ensure_pool()._decode = slow
        # 512 rows: threshold flush submits the job (pending empties);
        # +100 rows stay PENDING — the barrier must flush both
        src.ingest([json.dumps({"count": i}).encode() for i in range(612)])
        src.on_barrier(Barrier(checkpoint_id=1, qos=1))
        kinds = [type(x).__name__ for x in got]
        assert kinds == ["ColumnBatch", "ColumnBatch", "Barrier"]
        assert sum(b.n for b in got[:2]) == 612
        src.on_close()

    def test_msg_batch_cannot_overtake_raw_batch_in_ring(self, native):
        """Mixed ingestion shapes share the ordered ring: a dict payload
        flushed after a raw drain must emit after it, even when the raw
        decode is slow."""
        src, got = make_source(2, micro_batch_rows=256)
        inner = src._decode_job

        def slow(job):
            if job[0] == "raw":
                time.sleep(0.1)
            return inner(job)

        src._ensure_pool()._decode = slow
        src.ingest([json.dumps({"count": i}).encode() for i in range(256)])
        src.ingest([{"count": 999}] * 256)  # dict payloads -> msgs job
        src._flush()
        assert [b.n for b in got] == [256, 256]
        assert got[0].columns["count"][0] == 0  # raw batch first
        assert got[1].columns["count"][0] == 999
        src.on_close()


def obj_col(vals):
    col = np.empty(len(vals), dtype=object)
    col[:] = vals
    return col


def make_fused(sql="SELECT count(*) AS c, avg(temperature) AS a FROM s "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
               micro_batch=256, capacity=64):
    from ekuiper_tpu.ops.aggspec import extract_kernel_plan
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.sql.parser import parse_select

    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    node = FusedWindowAggNode(
        "f", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=capacity, micro_batch=micro_batch)
    node.state = node.gb.init_state()
    return node


class TestPrepUploadStage:
    def test_pool0_default_path_unchanged(self):
        src, got = make_source(0)
        assert src.prep_ctx is None
        src.ingest([{"count": 1}] * 10)
        src._flush()
        src.on_close()
        assert got and all(b.shared_ctx is None for b in got)

    def test_prep_ctx_rides_pooled_batches(self, native):
        src, got = make_source(2)
        assert src.prep_ctx is not None
        src.ingest([json.dumps({"count": i}).encode() for i in range(600)])
        src._flush()
        src.on_close()
        assert got and all(b.shared_ctx is src.prep_ctx for b in got)

    def test_prep_upload_opt_out(self, native):
        from ekuiper_tpu.runtime.nodes_source import SourceNode

        src = SourceNode(
            "s", connector=type("C", (), {
                "open": lambda self, cb: None,
                "close": lambda self: None})(),
            schema=SCHEMA, converter=JsonConverter(),
            decode_pool_size=2, prep_upload=False)
        assert src.prep_ctx is None

    def test_precompute_builds_fused_share_keys(self, native):
        import jax.numpy as jnp

        src, got = make_source(2, micro_batch_rows=256)
        src.prep_ctx.register_upload("deviceId", ["temperature", "count"],
                                     256)
        payloads = mixed_payloads(512, seed=21)
        src.ingest(payloads[:256])
        src.ingest(payloads[256:])
        src._flush()
        src.on_close()
        assert len(got) == 2
        for b in got:
            st = b.share_state
            assert ("slots", "deviceId") in st
            assert ("dslots", "deviceId", 256, True) in st
            assert ("dcol", "temperature", 256) in st
            dev, dm = st[("dcol", "temperature", 256)]
            assert isinstance(dev, jnp.ndarray) and dev.shape == (256,)
            dslots = st[("dslots", "deviceId", 256, True)]
            assert dslots.dtype == jnp.uint16
        # the upload stage accrued on the SOURCE node
        stages = src.stats.snapshot()["stage_timings"]
        assert "upload" in stages and stages["upload"]["calls"] >= 2
        # slots match an independent python encode of the same columns
        from ekuiper_tpu.ops.keytable import KeyTable

        ref = KeyTable()
        ref._native_ok = False
        for b in got:
            slots, n_keys, _ = b.share_state[("slots", "deviceId")]
            ref_slots, _ = ref.encode_column(b.columns["deviceId"])
            np.testing.assert_array_equal(slots, ref_slots)

    def test_fused_node_consumes_pre_uploaded_inputs(self, native):
        """Parity: a fused node fed prep-uploaded pooled batches computes
        the same window state as one fed the inline (pool=0) batches, and
        actually hits the pre-built share entries."""
        outs = []
        for pool in (0, 2):
            src, got = make_source(pool, micro_batch_rows=256)
            if src.prep_ctx is not None:
                src.prep_ctx.register_upload(
                    "deviceId", ["temperature", "count"], 256)
            payloads = mixed_payloads(1024, seed=33)
            for i in range(0, 1024, 256):  # aligned drains: 256-row batches
                src.ingest(payloads[i:i + 256])
            src._flush()
            src.on_close()
            node = make_fused()
            for b in got:
                prebuilt = (b.share_state is not None
                            and ("dslots", "deviceId", 256, True)
                            in b.share_state)
                node.process(b)
                if pool and b.n == 256:
                    assert prebuilt  # the pool built it BEFORE the fold
            assert node._shared_slots_ok is not False
            res, act = node.gb.finalize(node.state, max(node.kt.n_keys, 1))
            outs.append((node.kt.decode_all(),
                         [np.asarray(r) for r in res], np.asarray(act)))
        keys_a, res_a, act_a = outs[0]
        keys_b, res_b, act_b = outs[1]
        assert keys_a == keys_b
        for ra, rb in zip(res_a, res_b):
            np.testing.assert_array_equal(ra, rb)  # NaN-positions equal too
        np.testing.assert_array_equal(act_a, act_b)

    def test_out_of_order_pool_encode_tolerated(self):
        """Pool workers may key-encode batch k+1 before batch k's snapshot
        is consumed; the fused sync must tolerate its table running ahead
        of an older snapshot instead of poisoning slot reuse."""
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.runtime.ingest import IngestPrepCtx

        ctx = IngestPrepCtx()
        a = ColumnBatch(n=3, columns={
            "deviceId": obj_col(["a", "b", "a"]),
            "temperature": np.array([1, 2, 3], dtype=np.float32)},
            emitter="s")
        b = ColumnBatch(n=3, columns={
            "deviceId": obj_col(["c", "a", "d"]),
            "temperature": np.array([4, 5, 6], dtype=np.float32)},
            emitter="s")
        for batch in (a, b):
            batch.ensure_share_state()
            batch.shared_ctx = ctx
        ctx.encode(b, "deviceId")  # pool finished the LATER batch first
        ctx.encode(a, "deviceId")
        node = make_fused()
        node.process(a)  # emission order: a then b
        node.process(b)
        assert node._shared_slots_ok is True
        assert node.kt.decode_all() == ["c", "a", "d", "b"]
        res, act = node.gb.finalize(node.state, node.kt.n_keys)
        counts = {node.kt.decode(i): int(res[0][i])
                  for i in range(node.kt.n_keys)}
        assert counts == {"a": 3, "b": 1, "c": 1, "d": 1}

    def test_capacity_grow_flips_slot_share_key(self, monkeypatch):
        """The grow round-trip: once the neutral table's capacity crosses
        the slot-dtype boundary, precompute keys new uploads under
        u16=False — in-flight uint16 pre-uploads simply miss the fused
        lookup and are rebuilt there (never folded with a stale dtype)."""
        import ekuiper_tpu.ops.groupby as groupby_mod
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.ops.keytable import KeyTable
        from ekuiper_tpu.runtime.ingest import IngestPrepCtx

        monkeypatch.setattr(
            groupby_mod, "slot_dtype",
            lambda cap: np.uint16 if cap <= 16 else np.int32)
        ctx = IngestPrepCtx()
        kt = KeyTable(initial_capacity=16)
        kt._native_ok = False
        ctx.key_tables["deviceId"] = kt
        ctx.register_upload("deviceId", ["temperature"], 32)

        def mk(keys):
            b = ColumnBatch(n=len(keys), columns={
                "deviceId": obj_col(keys),
                "temperature": np.arange(len(keys), dtype=np.float32)},
                emitter="s")
            b.ensure_share_state()
            b.shared_ctx = ctx
            return b

        b1 = mk([f"k{i}" for i in range(10)])
        ctx.precompute(b1)
        assert ("dslots", "deviceId", 32, True) in b1.share_state
        b2 = mk([f"n{i}" for i in range(20)])  # 30 keys > 16: capacity 32
        ctx.precompute(b2)
        assert kt.capacity == 32
        assert ("dslots", "deviceId", 32, False) in b2.share_state
        assert ("dslots", "deviceId", 32, True) not in b2.share_state

    def test_pool_depth_gauges(self, native):
        src, got = make_source(1, micro_batch_rows=256)
        assert src.pool_depths() is None  # pool starts lazily
        gate = threading.Event()
        inner = src._decode_job

        def slow(job):
            gate.wait(timeout=5)
            return inner(job)

        src._ensure_pool()._decode = slow
        src.ingest([json.dumps({"count": i}).encode() for i in range(512)])
        time.sleep(0.05)
        ring, queue = src.pool_depths()
        assert ring >= 1  # submitted, not yet emitted
        gate.set()
        src._flush()
        src.on_close()
        ring, queue = src.pool_depths()
        assert ring == 0 and queue == 0

    def test_pool_gauges_render_in_prometheus(self, native):
        from ekuiper_tpu.observability.prometheus import render

        src, got = make_source(2)
        src.ingest([json.dumps({"count": i}).encode() for i in range(600)])
        src._flush()

        class FakeTopo:
            from ekuiper_tpu.observability.histogram import LatencyHistogram
            e2e_hist = LatencyHistogram()

            def live_shared(self):
                return []

            def all_nodes(self):
                return [src]

        class FakeState:
            topo = FakeTopo()

        class FakeReg:
            def list(self):
                return [{"id": "r1", "status": "running"}]

            def state(self, rid):
                return FakeState()

        text = render(FakeReg())
        assert 'kuiper_ingest_ring_depth{rule="r1",op="s"}' in text
        assert 'kuiper_decode_pool_queue{rule="r1",op="s"}' in text
        src.on_close()


class TestStagePrometheus:
    def test_stage_lines_render(self):
        from ekuiper_tpu.observability.prometheus import render

        class FakeReg:
            def list(self):
                return [{"id": "r1", "status": "running"}]

            def state(self, rid):
                class S:
                    topo = None
                return S()

        # no rules with topos -> no stage rows, but the section must render
        text = render(FakeReg())
        assert "kuiper_rule_status" in text
        # direct StatManager path: stages flow into the snapshot
        from ekuiper_tpu.utils.metrics import StatManager

        sm = StatManager("source", "s1")
        sm.observe_stage("decode", 1500, rows=100)
        sm.observe_stage("decode", 500, rows=50)
        snap = sm.snapshot()["stage_timings"]["decode"]
        assert snap == {"calls": 2, "total_us": 2000, "rows": 150}
