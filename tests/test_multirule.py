"""Batched homogeneous rules (parallel/multirule.py): one vmapped program
must produce exactly what N independent single-rule kernels produce."""
import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.parallel.multirule import (
    BatchedGroupBy, build_rule_batch)
from ekuiper_tpu.sql.parser import parse_select


def _sql(thresh, upper=None):
    where = f"temperature > {thresh}"
    if upper is not None:
        where += f" AND temperature < {upper}"
    return (f"SELECT deviceId, avg(temperature) AS a, count(*) AS c, "
            f"max(temperature) AS mx FROM demo WHERE {where} "
            f"GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")


class TestBuildRuleBatch:
    def test_homogeneous(self):
        stmts = [parse_select(_sql(t)) for t in (10, 20, 30)]
        spec = build_rule_batch(["r0", "r1", "r2"], stmts)
        assert spec.params.shape == (3, 1)
        np.testing.assert_array_equal(
            spec.params[:, 0], np.array([10, 20, 30], dtype=np.float32))
        assert "__param_0" in spec.param_names
        assert "__param_0" not in spec.plan.columns

    def test_multi_param(self):
        stmts = [parse_select(_sql(10, 50)), parse_select(_sql(20, 60))]
        spec = build_rule_batch(["a", "b"], stmts)
        assert spec.params.shape == (2, 2)

    def test_heterogeneous_rejected(self):
        stmts = [
            parse_select(_sql(10)),
            parse_select("SELECT deviceId, sum(temperature) AS a FROM demo "
                         "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        ]
        with pytest.raises(ValueError, match="not homogeneous"):
            build_rule_batch(["a", "b"], stmts)

    def test_structurally_different_where_rejected(self):
        stmts = [parse_select(_sql(10)), parse_select(_sql(10, 99))]
        with pytest.raises(ValueError, match="not homogeneous"):
            build_rule_batch(["a", "b"], stmts)


class TestBatchedParity:
    def test_vs_individual_kernels(self):
        thresholds = [12.0, 18.0, 22.0, 25.0, 30.0, 5.0, 15.0, 28.0]
        stmts = [parse_select(_sql(t)) for t in thresholds]
        spec = build_rule_batch([f"r{i}" for i in range(8)], stmts)

        rng = np.random.default_rng(0)
        n = 500
        keys = np.array([f"d{i}" for i in rng.integers(0, 20, n)],
                        dtype=np.object_)
        temp = rng.normal(20, 8, n).astype(np.float32)

        kt = KeyTable(64)
        slots, _ = kt.encode_column(keys)

        batched = BatchedGroupBy(spec, capacity=64, micro_batch=128)
        bstate = batched.init_state()
        bstate = batched.fold(bstate, {"temperature": temp}, slots)
        bouts, bact = batched.finalize(bstate, kt.n_keys)

        for r, stmt in enumerate(stmts):
            plan = extract_kernel_plan(stmt)
            gb = DeviceGroupBy(plan, capacity=64, micro_batch=128)
            st = gb.init_state()
            st = gb.fold(st, {"temperature": temp}, slots)
            outs, act = gb.finalize(st, kt.n_keys)
            np.testing.assert_allclose(bact[r], act, rtol=1e-6)
            for i in range(len(outs)):
                np.testing.assert_allclose(
                    np.asarray(bouts[i][r], dtype=np.float64),
                    np.asarray(outs[i], dtype=np.float64),
                    rtol=1e-5, equal_nan=True)

    def test_reset_and_grow(self):
        stmts = [parse_select(_sql(t)) for t in (10.0, 20.0)]
        spec = build_rule_batch(["a", "b"], stmts)
        kt = KeyTable(4)
        batched = BatchedGroupBy(spec, capacity=4, micro_batch=32)
        state = batched.init_state()
        keys = np.array([f"k{i}" for i in range(10)], dtype=np.object_)
        temp = np.full(10, 25.0, dtype=np.float32)
        slots, grew = kt.encode_column(keys)
        assert grew
        state = batched.grow(state, kt.capacity)
        state = batched.fold(state, {"temperature": temp}, slots)
        outs, act = batched.finalize(state, kt.n_keys)
        assert outs[1].shape == (2, 10)
        np.testing.assert_array_equal(outs[1][0], np.ones(10))  # count
        state = batched.reset_pane(state, 0)
        outs2, act2 = batched.finalize(state, kt.n_keys)
        assert not np.any(act2)
