"""COUNT-window async device emission: the boundary dispatches the device
finalize and keeps folding; a worker thread delivers the result. Ordering
holds across windows, and barriers/EOF drain the queue first
(runtime/nodes_fused.py _emit_count_async).
"""
import numpy as np

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select

SQL = ("SELECT deviceId, hll(uid) AS uniq, count(*) AS c FROM s "
       "GROUP BY deviceId, COUNTWINDOW(100)")


def make_node():
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    node = FusedWindowAggNode(
        "ca", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=128,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    return node, got


def batch(n, key="d0", uid_base=0, ts=1000):
    return ColumnBatch(
        n=n,
        columns={"deviceId": np.array([key] * n, dtype=np.object_),
                 "uid": np.arange(uid_base, uid_base + n, dtype=np.int64)},
        timestamps=np.full(n, ts, dtype=np.int64), emitter="s")


class TestAsyncCountEmit:
    def test_enabled_for_count_windows(self):
        node, _ = make_node()
        assert node._async_count

    def test_emission_delivered_after_drain(self):
        node, got = make_node()
        node.process(batch(100))
        node._drain_async_emits()
        assert len(got) == 1
        cb = got[0]
        assert cb.columns["c"][0] == 100
        # 100 distinct uids, HLL ~6.5% error band
        assert 80 <= cb.columns["uniq"][0] <= 120
        info = node.last_emit_info
        assert info is not None and info["source"] == "device-async"

    def test_two_windows_in_order(self):
        node, got = make_node()
        node.process(batch(100, uid_base=0))
        node.process(batch(100, uid_base=0))  # same uids again
        node._drain_async_emits()
        assert len(got) == 2
        # each window counted exactly its own 100 rows
        assert [cb.columns["c"][0] for cb in got] == [100, 100]

    def test_snapshot_drains_queue(self):
        node, got = make_node()
        node.process(batch(100))
        snap = node.snapshot_state()
        # the drain inside snapshot_state delivered the pending window
        assert len(got) == 1
        assert snap["rows_in_window"] == 0

    def test_partial_window_not_emitted(self):
        node, got = make_node()
        node.process(batch(60))
        node._drain_async_emits()
        assert got == []
        node.process(batch(40, uid_base=60))
        node._drain_async_emits()
        assert len(got) == 1
        assert got[0].columns["c"][0] == 100

    def test_close_flushes_worker(self):
        node, got = make_node()
        node.process(batch(100))
        node.on_close()
        assert len(got) == 1

    def test_wedged_drain_aborts_snapshot_but_not_close(self):
        """A stalled device fetch must not let a checkpoint COMMIT without
        the in-flight emission (offsets would advance past replayable rows):
        snapshot raises; close logs and proceeds."""
        import pytest
        import queue as _q

        node, _ = make_node()
        node.drain_deadline_s = 0.05
        node._emit_q = _q.Queue()
        node._emit_q.put(("wedged",))  # never task_done'd: a stuck fetch
        with pytest.raises(RuntimeError, match="aborting this checkpoint"):
            node.snapshot_state()
        node._drain_async_emits()  # close/EOF path: logs, returns
        assert node._emit_q.unfinished_tasks == 1  # still owed to the sink


class TestHeavyHittersGrow:
    def test_capacity_grow_preserves_sketch(self):
        """>capacity distinct keys force an on-device grow mid-window; the
        sketch partials survive and decode correctly."""
        from collections import Counter

        from ekuiper_tpu.runtime.events import Trigger

        sql = ("SELECT k, heavy_hitters(v, 2) AS top FROM s "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "hhg", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=32, micro_batch=64,
            direct_emit=build_direct_emit(stmt, plan, ["k"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        rng = np.random.default_rng(5)
        n = 4000
        keys = np.array([f"k{i}" for i in rng.integers(0, 100, n)],
                        dtype=np.object_)
        p = rng.random(n)
        vals = np.where(p < 0.5, 1, np.where(p < 0.8, 2, 3)).astype(np.int64)
        node.process(ColumnBatch(
            n=n, columns={"k": keys, "v": vals},
            timestamps=np.full(n, 1000, dtype=np.int64), emitter="s"))
        assert node.gb.capacity >= 100 > 32
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        msgs = []
        for item in got:
            msgs.extend(item if isinstance(item, list) else [item])
        assert len(msgs) == 100
        # sketch recovery is probabilistic: a value colliding with a heavier
        # one in BOTH depth rows (~0.1%/key) goes unrecovered — demand the
        # top-1 exactly everywhere and the full top-2 on >=95% of keys
        full_matches = 0
        for m in msgs:
            exact = Counter(
                vals[keys == m["k"]].tolist()).most_common(2)
            got_vals = [d["value"] for d in m["top"]]
            assert got_vals[0] == exact[0][0]
            full_matches += got_vals == [v for v, _ in exact]
        assert full_matches >= 95
