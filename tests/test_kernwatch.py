"""Kernel observatory (observability/kernwatch.py): XLA cost-analysis
capture with graceful no-estimate fallback, device-timing sampling
cadence + measured overhead bound, roofline math, the Prometheus/REST
surfaces, and the health plane's device/host bottleneck axis — all
CPU/mock-clock tier-1 (the sampling path rides `block_until_ready`,
which a CPU jit exercises exactly like a TPU one)."""
import gc
import time
import types

import numpy as np
import pytest

from ekuiper_tpu.observability import devwatch, kernwatch
from ekuiper_tpu.observability.devwatch import watched_jit
from ekuiper_tpu.observability.kernwatch import KernelRecord, roofline
from ekuiper_tpu.utils.rulelog import set_rule_context

#: a deterministic peak-spec the tests pin the device cache to, so the
#: roofline numbers below are exact regardless of the host's real kind
TEST_SPEC = {"name": "test dev", "peak_flops": 1e9, "hbm_gbs": 1.0,
             "h2d_gbs": 1.0}


@pytest.fixture(autouse=True)
def _clean():
    devwatch.registry().clear()
    kernwatch.reset()
    set_rule_context(None)
    yield
    devwatch.registry().clear()
    kernwatch.reset()
    set_rule_context(None)


def _pin_spec(spec=TEST_SPEC):
    """Pre-seed the device-spec cache (kernwatch.reset() clears it)."""
    kernwatch._device_spec_cache.clear()
    kernwatch._device_spec_cache.append(
        {"kind": "testdev", "spec": dict(spec) if spec else None})


# ---------------------------------------------------------------- roofline
class TestRoofline:
    def test_memory_bound_when_bytes_ratio_dominates(self):
        # 1e6 bytes in 2000us against a 1 GB/s roof -> 0.5 of HBM peak;
        # 1e5 flops in 2000us against 1 GFLOP/s -> 0.05 of compute peak
        rl = roofline(1e5, 1e6, 2000.0, TEST_SPEC)
        assert rl == {"util": 0.5, "bound": "memory"}

    def test_compute_bound_when_flops_ratio_dominates(self):
        rl = roofline(1e6, 1e4, 2000.0, TEST_SPEC)
        assert rl["bound"] == "compute"
        assert rl["util"] == 0.5

    def test_degrades_to_empty(self):
        assert roofline(1e6, 1e6, 1000.0, None) == {}       # unknown kind
        assert roofline(1e6, 1e6, 0.0, TEST_SPEC) == {}     # no time
        assert roofline(None, None, 1000.0, TEST_SPEC) == {}  # no cost

    def test_utilization_above_one_is_reported_not_clamped(self):
        # a wrong peak table must be VISIBLE (util > 1), never hidden
        rl = roofline(None, 1e7, 1000.0, TEST_SPEC)
        assert rl["util"] == pytest.approx(10.0)


# ----------------------------------------------------------- KernelRecord
class TestKernelRecord:
    def test_sampling_cadence(self):
        rec = KernelRecord("t.op")
        rec.sample_every = 4
        fired = [rec.tick() for _ in range(12)]
        assert fired == [False, False, False, True] * 3

    def test_zero_cadence_disables_sampling(self):
        rec = KernelRecord("t.op")
        rec.sample_every = 0
        assert not any(rec.tick() for _ in range(64))

    def test_dispatch_floor_split(self):
        """device time = blocked total minus the site's running-minimum
        dispatch time (pure host work) — the floor ratchets DOWN only."""
        _pin_spec()
        rec = KernelRecord("t.op")
        rec.record_sample(dispatch_us=40.0, total_us=100.0)
        assert rec.dispatch_floor_us == 40.0
        assert rec.device_us == 60.0
        rec.record_sample(dispatch_us=20.0, total_us=120.0)  # new floor
        assert rec.dispatch_floor_us == 20.0
        assert rec.device_us == 60.0 + 100.0
        rec.record_sample(dispatch_us=50.0, total_us=70.0)  # floor holds
        assert rec.dispatch_floor_us == 20.0
        snap = rec.snapshot()
        assert snap["samples"] == 3
        assert snap["device_us_total"] == pytest.approx(210.0)
        assert snap["dispatch_us_total"] == pytest.approx(110.0)

    def test_transfer_estimate_capped_by_device_time(self):
        _pin_spec()  # h2d 1 GB/s -> 1e3 bytes/us
        rec = KernelRecord("t.op")
        rec.record_sample(dispatch_us=0.0, total_us=50.0, h2d_bytes=10_000)
        assert rec.transfer_us == pytest.approx(10.0)  # 10k / 1e3
        rec.record_sample(dispatch_us=0.0, total_us=5.0, h2d_bytes=10**9)
        # the estimate can never exceed the measured device wait
        assert rec.transfer_us == pytest.approx(10.0 + 5.0)

    def test_sampled_roofline_rides_cost(self):
        _pin_spec()
        rec = KernelRecord("t.op")
        rec.set_cost(flops=None, bytes_=5e5)
        rec.record_sample(dispatch_us=0.0, total_us=1000.0)
        # 5e5 bytes / 1e-3 s = 5e8 B/s against 1 GB/s -> 0.5, memory-bound
        assert rec.roofline_util() == pytest.approx(0.5)
        snap = rec.snapshot()
        assert snap["bound"] == "memory"
        assert snap["last_sample"]["roofline_util"] == pytest.approx(0.5)

    def test_set_cost_intensity(self):
        rec = KernelRecord("t.op")
        rec.set_cost(flops=2e6, bytes_=8e6)
        assert rec.cost == {"flops": 2e6, "bytes": 8e6, "intensity": 0.25}


# ------------------------------------------------------------ cost capture
class _FakeJitted:
    """jit stand-in whose lower().cost_analysis() is scripted."""

    def __init__(self, result):
        self._result = result

    def lower(self, *a, **k):
        if isinstance(self._result, Exception):
            raise self._result
        return self

    def cost_analysis(self):
        return self._result


class TestCostCapture:
    def test_captures_flops_bytes_intensity(self):
        rec = KernelRecord("t.op")
        rec.on_compile(_FakeJitted({"flops": 100.0, "bytes accessed": 400.0,
                                    "utilization": 0.1}), (), {})
        assert rec.cost == {"flops": 100.0, "bytes": 400.0,
                            "intensity": 0.25}
        assert rec.cost_error is None

    def test_list_result_uses_first_device(self):
        rec = KernelRecord("t.op")
        rec.on_compile(_FakeJitted([{"flops": 7.0}]), (), {})
        assert rec.cost == {"flops": 7.0}

    def test_no_estimates_backend_degrades(self):
        """CPU-class backends may return nothing — the record must keep
        working (cost None, reason recorded) instead of raising."""
        for result in (None, [], {}, {"other": 1.0},
                       {"flops": float("nan"), "bytes accessed": -1.0}):
            rec = KernelRecord("t.op")
            rec.on_compile(_FakeJitted(result), (), {})
            assert rec.cost is None
            assert rec.cost_error
        rec = KernelRecord("t.op")
        rec.on_compile(_FakeJitted(RuntimeError("no lowering")), (), {})
        assert rec.cost is None
        assert "no lowering" in rec.cost_error

    def test_watched_jit_compile_captures_or_degrades(self):
        """End to end on the real backend: after one compile the site has
        EITHER a cost estimate or a recorded degradation reason — never
        silence, never an exception on the call path."""
        fn = watched_jit(lambda v: v * 2.0, op="kern.cost")
        fn(np.ones(32, dtype=np.float32))
        kern = fn.rec.kern
        assert (kern.cost is not None) or kern.cost_error

    def test_cost_error_not_sticky_across_recompiles(self):
        rec = KernelRecord("t.op")
        rec.on_compile(_FakeJitted({}), (), {})
        assert rec.cost_error
        rec.on_compile(_FakeJitted({"flops": 3.0}), (), {})
        assert rec.cost == {"flops": 3.0}
        assert rec.cost_error is None


# ------------------------------------------------- sampling via watched_jit
class TestSampledTiming:
    def test_every_nth_call_is_sampled(self):
        fn = watched_jit(lambda v: v + 1.0, op="kern.fold")
        fn.rec.kern.sample_every = 2
        x = np.zeros(16, dtype=np.float32)
        for _ in range(8):
            fn(x)
        kern = fn.rec.kern
        assert kern.samples == 4
        assert kern.dispatch_floor_us is not None
        assert kern.device_us >= 0.0
        snap = kern.snapshot()
        assert snap["dispatch_us_total"] > 0.0

    def test_compiling_call_is_never_a_timing_sample(self):
        """A call that traced+compiled must not land in the device-time
        sample set — its wall time is the compile, which would poison the
        dispatch floor and double-count against the compile histogram in
        the dispatch/compile/device decomposition."""
        fn = watched_jit(lambda v: v * 2.0, op="kern.fold")
        fn.rec.kern.sample_every = 1  # every call would sample
        x = np.zeros(16, dtype=np.float32)
        fn(x)  # compiles -> skipped
        assert fn.rec.kern.samples == 0
        fn(x)  # cache hit -> sampled
        assert fn.rec.kern.samples == 1
        fn(np.zeros(32, dtype=np.float32))  # new shape: compiles again
        assert fn.rec.kern.samples == 1

    def test_boundary_kind_uses_dense_cadence(self):
        fn = watched_jit(lambda v: v, op="kern.finalize", kind="boundary")
        assert fn.rec.kern.kind == "boundary"
        assert (fn.rec.kern.sample_every
                == kernwatch.DEFAULT_SAMPLING["boundary"])

    def test_unknown_kind_falls_back_to_hot(self):
        assert KernelRecord("t.op", kind="bogus").kind == "hot"

    def test_sample_never_breaks_the_call(self):
        """A sampling failure (unblockable output) must not surface to
        the caller — telemetry is sacrificial."""
        rec = KernelRecord("t.op")
        rec.sample(object(), 0.0, 0.0, (), {})  # not a jax type: no crash
        # numpy arg-byte walk rides the same contract
        rec.sample(None, 0.0, 0.0, (np.zeros(4),), {})

    def test_set_sampling_updates_live_records_and_returns_prior(self):
        fn = watched_jit(lambda v: v, op="kern.fold")
        prior = kernwatch.set_sampling(hot=3)
        try:
            assert fn.rec.kern.sample_every == 3
            assert kernwatch.DEFAULT_SAMPLING["hot"] == 3
            assert prior["hot"] != 3 or prior["hot"] == 64
        finally:
            kernwatch.set_sampling(**prior)
        assert kernwatch.DEFAULT_SAMPLING["hot"] == prior["hot"]

    def test_overhead_bound(self):
        """The amortized per-call cost at the hot cadence (one cadence
        check always + one blocked sample every N) must stay under 1% of
        a realistic fold dispatch — the same bar devwatch holds. An
        absolute floor keeps the bound meaningful on very fast hosts."""
        import jax
        import jax.numpy as jnp

        rec = KernelRecord("t.op")
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            rec.tick()
        tick_us = (time.perf_counter() - t0) * 1e6 / n

        f = jax.jit(lambda v: v)
        x = np.zeros(8, dtype=np.float32)
        jax.block_until_ready(f(x))
        m = 300
        t0 = time.perf_counter()
        for _ in range(m):
            f(x)
        bare_us = (time.perf_counter() - t0) * 1e6 / m
        t0 = time.perf_counter()
        for _ in range(m):
            ta = time.perf_counter()
            out = f(x)
            tb = time.perf_counter()
            rec.sample(out, ta, tb, (x,), {})
        sample_us = max(
            (time.perf_counter() - t0) * 1e6 / m - bare_us, 0.0)
        per_call = tick_us + sample_us / kernwatch.DEFAULT_SAMPLING["hot"]

        # a real (small) fold: segment-sum over 64k rows into 16k slots
        slots = np.random.default_rng(0).integers(
            0, 16_384, 65_536).astype(np.int32)
        vals = np.ones(65_536, dtype=np.float32)
        fold = jax.jit(lambda s, v: jnp.zeros(16_384).at[s].add(v))
        jax.block_until_ready(fold(slots, vals))
        t0 = time.perf_counter()
        for _ in range(20):
            fold(slots, vals)
        fold_us = (time.perf_counter() - t0) * 1e6 / 20
        assert per_call < max(0.01 * fold_us, 2.0), (
            f"kernwatch overhead {per_call:.3f}us/call vs fold "
            f"{fold_us:.1f}us — over the 1% bar")


# ------------------------------------------------------- rollups + surfaces
class TestSurfacesAndRollups:
    def _sampled_site(self, op="kern.fold", rule="kr1", device_us=900.0,
                      dispatch_us=100.0):
        set_rule_context(rule)
        fn = watched_jit(lambda v: v, op=op)
        set_rule_context(None)
        kern = fn.rec.kern
        # both samples share the dispatch floor, so each contributes
        # exactly `device_us` of post-floor device time
        kern.record_sample(dispatch_us=dispatch_us,
                           total_us=dispatch_us + device_us)
        kern.record_sample(dispatch_us=dispatch_us,
                           total_us=dispatch_us + device_us)
        return fn

    def test_rule_status_reports_split_and_ops(self):
        _pin_spec()
        fn = self._sampled_site()
        st = kernwatch.rule_status("kr1")
        assert st["samples"] == 2
        assert st["device_ms"] == pytest.approx(1.8, abs=0.01)
        assert st["device_share"] > 0.8
        assert "kern.fold" in st["ops"]
        assert kernwatch.rule_status("other") == {}
        del fn

    def test_diagnostics_shape(self):
        _pin_spec()
        fn = self._sampled_site()  # bound: live watches are weakref'd
        d = kernwatch.diagnostics()
        assert d["device"]["kind"] == "testdev"
        assert set(d["sampling"]) == {"hot", "boundary"}
        assert d["sites"] and d["sites"][0]["op"] == "kern.fold"
        assert d["totals"]["samples"] == 2
        from ekuiper_tpu.server.rest import RestApi

        assert RestApi.diagnostics_kernels()["totals"] == d["totals"]

    def test_prometheus_families_render(self):
        _pin_spec()
        fn = self._sampled_site()
        fn.rec.kern.set_cost(flops=1e6, bytes_=4e6)
        fn.rec.kern.record_sample(dispatch_us=10.0, total_us=1000.0)
        out = []
        kernwatch.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        for fam in ("kuiper_kernel_device_ms", "kuiper_kernel_dispatch_ms",
                    "kuiper_kernel_flops", "kuiper_kernel_bytes",
                    "kuiper_kernel_roofline_util"):
            assert f"# TYPE {fam}" in text
            assert f"# HELP {fam}" in text
        assert 'kuiper_kernel_flops{op="kern.fold",rule="kr1"} 1000000' \
            in text
        # a bytes-only estimate must not fabricate a 0-FLOPs measurement
        partial = self._sampled_site(op="kern.partial", rule="kr2")
        partial.rec.kern.set_cost(flops=None, bytes_=7e6)
        out2 = []
        kernwatch.render_prometheus(out2, lambda s: s)
        text2 = "\n".join(out2)
        assert 'kuiper_kernel_bytes{op="kern.partial",rule="kr2"} 7000000' \
            in text2
        assert 'kuiper_kernel_flops{op="kern.partial"' not in text2

    def test_retired_counters_stay_monotonic(self):
        """A dying jit site folds its sampled time into the module rollup
        (via devwatch retire) so exported counters never go backwards on
        rule restart."""
        fn = self._sampled_site()
        fn.rec.calls = 2  # devwatch skips never-used watches
        before = kernwatch.aggregate()[("kern.fold", "kr1")]["device_us"]
        assert before > 0
        del fn
        gc.collect()
        after = kernwatch.aggregate()[("kern.fold", "kr1")]
        assert after["device_us"] == pytest.approx(before)
        assert kernwatch.rule_ops("kr1")["kern.fold"]["samples"] == 2

    def test_rule_ops_all_single_pass_matches_per_rule(self):
        """The tick-shared one-pass map (what the health evaluator uses)
        agrees with the per-rule view, including retired counters."""
        a = self._sampled_site(op="kern.fold", rule="ra")
        b = self._sampled_site(op="kern.fold", rule="rb")
        a.rec.calls = b.rec.calls = 2
        del b
        gc.collect()  # rb retires into the rollup
        allops = kernwatch.rule_ops_all()
        assert set(allops) >= {"ra", "rb"}
        for rid in ("ra", "rb"):
            assert allops[rid] == kernwatch.rule_ops(rid)
            assert allops[rid]["kern.fold"]["samples"] == 2
        del a

    def test_bench_summary_ranks_by_device_time(self):
        _pin_spec()
        hot = self._sampled_site(op="kern.hot", device_us=5000.0)
        cool = self._sampled_site(op="kern.cool", device_us=10.0)
        top = kernwatch.bench_summary(top=1)
        assert top["device"] == "testdev"
        assert [r["op"] for r in top["top"]] == ["kern.hot"]


# ----------------------------------------- health-plane device/host axis
class TestHealthDeviceAxis:
    def _track(self):
        return types.SimpleNamespace(prev_kern={})

    def test_device_axis_from_sampled_deltas(self):
        from ekuiper_tpu.observability.health import HealthEvaluator

        _pin_spec()
        set_rule_context("r1")
        fn = watched_jit(lambda v: v, op="kern.fold")
        set_rule_context(None)
        fn.rec.kern.set_cost(flops=None, bytes_=5e5)
        fn.rec.kern.record_sample(dispatch_us=100.0, total_us=1000.0)
        fn.rec.kern.record_sample(dispatch_us=100.0, total_us=1000.0)
        tr = self._track()
        axis = HealthEvaluator._device_axis("r1", tr)
        assert axis["axis"] == "device"
        assert axis["device_share"] > 0.85
        assert axis["op"] == "kern.fold"
        assert axis["samples"] == 2
        assert axis["roofline_util"] is not None
        assert axis["bound"] == "memory"
        # no new samples since -> the axis is NOT asserted this tick
        assert HealthEvaluator._device_axis("r1", tr) is None
        # fresh samples revive it; against the 100us floor the dispatch
        # now dominates the new delta (900 host vs 850 post-floor wait)
        fn.rec.kern.record_sample(dispatch_us=900.0, total_us=950.0)
        axis = HealthEvaluator._device_axis("r1", tr)
        assert axis["axis"] == "host"

    def test_axis_absent_without_samples(self):
        from ekuiper_tpu.observability.health import HealthEvaluator

        assert HealthEvaluator._device_axis("r1", self._track()) is None

    def test_verdict_bottleneck_carries_axis(self, mock_clock):
        """Full evaluator tick: when the rule's kernels were sampled this
        tick, the bottleneck verdict gains axis/device_time — 'fold is
        dominant' becomes 'fold is device-bound at N% of roof'."""
        from tests.test_health import FakeNode, FakeTopo, _evaluator

        _pin_spec()
        set_rule_context("r1")
        fn = watched_jit(lambda v: v, op="kern.fold")
        set_rule_context(None)
        fold = FakeNode("fused", "op")
        topo = FakeTopo([FakeNode("src", "source"), fold])
        ev = _evaluator(topo)
        fold.stats.observe_stage("fold", 80_000)
        fn.rec.kern.record_sample(dispatch_us=0.0, total_us=0.0)
        fn.rec.kern.record_sample(dispatch_us=50.0, total_us=2000.0)
        bn = ev.tick()["r1"]["bottleneck"]
        assert bn["stage"] == "fold"
        assert bn["axis"] == "device"
        assert bn["device_time"]["device_us"] > 0
        # next tick, nothing sampled: the axis disappears, the stage stays
        fold.stats.observe_stage("fold", 1_000)
        bn = ev.tick()["r1"]["bottleneck"]
        assert bn["stage"] == "fold"
        assert "axis" not in bn
