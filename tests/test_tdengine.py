"""TDengine3 sink: statement construction goldens mirror the reference's
own unit expectations (extensions/impl/tdengine3/tdengine3_test.go:160-252)
and the REST transport runs against a local taosAdapter mock."""
import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ekuiper_tpu.io.tdengine_io import (Tdengine3Sink, build_insert,
                                        build_insert_many)
from ekuiper_tpu.utils.infra import EngineError


class TestBuildInsert:
    def test_now_ts_and_string_quoting(self):
        # ref golden: INSERT INTO t (ts,f1) values (now,"v1")
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "fields": ["f1"]},
            {"f1": "v1"})
        assert stmt == 'INSERT INTO t (ts,f1) values (now,"v1")'

    def test_provide_ts_with_stable_tags(self):
        # ref golden: INSERT INTO t (ts,k1) USING st TAGS("t1")
        #             values (1737628594255,"v1")
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "provideTs": True,
             "sTable": "st", "tagFields": ["tag"], "fields": ["k1"]},
            {"ts": 1737628594255, "k1": "v1", "tag": "t1"})
        assert stmt == ('INSERT INTO t (ts,k1) USING st TAGS("t1") '
                        'values (1737628594255,"v1")')

    def test_numeric_tag_and_multiple_fields(self):
        # ref golden: INSERT INTO t (ts,k1,k2) USING st TAGS("t1",2)
        #             values (1737628594255,"v1",2)
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "provideTs": True,
             "sTable": "st", "tagFields": ["tg1", "tg2"],
             "fields": ["k1", "k2"]},
            {"ts": 1737628594255, "k1": "v1", "k2": 2, "tg1": "t1",
             "tg2": 2})
        assert stmt == ('INSERT INTO t (ts,k1,k2) USING st TAGS("t1",2) '
                        'values (1737628594255,"v1",2)')

    def test_all_fields_when_unspecified(self):
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts"},
            {"b": 1, "a": "x"})
        assert stmt == 'INSERT INTO t (ts,a,b) values (now,"x",1)'

    def test_missing_ts_field_errors(self):
        with pytest.raises(EngineError, match="timestamp field"):
            build_insert({"table": "t", "tsFieldName": "ts",
                          "provideTs": True}, {"a": 1})

    def test_missing_selected_field_errors(self):
        with pytest.raises(EngineError, match="field not found"):
            build_insert({"table": "t", "tsFieldName": "ts",
                          "fields": ["nope"]}, {"a": 1})


class _Adapter:
    """taosAdapter /rest/sql mock."""

    def __init__(self, code=0):
        self.requests = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.requests.append(
                    (self.path, self.headers.get("Authorization"),
                     self.rfile.read(n).decode()))
                body = json.dumps({"code": code, "desc": "err" if code else ""})
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body.encode())

            def log_message(self, *a):
                pass

        self.srv = HTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.srv.shutdown()


class TestRestTransport:
    def test_collect_posts_with_basic_auth(self):
        srv = _Adapter()
        sink = Tdengine3Sink()
        sink.configure({"host": "127.0.0.1", "port": srv.port,
                        "database": "db1", "table": "t",
                        "fields": ["f1"]})
        sink.collect({"f1": "v1"})
        sink.collect([{"f1": "v2"}, {"f1": "v3"}])
        sink.close()
        srv.close()
        # one POST per collect(): the list batches into a multi-row INSERT
        assert len(srv.requests) == 2
        path, auth, body = srv.requests[0]
        assert path == "/rest/sql/db1"
        assert auth == "Basic " + base64.b64encode(b"root:taosdata").decode()
        assert body == 'INSERT INTO t (ts,f1) values (now,"v1")'
        assert srv.requests[1][2] == \
            'INSERT INTO t (ts,f1) values (now,"v2")(now,"v3")'

    def test_broker_error_code_raises(self):
        srv = _Adapter(code=534)
        sink = Tdengine3Sink()
        sink.configure({"host": "127.0.0.1", "port": srv.port,
                        "database": "db1", "table": "t"})
        with pytest.raises(EngineError, match="534"):
            sink.collect({"a": 1})
        srv.close()

    def test_requires_database_and_table(self):
        with pytest.raises(EngineError, match="database"):
            Tdengine3Sink().configure({"table": "t"})
        with pytest.raises(EngineError, match="table"):
            Tdengine3Sink().configure({"database": "d"})

    def test_registered_unsgated(self):
        from ekuiper_tpu.io import registry

        assert "tdengine3" in registry.sink_types()


class TestBuildInsertMany:
    """Multi-row batching goldens: every value group must byte-match what
    the single-row builder would have produced for that row (the existing
    builder is the spec — VERDICT r5 weak #5)."""

    CFG = {"table": "t", "tsFieldName": "ts", "provideTs": True,
           "fields": ["f1"]}

    def test_single_row_matches_build_insert(self):
        row = {"ts": 1, "f1": "a"}
        assert build_insert_many(self.CFG, [row]) == \
            [build_insert(self.CFG, row)]

    def test_multi_row_one_statement(self):
        rows = [{"ts": 1, "f1": "a"}, {"ts": 2, "f1": "b"},
                {"ts": 3, "f1": "c"}]
        stmts = build_insert_many(self.CFG, rows)
        assert stmts == ['INSERT INTO t (ts,f1) values (1,"a")(2,"b")(3,"c")']
        # golden vs the single-row builder: shared prefix + each row's group
        singles = [build_insert(self.CFG, r) for r in rows]
        prefix, g0 = singles[0].split(" values ")
        assert stmts[0].startswith(prefix + " values ")
        groups = stmts[0].split(" values ", 1)[1]
        assert groups == "".join(s.split(" values ", 1)[1] for s in singles)

    def test_tag_change_splits_statements(self):
        cfg = {"table": "t", "tsFieldName": "ts", "provideTs": True,
               "sTable": "st", "tagFields": ["tag"], "fields": ["k1"]}
        rows = [{"ts": 1, "k1": "a", "tag": "x"},
                {"ts": 2, "k1": "b", "tag": "x"},
                {"ts": 3, "k1": "c", "tag": "y"}]
        stmts = build_insert_many(cfg, rows)
        assert len(stmts) == 2
        assert stmts[0] == ('INSERT INTO t (ts,k1) USING st TAGS("x")'
                            ' values (1,"a")(2,"b")')
        assert stmts[1] == ('INSERT INTO t (ts,k1) USING st TAGS("y")'
                            ' values (3,"c")')

    def test_column_set_change_splits_statements(self):
        cfg = {"table": "t", "tsFieldName": "ts", "provideTs": True}
        rows = [{"ts": 1, "a": 1}, {"ts": 2, "a": 2, "b": 3}]
        stmts = build_insert_many(cfg, rows)
        assert stmts == ['INSERT INTO t (ts,a) values (1,1)',
                         'INSERT INTO t (ts,a,b) values (2,2,3)']

    def test_bad_row_fails_before_any_statement(self):
        with pytest.raises(EngineError):
            build_insert_many(self.CFG, [{"ts": 1, "f1": "a"}, {"ts": 2}])

    def test_oversized_emit_chunks_below_sql_length_cap(self):
        from ekuiper_tpu.io import tdengine_io

        rows = [{"ts": i, "f1": "x" * 200} for i in range(6000)]
        stmts = build_insert_many(self.CFG, rows)
        assert len(stmts) > 1  # ~1.2MB of value groups must split
        assert all(len(s) <= tdengine_io._MAX_STMT_BYTES + 1024
                   for s in stmts)
        # no row lost or reordered across the chunk cuts
        groups = "".join(s.split(" values ", 1)[1] for s in stmts)
        singles = "".join(
            build_insert(self.CFG, r).split(" values ", 1)[1] for r in rows)
        assert groups == singles
