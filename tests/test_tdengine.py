"""TDengine3 sink: statement construction goldens mirror the reference's
own unit expectations (extensions/impl/tdengine3/tdengine3_test.go:160-252)
and the REST transport runs against a local taosAdapter mock."""
import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ekuiper_tpu.io.tdengine_io import Tdengine3Sink, build_insert
from ekuiper_tpu.utils.infra import EngineError


class TestBuildInsert:
    def test_now_ts_and_string_quoting(self):
        # ref golden: INSERT INTO t (ts,f1) values (now,"v1")
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "fields": ["f1"]},
            {"f1": "v1"})
        assert stmt == 'INSERT INTO t (ts,f1) values (now,"v1")'

    def test_provide_ts_with_stable_tags(self):
        # ref golden: INSERT INTO t (ts,k1) USING st TAGS("t1")
        #             values (1737628594255,"v1")
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "provideTs": True,
             "sTable": "st", "tagFields": ["tag"], "fields": ["k1"]},
            {"ts": 1737628594255, "k1": "v1", "tag": "t1"})
        assert stmt == ('INSERT INTO t (ts,k1) USING st TAGS("t1") '
                        'values (1737628594255,"v1")')

    def test_numeric_tag_and_multiple_fields(self):
        # ref golden: INSERT INTO t (ts,k1,k2) USING st TAGS("t1",2)
        #             values (1737628594255,"v1",2)
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts", "provideTs": True,
             "sTable": "st", "tagFields": ["tg1", "tg2"],
             "fields": ["k1", "k2"]},
            {"ts": 1737628594255, "k1": "v1", "k2": 2, "tg1": "t1",
             "tg2": 2})
        assert stmt == ('INSERT INTO t (ts,k1,k2) USING st TAGS("t1",2) '
                        'values (1737628594255,"v1",2)')

    def test_all_fields_when_unspecified(self):
        stmt = build_insert(
            {"table": "t", "tsFieldName": "ts"},
            {"b": 1, "a": "x"})
        assert stmt == 'INSERT INTO t (ts,a,b) values (now,"x",1)'

    def test_missing_ts_field_errors(self):
        with pytest.raises(EngineError, match="timestamp field"):
            build_insert({"table": "t", "tsFieldName": "ts",
                          "provideTs": True}, {"a": 1})

    def test_missing_selected_field_errors(self):
        with pytest.raises(EngineError, match="field not found"):
            build_insert({"table": "t", "tsFieldName": "ts",
                          "fields": ["nope"]}, {"a": 1})


class _Adapter:
    """taosAdapter /rest/sql mock."""

    def __init__(self, code=0):
        self.requests = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.requests.append(
                    (self.path, self.headers.get("Authorization"),
                     self.rfile.read(n).decode()))
                body = json.dumps({"code": code, "desc": "err" if code else ""})
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body.encode())

            def log_message(self, *a):
                pass

        self.srv = HTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.srv.shutdown()


class TestRestTransport:
    def test_collect_posts_with_basic_auth(self):
        srv = _Adapter()
        sink = Tdengine3Sink()
        sink.configure({"host": "127.0.0.1", "port": srv.port,
                        "database": "db1", "table": "t",
                        "fields": ["f1"]})
        sink.collect({"f1": "v1"})
        sink.collect([{"f1": "v2"}, {"f1": "v3"}])
        sink.close()
        srv.close()
        assert len(srv.requests) == 3
        path, auth, body = srv.requests[0]
        assert path == "/rest/sql/db1"
        assert auth == "Basic " + base64.b64encode(b"root:taosdata").decode()
        assert body == 'INSERT INTO t (ts,f1) values (now,"v1")'

    def test_broker_error_code_raises(self):
        srv = _Adapter(code=534)
        sink = Tdengine3Sink()
        sink.configure({"host": "127.0.0.1", "port": srv.port,
                        "database": "db1", "table": "t"})
        with pytest.raises(EngineError, match="534"):
            sink.collect({"a": 1})
        srv.close()

    def test_requires_database_and_table(self):
        with pytest.raises(EngineError, match="database"):
            Tdengine3Sink().configure({"table": "t"})
        with pytest.raises(EngineError, match="table"):
            Tdengine3Sink().configure({"database": "d"})

    def test_registered_unsgated(self):
        from ekuiper_tpu.io import registry

        assert "tdengine3" in registry.sink_types()
