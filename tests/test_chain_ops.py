"""Sink/source chain operator tests — modeled on the reference's operator
Apply tests (internal/topo/node/batch_op_test.go, cache, rate_limit,
dedup_trigger) with the mock clock driving timers deterministically."""
import pytest

from ekuiper_tpu.runtime.nodes_chain import (
    BatchNode, CacheNode, CompressNode, DecompressNode, DecryptNode,
    DedupTriggerNode, EncryptNode, RateLimitNode,
)
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.codecs import (
    AesEncryptor, compression_algorithms, get_compressor,
)


class Collect:
    """Downstream stub capturing emitted items synchronously."""

    def __init__(self):
        self.items = []

    def put(self, item, from_name=None):
        self.items.append(item)


def drive(node, items, clock=None, advance_ms=0):
    """Feed items through process() directly (synchronous unit style)."""
    sink = Collect()
    node.outputs.append(sink)
    for it in items:
        node._dispatch(it)
    if clock is not None and advance_ms:
        clock.advance(advance_ms)
    return sink


# ------------------------------------------------------------------- codecs
@pytest.mark.parametrize("alg", compression_algorithms())
def test_compressor_roundtrip(alg):
    comp, decomp = get_compressor(alg)
    data = b"hello streaming world" * 100
    assert decomp(comp(data)) == data
    assert len(comp(data)) < len(data)


@pytest.mark.parametrize("mode", ["gcm", "cfb"])
def test_aes_roundtrip(mode):
    enc = AesEncryptor(b"0123456789abcdef", mode)
    data = b"secret payload"
    ct = enc.encrypt(data)
    assert ct != data
    assert enc.decrypt(ct) == data
    # fresh nonce per message
    assert enc.encrypt(data) != ct


def test_compress_decompress_nodes():
    c = CompressNode("c", "gzip")
    d = DecompressNode("d", "gzip")
    mid = drive(c, [b"payload bytes"])
    out = drive(d, mid.items)
    assert out.items == [b"payload bytes"]


def test_encrypt_decrypt_nodes():
    props = {"key": "0123456789abcdef"}
    e = EncryptNode("e", "aes", props)
    d = DecryptNode("d", "aes", props)
    mid = drive(e, [b"topsecret"])
    out = drive(d, mid.items)
    assert out.items == [b"topsecret"]


# -------------------------------------------------------------------- batch
def test_batch_by_size():
    n = BatchNode("b", size=3)
    sink = drive(n, [1, 2])
    assert sink.items == []
    drive_more = [3]
    for it in drive_more:
        n._dispatch(it)
    assert sink.items == [[1, 2, 3]]


def test_batch_by_linger(mock_clock):
    n = BatchNode("b", linger_ms=100)
    n.on_open()
    sink = drive(n, [1, 2], clock=mock_clock, advance_ms=100)
    assert sink.items == [[1, 2]]
    # empty linger tick emits nothing
    mock_clock.advance(100)
    assert sink.items == [[1, 2]]


# ---------------------------------------------------------------- ratelimit
def test_rate_limit_keeps_latest(mock_clock):
    n = RateLimitNode("rl", interval_ms=1000)
    n.on_open()
    sink = Collect()
    n.outputs.append(sink)
    for i in range(5):
        n._dispatch({"i": i})
    mock_clock.advance(1000)
    assert sink.items == [{"i": 4}]
    # nothing new -> no emission
    mock_clock.advance(1000)
    assert sink.items == [{"i": 4}]
    n._dispatch({"i": 9})
    mock_clock.advance(1000)
    assert sink.items == [{"i": 4}, {"i": 9}]


# -------------------------------------------------------------------- dedup
def test_dedup_trigger_suppresses_overlap():
    n = DedupTriggerNode("dd", alias="win")
    sink = Collect()
    n.outputs.append(sink)
    n._dispatch({"start": 0, "end": 100})
    n._dispatch({"start": 50, "end": 150})   # novel: [100,150)
    n._dispatch({"start": 20, "end": 90})    # fully covered -> suppressed
    assert len(sink.items) == 2
    assert sink.items[0]["win"] == [[0, 100]]
    assert sink.items[1]["win"] == [[100, 150]]


def test_dedup_trigger_expiry():
    n = DedupTriggerNode("dd", alias="win", now_field="now", expire_ms=1000)
    sink = Collect()
    n.outputs.append(sink)
    n._dispatch({"start": 0, "end": 100})
    # far future event expires the old interval; same range is novel again
    n._dispatch({"start": 0, "end": 100, "now": 10_000}, )
    assert len(sink.items) == 2


def test_dedup_trigger_state_roundtrip():
    n = DedupTriggerNode("dd")
    n._dispatch({"start": 0, "end": 10})
    st = n.snapshot_state()
    n2 = DedupTriggerNode("dd")
    n2.restore_state(st)
    assert n2._seen == [[0, 10]]


# -------------------------------------------------------------------- cache
class AckingCollect(Collect):
    """Downstream stub that confirms every delivery, like a healthy sink."""

    def __init__(self, cache):
        super().__init__()
        self.cache = cache

    def put(self, item, from_name=None):
        super().put(item)
        self.cache.ack(item)


def test_cache_passthrough_and_nack_resend(mock_clock):
    store = kv.get_store()
    c = CacheNode("cache", store_kv=store.kv("t:cache"), resend_interval_ms=50)
    sink = AckingCollect(c)
    c.outputs.append(sink)
    c._dispatch({"a": 1})
    assert sink.items == [{"a": 1}]  # healthy passthrough
    # sink failure: nack comes back; resend after interval
    c.nack({"a": 1})
    assert c.pending() == 1
    mock_clock.advance(50)
    assert sink.items[-1] == {"a": 1}
    assert c.pending() == 0


def test_cache_keeps_order_behind_backlog(mock_clock):
    store = kv.get_store()
    c = CacheNode("cache", store_kv=store.kv("t:cache2"), resend_interval_ms=50)
    sink = AckingCollect(c)
    c.outputs.append(sink)
    c.nack({"i": 0})
    c._dispatch({"i": 1})  # must queue behind the nacked item
    c._dispatch({"i": 2})
    for _ in range(4):
        mock_clock.advance(50)
    assert [x["i"] for x in sink.items] == [0, 1, 2]


def test_cache_disk_spill(mock_clock):
    store = kv.get_store()
    c = CacheNode("cache", store_kv=store.kv("t:cache3"),
                  memory_threshold=2, resend_interval_ms=10)
    sink = AckingCollect(c)
    c.outputs.append(sink)
    c.nack({"i": 0})
    for i in range(1, 6):
        c._enqueue({"i": i})
    assert c.pending() == 6
    for _ in range(10):
        mock_clock.advance(10)
    assert [x["i"] for x in sink.items] == [0, 1, 2, 3, 4, 5]


def test_cache_disk_record_survives_until_ack(mock_clock):
    """A spilled record must outlive a failed delivery (deleted on ack only)."""
    store = kv.get_store()
    ns = store.kv("t:cache4")
    c = CacheNode("cache", store_kv=ns, memory_threshold=0,
                  resend_interval_ms=10)
    sink = Collect()  # never acks
    c.outputs.append(sink)
    c._enqueue({"i": 7})  # spills straight to disk (threshold 0)
    assert len(ns.keys()) == 1
    mock_clock.advance(10)  # resend emits, but no ack arrives
    assert sink.items == [{"i": 7}]
    assert len(ns.keys()) == 1  # record still on disk
    c.nack({"i": 7})  # delivery failed — will re-read the same record
    mock_clock.advance(10)
    assert sink.items == [{"i": 7}, {"i": 7}]
    c.ack({"i": 7})
    assert len(ns.keys()) == 0  # gone only after confirmed delivery


def test_cache_barrier_spill_of_inflight_then_ack_no_duplicate(mock_clock):
    """A checkpoint that overlaps an unconfirmed in-flight delivery must not
    produce a duplicate: the barrier spills the in-flight payload to disk,
    and the LATE ack has to delete that record (and the resend timer must
    not redeliver it while the original delivery is still outstanding)."""
    store = kv.get_store()
    ns = store.kv("t:cache6")
    c = CacheNode("cache", store_kv=ns, resend_interval_ms=10)
    sink = Collect()  # acks are driven manually
    c.outputs.append(sink)
    c.nack({"i": 1})  # backlog of one
    mock_clock.advance(10)  # resend: mem in-flight, delivery outstanding
    assert sink.items == [{"i": 1}]
    st = c.snapshot_state()  # barrier: spills the in-flight item to disk
    assert st == {"spilled": 1}
    assert len(ns.keys()) == 1
    # delivery still outstanding: resends must hold off, not redeliver
    for _ in range(3):
        mock_clock.advance(10)
    assert sink.items == [{"i": 1}]
    c.ack({"i": 1})  # the late ack for the pre-barrier delivery
    assert len(ns.keys()) == 0  # spilled record deleted — no replay
    for _ in range(3):
        mock_clock.advance(10)
    assert sink.items == [{"i": 1}]  # exactly one delivery, no failure → no dup
    assert c.pending() == 0


def test_cache_barrier_spill_of_inflight_then_nack_single_replay(mock_clock):
    """If the spilled in-flight delivery ultimately FAILS, the disk record is
    the one retry copy — the nack must not re-enqueue a second copy."""
    store = kv.get_store()
    ns = store.kv("t:cache7")
    c = CacheNode("cache", store_kv=ns, resend_interval_ms=10)
    sink = Collect()
    c.outputs.append(sink)
    c.nack({"i": 2})
    mock_clock.advance(10)
    assert sink.items == [{"i": 2}]
    c.snapshot_state()
    c.nack({"i": 2})  # delivery failed after the barrier
    assert c.pending() == 1  # exactly the disk record, not two copies
    mock_clock.advance(10)  # replay from disk
    assert sink.items == [{"i": 2}, {"i": 2}]
    c.ack({"i": 2})
    assert len(ns.keys()) == 0
    assert c.pending() == 0


def test_cache_resend_delivers_template_strings(mock_clock):
    """Rendered dataTemplate payloads round-trip through nack/resend intact
    (SinkNode treats str as opaque pass-through)."""
    from ekuiper_tpu.runtime.nodes_sink import SinkNode

    class FlakySink:
        def __init__(self):
            self.fail = 1
            self.got = []

        def configure(self, p): pass

        def connect(self): pass

        def collect(self, item):
            if self.fail:
                self.fail -= 1
                raise RuntimeError("down")
            self.got.append(item)

        def close(self): pass

    store = kv.get_store()
    c = CacheNode("cache", store_kv=store.kv("t:cache5"), resend_interval_ms=10)
    flaky = FlakySink()
    s = SinkNode("sink", flaky, data_template="val={{.a}}", cache_node=c)
    c.outputs.append(s.inq_stub if hasattr(s, "inq_stub") else _Direct(s))
    s._dispatch({"a": 5})  # first collect fails -> nack({"a": 5})
    assert c.pending() == 1
    mock_clock.advance(10)  # resend -> SinkNode re-transforms -> success
    assert flaky.got == ["val=5"]
    assert c.pending() == 0


class _Direct:
    """Adapter: cache emits synchronously into the sink's dispatch."""

    def __init__(self, node):
        self.node = node

    def put(self, item, from_name=None):
        self.node._dispatch(item)


def test_sink_chain_in_rule_plan():
    """Planner assembles batch→encode→compress→cache→sink for action props."""
    from ekuiper_tpu.planner.planner import plan_rule
    from ekuiper_tpu.runtime.rule import RuleDef
    from ekuiper_tpu.server.processors import StreamProcessor

    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM s1 (a bigint) WITH (TYPE="memory", DATASOURCE="t")')
    rule = RuleDef(id="r_chain", sql="SELECT a FROM s1", actions=[
        {"memory": {"topic": "out", "batchSize": 10, "compression": "gzip",
                    "enableCache": True}}])
    topo = plan_rule(rule, store)
    names = [n.name for n in topo.ops]
    assert any("batch" in n for n in names)
    assert any("compress" in n for n in names)
    assert any("cache" in n for n in names)
