"""DABA sliding rings (ISSUE 11): parity of the constant-time sliding
implementation (ops/slidingring.py, `slidingImpl=daba`) against the
legacy refold-on-trigger path (`slidingImpl=refold`) — same batches, same
triggers, same emitted windows, across window shapes, aggregate classes,
clock modes, eviction pressure, and kill/restore.

The refold path is the exactness baseline (tests/test_sliding_device.py
proves it against ground truth); this suite proves the DABA rings match
it, so the default swap cannot silently change semantics."""
import json

import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.ops.slidingring import (ADD_COMBINE, MAX_COMBINE,
                                         MIN_COMBINE, SlidingRing,
                                         plan_ring_layout)
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select

SQL_INV = ("SELECT deviceId, count(*) AS c, sum(temp) AS s, "
           "avg(temp) AS a, stddev(temp) AS sd FROM s GROUP BY deviceId, "
           "SLIDINGWINDOW(ss, 2) OVER (WHEN temp > 90)")
SQL_MM = ("SELECT deviceId, min(temp) AS mn, max(temp) AS mx, "
          "count(*) AS c FROM s GROUP BY deviceId, "
          "SLIDINGWINDOW(ss, 2) OVER (WHEN temp > 90)")
SQL_SKETCH = ("SELECT deviceId, percentile_approx(temp, 0.9) AS p90, "
              "distinct_count_approx(temp) AS dc FROM s GROUP BY deviceId, "
              "SLIDINGWINDOW(ss, 2) OVER (WHEN temp > 90)")

# identical fold inputs -> identical integer counts and min/max picks;
# float accumulations (sum/avg/stddev) compare loose, sketch FINAL values
# looser still (the refold path finalizes on device f32, the ring path
# in the numpy twins — same bins/registers, ±ulp value math)
EXACT_FIELDS = {"c", "mn", "mx"}


def mknode(sql, impl, capacity=64, micro_batch=128):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, sql
    node = FusedWindowAggNode(
        f"sr_{impl}", stmt.window, plan,
        dims=[d.expr for d in stmt.dimensions],
        capacity=capacity, micro_batch=micro_batch,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        sliding_impl=impl)
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    return node, got


def flat(items):
    msgs = []
    for item in items:
        if isinstance(item, ColumnBatch):
            msgs.extend(item.to_messages())
        elif isinstance(item, list):
            msgs.extend(item)
        else:
            msgs.append(item.message if hasattr(item, "message") else item)
    return msgs


def per_trigger(items):
    return [{m["deviceId"]: m for m in flat([item])} for item in items]


def run_pair(sql, batches, **kw):
    """Drive the SAME batches through both impls; returns per-trigger
    emission lists (daba, refold) plus the daba node."""
    node_d, got_d = mknode(sql, "daba", **kw)
    node_r, got_r = mknode(sql, "refold", **kw)
    assert node_d.sliding_impl == "daba"
    assert node_r.sliding_impl == "refold"
    for b in batches:
        node_d.process(b)
        node_r.process(b)
    node_d._drain_async_emits()
    node_r._drain_async_emits()
    return per_trigger(got_d), per_trigger(got_r), node_d


def assert_parity(trig_d, trig_r):
    assert len(trig_d) == len(trig_r) >= 1
    for td, tr in zip(trig_d, trig_r):
        assert set(td) == set(tr)
        for key, mr in tr.items():
            md = td[key]
            for f, vr in mr.items():
                vd = md[f]
                if vr is None or vd is None or isinstance(vr, str):
                    assert vd == vr, (key, f, vd, vr)
                elif f in EXACT_FIELDS:
                    assert vd == vr, (key, f, vd, vr)
                elif f == "dc":  # hll estimate rounds to an integer
                    assert abs(vd - vr) <= 1, (key, f, vd, vr)
                else:
                    np.testing.assert_allclose(
                        vd, vr, rtol=1e-4, atol=1e-4,
                        err_msg=f"{key}.{f}")


def trigger_batches(trigger_ts, keys=5, rows=48, t0=10_000, step=100,
                    n_batches=12, seed=3):
    """Monotone timestamped batches; for each requested trigger time the
    row closest to it (within its batch span) carries the trigger temp
    (>90), everything else stays below it — deterministic cadences."""
    rng = np.random.default_rng(seed)
    out = []
    t = t0
    for _ in range(n_batches):
        ids = np.array([f"d{i}" for i in rng.integers(0, keys, rows)],
                       dtype=np.object_)
        temp = rng.uniform(0, 88, rows).astype(np.float32)
        ts = t + np.sort(rng.integers(0, step, rows)).astype(np.int64)
        for tv in trigger_ts:
            if t <= tv < t + step:
                temp[int(np.argmin(np.abs(ts - tv)))] = 95.0
        out.append(ColumnBatch(
            n=rows, columns={"deviceId": ids, "temp": temp},
            timestamps=ts, emitter="s"))
        t += step
    return out


def endspike_batches(n_batches=3, rows=32, keys=4, t0=10_000, step=100,
                     seed=2):
    """Batches whose LAST row of the LAST batch is the trigger — the
    trigger lands in the head bucket (the ring's fast-path shape)."""
    rng = np.random.default_rng(seed)
    out = []
    t = t0
    for i in range(n_batches):
        ids = np.array([f"d{j}" for j in rng.integers(0, keys, rows)],
                       dtype=np.object_)
        temp = rng.uniform(0, 88, rows).astype(np.float32)
        ts = t + np.sort(rng.integers(0, step, rows)).astype(np.int64)
        if i == n_batches - 1:
            temp[-1] = 99.0
            ts[-1] = max(int(ts[-1]), int(ts.max()))
        out.append(ColumnBatch(
            n=rows, columns={"deviceId": ids, "temp": temp},
            timestamps=ts, emitter="s"))
        t += step
    return out


def random_trigger_batches(seed=7, n_batches=12, rows=48, keys=5,
                           t0=10_000, step=100, spike_every=17):
    rng = np.random.default_rng(seed)
    out = []
    t = t0
    k = 0
    for _ in range(n_batches):
        ids = np.array([f"d{i}" for i in rng.integers(0, keys, rows)],
                       dtype=np.object_)
        temp = rng.uniform(0, 88, rows).astype(np.float32)
        ts = t + np.sort(rng.integers(0, step, rows)).astype(np.int64)
        for i in range(rows):
            k += 1
            if k % spike_every == 0:
                temp[i] = 99.0
        out.append(ColumnBatch(
            n=rows, columns={"deviceId": ids, "temp": temp},
            timestamps=ts, emitter="s"))
        t += step
    return out


class TestWindowShapes:
    """DABA vs refold across the three trigger cadences: tumbling-
    degenerate (disjoint windows), hopping (regular overlap), and true
    sliding (arbitrary trigger times)."""

    def test_tumbling_degenerate(self):
        # one trigger every window length: windows tile without overlap
        trig = [12_000, 14_000, 16_000, 18_000]
        batches = trigger_batches(trig, n_batches=85, step=100)
        trig_d, trig_r, _ = run_pair(SQL_INV, batches)
        assert_parity(trig_d, trig_r)

    def test_hopping_shape(self):
        # trigger every 500ms on a 2s window: 4x overlap, hopping-like
        trig = list(range(12_000, 18_001, 500))
        batches = trigger_batches(trig, n_batches=85, step=100)
        trig_d, trig_r, _ = run_pair(SQL_INV, batches)
        assert_parity(trig_d, trig_r)

    def test_true_sliding_invertible(self):
        trig_d, trig_r, node = run_pair(
            SQL_INV, random_trigger_batches(seed=7, n_batches=30))
        assert_parity(trig_d, trig_r)
        # the DABA node kept NO device batch cache: the refold-era
        # _dev_ring stays empty (the stall class it carried is gone)
        assert node._dev_ring_bytes == 0
        assert not any(e is not None
                       for lst in node._dev_ring.values() for e in lst)

    def test_true_sliding_min_max(self):
        trig_d, trig_r, node = run_pair(
            SQL_MM, random_trigger_batches(seed=11, n_batches=30))
        assert_parity(trig_d, trig_r)
        assert node.ring is not None and node.ring.mm_comps == ["mn", "mx"]

    def test_true_sliding_sketches(self):
        trig_d, trig_r, _ = run_pair(
            SQL_SKETCH, random_trigger_batches(seed=13, n_batches=30))
        assert_parity(trig_d, trig_r)

    def test_delay_windows(self):
        """SLIDINGWINDOW(ss, 2, 1): delayed emission takes the exact
        fallback on the DABA path — parity must hold regardless."""
        from ekuiper_tpu.utils import timex

        sql = ("SELECT deviceId, count(*) AS c, max(temp) AS mx FROM s "
               "GROUP BY deviceId, SLIDINGWINDOW(ss, 2, 1) "
               "OVER (WHEN temp > 90)")
        batches = random_trigger_batches(seed=5, n_batches=20)
        node_d, got_d = mknode(sql, "daba")
        node_r, got_r = mknode(sql, "refold")
        clock = timex.get_clock()
        for b in batches:
            clock.set(int(b.timestamps[-1]))
            node_d.process(b)
            node_r.process(b)
        # fire every pending delayed emission on both nodes
        clock.advance(5_000)
        for node in (node_d, node_r):
            for t in sorted(node._pending_slides):
                node._pending_slides.pop(t, None)
                node._emit_sliding(t)
            node._drain_async_emits()
        assert_parity(per_trigger(got_d), per_trigger(got_r))


class TestClockModes:
    def test_processing_time_mock_clock(self, mock_clock):
        """Batches WITHOUT timestamps stamp at now_ms — drive the mock
        clock so both impls bucket identically."""
        rng = np.random.default_rng(23)
        node_d, got_d = mknode(SQL_INV, "daba")
        node_r, got_r = mknode(SQL_INV, "refold")
        mock_clock.set(50_000)
        for i in range(40):
            rows = 32
            ids = np.array([f"d{j}" for j in rng.integers(0, 4, rows)],
                           dtype=np.object_)
            temp = rng.uniform(0, 88, rows).astype(np.float32)
            if i % 7 == 6:
                temp[-1] = 97.0
            b = ColumnBatch(n=rows,
                            columns={"deviceId": ids, "temp": temp},
                            emitter="s")
            node_d.process(b)
            node_r.process(b)
            mock_clock.advance(100)
        node_d._drain_async_emits()
        node_r._drain_async_emits()
        assert_parity(per_trigger(got_d), per_trigger(got_r))


class TestEviction:
    def test_evict_past_capacity(self):
        """A stream longer than the pane ring retention: old buckets
        recycle, the running totals evict in lockstep, and every emitted
        window still matches the refold path (which refolds from its row
        ring). 100+ buckets on a ~83-slot ring."""
        batches = random_trigger_batches(seed=31, n_batches=90, rows=24,
                                         spike_every=29)
        trig_d, trig_r, node = run_pair(SQL_INV, batches)
        span_ms = 90 * 100
        assert span_ms // node.bucket_ms > node.n_ring_panes
        assert_parity(trig_d, trig_r)

    def test_gap_jump_rebuilds(self):
        """A time gap far wider than the advance hysteresis marks the
        ring dirty; the next trigger rebuilds from the panes (flip) and
        stays exact."""
        b1 = trigger_batches([10_250], n_batches=3, t0=10_000)
        b2 = trigger_batches([28_250], n_batches=3, t0=28_000, seed=9)
        trig_d, trig_r, _ = run_pair(SQL_INV, b1 + b2)
        assert len(trig_d) == 2
        assert_parity(trig_d, trig_r)

    def test_late_rows_mark_dirty_and_stay_exact(self):
        """Rows folding into already-absorbed buckets taint the running
        partials; the next trigger must rebuild rather than serve them."""
        def b(ts_list, temps):
            k = len(ts_list)
            return ColumnBatch(
                n=k,
                columns={"deviceId": np.array(["d0"] * k, dtype=np.object_),
                         "temp": np.asarray(temps, dtype=np.float32)},
                timestamps=np.asarray(ts_list, dtype=np.int64), emitter="s")

        node_d, got_d = mknode(SQL_INV, "daba")
        node_r, got_r = mknode(SQL_INV, "refold")
        for node in (node_d, node_r):
            node.process(b([10_000, 10_100, 10_200], [50.0, 50.0, 50.0]))
            # 8 buckets behind the head: folds into a closed bucket
            node.process(b([10_150], [50.0]))
            node.process(b([10_400], [95.0]))  # trigger
            node._drain_async_emits()
        td, tr = per_trigger(got_d), per_trigger(got_r)
        assert_parity(td, tr)
        assert td[0]["d0"]["c"] == 5  # the late row counted


class TestKillRestore:
    @pytest.mark.parametrize("impl", ["daba", "refold"])
    def test_snapshot_roundtrip_within_impl(self, impl):
        batches = random_trigger_batches(seed=17, n_batches=16)
        # uninterrupted reference
        ref_node, ref_got = mknode(SQL_INV, impl)
        for b in batches:
            ref_node.process(b)
        ref_node._drain_async_emits()
        # kill after batch 8, restore, continue
        n1, got1 = mknode(SQL_INV, impl)
        for b in batches[:8]:
            n1.process(b)
        n1._drain_async_emits()
        snap = json.loads(json.dumps(n1.snapshot_state()))
        n2, got2 = mknode(SQL_INV, impl)
        n2.restore_state(snap)
        for b in batches[8:]:
            n2.process(b)
        n2._drain_async_emits()
        ref = per_trigger(ref_got)
        after = per_trigger(got2)
        assert len(after) >= 1
        assert len(ref) == len(per_trigger(got1)) + len(after)
        # post-restore windows (some straddle the checkpoint) match the
        # uninterrupted run
        assert_parity(after, ref[-len(after):])

    def test_cross_impl_restore(self):
        """A refold-era checkpoint restores into a DABA node (and back):
        the pane state layout is shared, the ring partials rebuild from
        the restored panes on the first trigger."""
        batches = random_trigger_batches(seed=19, n_batches=16)
        for src, dst in (("refold", "daba"), ("daba", "refold")):
            n1, _ = mknode(SQL_INV, src)
            for b in batches[:8]:
                n1.process(b)
            n1._drain_async_emits()
            snap = json.loads(json.dumps(n1.snapshot_state()))
            n2, got2 = mknode(SQL_INV, dst)
            n2.restore_state(snap)
            nr, gotr = mknode(SQL_INV, "refold")
            nr.restore_state(json.loads(json.dumps(snap)))
            for b in batches[8:]:
                n2.process(b)
                nr.process(b)
            n2._drain_async_emits()
            nr._drain_async_emits()
            assert_parity(per_trigger(got2), per_trigger(gotr))


class TestRingGuardrails:
    def test_budget_fallback_to_refold(self):
        """A ring whose static footprint exceeds slidingDevRingMb must
        refuse the DABA allocation and keep the refold path."""
        stmt = parse_select(SQL_SKETCH)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "tiny", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            dev_ring_budget_mb=0, sliding_impl="daba")
        assert node.sliding_impl == "refold"
        assert node.ring is None

    def test_memwatch_probe_registered(self):
        from ekuiper_tpu.observability import memwatch

        node, _ = mknode(SQL_INV, "daba")
        node.on_open()
        comps = {r["component"]
                 for r in memwatch.registry().snapshot()
                 if r["component"].startswith(("sliding", "dev_ring"))}
        assert "sliding_ring" in comps and "dev_ring" in comps
        # bytes appear once the ring allocates (first served trigger)
        for b in endspike_batches():
            node.process(b)
        node._drain_async_emits()
        assert node.ring_dev_bytes() > 0
        rows = {r["component"]: r["bytes"]
                for r in memwatch.registry().snapshot()
                if r["component"] == "sliding_ring"}
        assert rows.get("sliding_ring", 0) > 0
        # and the refold-era cache stays unbudgeted/empty under daba
        assert node._dev_ring_bytes == 0

    def test_estimate_matches_allocation(self):
        node, _ = mknode(SQL_INV, "daba")
        for b in endspike_batches():
            node.process(b)
        node._drain_async_emits()
        est = node.ring.estimate_bytes(node.gb.capacity)
        assert node.ring_dev_bytes() == est

    def test_combine_classes_are_total(self):
        """Every device component must have a ring combine class —
        a new component without one must fail loudly at plan time."""
        from ekuiper_tpu.ops.groupby import _INIT

        for comp in _INIT:
            assert (comp in ADD_COMBINE or comp in MIN_COMBINE
                    or comp in MAX_COMBINE), comp

    def test_admission_prices_ring_sites(self):
        """QoS admission must price a DABA sliding rule's extra compile
        surface (3 ring sites + components_dyn), not just the shared
        group-by sites — the signature budget would otherwise invert."""
        from ekuiper_tpu.observability import jitcert

        plan = extract_kernel_plan(parse_select(SQL_INV))
        base = jitcert.estimate_plan_signatures(plan, 1, 128, 64)
        ring = jitcert.estimate_plan_signatures(plan, 1, 128, 64,
                                                sliding_ring_slots=83)
        assert ring == base + 4

    def test_rule_option_plumbs(self):
        from ekuiper_tpu.planner.planner import RuleDef, merged_options

        opts = merged_options(RuleDef(id="r", sql="",
                                      options={"slidingImpl": "refold"}))
        assert opts.sliding_impl == "refold"
        assert merged_options(RuleDef(id="r", sql="")).sliding_impl == "daba"

    def test_layout_is_plan_time(self):
        layout = plan_ring_layout(2_000, 0, wide=False)
        assert layout.n_panes == layout.n_ring_panes + 1
        assert layout.span_buckets == -(-2_000 // layout.bucket_ms)
        node, _ = mknode(SQL_INV, "daba")
        assert node.bucket_ms == layout.bucket_ms
        assert node.n_ring_panes == layout.n_ring_panes


class TestBudgetAwareLayout:
    """ROADMAP item-2 remnant: wide-hll sliding rules must take the DABA
    ring inside the slidingDevRingMb budget by coarsening their ring
    geometry, instead of silently falling back to refold; and the
    budget check must price exactly what init_state allocates."""

    WIDE_SQL = ("SELECT deviceId, distinct_count_approx(temp) AS dc, "
                "percentile_approx(temp, 0.9) AS p90, count(*) AS c "
                "FROM s GROUP BY deviceId, "
                "SLIDINGWINDOW(ss, 30) OVER (WHEN temp > 90)")

    def test_estimate_matches_allocation(self):
        stmt = parse_select(SQL_MM)
        plan = extract_kernel_plan(stmt)
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.ops.slidingring import ring_layout_for

        layout = ring_layout_for(stmt.window, plan)
        gb = DeviceGroupBy(plan, capacity=32, n_panes=layout.n_panes,
                           micro_batch=16)
        ring = SlidingRing(gb, layout)
        state = ring.init_state()
        assert ring.state_nbytes(state) == ring.estimate_bytes(32)

    def test_plan_time_estimate_matches_kernel_estimate(self):
        """The planner's no-kernel estimate (_plan_ring_bytes) must
        price the same bytes SlidingRing.estimate_bytes reports."""
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.ops.slidingring import (_plan_ring_bytes,
                                                 ring_layout_for)

        stmt = parse_select(self.WIDE_SQL)
        plan = extract_kernel_plan(stmt)
        layout = ring_layout_for(stmt.window, plan)
        gb = DeviceGroupBy(plan, capacity=64, n_panes=layout.n_panes,
                           micro_batch=16)
        ring = SlidingRing(gb, layout)
        mm_slot, fixed = _plan_ring_bytes(plan, 64)
        assert fixed + (1 + layout.n_ring_panes) * mm_slot == \
            ring.estimate_bytes(64)

    def test_wide_hll_coarsens_into_budget(self):
        """A wide-hll sliding rule whose default geometry would blow the
        budget coarsens its buckets until the ring fits — and takes the
        DABA ring, not the refold fallback."""
        from ekuiper_tpu.ops.slidingring import (_plan_ring_bytes,
                                                 ring_layout_for)

        stmt = parse_select(self.WIDE_SQL)
        plan = extract_kernel_plan(stmt)
        capacity = 2048
        default = ring_layout_for(stmt.window, plan)
        mm_slot, fixed = _plan_ring_bytes(plan, capacity)
        default_bytes = fixed + (1 + default.n_ring_panes) * mm_slot
        # pick a budget the default layout misses but a coarser fits
        budget_mb = max(int(default_bytes * 0.6) >> 20, 1)
        fitted = ring_layout_for(stmt.window, plan, capacity=capacity,
                                 budget_mb=budget_mb)
        assert fitted.n_ring_panes < default.n_ring_panes
        fitted_bytes = fixed + (1 + fitted.n_ring_panes) * mm_slot
        assert fitted_bytes <= budget_mb << 20
        node = FusedWindowAggNode(
            "wide", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=capacity, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            dev_ring_budget_mb=budget_mb, sliding_impl="daba")
        assert node.sliding_impl == "daba", "wide-hll rule must ride DABA"
        assert node.ring.estimate_bytes(capacity) <= budget_mb << 20

    def test_impossible_budget_still_refolds(self):
        stmt = parse_select(self.WIDE_SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "none", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=2048, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            dev_ring_budget_mb=0, sliding_impl="daba")
        assert node.sliding_impl == "refold"
