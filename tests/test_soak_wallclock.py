"""Wall-clock soak (reference analogue: fvt/ suites): a minutes-scale run
with REAL time — continuous file-source traffic, short checkpoint
intervals, repeated kill/restore cycles, and a flapping sink buffered by
the CacheNode — asserting the at-least-once contract (no loss) and
bounded memory. Marked slow; run summary documented in docs/PERF_NOTES.md.
"""
import json
import os
import threading
import time

import pytest

from ekuiper_tpu.io.memory import MemorySink
from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem

N_ROWS = 120_000
WINDOW = 1000


def _rss_mb() -> float:
    with open("/proc/self/statm") as f:
        pages = int(f.read().split()[1])
    return pages * os.sysconf("SC_PAGE_SIZE") / 1e6


@pytest.fixture
def real_clock():
    """This soak runs on the REAL clock (timers, checkpoint intervals,
    resend backoff all at wall-clock pace)."""
    from ekuiper_tpu.utils import timex

    timex.use_real_clock()
    yield
    timex.use_real_clock()


@pytest.mark.slow
class TestWallClockSoak:
    def test_kill_restore_flapping_sink_no_loss(self, real_clock, tmp_path):
        """qos1 rule over a rewindable file source with a short checkpoint
        interval; the topo is closed and re-planned repeatedly mid-stream
        while the sink flaps up/down (CacheNode spill + resend). Contract:
        every uid is delivered AT LEAST once; memory growth stays bounded."""
        mem.reset()
        store = kv.get_store()
        path = tmp_path / "soak.jsonl"
        with open(path, "w") as f:
            for i in range(N_ROWS):
                f.write(json.dumps(
                    {"uid": i, "deviceId": f"d{i % 50}",
                     "v": float(i % 7)}) + "\n")
        store.kv("source_conf").set("file:soaklines", {"fileType": "lines"})
        StreamProcessor(store).exec_stmt(
            f'CREATE STREAM soakf (uid BIGINT, deviceId STRING, v FLOAT) '
            f'WITH (DATASOURCE="{path}", TYPE="file", FORMAT="JSON", '
            f'CONF_KEY="soaklines")')

        got_uids = set()
        got_count = [0]
        flap = {"down": False}
        orig_collect = MemorySink.collect

        def flaky_collect(self, item):
            if flap["down"]:
                raise ConnectionError("sink flapping (soak)")
            orig_collect(self, item)

        MemorySink.collect = flaky_collect

        def on_msg(_t, payload):
            msgs = payload if isinstance(payload, list) else [payload]
            for m in msgs:
                if isinstance(m, dict) and "uid" in m:
                    got_uids.add(m["uid"])
                    got_count[0] += 1

        mem.subscribe("soak/out", on_msg)

        def make_topo():
            return plan_rule(RuleDef(
                id="soakrule",
                sql="SELECT uid, deviceId FROM soakf WHERE v >= 0",
                actions=[{"memory": {
                    "topic": "soak/out", "enableCache": True,
                    "memoryCacheThreshold": 256,
                    "resendInterval": 50}}],
                options={"qos": 1, "checkpointInterval": 800}), store)

        rss_start = _rss_mb()
        try:
            deadline = time.time() + 90
            cycles = 0
            while len(got_uids) < N_ROWS and time.time() < deadline:
                topo = make_topo()
                topo.open()
                t0 = time.time()
                if cycles < 2:
                    # early lives: sink goes DOWN mid-life and STAYS down
                    # through the kill — the backlog must survive via the
                    # cache spill and resend in a later life
                    while time.time() - t0 < 2.5:
                        flap["down"] = time.time() - t0 >= 0.8
                        time.sleep(0.05)
                else:
                    flap["down"] = False
                    while (time.time() - t0 < 4.0
                           and len(got_uids) < N_ROWS):
                        time.sleep(0.05)
                topo.close()  # kill this life; next cycle restores
                flap["down"] = False
                cycles += 1
            assert cycles >= 3, "soak must span multiple kill/restore cycles"
            missing = set(range(N_ROWS)) - got_uids
            assert not missing, (
                f"lost {len(missing)} uids (first: {sorted(missing)[:5]}) "
                f"after {cycles} cycles — at-least-once violated")
            # duplicates are allowed (at-least-once), but must be bounded by
            # the replay spans, not systemic re-delivery
            assert got_count[0] < N_ROWS * 3, got_count[0]
            growth = _rss_mb() - rss_start
            assert growth < 600, f"RSS grew {growth:.0f}MB during soak"
        finally:
            MemorySink.collect = orig_collect
            mem.reset()

    def test_count_window_state_survives_kills(self, real_clock, tmp_path):
        """Device-path COUNTWINDOW partials + _rows_in_window ride
        checkpoints across kill/restore: the sum of emitted window counts
        covers every complete window at least once."""
        mem.reset()
        store = kv.get_store()
        n = 60_000
        path = tmp_path / "soakc.jsonl"
        with open(path, "w") as f:
            for i in range(n):
                f.write(json.dumps(
                    {"uid": i, "deviceId": f"d{i % 20}",
                     "v": float(i % 5)}) + "\n")
        store.kv("source_conf").set("file:soaklines", {"fileType": "lines"})
        StreamProcessor(store).exec_stmt(
            f'CREATE STREAM soakc (uid BIGINT, deviceId STRING, v FLOAT) '
            f'WITH (DATASOURCE="{path}", TYPE="file", FORMAT="JSON", '
            f'CONF_KEY="soaklines")')
        counts = []
        mem.subscribe("soak/cnt", lambda _t, p: counts.extend(
            m["c"] for m in (p if isinstance(p, list) else [p])
            if isinstance(m, dict) and "c" in m))

        def make_topo():
            # end-to-end at-least-once for window EMISSIONS needs the sink
            # cache (reference SyncCache): without it, a kill can cut an
            # in-flight emission after the window state already reset
            return plan_rule(RuleDef(
                id="soakcw",
                sql=(f"SELECT deviceId, count(*) AS c FROM soakc "
                     f"GROUP BY deviceId, COUNTWINDOW({WINDOW})"),
                actions=[{"memory": {"topic": "soak/cnt",
                                     "enableCache": True,
                                     "resendInterval": 30}}],
                options={"qos": 1, "checkpointInterval": 700}), store)

        deadline = time.time() + 60
        target = (n // WINDOW) * WINDOW
        lives = 0
        try:
            while time.time() < deadline:
                topo = make_topo()
                topo.open()
                t0 = time.time()
                if lives < 2:
                    # first lives are ALWAYS killed mid-stream, regardless
                    # of progress — the restore path must carry the rest
                    time.sleep(1.5)
                else:
                    while time.time() - t0 < 3.0 and sum(counts) < target:
                        time.sleep(0.05)
                topo.close()
                lives += 1
                if lives >= 2 and sum(counts) >= target:
                    break
            assert lives >= 2
            assert sum(counts) >= target, (
                f"window counts {sum(counts)} < {target} after {lives} "
                "lives — rows lost beyond the QoS contract")
        finally:
            mem.reset()
