"""Websocket / redis / neuron connectors + connection CRUD/ping."""
import json
import socket
import threading
import time

import pytest

from ekuiper_tpu.io import registry as io_registry
from ekuiper_tpu.io.connections import ConnectionManager, ping
from ekuiper_tpu.io.redis_io import RespClient
from ekuiper_tpu.store import kv


# ------------------------------------------------------------ fake redis
class FakeRedis:
    """Tiny RESP2 server: SET/GET/LPUSH/LRANGE/HGETALL/PUBLISH/SUBSCRIBE/
    PING, enough to exercise the connectors."""

    def __init__(self):
        self.data = {}
        self.lists = {}
        self.hashes = {}
        self.subs = []  # (conn, channels)
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    def close(self):
        self._stop = True
        self.srv.close()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _reply_bulk(v):
        if v is None:
            return b"$-1\r\n"
        b = v if isinstance(v, bytes) else str(v).encode()
        return b"$%d\r\n%s\r\n" % (len(b), b)

    def _serve(self, conn):
        buf = b""

        def read_cmd():
            nonlocal buf
            while True:
                if b"\r\n" in buf:
                    head, rest = buf.split(b"\r\n", 1)
                    if head.startswith(b"*"):
                        n = int(head[1:])
                        args = []
                        cur = rest
                        ok = True
                        for _ in range(n):
                            if b"\r\n" not in cur:
                                ok = False
                                break
                            ln, cur = cur.split(b"\r\n", 1)
                            size = int(ln[1:])
                            if len(cur) < size + 2:
                                ok = False
                                break
                            args.append(cur[:size])
                            cur = cur[size + 2:]
                        if ok:
                            buf = cur
                            return [a.decode() for a in args]
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf += chunk

        while True:
            cmd = read_cmd()
            if cmd is None:
                return
            op = cmd[0].upper()
            if op == "PING":
                conn.sendall(b"+PONG\r\n")
            elif op == "SET":
                self.data[cmd[1]] = cmd[2]
                conn.sendall(b"+OK\r\n")
            elif op == "GET":
                conn.sendall(self._reply_bulk(self.data.get(cmd[1])))
            elif op in ("LPUSH", "RPUSH"):
                lst = self.lists.setdefault(cmd[1], [])
                lst.insert(0, cmd[2]) if op == "LPUSH" else lst.append(cmd[2])
                conn.sendall(b":%d\r\n" % len(lst))
            elif op == "HGETALL":
                h = self.hashes.get(cmd[1], {})
                out = [b"*%d\r\n" % (len(h) * 2)]
                for k, v in h.items():
                    out.append(self._reply_bulk(k))
                    out.append(self._reply_bulk(v))
                conn.sendall(b"".join(out))
            elif op == "SUBSCRIBE":
                self.subs.append((conn, cmd[1:]))
                for i, ch in enumerate(cmd[1:]):
                    conn.sendall(
                        b"*3\r\n" + self._reply_bulk("subscribe")
                        + self._reply_bulk(ch) + b":%d\r\n" % (i + 1))
            elif op == "PUBLISH":
                n = 0
                for sconn, chans in self.subs:
                    if cmd[1] in chans:
                        sconn.sendall(
                            b"*3\r\n" + self._reply_bulk("message")
                            + self._reply_bulk(cmd[1])
                            + self._reply_bulk(cmd[2]))
                        n += 1
                conn.sendall(b":%d\r\n" % n)
            else:
                conn.sendall(b"-ERR unknown\r\n")


@pytest.fixture
def fake_redis():
    srv = FakeRedis()
    yield srv
    srv.close()


class TestRedis:
    def test_resp_client(self, fake_redis):
        cli = RespClient("127.0.0.1", fake_redis.port)
        cli.connect()
        assert cli.command("PING") == "PONG"
        cli.command("SET", "k", "v")
        assert cli.command("GET", "k") == b"v"
        cli.close()

    def test_sink_set_and_list(self, fake_redis):
        sink = io_registry.create_sink("redis")
        sink.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                        "field": "deviceId"})
        sink.connect()
        sink.collect({"deviceId": "d1", "t": 20})
        assert json.loads(fake_redis.data["d1"]) == {"deviceId": "d1", "t": 20}
        lsink = io_registry.create_sink("redis")
        lsink.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                         "key": "q", "dataType": "list"})
        lsink.connect()
        lsink.collect([{"a": 1}, {"a": 2}])
        assert len(fake_redis.lists["q"]) == 2
        sink.close(); lsink.close()

    def test_sub_source_roundtrip(self, fake_redis):
        src = io_registry.create_source("redissub")
        src.configure("news", {"addr": f"127.0.0.1:{fake_redis.port}"})
        got = []
        src.open(got.append)
        deadline = time.time() + 5
        while time.time() < deadline and not fake_redis.subs:
            time.sleep(0.02)
        pub = RespClient("127.0.0.1", fake_redis.port)
        pub.connect()
        pub.command("PUBLISH", "news", json.dumps({"x": 1}))
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        src.close(); pub.close()
        assert got and got[0] == {"x": 1}

    def test_lookup(self, fake_redis):
        fake_redis.data["dev9"] = json.dumps({"site": "lx"})
        lk = io_registry.create_lookup("redis")
        lk.configure("", {"addr": f"127.0.0.1:{fake_redis.port}"})
        lk.open()
        assert lk.lookup([], ["id"], ["dev9"]) == [{"site": "lx"}]
        assert lk.lookup([], ["id"], ["absent"]) == []
        lk.close()


class TestWebsocket:
    def test_server_mode_source_and_sink(self):
        from websockets.sync.client import connect

        src = io_registry.create_source("websocket")
        src.configure("/ws/demo", {"port": 0})
        got = []
        src.open(got.append)
        port = src._server.actual_port
        sink = io_registry.create_sink("websocket")
        sink.configure({"path": "/ws/demo", "port": 0})
        # share the same server instance (port key 0 in the pool)
        sink.connect()
        with connect(f"ws://127.0.0.1:{port}/ws/demo") as ws:
            ws.send(json.dumps({"hello": 1}))
            deadline = time.time() + 5
            while time.time() < deadline and not got:
                time.sleep(0.02)
            assert got == [{"hello": 1}]
            sink.collect({"reply": 2})
            msg = json.loads(ws.recv(timeout=5))
            assert msg == {"reply": 2}
        src.close()
        sink.close()

    def test_client_mode_source(self):
        from websockets.sync.server import serve

        def handler(conn):
            conn.send(json.dumps({"from": "server"}))
            time.sleep(0.5)

        srv = serve(handler, "127.0.0.1", 0)
        port = srv.socket.getsockname()[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        src = io_registry.create_source("websocket")
        src.configure("", {"addr": f"ws://127.0.0.1:{port}/x"})
        got = []
        src.open(got.append)
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        src.close()
        srv.shutdown()
        assert got and got[0] == {"from": "server"}


class TestNeuron:
    def test_pair_roundtrip(self):
        from ekuiper_tpu.plugin import ipc

        url = ipc.ipc_url("neuron-test")
        peer = ipc.Socket(ipc.PAIR)
        peer.listen(url)
        recvd = []

        frame = json.dumps(
            {"group_name": "g1", "values": {"tag1": 9}}).encode()
        stop = threading.Event()

        def gateway():
            # the fake neuron gateway: emit the tag frame continuously
            # (frames sent before a peer dials are dropped by the native
            # pair transport) and collect written commands until stopped
            for _ in range(400):
                if stop.is_set():
                    return
                try:
                    peer.send(frame, timeout_ms=100)
                except Exception:
                    pass
                try:
                    raw = peer.recv(timeout_ms=50)
                    if raw:
                        recvd.append(json.loads(raw.decode()))
                except Exception:
                    continue

        threading.Thread(target=gateway, daemon=True).start()
        src = io_registry.create_source("neuron")
        src.configure("", {"url": url})
        got = []
        src.open(got.append)
        sink = io_registry.create_sink("neuron")
        sink.configure({"url": url, "nodeName": "n1", "groupName": "g1",
                        "tags": ["temperature"]})
        sink.connect()
        sink.collect({"temperature": 21.5, "other": 1})
        deadline = time.time() + 8
        while time.time() < deadline and not (got and recvd):
            time.sleep(0.02)
        stop.set()
        src.close(); sink.close(); peer.close()
        assert got and got[0]["values"] == {"tag1": 9}
        assert recvd and recvd[0] == {
            "node_name": "n1", "group_name": "g1",
            "tag_name": "temperature", "tag_value": 21.5}


class TestConnections:
    def test_crud_and_ping(self, fake_redis):
        mgr = ConnectionManager(kv.get_store())
        mgr.create({"id": "c1", "typ": "redis",
                    "props": {"addr": f"127.0.0.1:{fake_redis.port}"}})
        assert [c["id"] for c in mgr.list()] == ["c1"]
        assert mgr.ping("c1") == "ok"
        mgr.update("c1", {"typ": "memory", "props": {}})
        assert mgr.get("c1")["typ"] == "memory"
        mgr.delete("c1")
        with pytest.raises(Exception, match="not found"):
            mgr.get("c1")

    def test_ping_failure_reports_reason(self):
        with pytest.raises(Exception, match="ping failed"):
            ping("redis", {"addr": "127.0.0.1:1", "timeout": 300})
