"""Shared-source subtopology: N rules over one stream share one ingest +
decode pipeline (reference: internal/topo/subtopo.go, subtopo_pool.go)."""
import time

import numpy as np

from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.runtime import subtopo
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _mk_stream(store):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="t/shared", TYPE="memory", FORMAT="JSON")'
    )


def _rule(rule_id, threshold, qos=0):
    return RuleDef(
        id=rule_id,
        sql=(f"SELECT deviceId, temperature FROM demo "
             f"WHERE temperature > {threshold}"),
        actions=[{"memory": {"topic": f"res/{rule_id}"}}],
        options={"qos": qos} if qos else {},
    )


def _results(sink):
    out = []
    for item in list(sink.results):
        out.extend(item if isinstance(item, list) else [item])
    return out


class TestSubtopoPool:
    def test_two_rules_one_source(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        t1 = plan_rule(_rule("r1", 25), store)
        t2 = plan_rule(_rule("r2", 10), store)
        # both rules rode the pool: no private sources, same subtopo key;
        # the live instance resolves at open()
        assert not t1.sources and not t2.sources
        assert t1.shared[0][0].key == t2.shared[0][0].key
        t1.open()
        t2.open()
        assert subtopo.pool_size() == 1
        st = t1._live_shared[0][0]
        assert st is t2._live_shared[0][0]
        assert st.ref_count() == 2
        try:
            mem.publish("t/shared", {"deviceId": "a", "temperature": 30.0})
            mem.publish("t/shared", {"deviceId": "b", "temperature": 20.0})
            mock_clock.advance(20)  # linger flush
            deadline = time.time() + 5
            while time.time() < deadline and not (
                t1.sinks[0].results and t2.sinks[0].results
            ):
                time.sleep(0.01)
            r1 = _results(t1.sinks[0])
            r2 = _results(t2.sinks[0])
            # one decode, two different filters applied per rule
            assert [m["deviceId"] for m in r1] == ["a"]
            assert sorted(m["deviceId"] for m in r2) == ["a", "b"]
        finally:
            t1.close()
            assert st.ref_count() == 1  # r2 still attached, source still live
            t2.close()
        assert st.ref_count() == 0
        assert subtopo.pool_size() == 0  # closed and evicted on last detach

    def test_qos_rule_gets_private_source(self):
        store = kv.get_store()
        _mk_stream(store)
        t1 = plan_rule(_rule("rq", 5, qos=1), store)
        assert t1.sources and not t1.shared
        assert subtopo.pool_size() == 0

    def test_different_options_do_not_share(self):
        store = kv.get_store()
        _mk_stream(store)
        t1 = plan_rule(_rule("ra", 5), store)
        r = _rule("rb", 5)
        r.options = {"micro_batch_rows": 128}
        t2 = plan_rule(r, store)
        assert t1.shared[0][0].key != t2.shared[0][0].key
        t1.open(); t2.open()
        try:
            assert subtopo.pool_size() == 2
        finally:
            t1.close(); t2.close()

    def test_reopen_after_pool_close(self, mock_clock):
        """A rule opened AFTER the pooled subtopo closed (last peer
        detached) must get a fresh, working pipeline."""
        store = kv.get_store()
        _mk_stream(store)
        t1 = plan_rule(_rule("rr1", 0), store)
        t2 = plan_rule(_rule("rr2", 0), store)
        t1.open()
        t1.close()  # last detach -> subtopo closes and is evicted
        assert subtopo.pool_size() == 0
        t2.open()  # must resolve a FRESH subtopo, not the dead one
        try:
            assert subtopo.pool_size() == 1
            mem.publish("t/shared", {"deviceId": "x", "temperature": 1.0})
            mock_clock.advance(20)
            deadline = time.time() + 5
            while time.time() < deadline and not t2.sinks[0].results:
                time.sleep(0.01)
            assert any(m["deviceId"] == "x" for m in _results(t2.sinks[0]))
        finally:
            t2.close()

    def test_share_source_off(self):
        store = kv.get_store()
        _mk_stream(store)
        r = _rule("rc", 5)
        r.options = {"share_source": False}
        t = plan_rule(r, store)
        assert t.sources and not t.shared

    def test_fanout_survives_detach_during_traffic(self, mock_clock):
        """Detaching one rule mid-stream must not break the other's feed
        (copy-on-write outputs)."""
        store = kv.get_store()
        _mk_stream(store)
        t1 = plan_rule(_rule("rd1", 0), store)
        t2 = plan_rule(_rule("rd2", 0), store)
        t1.open(); t2.open()
        try:
            for i in range(5):
                mem.publish("t/shared", {"deviceId": f"d{i}", "temperature": 1.0})
            mock_clock.advance(20)
            t1.close()  # detach while t2 keeps consuming
            mem.publish("t/shared", {"deviceId": "after", "temperature": 1.0})
            mock_clock.advance(20)
            deadline = time.time() + 5
            while time.time() < deadline:
                if any(m["deviceId"] == "after" for m in _results(t2.sinks[0])):
                    break
                time.sleep(0.01)
            assert any(m["deviceId"] == "after" for m in _results(t2.sinks[0]))
        finally:
            t2.close()
