"""OTLP span export: the hand-encoded wire bytes (observability/otlp.py)
are decoded with protoc + google.protobuf against a schema derived from the
official opentelemetry-proto field numbers — an independent decoder, so an
encoding bug can't validate itself. Plus the HTTP batching exporter and the
tracer tee (reference pkg/tracer/manager.go:28-76)."""
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ekuiper_tpu.observability.otlp import (OtlpExporter,
                                            encode_export_request,
                                            from_config)
from ekuiper_tpu.observability.tracer import Span, Tracer

# Official opentelemetry-proto subset (field numbers from trace/v1/
# trace.proto, common/v1/common.proto, resource/v1/resource.proto,
# collector/trace/v1/trace_service.proto) — used ONLY as the decode schema.
OTLP_PROTO = """
syntax = "proto3";
package otlptest;

message AnyValue {
  oneof value {
    string string_value = 1;
    bool bool_value = 2;
    int64 int_value = 3;
    double double_value = 4;
  }
}
message KeyValue { string key = 1; AnyValue value = 2; }
message Resource { repeated KeyValue attributes = 1; }
message InstrumentationScope { string name = 1; string version = 2; }
message Span {
  bytes trace_id = 1;
  bytes span_id = 2;
  string trace_state = 3;
  bytes parent_span_id = 4;
  string name = 5;
  int32 kind = 6;
  fixed64 start_time_unix_nano = 7;
  fixed64 end_time_unix_nano = 8;
  repeated KeyValue attributes = 9;
}
message ScopeSpans {
  InstrumentationScope scope = 1;
  repeated Span spans = 2;
  string schema_url = 3;
}
message ResourceSpans {
  Resource resource = 1;
  repeated ScopeSpans scope_spans = 2;
  string schema_url = 3;
}
message ExportTraceServiceRequest { repeated ResourceSpans resource_spans = 1; }

service Noop { rpc Export(ExportTraceServiceRequest) returns (ExportTraceServiceRequest); }
"""


@pytest.fixture(scope="module")
def decoder():
    """protoc-compiled ExportTraceServiceRequest class."""
    from ekuiper_tpu.services.schema import ProtoServiceSchema

    schema = ProtoServiceSchema(OTLP_PROTO)
    cls, _ = schema.methods["Export"][1], schema.methods["Export"][2]
    return cls


def _spans():
    return [
        Span("t0000002a", "s00000001", "", "r1", "source", 1000, 250,
             "ColumnBatch", 16),
        Span("t0000002a", "s00000002", "s00000001", "r1", "window_agg",
             1001, 1250, "list", 3),
    ]


class TestEncoding:
    def test_decodes_with_official_schema(self, decoder):
        body = encode_export_request(_spans(), service_name="svc-x")
        req = decoder.FromString(body)
        assert len(req.resource_spans) == 1
        rs = req.resource_spans[0]
        res_attrs = {kv.key: kv.value.string_value
                     for kv in rs.resource.attributes}
        assert res_attrs == {"service.name": "svc-x"}
        assert rs.scope_spans[0].scope.name == "ekuiper_tpu.tracer"
        spans = rs.scope_spans[0].spans
        assert len(spans) == 2
        s0, s1 = spans
        assert len(s0.trace_id) == 16 and len(s0.span_id) == 8
        assert s0.trace_id == s1.trace_id  # same engine trace
        assert s0.span_id != s1.span_id
        assert s1.parent_span_id == s0.span_id  # deterministic id mapping
        assert s0.name == "r1/source" and s1.name == "r1/window_agg"
        assert s0.kind == 1  # INTERNAL
        assert s0.start_time_unix_nano == 1000 * 1_000_000
        assert s0.end_time_unix_nano == s0.start_time_unix_nano + 250_000
        attrs = {kv.key: kv.value for kv in s1.attributes}
        assert attrs["op"].string_value == "window_agg"
        assert attrs["item.rows"].int_value == 3
        assert attrs["item.kind"].string_value == "list"


class _Collector:
    """Minimal in-process OTLP/HTTP collector."""

    def __init__(self):
        self.bodies = []
        self.headers = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.bodies.append((self.path, self.rfile.read(n)))
                outer.headers.append(dict(self.headers))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.srv = HTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.srv.shutdown()


@pytest.fixture
def collector():
    c = _Collector()
    yield c
    c.close()


class TestExporter:
    def test_http_post_batch(self, collector, decoder):
        exp = OtlpExporter(f"127.0.0.1:{collector.port}",
                           batch_interval_ms=50)
        for s in _spans():
            exp.on_span(s)
        deadline = time.time() + 5
        while time.time() < deadline and not collector.bodies:
            time.sleep(0.02)
        exp.close()
        assert collector.bodies, "no export arrived"
        path, body = collector.bodies[0]
        assert path == "/v1/traces"
        assert collector.headers[0]["Content-Type"] == "application/x-protobuf"
        req = decoder.FromString(body)
        got = [s.name for rs in req.resource_spans
               for ss in rs.scope_spans for s in ss.spans]
        assert got == ["r1/source", "r1/window_agg"]
        assert exp.stats()["exported"] == 2

    def test_collector_down_bounds_memory(self):
        exp = OtlpExporter("127.0.0.1:1", batch_max_spans=4,
                           batch_interval_ms=50)
        for _ in range(100):
            for s in _spans():
                exp.on_span(s)
        time.sleep(0.3)
        exp.close()
        st = exp.stats()
        assert st["exported"] == 0 and st["errors"] >= 1
        assert st["dropped"] > 0  # bounded, never blocked

    def test_tracer_tee(self, collector, decoder):
        tracer = Tracer()
        exp = OtlpExporter(f"127.0.0.1:{collector.port}",
                           batch_interval_ms=50)
        tracer.exporter = exp
        tracer.enable("r9")
        tracer.record("r9", "decode", 5, 10, "dict", 1)
        tracer.record("other_rule_not_traced", "decode", 5, 10, "dict", 1)
        deadline = time.time() + 5
        while time.time() < deadline and not collector.bodies:
            time.sleep(0.02)
        tracer.set_exporter(None)  # closes the exporter
        names = [s.name for _, b in collector.bodies
                 for rs in decoder.FromString(b).resource_spans
                 for ss in rs.scope_spans for s in ss.spans]
        assert names == ["r9/decode"]  # only traced rules tee to OTLP

    def test_config_gate_default_off(self):
        from ekuiper_tpu.utils.config import Config

        assert from_config(Config()) is None
        cfg = Config()
        cfg.open_telemetry.enable_remote_collector = True
        cfg.open_telemetry.remote_endpoint = "127.0.0.1:9"
        exp = from_config(cfg)
        assert exp is not None and exp.url == "http://127.0.0.1:9/v1/traces"
        exp.close()


def test_config_service_name_plumbs():
    from ekuiper_tpu.utils.config import Config

    cfg = Config()
    cfg.open_telemetry.enable_remote_collector = True
    cfg.open_telemetry.remote_endpoint = "127.0.0.1:9"
    cfg.open_telemetry.service_name = "edge-7"
    exp = from_config(cfg)
    assert exp.service_name == "edge-7"
    exp.close()
