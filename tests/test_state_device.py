"""Device-path STATE windows: condition-bounded windows fold on the fused
kernel (vectorized begin/emit masks, segment folds, emit+reset per close),
with parity against the host buffered path.
"""
import time

import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.planner.planner import device_path_eligible
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.utils.config import RuleOptionConfig

SQL = ("SELECT deviceId, count(*) AS c, avg(v) AS a FROM s "
       "GROUP BY deviceId, STATEWINDOW(st = 1, st = 0)")


def make_node():
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    node = FusedWindowAggNode(
        "st", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=128,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    return node, got


def batch(devs, vs, sts, ts=1000):
    n = len(devs)
    return ColumnBatch(
        n=n,
        columns={"deviceId": np.array(devs, dtype=np.object_),
                 "v": np.asarray(vs, dtype=np.float32),
                 "st": np.asarray(sts, dtype=np.int64)},
        timestamps=np.full(n, ts, dtype=np.int64), emitter="s")


def msgs_of(got):
    out = []
    for item in got:
        out.append(sorted(
            (m["deviceId"], m["c"], round(m["a"], 4))
            for m in (item if isinstance(item, list) else [item])))
    return out


class TestStateDevice:
    def test_eligibility(self):
        stmt = parse_select(SQL)
        assert device_path_eligible(stmt, RuleOptionConfig()) is not None
        # mesh + event time both device-eligible since round 5 (toggle scan
        # is host-side; span folds/finalize shard; watermark orders rows)
        opts = RuleOptionConfig(
            plan_optimize_strategy={"mesh": {"rows": 2, "keys": 4}})
        assert device_path_eligible(stmt, opts) is not None
        assert device_path_eligible(
            stmt, RuleOptionConfig(is_event_time=True)) is not None
        # WHERE still forces the host path (pre-window filter divergence)
        stmt2 = parse_select(SQL.replace(" GROUP BY", " WHERE v > 0 GROUP BY"))
        assert device_path_eligible(stmt2, RuleOptionConfig()) is None

    def test_open_close_within_one_batch(self):
        node, got = make_node()
        # rows: ignored, begin, data, data, close, ignored, begin, close
        node.process(batch(
            ["x", "a", "a", "b", "a", "x", "b", "b"],
            [9.0, 1.0, 2.0, 3.0, 4.0, 9.0, 10.0, 20.0],
            [5, 1, 5, 5, 0, 5, 1, 0]))
        assert msgs_of(got) == [
            [("a", 3, round(7.0 / 3, 4)), ("b", 1, 3.0)],  # rows 1..4
            [("b", 2, 15.0)],                              # rows 6..7
        ]

    def test_window_spans_batches(self):
        node, got = make_node()
        node.process(batch(["a", "a"], [1.0, 2.0], [1, 5]))  # opens, stays
        node.process(batch(["a", "b"], [3.0, 4.0], [5, 5]))  # still open
        assert got == []
        node.process(batch(["b"], [5.0], [0]))               # closes
        assert msgs_of(got) == [[("a", 3, 2.0), ("b", 2, 4.5)]]

    def test_rows_outside_window_excluded(self):
        node, got = make_node()
        node.process(batch(["a"], [100.0], [0]))  # emit cond while CLOSED
        node.process(batch(["a"], [200.0], [5]))  # plain row while closed
        assert got == []
        node.process(batch(["a", "a"], [1.0, 2.0], [1, 0]))
        assert msgs_of(got) == [[("a", 2, 1.5)]]

    def test_checkpoint_restores_open_flag(self):
        node, got = make_node()
        node.process(batch(["a"], [1.0], [1]))  # open
        snap = node.snapshot_state()
        assert snap["state_open"] is True
        node2, got2 = make_node()
        node2.restore_state(snap)
        node2.process(batch(["a"], [3.0], [0]))  # closes restored window
        assert msgs_of(got2) == [[("a", 2, 2.0)]]

    def test_parity_with_host_path(self, mock_clock):
        """End-to-end: device and host topologies on the same stream."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM sw (deviceId STRING, v FLOAT, st BIGINT) '
            'WITH (DATASOURCE="t/sw", TYPE="memory", FORMAT="JSON")')
        sql = ("SELECT deviceId, count(*) AS c, sum(v) AS sv FROM sw "
               "GROUP BY deviceId, STATEWINDOW(st = 1, st = 0)")
        td = plan_rule(RuleDef(id="std", sql=sql,
                               actions=[{"memory": {"topic": "sw/d"}}],
                               options={}), store)
        th = plan_rule(RuleDef(id="sth", sql=sql,
                               actions=[{"memory": {"topic": "sw/h"}}],
                               options={"use_device_kernel": False}), store)
        assert any("Fused" in type(n).__name__ for n in td.ops)
        sd, sh = td.sinks[0], th.sinks[0]
        td.open()
        th.open()
        try:
            rows = [
                {"deviceId": "a", "v": 1.0, "st": 1},
                {"deviceId": "b", "v": 2.0, "st": 5},
                {"deviceId": "a", "v": 3.0, "st": 0},
                {"deviceId": "a", "v": 9.0, "st": 5},  # outside any window
                {"deviceId": "b", "v": 4.0, "st": 1},
                {"deviceId": "b", "v": 5.0, "st": 0},
            ]
            for r in rows:
                mem.publish("t/sw", r)
            mock_clock.advance(20)
            deadline = time.time() + 8
            while time.time() < deadline and (
                    len(sd.results) < 2 or len(sh.results) < 2):
                time.sleep(0.02)
        finally:
            td.close()
            th.close()
            mem.reset()

        def norm(res):
            return [sorted((m["deviceId"], m["c"], m["sv"])
                           for m in (x if isinstance(x, list) else [x]))
                    for x in res]

        assert len(sd.results) == 2
        assert norm(sd.results) == norm(sh.results)
        assert norm(sd.results)[0] == [("a", 2, 4.0), ("b", 1, 2.0)]

    def test_begin_row_does_not_self_close(self):
        """A row satisfying BOTH conditions opens the window and stays open
        (host semantics: emit is not evaluated on the opening row)."""
        sql = ("SELECT deviceId, count(*) AS c, avg(v) AS a FROM s "
               "GROUP BY deviceId, STATEWINDOW(st >= 1, st >= 1)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "sc", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        node.process(batch(["a", "a", "a"], [1.0, 2.0, 3.0], [1, 1, 1]))
        # row0 opens (no self-close); row1 closes; row2 reopens, stays open
        assert msgs_of(got) == [[("a", 2, 1.5)]]
        assert node._state_open

    def test_where_clause_routes_to_host(self):
        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM s WHERE v > 0 "
            "GROUP BY deviceId, STATEWINDOW(st = 1, st = 0)")
        assert device_path_eligible(stmt, RuleOptionConfig()) is None
