"""Foundation tests: mock clock, KV store, cast, columnar batch."""
import numpy as np
import pytest

from ekuiper_tpu.data import cast
from ekuiper_tpu.data.batch import ColumnBatch, from_tuples
from ekuiper_tpu.data.rows import Tuple
from ekuiper_tpu.data.types import DataType, Field, Schema
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils import timex


class TestMockClock:
    def test_advance_and_now(self, mock_clock):
        assert timex.now_ms() == 0
        mock_clock.advance(1500)
        assert timex.now_ms() == 1500

    def test_timer_fires_on_advance(self, mock_clock):
        fired = []
        timer = mock_clock.after(1000, lambda ts: fired.append(ts))
        mock_clock.advance(999)
        assert not timer.fired and fired == []
        mock_clock.advance(1)
        assert timer.fired and fired == [1000]

    def test_ticker_reregister_within_one_advance(self, mock_clock):
        ticks = []

        def on_tick(ts):
            ticks.append(ts)
            if len(ticks) < 5:
                mock_clock.after(10, on_tick)

        mock_clock.after(10, on_tick)
        mock_clock.advance(100)
        assert ticks == [10, 20, 30, 40, 50]
        # clock must land exactly on the advance target, not double-count
        # time moved while firing timers
        assert timex.now_ms() == 100

    def test_timer_stop(self, mock_clock):
        timer = mock_clock.after(10)
        timer.stop()
        mock_clock.advance(20)
        assert not timer.fired

    def test_cannot_go_backwards(self, mock_clock):
        mock_clock.set(100)
        with pytest.raises(ValueError):
            mock_clock.set(50)

    def test_window_alignment(self):
        assert timex.align_to_window(0, 10_000) == 0
        assert timex.align_to_window(1, 10_000) == 10_000
        assert timex.align_to_window(10_000, 10_000) == 10_000
        assert timex.align_to_window(19_999, 10_000) == 20_000


class TestKV:
    def test_memory_roundtrip(self):
        store = kv.get_store()
        table = store.kv("stream")
        table.set("demo", {"sql": "CREATE STREAM demo () WITH ()"})
        assert table.get("demo")["sql"].startswith("CREATE")
        assert table.keys() == ["demo"]
        assert table.delete("demo")
        assert table.get("demo") is None
        assert not table.delete("demo")

    def test_setnx(self):
        table = kv.get_store().kv("rule")
        assert table.setnx("r1", {"id": "r1"})
        assert not table.setnx("r1", {"id": "other"})
        assert table.get("r1")["id"] == "r1"

    def test_sqlite_roundtrip(self, tmp_path):
        store = kv.Store("sqlite", str(tmp_path))
        table = store.kv("stream")
        table.set("a", [1, 2, 3])
        assert table.get("a") == [1, 2, 3]
        assert table.setnx("b", "x") and not table.setnx("b", "y")
        assert sorted(table.keys()) == ["a", "b"]
        store.close()


class TestCast:
    def test_numeric(self):
        assert cast.to_int("42") == 42
        assert cast.to_int(3.0) == 3
        assert cast.to_float("3.5") == 3.5
        assert cast.to_bool("true") is True
        with pytest.raises(cast.CastError):
            cast.to_int("abc")
        with pytest.raises(cast.CastError):
            cast.to_int(3.5, strict=cast.STRICT)

    def test_datetime(self):
        assert cast.to_datetime_ms(1700000000000) == 1700000000000
        assert cast.to_datetime_ms("1970-01-01T00:00:01Z") == 1000

    def test_typed_struct_array(self):
        f = Field("xs", DataType.ARRAY, elem_type=DataType.BIGINT)
        assert cast.to_typed(["1", 2, 3.0], f) == [1, 2, 3]

    def test_compare(self):
        assert cast.compare(1, 2.5) == -1
        assert cast.compare("a", "a") == 0
        assert cast.compare(None, 1) is None
        assert cast.compare([1, 2], [1, 3]) == -1


class TestColumnBatch:
    def _tuples(self):
        return [
            Tuple(emitter="demo", message={"device": "d1", "temp": 20.0, "n": 1}, timestamp=100),
            Tuple(emitter="demo", message={"device": "d2", "temp": 21.5, "n": 2}, timestamp=200),
            Tuple(emitter="demo", message={"device": "d1", "temp": 23.0}, timestamp=300),
        ]

    def test_from_tuples_schemaless(self):
        b = from_tuples(self._tuples(), emitter="demo")
        assert b.n == 3
        assert b.columns["temp"].dtype == np.float32
        assert b.columns["n"].dtype == np.int64
        assert b.columns["device"].dtype == np.object_
        assert not b.is_valid("n")[2]  # missing n in 3rd row
        assert b.is_valid("temp").all()

    def test_from_tuples_with_schema(self):
        schema = Schema([
            Field("device", DataType.STRING),
            Field("temp", DataType.FLOAT),
            Field("n", DataType.BIGINT),
        ])
        b = from_tuples(self._tuples(), schema=schema)
        assert b.columns["temp"].dtype == np.float32
        assert list(b.timestamps) == [100, 200, 300]

    def test_roundtrip(self):
        b = from_tuples(self._tuples())
        rows = b.to_tuples()
        assert rows[0].message == {"device": "d1", "temp": 20.0, "n": 1}
        assert "n" not in rows[2].message
        assert rows[2].timestamp == 300

    def test_select_and_concat(self):
        b = from_tuples(self._tuples())
        hot = b.select(b.columns["temp"] > 21.0)
        assert hot.n == 2
        both = ColumnBatch.concat([b, hot])
        assert both.n == 5
        assert both.columns["temp"].dtype == np.float32

    def test_concat_missing_column(self):
        b1 = from_tuples([Tuple(message={"a": 1})])
        b2 = from_tuples([Tuple(message={"b": 2.0})])
        b = ColumnBatch.concat([b1, b2])
        assert b.n == 2
        assert not b.is_valid("a")[1]
        assert not b.is_valid("b")[0]


class TestClusterConfig:
    def test_cluster_section_parses(self, tmp_path):
        import json as _json

        from ekuiper_tpu.utils.config import load_config

        p = tmp_path / "cfg.json"
        p.write_text(_json.dumps({"cluster": {
            "enabled": True, "coordinator_address": "h0:8476",
            "num_processes": 4, "process_id": 2}}))
        cfg = load_config(str(p))
        assert cfg.cluster.enabled
        assert cfg.cluster.coordinator_address == "h0:8476"
        assert cfg.cluster.num_processes == 4 and cfg.cluster.process_id == 2
        assert not load_config(None).cluster.enabled
