"""Extension function plugins (geohash / image / model inference) —
goldens cross-checked against mmcloughlin/geohash (the reference's
library) and pillow round-trips; reference:
extensions/functions/{geohash,image,onnx}."""
import base64
import io
import os

import numpy as np
import pytest

from ekuiper_tpu.functions import registry as freg


def call(name, *args):
    fd = freg.lookup(name)
    assert fd is not None, f"{name} not registered"
    return fd.exec(list(args), {})


class TestGeohash:
    def test_encode_known_values(self):
        # canonical golden (Wikipedia geohash article):
        # (57.64911, 10.40744) -> u4pruydqqvj
        assert call("geohashEncode", 57.64911, 10.40744, 11) == "u4pruydqqvj"
        assert call("geohashEncode", 48.858, 2.294, 6) == "u09tun"
        assert call("geohashEncode", 0.0, 0.0, 1) == "s"
        assert call("geohashEncode", -90.0, -180.0, 4) == "0000"

    def test_decode_roundtrip(self):
        h = call("geohashEncode", 48.858, 2.294)
        pos = call("geohashDecode", h)
        assert abs(pos["Latitude"] - 48.858) < 1e-5
        assert abs(pos["Longitude"] - 2.294) < 1e-5

    def test_int_roundtrip(self):
        code = call("geohashEncodeInt", 48.858, 2.294)
        assert isinstance(code, int) and code > 0
        pos = call("geohashDecodeInt", code)
        # 64-bit hash = 32 bits/axis: lon resolution 360/2^32 ≈ 8.4e-8
        assert abs(pos["Latitude"] - 48.858) < 1e-6
        assert abs(pos["Longitude"] - 2.294) < 1e-6

    def test_bounding_box_contains_point(self):
        b = call("geohashBoundingBox", "u09tun")
        assert b["MinLat"] < 48.858 < b["MaxLat"]
        assert b["MinLng"] < 2.294 < b["MaxLng"]

    def test_neighbors(self):
        # neighbors tile the plane: each neighbor's box touches the center
        h = "u09tun"
        ns = call("geohashNeighbors", h)
        assert len(ns) == 8 and len(set(ns)) == 8 and h not in ns
        east = call("geohashNeighbor", h, "East")
        assert east in ns
        b0, b1 = call("geohashBoundingBox", h), call("geohashBoundingBox", east)
        assert abs(b1["MinLng"] - b0["MaxLng"]) < 1e-9
        assert abs(b1["MinLat"] - b0["MinLat"]) < 1e-9

    def test_neighbors_int(self):
        code = call("geohashEncodeInt", 10.0, 10.0)
        ns = call("geohashNeighborsInt", code)
        assert len(ns) == 8 and all(isinstance(n, int) for n in ns)

    def test_invalid_inputs(self):
        with pytest.raises(Exception):
            call("geohashDecode", "invalid!!")
        with pytest.raises(Exception):
            call("geohashNeighbor", "u09", "Up")


class TestImage:
    def _png(self, w=32, h=16):
        from PIL import Image

        img = Image.new("RGB", (w, h), (200, 10, 30))
        out = io.BytesIO()
        img.save(out, format="PNG")
        return out.getvalue()

    def test_resize_exact(self):
        from PIL import Image

        out = call("resize", self._png(), 8, 4)
        img = Image.open(io.BytesIO(out))
        assert img.size == (8, 4) and img.format == "PNG"

    def test_resize_base64_input(self):
        from PIL import Image

        out = call("resize", base64.b64encode(self._png()).decode(), 8, 4)
        assert Image.open(io.BytesIO(out)).size == (8, 4)

    def test_resize_raw_mode(self):
        out = call("resize", self._png(), 8, 4, True)
        assert isinstance(out, bytes) and len(out) == 8 * 4 * 3
        arr = np.frombuffer(out, dtype=np.uint8).reshape(4, 8, 3)
        assert arr[0, 0, 0] > 150  # red-dominant fill preserved

    def test_thumbnail_keeps_aspect(self):
        from PIL import Image

        out = call("thumbnail", self._png(32, 16), 8, 8)
        img = Image.open(io.BytesIO(out))
        assert img.size == (8, 4)  # aspect preserved, bounded by 8


class TestModelInfer:
    def test_torchscript_roundtrip(self, tmp_path, monkeypatch):
        torch = pytest.importorskip("torch")

        class Doubler(torch.nn.Module):
            def forward(self, x):
                return x * 2.0

        mdir = tmp_path / "models"
        mdir.mkdir()
        torch.jit.script(Doubler()).save(str(mdir / "doubler.pt"))
        from ekuiper_tpu.utils import config as cfgmod

        cfg = cfgmod.get_config()
        monkeypatch.setattr(cfg, "data_dir", str(tmp_path))
        import ekuiper_tpu.functions.funcs_ext as fx

        fx._MODELS.clear()
        out = call("model_infer", "doubler", [1.0, 2.5, 3.0])
        assert out == [2.0, 5.0, 6.0]
        # cached on second call
        assert "doubler" in fx._MODELS
        out2 = call("model_infer", "doubler", 4.0)
        assert out2 == [8.0]


class TestGeohashPoles:
    def test_pole_row_wraps_not_self(self):
        h = call("geohashEncode", 89.9999, 0.0, 6)  # top lat row
        north = call("geohashNeighbor", h, "North")
        assert north != h
        ns = call("geohashNeighbors", h)
        assert len(set(ns)) == 8 and h not in ns

    def test_model_name_traversal_rejected(self):
        with pytest.raises(Exception, match="invalid model name"):
            call("model_infer", "../../../etc/passwd", 1.0)
        with pytest.raises(Exception, match="invalid model name"):
            call("model_infer", "/abs/path.pt", 1.0)
