"""Chaos harness (tools/chaos.py) — a deterministic mini-storm: the
same shapes the churn_soak bench phase runs for a minute, compressed
into manual health/control ticks under the mock clock."""
import time

import pytest

from ekuiper_tpu.store import kv
from tools.chaos import DROP_TAXONOMY, ChaosHarness


@pytest.fixture
def api():
    from ekuiper_tpu.server.rest import RestApi

    api = RestApi(kv.get_store())
    # deterministic: manual ticks only
    api.health_evaluator.stop()
    api.qos_controller.stop()
    yield api
    api.rules.stop_all()


def _wait_running(api, rid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rs = api.rules.state(rid)
        if rs is not None and rs.topo is not None:
            return rs
        time.sleep(0.02)
    raise AssertionError(f"{rid} never opened a topo")


class TestChaosHarness:
    def test_storm_end_to_end(self, api, mock_clock):
        h = ChaosHarness(api)
        h.ensure_stream()
        work = h.workload_rules(2, window_s=10)
        victim = h.victim_rule()
        for rid in work + [victim]:
            _wait_running(api, rid)
        # a few churn steps: create/update/delete all through REST
        for _ in range(12):
            h.churn_step(target_live=4)
        assert h.counters["created"] >= 4
        assert h.counters["create_failed"] == 0
        # skewed publishing reaches the rules (shared source fan-out);
        # the victim's 2-deep buffers overflow with taxonomy reasons
        for i in range(30):
            h.publish_skew(200, hot_key=i % 3, n_keys=16)
        for rid in work:
            rs = api.rules.state(rid)
            rs.topo.wait_idle(5.0)
        drops = h.drops_by_reason()
        assert h.unexplained_drops() == {}
        for agg in drops.values():
            assert set(agg) <= DROP_TAXONOMY
        # victim breaches via drop burn -> the controller sheds IT, by
        # qos class, while the critical workload rules stay untouched.
        # The overflow is driven deterministically (mock-clock ticks see
        # the exact same deltas the live storm produces statistically).
        victim_entry = api.rules.state(victim).topo.entry_nodes()[0]
        for _ in range(4):
            victim_entry.stats.inc_dropped("buffer_full", n=500)
            api.health_evaluator.tick()
            api.qos_controller.tick()
        verdict = api.health_evaluator.verdicts().get(victim)
        assert verdict is not None
        assert verdict["state"] == "breaching"
        ctl = api.qos_controller
        assert ctl.shed_state()[victim]["level"] >= 1
        assert ctl.shed_state()[victim]["qos"] == "low"
        # the installed gate now counts shed rows under the taxonomy
        for _ in range(50):
            victim_entry.put({"x": 1})
        assert victim_entry.stats.dropped.get("shed_qos", 0) > 0
        ctl.tick()
        assert ctl.shed_totals().get((victim, "low"), 0) > 0
        for rid in work:
            assert ctl.shed_state()[rid]["qos"] == "critical"
            assert ctl.shed_state()[rid]["level"] == 0
        summary = h.summary()
        assert summary["admission"]["accept"] >= 5
        assert "unexplained_drops" in summary

    def test_kill_restore_brings_rules_back(self, api, mock_clock):
        h = ChaosHarness(api, stream="chaosk", topic="chaosk/t")
        h.ensure_stream()
        work = h.workload_rules(2, window_s=10)
        for rid in work:
            _wait_running(api, rid)
        running = h.hard_kill()
        assert set(running) >= set(work)
        for rid in work:
            assert api.rules.state(rid).topo is None
        rec = h.recover(running)
        assert rec["recovered"] == rec["expected"]
        assert rec["missing"] == []
        for rid in work:
            assert api.rules.state(rid).topo is not None

    def test_structured_rejection_surfaces(self, api, monkeypatch):
        h = ChaosHarness(api, stream="chaosr", topic="chaosr/t")
        h.ensure_stream()
        monkeypatch.setenv("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S", "1")
        rid = h._create({
            "id": "fatty",
            "sql": ("SELECT deviceId, avg(v) AS a FROM chaosr "
                    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)"),
            "actions": [{"nop": {}}]})
        assert rid is None  # structured 429, counted, not raised
        assert h.counters["create_rejected"] == 1
