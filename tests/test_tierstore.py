"""Tiered key state (ops/tierstore.py, docs/TIERED_STATE.md): layout
planning, demote/promote exactness, slot recycling, pane-epoch
staleness, spilled-window emission, the promote-before-harvest race,
telemetry, and checkpoints."""
import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.ops.tierstore import (HostTierStore, TierLayout,
                                       TierManager, TierStore,
                                       plan_tier_layout,
                                       state_bytes_per_key)
from ekuiper_tpu.runtime.events import Trigger
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select

SQL = ("SELECT deviceId, sum(v) AS s, count(*) AS c, min(v) AS mn "
       "FROM demo GROUP BY deviceId, HOPPINGWINDOW(ss, 4, 2)")


def _plan(sql=SQL):
    p = extract_kernel_plan(parse_select(sql))
    assert p is not None
    return p


def _batch(ids, vals):
    ids = np.array(ids, dtype=np.object_)
    return ColumnBatch(
        n=len(ids),
        columns={"deviceId": ids, "v": np.asarray(vals, np.float64)},
        timestamps=np.zeros(len(ids), np.int64), emitter="demo")


def _mknode(tier_mb, capacity=64, sql=SQL):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "t", stmt.window, plan, [d.expr for d in stmt.dimensions],
        capacity=capacity, micro_batch=128, prefinalize_lead_ms=0,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=False, tier_budget_mb=tier_mb)
    node.state = node.gb.init_state()
    out = []
    node.emit = lambda item, count=None, _o=out: _o.append(item)
    return node, out


def _flat(msgs):
    rows = {}
    for m in msgs:
        for r in (m if isinstance(m, list) else [m]):
            k = tuple(sorted(r.items()))
            rows[k] = rows.get(k, 0) + 1
    return rows


class TestLayout:
    def test_roomy_budget_disables(self):
        # budget covering 4x the requested capacity: tiering is a no-op
        assert plan_tier_layout(_plan(), 2, 1024, 1e6) is None

    def test_tight_budget_engages_and_clamps(self):
        plan = _plan()
        # n rides 3 specs (sum/count/min), s1 one, mn one, act — x2
        # panes, f32, + the uint32 touch slot
        per_key = state_bytes_per_key(plan, 2)
        assert per_key == (2 * (3 + 1 + 1 + 1)) * 4 + 4
        layout = plan_tier_layout(plan, 2, 1 << 20, 1.0)
        assert layout is not None
        assert 1024 <= layout.hot_slots < (1 << 20)

    def test_forced_off(self):
        assert plan_tier_layout(_plan(), 1, 1024, 0) is None


class TestTouchColumn:
    def test_fold_bumps_and_reset_preserves(self):
        plan = _plan()
        gb = DeviceGroupBy(plan, capacity=16, n_panes=2, micro_batch=8,
                           track_touch=True)
        st = gb.init_state()
        st = gb.fold(st, {"v": np.ones(4)},
                     np.array([0, 1, 0, 2], np.int32), pane_idx=0)
        touch = np.asarray(st["touch"])
        assert touch[:3].tolist() == [2, 1, 1]
        st = gb.reset_pane(st, 0)
        assert np.asarray(st["touch"])[:3].tolist() == [2, 1, 1]
        st = gb.grow(st, 32)
        t2 = np.asarray(st["touch"])
        assert t2.shape == (32,) and t2[:3].tolist() == [2, 1, 1]
        assert t2.dtype == np.uint32

    def test_untracked_state_has_no_touch(self):
        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2, micro_batch=8)
        assert "touch" not in gb.init_state()


class TestDemotePromote:
    def test_roundtrip_bit_exact(self):
        plan = _plan()
        gb = DeviceGroupBy(plan, capacity=32, n_panes=2, micro_batch=16,
                           track_touch=True)
        ref = DeviceGroupBy(plan, capacity=32, n_panes=2, micro_batch=16)
        ts = TierStore(gb, TierLayout(8, 4, 100, 1))
        st, rst = gb.init_state(), ref.init_state()
        cols = {"v": np.array([1., 2., 3., 4., 5., 6.])}
        slots = np.array([0, 1, 2, 0, 1, 2], np.int32)
        st = gb.fold(st, dict(cols), slots, pane_idx=0)
        rst = ref.fold(rst, dict(cols), slots, pane_idx=0)
        st, packed = ts.demote(st, np.array([1, 2], np.int32))
        packed_h = np.asarray(packed)
        # demoted slots read as identity now
        outs_mid, act_mid = gb.finalize(st, 3)
        assert act_mid[1] == 0 and act_mid[2] == 0
        st = ts.promote(st, packed_h[:2], np.array([1, 2], np.int32))
        outs, act = gb.finalize(st, 3)
        routs, ract = ref.finalize(rst, 3)
        for a, b in zip(outs, routs):
            np.testing.assert_array_equal(np.nan_to_num(a),
                                          np.nan_to_num(b))
        np.testing.assert_array_equal(act, ract)

    def test_idle_row_detection_and_stale_mask(self):
        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2, micro_batch=8,
                           track_touch=True)
        ts = TierStore(gb, TierLayout(8, 4, 100, 1))
        row = ts.init_row()
        assert ts.row_is_idle(row)
        st = gb.fold(gb.init_state(), {"v": np.ones(1)},
                     np.zeros(1, np.int32), pane_idx=1)
        st, packed = ts.demote(st, np.zeros(1, np.int32))
        live = np.asarray(packed)[0].copy()
        assert not ts.row_is_idle(live)
        # masking the one live pane (1) returns it to identity
        ts.mask_stale_panes(live, np.array([False, True]))
        assert ts.row_is_idle(live)


class TestKeyTable:
    def test_retire_recycle_and_log(self):
        kt = KeyTable(16)
        kt.track_new = True
        slots, _ = kt.encode_column(np.array(["a", "b", "c"], np.object_))
        assert kt.drain_new_keys() == [("a", 0), ("b", 1), ("c", 2)]
        kt.retire([1], ["b"])
        assert kt.free_slots() == [1]
        assert kt.decode(1) is None
        s2, grew = kt.encode_column(np.array(["d"], np.object_))
        assert s2[0] == 1 and not grew  # recycled, no growth
        assert kt.drain_new_keys() == [("d", 1)]
        # stale retire (slot re-assigned) is a no-op
        kt.retire([1], ["b"])
        assert kt.decode(1) == "d" and kt.free_slots() == []

    def test_restore_with_holes(self):
        kt = KeyTable(16)
        kt.restore(["a", None, "c"])
        assert kt.decode(0) == "a" and kt.decode(1) is None
        assert kt.free_slots() == [1]
        s, _ = kt.encode_column(np.array(["x"], np.object_))
        assert s[0] == 1

    def test_roundtrip_through_decode_all(self):
        kt = KeyTable(16)
        kt.encode_column(np.array(["a", "b", "c"], np.object_))
        kt.retire([0], ["a"])
        kt2 = KeyTable(16)
        kt2.restore(kt.decode_all())
        assert kt2.decode_all() == [None, "b", "c"]
        assert kt2.free_slots() == [0]


class TestHostTierStore:
    def test_put_take_grow_bytes(self):
        hs = HostTierStore(8, 2, initial_rows=16)
        base = hs.nbytes()
        assert base == hs._rows.nbytes + hs._epochs.nbytes
        for i in range(40):  # force two grows
            hs.put(f"k{i}", np.full(8, i, np.float32),
                   np.zeros(2, np.int64))
        assert len(hs) == 40 and hs.nbytes() > base
        row, ep = hs.take("k7")
        assert row[0] == 7.0 and "k7" not in hs
        assert hs.take("k7") is None

    def test_memwatch_estimate_is_allocation(self):
        from ekuiper_tpu.observability import memwatch

        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2,
                           micro_batch=8, track_touch=True)
        mgr = TierManager(gb, KeyTable(16), TierLayout(8, 4, 100, 1),
                          rule_id="tr")
        rows = {r["component"]: r["bytes"]
                for r in memwatch.registry().snapshot()
                if r["rule"] == "tr"}
        assert rows.get("tier_host_store") == mgr.store.nbytes()
        assert rows["tier_host_store"] == \
            mgr.store._rows.nbytes + mgr.store._epochs.nbytes


class TestFusedIntegration:
    def test_demote_spill_emit_promote_parity(self):
        tiered, out_t = _mknode(0.001)
        plain, out_p = _mknode(0.0)
        assert tiered.tier is not None and plain.tier is None
        rng = np.random.default_rng(3)

        def feed(ids):
            vals = np.rint(rng.normal(50, 10, len(ids)))
            tiered.process(_batch(list(ids), vals))
            plain.process(_batch(list(ids), vals))

        def boundary(ts):
            tiered.on_trigger(Trigger(ts=ts))
            plain.on_trigger(Trigger(ts=ts))

        feed([f"c{i}" for i in range(10)] + ["h"])
        boundary(2000)
        tiered.tier._plan = list(range(10))
        tiered._tier_boundary()
        assert tiered.tier.demoted_total == 10
        assert len(tiered.kt.free_slots()) == 10
        # half reappear mid-window (promotion), fresh keys recycle slots
        feed([f"c{i}" for i in range(0, 10, 2)]
             + [f"n{i}" for i in range(4)] + ["h"])
        boundary(4000)
        boundary(6000)
        tiered._drain_async_emits()
        plain._drain_async_emits()
        assert _flat(out_t) == _flat(out_p)
        assert tiered.tier.promoted_total == 5
        assert tiered.gb.capacity == plain.gb.capacity  # no grow

    def test_promote_before_harvest_race(self):
        tiered, out_t = _mknode(0.001)
        plain, out_p = _mknode(0.0)
        held = []
        tiered.tier._submit = held.append  # hold the worker back
        rng = np.random.default_rng(4)
        vals = np.rint(rng.normal(50, 5, 6))
        for n in (tiered, plain):
            n.process(_batch([f"k{i}" for i in range(6)], vals))
            n.on_trigger(Trigger(ts=2000))
        tiered.tier._plan = list(range(6))
        tiered._tier_boundary()
        assert held  # harvest NOT run yet
        assert len(tiered.tier._inflight) == 6
        vals2 = np.rint(rng.normal(50, 5, 3))
        for n in (tiered, plain):
            n.process(_batch(["k0", "k1", "k2"], vals2))
            n.on_trigger(Trigger(ts=4000))
        # returning keys promoted straight off the pending device block
        assert tiered.tier.promoted_total == 3
        for payload in held:  # late harvest skips the consumed keys
            tiered.tier.worker_task(payload)
        assert len(tiered.tier._inflight) == 0
        for n in (tiered, plain):
            n.on_trigger(Trigger(ts=6000))
        tiered._drain_async_emits()
        plain._drain_async_emits()
        assert _flat(out_t) == _flat(out_p)

    def test_pane_epoch_masks_closed_windows(self):
        tiered, out_t = _mknode(0.001)
        tiered.process(_batch(["a", "b"], [1.0, 2.0]))
        tiered.on_trigger(Trigger(ts=2000))
        tiered.tier._plan = [0, 1]
        tiered._tier_boundary()
        # run past the full hopping span: both panes reset since demotion
        tiered.on_trigger(Trigger(ts=4000))
        tiered.on_trigger(Trigger(ts=6000))
        out_t.clear()
        # reappearance after expiry: stale rows must NOT merge
        tiered.process(_batch(["a"], [5.0]))
        tiered.on_trigger(Trigger(ts=8000))
        tiered._drain_async_emits()
        rows = _flat(out_t)
        key = next(k for k in rows if ("deviceId", "a") in k)
        assert dict(key)["s"] == 5.0 and dict(key)["c"] == 1

    def test_shared_slot_reuse_disabled(self):
        tiered, _ = _mknode(0.001)
        assert tiered._shared_slots_ok is False


class TestQuiescentMode:
    def test_live_spill_requeues(self):
        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2,
                           micro_batch=8, track_touch=True)
        kt = KeyTable(16)
        mgr = TierManager(gb, kt, TierLayout(4, 4, 100, 1),
                          quiescent_only=True)
        st = gb.init_state()
        kt.encode_column(np.array(["a", "b"], np.object_))
        kt.drain_new_keys()
        st = gb.fold(st, {"v": np.ones(2)},
                     np.array([0, 1], np.int32), pane_idx=0)
        mgr._plan = [0]  # "a" has LIVE data — quiescent mode must not lose it
        st = mgr.on_boundary(st)
        assert mgr.demoted_total == 1
        assert mgr._requeue  # harvested live row queued for re-promotion
        st = mgr.admit(st)
        assert mgr.promoted_total == 1
        assert "a" in kt._ids  # re-seated with a fresh slot
        outs, act = gb.finalize(st, kt.n_keys)
        alive = {kt.decode(i) for i in np.nonzero(act > 0)[0].tolist()}
        assert alive == {"a", "b"}


class TestTelemetry:
    def test_render_families(self):
        from ekuiper_tpu.ops import tierstore

        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2,
                           micro_batch=8, track_touch=True)
        mgr = TierManager(gb, KeyTable(16), TierLayout(8, 4, 100, 1),
                          rule_id="tr")
        mgr.demoted_total = 7
        out = []
        tierstore.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        assert 'kuiper_spill_demoted_total{rule="tr"} 7' in text
        for fam in ("kuiper_spill_promoted_total",
                    "kuiper_spill_resident_total",
                    "kuiper_tier_host_bytes"):
            assert f"# TYPE {fam}" in text
        diag = tierstore.diagnostics()
        assert diag and diag[0]["rule"] == "tr"
        assert diag[0]["demoted_total"] == 7

    def test_admission_prices_hot_set(self):
        import os

        from ekuiper_tpu.planner.planner import RuleDef
        from ekuiper_tpu.runtime.control import price_rule
        from ekuiper_tpu.store import kv

        store = kv
        rule = RuleDef.from_dict({
            "id": "tier_price", "sql": SQL,
            "actions": [{"log": {}}],
            "options": {"key_slots": 1 << 20},
        })
        old = os.environ.get("KUIPER_HBM_BUDGET_MB")
        try:
            os.environ["KUIPER_HBM_BUDGET_MB"] = "4"
            tiered_price = price_rule(rule, store)
            os.environ.pop("KUIPER_HBM_BUDGET_MB")
            untiered_price = price_rule(rule, store)
        finally:
            if old is not None:
                os.environ["KUIPER_HBM_BUDGET_MB"] = old
            else:
                os.environ.pop("KUIPER_HBM_BUDGET_MB", None)
        assert tiered_price.get("tier", {}).get("hot_slots")
        assert tiered_price["hbm_projected_bytes"] < \
            untiered_price["hbm_projected_bytes"]

    def test_estimate_includes_tier_sites(self):
        from ekuiper_tpu.observability import jitcert

        plan = _plan()
        base = jitcert.estimate_plan_signatures(plan, 2, 128, 64)
        tiered = jitcert.estimate_plan_signatures(plan, 2, 128, 64,
                                                  tier_demote_batch=512)
        assert tiered > base


class TestCheckpoint:
    def test_manager_snapshot_roundtrip(self):
        gb = DeviceGroupBy(_plan(), capacity=16, n_panes=2,
                           micro_batch=8, track_touch=True)
        mgr = TierManager(gb, KeyTable(16), TierLayout(8, 4, 100, 1))
        row = mgr.ts.init_row()
        row[-1] = 3.0  # act pane 1
        mgr.store.put("k", row, np.array([0, 5], np.int64))
        mgr.note_pane_reset(0)
        snap = mgr.snapshot()
        gb2 = DeviceGroupBy(_plan(), capacity=16, n_panes=2,
                            micro_batch=8, track_touch=True)
        mgr2 = TierManager(gb2, KeyTable(16), TierLayout(8, 4, 100, 1))
        mgr2.restore(snap)
        assert "k" in mgr2.store
        r2, e2 = mgr2.store.peek("k")
        np.testing.assert_array_equal(r2, row)
        assert e2.tolist() == [0, 5]
        assert mgr2._pane_epoch.tolist() == [1, 0]

    def test_fused_cross_tier_restore(self):
        tiered, out_t = _mknode(0.001)
        tiered.process(_batch(["a", "b", "c"], [1.0, 2.0, 3.0]))
        tiered.on_trigger(Trigger(ts=2000))
        tiered.tier._plan = [0, 1]
        tiered._tier_boundary()
        snap = tiered.snapshot_state()
        assert snap["tier"]["keys"]  # cold tier serialized
        assert None in snap["keys"]  # hot-tier holes serialized
        restored, out_r = _mknode(0.001)
        restored.restore_state(snap)
        assert len(restored.tier.store) == len(tiered.tier.store)
        assert restored.kt.free_slots() == tiered.kt.free_slots()
        # demoted-at-kill key comes back queryable in both runs
        for n in (tiered, restored):
            n.process(_batch(["a"], [10.0]))
            n.on_trigger(Trigger(ts=4000))
            n._drain_async_emits()
        assert _flat(out_t[-2:]) == _flat(out_r[-2:]) or \
            _flat(out_t) != {} and _flat(out_r) != {}
        # exact: window 2 covers a's promoted pane-0 partial + new row
        def val(out):
            for m in reversed(out):
                for r in (m if isinstance(m, list) else [m]):
                    if r.get("deviceId") == "a":
                        return (r["s"], r["c"])
            return None
        assert val(out_t) == val(out_r) == (11.0, 2)


class TestEventTime:
    def test_event_time_tiered_parity(self):
        """Event-time tumbling with tiering: bucket-pane epochs gate
        spilled validity; demote mid-stream + reappearance stays exact
        vs the untiered node (watermark-driven emission)."""
        from ekuiper_tpu.runtime.events import Watermark

        sql = ("SELECT deviceId, sum(v) AS s, count(*) AS c FROM demo "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)

        def mk(tier_mb):
            node = FusedWindowAggNode(
                "evt", stmt.window, plan,
                [d.expr for d in stmt.dimensions],
                capacity=64, micro_batch=128, prefinalize_lead_ms=0,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                emit_columnar=False, is_event_time=True,
                tier_budget_mb=tier_mb)
            node.state = node.gb.init_state()
            out = []
            node.emit = lambda item, count=None, _o=out: _o.append(item)
            return node, out

        tiered, out_t = mk(0.001)
        plain, out_p = mk(0.0)
        assert tiered.tier is not None

        def ebatch(ids, vals, tss):
            ids = np.array(ids, dtype=np.object_)
            return ColumnBatch(
                n=len(ids),
                columns={"deviceId": ids,
                         "v": np.asarray(vals, np.float64)},
                timestamps=np.asarray(tss, np.int64), emitter="demo")

        for n in (tiered, plain):
            n.process(ebatch(["a", "b", "c"], [1., 2., 3.],
                             [100, 150, 200]))
            n.on_watermark(Watermark(ts=1100))  # bucket 0 emits
        tiered.tier._plan = [0, 1]  # demote a, b (quiescent post-emit)
        tiered._tier_boundary()
        for n in (tiered, plain):
            n.process(ebatch(["a", "d"], [10., 20.], [1300, 1400]))
            n.on_watermark(Watermark(ts=2500))
        for n in (tiered, plain):
            n._drain_async_emits()
        assert _flat(out_t) == _flat(out_p)
        assert tiered.tier.demoted_total == 2
