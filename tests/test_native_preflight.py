"""Tier-1 native-decoder preflight (tools/check_native.py): the GCC-10
class of regression — extension silently failing to build and every
"native" path running the Python fallback — must FAIL tests, not skip
them, wherever a toolchain exists to build with."""
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _toolchain_present() -> bool:
    import sysconfig

    if shutil.which("make") is None:
        return False
    if shutil.which("g++") is None and shutil.which("c++") is None:
        return False
    inc = sysconfig.get_paths().get("include")
    return bool(inc and (Path(inc) / "Python.h").exists())


def test_native_preflight_passes():
    if not _toolchain_present():
        pytest.skip("no native toolchain: cannot build ekjsoncol here")
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_native.py")],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, (
        "native decoder preflight FAILED — the native path is silently "
        f"falling back to Python:\n{proc.stderr}\n{proc.stdout}")
