"""Schema registry + protobuf converter tests — modeled on the reference's
internal/schema/registry_test.go and converter/protobuf tests."""
import time

import pytest

from ekuiper_tpu.io.converters import get_converter
from ekuiper_tpu.schema.registry import SchemaRegistry
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.infra import EngineError

PROTO = """
syntax = "proto3";
package test;
message Sensor {
  string device = 1;
  double temperature = 2;
  int64 ts = 3;
}
"""


@pytest.fixture
def reg(tmp_path):
    r = SchemaRegistry(kv.get_store(), etc_dir=str(tmp_path / "schemas"))
    SchemaRegistry.set_global(r)
    yield r
    for name in list(r.list()):
        r.delete(name)


def test_schema_crud(reg):
    reg.create({"name": "sensor", "type": "protobuf", "content": PROTO})
    assert reg.list() == ["sensor"]
    rec = reg.get("sensor")
    assert "message Sensor" in rec["content"]
    reg.delete("sensor")
    assert reg.list() == []


def test_schema_rejects_bad_proto(reg):
    with pytest.raises(EngineError, match="protoc failed"):
        reg.create({"name": "bad", "type": "protobuf",
                    "content": "this is not proto"})
    assert reg.list() == []


def test_protobuf_roundtrip(reg):
    reg.create({"name": "sensor", "type": "protobuf", "content": PROTO})
    conv = get_converter("protobuf", schema_id="sensor.Sensor")
    raw = conv.encode({"device": "d1", "temperature": 21.5, "ts": 1000})
    assert isinstance(raw, bytes) and len(raw) > 0
    back = conv.decode(raw)
    assert back["device"] == "d1"
    assert back["temperature"] == 21.5
    assert int(back["ts"]) == 1000


def test_protobuf_message_name_qualified(reg):
    reg.create({"name": "sensor", "type": "protobuf", "content": PROTO})
    # package-qualified lookup also works
    conv = get_converter("protobuf", schema_id="sensor.test.Sensor")
    raw = conv.encode({"device": "x", "temperature": 1.0, "ts": 1})
    assert conv.decode(raw)["device"] == "x"


def test_protobuf_stream_e2e(reg):
    """CREATE STREAM ... FORMAT=protobuf SCHEMAID=... end-to-end through a
    rule: bytes in -> decoded -> filtered -> sink."""
    from ekuiper_tpu.io.memory import publish, subscribe
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.server.rule_manager import RuleRegistry
    from ekuiper_tpu.utils import timex

    reg.create({"name": "sensor", "type": "protobuf", "content": PROTO})
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM pb (device string, temperature float) WITH '
        '(TYPE="memory", DATASOURCE="pbt", FORMAT="protobuf", '
        'SCHEMAID="sensor.Sensor")')
    got = []
    unsub = subscribe("pbout", lambda t, d: got.append(d))
    timex.use_real_clock()
    rr = RuleRegistry(store)
    rr.create({"id": "rpb",
               "sql": "SELECT device, temperature FROM pb WHERE temperature > 20",
               "actions": [{"memory": {"topic": "pbout"}}]})
    time.sleep(0.3)
    conv = get_converter("protobuf", schema_id="sensor.Sensor")
    publish("pbt", conv.encode({"device": "hot", "temperature": 30.0, "ts": 1}))
    publish("pbt", conv.encode({"device": "cold", "temperature": 5.0, "ts": 2}))
    time.sleep(1.0)
    rr.stop("rpb")
    rr.delete("rpb")
    unsub()
    rows = [r for g in got for r in (g if isinstance(g, list) else [g])]
    assert [r["device"] for r in rows] == ["hot"]


def test_schema_persistence(tmp_path):
    store = kv.get_store()
    r1 = SchemaRegistry(store, etc_dir=str(tmp_path / "s"))
    r1.create({"name": "p1", "type": "protobuf", "content": PROTO})
    r2 = SchemaRegistry(store, etc_dir=str(tmp_path / "s"))
    assert r2.list() == ["p1"]
    assert r2.message_class("p1", "Sensor") is not None
    r2.delete("p1")
