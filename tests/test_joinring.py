"""Device join-ring kernel (ops/joinring.py): match-mask parity against
the numpy twin, NULL-key semantics (NULL = NULL is true in this engine),
band arithmetic, residual three-valued logic, window fallback reasons,
and the time-bucketed dual-side ring mechanics."""
import random

import numpy as np
import pytest

from ekuiper_tpu.ops.joinring import (JOIN_PAD_FLOOR, JoinRing,
                                      JoinWindowFallback, SideBatch,
                                      TS_RANGE_CAP)
from ekuiper_tpu.planner import relational
from ekuiper_tpu.sql.expr_ir import NotVectorizable
from ekuiper_tpu.sql.parser import parse_select


def _lower(sql):
    stmt = parse_select(sql)
    return relational.lower_join(stmt, stmt.joins)


def _side(keys, ts=None, **cols):
    b = SideBatch(n=len(keys))
    b.key_cols.append(list(keys))
    if ts is not None:
        b.band = list(ts)
    for name, vals in cols.items():
        b.cols[name] = list(vals)
    return b


JOIN_SQL = ("SELECT l.v, r.w FROM l INNER JOIN r ON l.k = r.k "
            "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 AND l.v > r.w "
            "GROUP BY TUMBLINGWINDOW(ss, 1)")


class TestMatchParity:
    def test_randomized_device_equals_host(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=64)
        rng = random.Random(11)
        for _ in range(8):
            nl, nr = rng.randint(0, 12), rng.randint(0, 12)
            left = _side(
                [rng.choice(["a", "b", None, ""]) for _ in range(nl)],
                ts=[rng.choice([rng.randint(0, 30), None])
                    for _ in range(nl)],
                __jl_v=[rng.choice([1.0, 5.0, None]) for _ in range(nl)])
            right = _side(
                [rng.choice(["a", "b", None, ""]) for _ in range(nr)],
                ts=[rng.choice([rng.randint(0, 30), None])
                    for _ in range(nr)],
                __jr_w=[rng.choice([0.0, 3.0, None]) for _ in range(nr)])
            dev = ring.match(left, right)
            host = ring.match_host(left, right)
            assert dev.shape == (nl, nr)
            np.testing.assert_array_equal(dev, host)

    def test_null_keys_pair_with_each_other_not_empty_string(self):
        # this engine evaluates NULL = NULL as true (sql/eval.py), and
        # NULL = "" as false — the ring must encode both distinctly
        ring = _lower(
            "SELECT l.v, r.w FROM l INNER JOIN r ON l.k = r.k "
            "GROUP BY TUMBLINGWINDOW(ss, 1)").build_ring(capacity=16)
        mask = ring.match(_side([None, "", "a"]), _side([None, "", "a"]))
        np.testing.assert_array_equal(mask, np.eye(3, dtype=bool))

    def test_band_bounds_inclusive(self):
        ring = _lower(
            "SELECT l.v FROM l INNER JOIN r ON l.k = r.k "
            "AND l.ts - r.ts >= -2 AND l.ts - r.ts <= 2 "
            "GROUP BY TUMBLINGWINDOW(ss, 1)").build_ring(capacity=16)
        left = _side(["a"] * 1, ts=[10])
        right = _side(["a"] * 5, ts=[7, 8, 10, 12, 13])
        mask = ring.match(left, right)
        assert mask.tolist() == [[False, True, True, True, False]]

    def test_residual_null_is_not_a_match(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=16)
        left = _side(["a", "a"], ts=[0, 0], __jl_v=[5.0, None])
        right = _side(["a"], ts=[0], __jr_w=[1.0])
        mask = ring.match(left, right)
        assert mask.tolist() == [[True], [False]]


class TestFallbackContract:
    def test_non_integer_event_time_reason(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=16)
        with pytest.raises(JoinWindowFallback) as ei:
            ring.match(_side(["a"], ts=["not-a-ts"], __jl_v=[1.0]),
                       _side(["a"], ts=[0], __jr_w=[0.0]))
        assert ei.value.reason == "join_ts_type"

    def test_ts_range_overflow_reason(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=16)
        with pytest.raises(JoinWindowFallback) as ei:
            ring.match(
                _side(["a", "a"], ts=[0, TS_RANGE_CAP + 10],
                      __jl_v=[1.0, 1.0]),
                _side(["a"], ts=[0], __jr_w=[0.0]))
        assert ei.value.reason == "join_ts_range"


class TestRingMechanics:
    def test_append_window_evict(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=16, bucket_ms=10)
        for t in range(0, 50, 5):
            ring.append("l", _side(["a"], ts=[t], __jl_v=[1.0]))
        assert ring.ring_rows("l") == 10
        win = ring.window("l", 10, 29)
        assert all(10 <= t <= 29 for t in win.band)
        assert win.n >= 4  # bucket granularity may over-select; never under
        evicted = ring.evict(before_ts=20)
        assert evicted > 0
        assert ring.ring_rows("l") < 10
        assert ring.nbytes() > 0
        ring.reset_ring()
        assert ring.ring_rows("l") == 0

    def test_capacity_doubles_under_key_pressure(self):
        ring = _lower(
            "SELECT l.v FROM l INNER JOIN r ON l.k = r.k "
            "GROUP BY TUMBLINGWINDOW(ss, 1)").build_ring(capacity=4)
        n = 64
        keys = [f"k{i}" for i in range(n)]
        mask = ring.match(_side(keys), _side(keys))
        np.testing.assert_array_equal(mask, np.eye(n, dtype=bool))
        assert ring.capacity >= n

    def test_pads_power_of_two(self):
        ring = _lower(JOIN_SQL).build_ring(capacity=16)
        mask = ring.match(
            _side(["a"] * 3, ts=[0] * 3, __jl_v=[1.0] * 3),
            _side(["a"] * (JOIN_PAD_FLOOR + 1),
                  ts=[0] * (JOIN_PAD_FLOOR + 1),
                  __jr_w=[0.0] * (JOIN_PAD_FLOOR + 1)))
        assert mask.shape == (3, JOIN_PAD_FLOOR + 1)


class TestLoweringGrammar:
    def test_rejects_multiway_join(self):
        stmt = parse_select(
            "SELECT a.v FROM a INNER JOIN b ON a.k = b.k "
            "INNER JOIN c ON a.k = c.k GROUP BY TUMBLINGWINDOW(ss, 1)")
        with pytest.raises(NotVectorizable) as ei:
            relational.lower_join(stmt, stmt.joins)
        assert ei.value.reason == "join_multiway"

    def test_cross_stream_comparison_lowers_half_open_band(self):
        # no equi key: the affine comparison takes the band lane with a
        # half-open bound (> v becomes >= v+1 over the integer domain;
        # non-integral values fall back per window at runtime)
        stmt = parse_select(
            "SELECT l.v FROM l INNER JOIN r ON l.v > r.w "
            "GROUP BY TUMBLINGWINDOW(ss, 1)")
        low = relational.lower_join(stmt, stmt.joins)
        assert low.key_l == []
        assert (low.band_l, low.band_r, low.lo, low.hi) == ("v", "w", 1, None)

    def test_rejects_join_with_no_lowerable_conjunct(self):
        # an ON clause the expression IR cannot compile at all
        stmt = parse_select(
            "SELECT l.v FROM l INNER JOIN r ON l.s LIKE r.p "
            "GROUP BY TUMBLINGWINDOW(ss, 1)")
        with pytest.raises(NotVectorizable) as ei:
            relational.lower_join(stmt, stmt.joins)
        assert ei.value.reason.startswith("join_")

    def test_cross_join_lowers_without_on(self):
        stmt = parse_select("SELECT l.v, r.w FROM l CROSS JOIN r "
                            "GROUP BY TUMBLINGWINDOW(ss, 1)")
        low = relational.lower_join(stmt, stmt.joins)
        assert low.key_l == [] and low.residual_dev is None

    def test_band_lowers_to_int_bounds(self):
        low = _lower(JOIN_SQL)
        assert (low.lo, low.hi) == (-5, 5)
        assert low.band_l == "ts" and low.band_r == "ts"
        assert low.key_l == ["k"] and low.key_r == ["k"]
        rl, rr = low.resid_signature()
        assert list(rl) == ["__jl_v"] and list(rr) == ["__jr_w"]
