"""Device-path heavy_hitters (BASELINE config #2): count-min totals +
group-testing bit recovery as a fused wide kernel component, with reversible
dictionary encoding so values of any type decode exactly at emit.

Reference scenario: HOPPINGWINDOW GROUP BY device_id with a count-min
heavy-hitters UDF (BASELINE.json configs[1]); host-path exact semantics in
functions/funcs_sketch.py f_heavy_hitters.
"""
from collections import Counter

import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import ValueDict, extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.planner.planner import device_path_eligible
from ekuiper_tpu.runtime.events import Trigger
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.utils.config import RuleOptionConfig

SQL = ("SELECT deviceId, heavy_hitters(code, 3) AS top FROM s "
       "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")

SQL_HOP = ("SELECT deviceId, heavy_hitters(code, 2) AS top, count(*) AS c "
           "FROM s GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)")


def make_node(sql, **kw):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    node = FusedWindowAggNode(
        "hh", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=256,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]), **kw)
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    return node, got


def skewed_batch(rng, n=20000, keys=5, values="int", ts=1000):
    """~40/25/15% mass on three heavy values, tail uniform over 1000."""
    key_col = np.array([f"d{i}" for i in rng.integers(0, keys, n)],
                       dtype=np.object_)
    p = rng.random(n)
    code = np.where(
        p < 0.4, 7, np.where(p < 0.65, 13, np.where(
            p < 0.8, 99, rng.integers(100, 1100, n)))).astype(np.int64)
    if values == "str":
        code_col = np.array([f"ev{c}" for c in code], dtype=np.object_)
    else:
        code_col = code
    return ColumnBatch(
        n=n, columns={"deviceId": key_col, "code": code_col},
        timestamps=np.full(n, ts, dtype=np.int64), emitter="s")


def exact_topk(batch, k):
    keys = batch.columns["deviceId"]
    code = batch.columns["code"]
    out = {}
    for key in set(keys.tolist()):
        out[key] = Counter(code[keys == key].tolist()).most_common(k)
    return out


def check_parity(node, got_groups, batch, k, count_tol=0.05):
    """Sketch top-k values == exact top-k values; counts within tol."""
    exact = exact_topk(batch, k)
    assert got_groups, "no emission"
    seen_keys = set()
    for msg in got_groups:
        key = msg["deviceId"]
        seen_keys.add(key)
        want = exact[key]
        got = msg["top"]
        assert [d["value"] for d in got] == [v for v, _ in want]
        for d, (_, cnt) in zip(got, want):
            assert d["count"] >= cnt  # count-min never underestimates
            assert d["count"] <= cnt * (1 + count_tol) + 5
    assert seen_keys == set(exact)


def collect_msgs(got):
    msgs = []
    for item in got:
        if isinstance(item, list):
            msgs.extend(item)
        elif isinstance(item, dict):
            msgs.append(item)
    return msgs


class TestHeavyHittersDevice:
    def test_tumbling_int_parity(self):
        rng = np.random.default_rng(1)
        node, got = make_node(SQL)
        batch = skewed_batch(rng)
        node.process(batch)
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        check_parity(node, collect_msgs(got), batch, 3)

    def test_tumbling_string_values_decode(self):
        rng = np.random.default_rng(2)
        node, got = make_node(SQL)
        batch = skewed_batch(rng, values="str")
        node.process(batch)
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        msgs = collect_msgs(got)
        assert msgs
        for m in msgs:
            vals = [d["value"] for d in m["top"]]
            assert vals[0] == "ev7"  # heaviest decodes to the original str
            assert all(isinstance(v, str) for v in vals)

    def test_hopping_pane_merge(self):
        """Two 5s panes fold separately; the 10s window merges them by +
        and recovers the combined heavy hitters."""
        rng = np.random.default_rng(3)
        node, got = make_node(SQL_HOP)
        b1 = skewed_batch(rng, n=8000, ts=1000)
        node.process(b1)
        node.on_trigger(Trigger(ts=5_000))
        node._drain_async_emits()
        node.cur_pane = 1
        b2 = skewed_batch(rng, n=8000, ts=6000)
        node.process(b2)
        got.clear()
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        msgs = collect_msgs(got)
        assert msgs
        both = ColumnBatch(
            n=b1.n + b2.n,
            columns={k: np.concatenate([b1.columns[k], b2.columns[k]])
                     for k in b1.columns},
            timestamps=np.concatenate([b1.timestamps, b2.timestamps]),
            emitter="s")
        exact = exact_topk(both, 2)
        for m in msgs:
            assert [d["value"] for d in m["top"]] == [
                v for v, _ in exact[m["deviceId"]]]
            assert m["c"] == sum(
                1 for x in both.columns["deviceId"] if x == m["deviceId"])

    def test_checkpoint_restore_preserves_dict_and_sketch(self):
        rng = np.random.default_rng(4)
        node, got = make_node(SQL)
        batch = skewed_batch(rng, n=10000)
        node.process(batch)
        snap = node.snapshot_state()
        assert "hh_dicts" in snap

        node2, got2 = make_node(SQL)
        node2.restore_state(snap)
        batch2 = skewed_batch(rng, n=10000, ts=2000)
        node2.process(batch2)
        node2.on_trigger(Trigger(ts=10_000))
        node2._drain_async_emits()
        both = ColumnBatch(
            n=batch.n + batch2.n,
            columns={k: np.concatenate([batch.columns[k], batch2.columns[k]])
                     for k in batch.columns},
            timestamps=np.concatenate([batch.timestamps, batch2.timestamps]),
            emitter="s")
        check_parity(node2, collect_msgs(got2), both, 3)

    def test_null_values_masked(self):
        node, got = make_node(SQL)
        code = np.array([7, None, 7, None, 13], dtype=np.object_)
        keys = np.array(["d0"] * 5, dtype=np.object_)
        node.process(ColumnBatch(
            n=5, columns={"deviceId": keys, "code": code},
            timestamps=np.full(5, 1000, dtype=np.int64), emitter="s"))
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        msgs = collect_msgs(got)
        assert len(msgs) == 1
        assert msgs[0]["top"] == [
            {"value": 7, "count": 2}, {"value": 13, "count": 1}]

    def test_empty_group_emits_empty_list(self):
        node, got = make_node(SQL)
        code = np.array([None, None], dtype=np.object_)
        keys = np.array(["d0", "d0"], dtype=np.object_)
        node.process(ColumnBatch(
            n=2, columns={"deviceId": keys, "code": code},
            timestamps=np.full(2, 1000, dtype=np.int64), emitter="s"))
        node.on_trigger(Trigger(ts=10_000))
        node._drain_async_emits()
        msgs = collect_msgs(got)
        assert len(msgs) == 1
        assert msgs[0]["top"] == []


class TestPlannerGates:
    def _opts(self, **kw):
        return RuleOptionConfig(**kw)

    def test_eligible_single_chip(self):
        stmt = parse_select(SQL)
        assert device_path_eligible(stmt, self._opts()) is not None

    def test_mesh_routes_to_host(self):
        stmt = parse_select(SQL)
        opts = self._opts(
            plan_optimize_strategy={"mesh": {"devices": 8}})
        assert device_path_eligible(stmt, opts) is None

    def test_hh_in_having_routes_to_host(self):
        stmt = parse_select(
            "SELECT deviceId, heavy_hitters(code, 3) AS top FROM s "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10) "
            "HAVING count(*) > 1")
        # count(*) HAVING is fine — hh itself is a bare field
        assert device_path_eligible(stmt, self._opts()) is not None

    def test_hh_nested_expr_not_planned(self):
        stmt = parse_select(
            "SELECT deviceId, len(heavy_hitters(code, 3)) AS n FROM s "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        assert device_path_eligible(stmt, self._opts()) is None

    def test_bad_args_not_planned(self):
        stmt = parse_select(
            "SELECT deviceId, heavy_hitters(code * 2, 3) AS top FROM s "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        assert extract_kernel_plan(stmt) is None


class TestValueDict:
    def test_roundtrip_mixed(self):
        vd = ValueDict()
        col = np.array(["a", "b", "a", None, "c"], dtype=np.object_)
        codes = vd.encode(col)
        assert np.isnan(codes[3])
        assert codes[0] == codes[2]
        assert vd.decode(int(codes[1])) == "b"

    def test_numeric_nan_passthrough(self):
        vd = ValueDict()
        col = np.array([1.5, np.nan, 1.5, 2.5], dtype=np.float64)
        codes = vd.encode(col)
        assert np.isnan(codes[1])
        assert codes[0] == codes[2] != codes[3]
        # a second batch reuses the same codes
        codes2 = vd.encode(np.array([2.5, 1.5]))
        assert codes2[0] == codes[3] and codes2[1] == codes[0]

    def test_snapshot_restore(self):
        vd = ValueDict()
        vd.encode(np.array(["x", "y"], dtype=np.object_))
        vd2 = ValueDict()
        vd2.restore(vd.snapshot())
        assert vd2.decode(0) == "x"
        c = vd2.encode(np.array(["y", "z"], dtype=np.object_))
        assert c[0] == 1.0 and c[1] == 2.0
