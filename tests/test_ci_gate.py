"""tools/ci_gate.py — the one-command static-analysis verdict must run
green on the tree (tier-1, the same contract as each gate individually)
and fail loudly when any gate fails."""
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run(*args, timeout=600):
    return subprocess.run(
        [sys.executable, "tools/ci_gate.py", *args],
        capture_output=True, text=True, timeout=timeout, cwd=str(REPO))


def test_full_gate_green_with_json_verdict():
    """THE gate: kuiperlint + jitcert certify/diff + check_metrics +
    benchdiff --smoke, one JSON verdict, exit 0."""
    proc = _run("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict["ok"] is True
    names = {g["gate"] for g in verdict["gates"]}
    assert names == {"kuiperlint", "jitcert_certify", "jitcert_diff",
                     "probe_exprs", "probe_tiering", "probe_multichip",
                     "probe_joins", "probe_fleetobs", "check_metrics",
                     "benchdiff_smoke", "cold_start"}
    assert all(g["ok"] and g["returncode"] == 0
               for g in verdict["gates"])


def test_skip_and_unknown_gate():
    proc = _run("--json", "--skip",
                "jitcert_diff,benchdiff_smoke,check_metrics,kuiperlint,"
                "probe_exprs,probe_tiering,probe_multichip,probe_joins,"
                "probe_fleetobs,cold_start")
    assert proc.returncode == 0
    verdict = json.loads(proc.stdout)
    assert [g["gate"] for g in verdict["gates"]] == ["jitcert_certify"]
    assert "benchdiff_smoke" in verdict["skipped"]
    proc = _run("--skip", "no-such-gate")
    assert proc.returncode == 2
