"""Latency histogram math (observability/histogram.py): bucket
boundaries, merge, decay, percentile accuracy, and the Prometheus
cumulative-bucket mapping."""
import random
import threading

import numpy as np
import pytest

from ekuiper_tpu.observability.histogram import (
    E2E_BOUNDS_MS,
    LatencyHistogram,
    MAX_BITS,
    SUB_BITS,
    _bucket_max,
    _index,
    render_prom_histogram,
)


class TestBuckets:
    def test_linear_range_is_exact(self):
        for v in range(1 << SUB_BITS):
            assert _index(v) == v
            assert _bucket_max(v) == v

    def test_bucket_contains_value(self):
        # every value maps to a bucket whose [implied lower, max] range
        # contains it, with relative width <= 2^-SUB_BITS
        for v in (16, 17, 31, 32, 100, 1000, 65_535, 10**6, 10**9, 2**40):
            idx = _index(v)
            hi = _bucket_max(idx)
            assert v <= hi
            assert hi - v <= max(v >> SUB_BITS, 1), (v, hi)

    def test_index_monotonic(self):
        vals = sorted(random.Random(3).sample(range(1, 10**7), 5000))
        idxs = [_index(v) for v in vals]
        assert idxs == sorted(idxs)

    def test_clamp_at_top(self):
        top = _index(2**MAX_BITS)
        assert top == _index(2**60)
        assert _bucket_max(top) == 2**MAX_BITS - 1


class TestRecordPercentile:
    def test_percentile_tracks_numpy(self):
        rng = random.Random(7)
        vals = [rng.randint(0, 2_000_000) for _ in range(30_000)]
        h = LatencyHistogram()
        for v in vals:
            h.record(v)
        assert h.count == len(vals)
        assert h.sum == sum(vals)
        assert h.max == max(vals)
        assert h.min == min(vals)
        for q in (50, 90, 99, 99.9):
            true = float(np.percentile(vals, q))
            est = h.percentile(q)
            # bucket upper edge: overestimates by <= 6.25%, never under
            assert true <= est + 1
            assert est <= true * (1 + 2**-SUB_BITS) + 1, (q, est, true)

    def test_empty_and_single(self):
        h = LatencyHistogram()
        assert h.percentile(99) == 0
        assert h.snapshot() == {"count": 0, "p50": 0, "p90": 0, "p99": 0,
                                "max": 0}
        h.record(123)
        assert h.percentile(1) == h.percentile(100) == 123

    def test_negative_clamps_to_zero(self):
        h = LatencyHistogram()
        h.record(-5)
        assert h.count == 1 and h.max == 0

    def test_concurrent_records_all_land(self):
        h = LatencyHistogram()

        def work():
            for i in range(5000):
                h.record(i)

        ts = [threading.Thread(target=work) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == 20_000


class TestMergeDecay:
    def test_merge_is_additive(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (1, 10, 100):
            a.record(v)
        for v in (1000, 5):
            b.record(v)
        a.merge(b)
        assert a.count == 5
        assert a.sum == 1116
        assert a.min == 1 and a.max == 1000
        assert a.percentile(100) == 1000

    def test_merge_empty_noop(self):
        a = LatencyHistogram()
        a.record(7)
        a.merge(LatencyHistogram())
        assert a.count == 1 and a.min == 7

    def test_decay_halves_and_clears(self):
        h = LatencyHistogram()
        for _ in range(8):
            h.record(40)
        snap = h.snapshot_and_decay(0.5)
        assert snap["count"] == 8 and snap["p50"] == 40
        assert h.count == 4
        assert h.percentile(50) == 40  # shape preserved
        h.snapshot_and_decay(0.0)
        assert h.count == 0 and h.max == 0 and h.sum == 0

    def test_decay_drops_singletons(self):
        h = LatencyHistogram()
        h.record(99)
        h.snapshot_and_decay(0.5)  # int(1 * 0.5) == 0
        assert h.count == 0


class TestPromExport:
    def test_cumulative_monotonic_and_conservative(self):
        h = LatencyHistogram()
        for v in (0, 3, 49, 50, 51, 400, 70_000):
            h.record(v)
        cum = h.cumulative(E2E_BOUNDS_MS)
        assert cum == sorted(cum)
        assert cum[-1] <= h.count  # 70k exceeds the ladder -> only +Inf
        # never under-reports latency: count at `le=50` must not exceed
        # the true number of samples <= 50
        le50 = cum[E2E_BOUNDS_MS.index(50)]
        assert le50 <= 4

    def test_render_lines(self):
        h = LatencyHistogram()
        for v in (2, 30, 800):
            h.record(v)
        out = []
        render_prom_histogram(out, "kuiper_rule_e2e_latency_ms",
                              'rule="r\\"1"', h)
        les = [ln.rsplit('le="', 1)[1].split('"')[0]
               for ln in out if "_bucket" in ln]
        assert les[-1] == "+Inf"
        assert [float(x) for x in les[:-1]] == sorted(float(x)
                                                      for x in les[:-1])
        assert out[-2] == 'kuiper_rule_e2e_latency_ms_sum{rule="r\\"1"} 832'
        assert out[-1] == 'kuiper_rule_e2e_latency_ms_count{rule="r\\"1"} 3'
        inf_val = int([ln for ln in out if 'le="+Inf"' in ln][0].split()[-1])
        assert inf_val == 3
