"""Segmented-scan analytic kernels (ops/segscan.py): device-vs-host twin
parity for the stateful lag shift and the per-collection rank sort,
partial-spill counters, capacity growth, and carry snapshot/restore."""
import random

import numpy as np

from ekuiper_tpu.ops.segscan import SegScan, shift_host, sort_host


def _rand_batch(rng, n, n_slots):
    slots = np.array([rng.randrange(n_slots) for _ in range(n)],
                     dtype=np.int32)
    vals = np.array([rng.choice([1.5, 2.5, 7.0, np.nan])
                     for _ in range(n)], dtype=np.float32)
    return slots, vals


class TestShiftParity:
    def test_stateful_lag_matches_host_across_batches(self):
        rng = random.Random(3)
        dev = SegScan(capacity=16)
        host_carry = {
            "cnt": np.zeros(16, np.int64),
            "last": np.zeros(16, np.float64),
            "has": np.zeros(16, bool),
            "acc": np.zeros(16, np.float64),
        }
        for _ in range(6):
            n = rng.randint(1, 40)
            slots, vals = _rand_batch(rng, n, 12)
            d = dev.shift(slots, vals, n)
            h = shift_host(host_carry, slots, vals, n)
            for key in ("row_number", "lag", "lag_has", "run_sum"):
                np.testing.assert_allclose(
                    np.asarray(d[key], dtype=np.float64),
                    np.asarray(h[key], dtype=np.float64),
                    rtol=1e-6, err_msg=key)

    def test_fresh_partition_has_no_lag(self):
        dev = SegScan(capacity=8)
        out = dev.shift(np.array([0, 1, 0], np.int32),
                        np.array([1.0, 2.0, 3.0], np.float32), 3)
        assert list(out["lag_has"]) == [False, False, True]
        assert float(out["lag"][2]) == 1.0

    def test_spill_counter_counts_continued_partitions(self):
        dev = SegScan(capacity=8)
        dev.shift(np.array([0, 1], np.int32),
                  np.array([1.0, 2.0], np.float32), 2)
        assert dev.spills_total == 0
        dev.shift(np.array([0, 2], np.int32),
                  np.array([3.0, 4.0], np.float32), 2)
        # slot 0 continued from the previous micro-batch; slot 2 is fresh
        assert dev.spills_total == 1

    def test_capacity_grows_and_preserves_carry(self):
        dev = SegScan(capacity=4)
        dev.shift(np.array([0], np.int32), np.array([9.0], np.float32), 1)
        out = dev.shift(np.array([40, 0], np.int32),
                        np.array([1.0, 2.0], np.float32), 2)
        assert dev.capacity >= 41
        assert bool(out["lag_has"][1]) and float(out["lag"][1]) == 9.0

    def test_snapshot_restore_roundtrip(self):
        import json

        a = SegScan(capacity=8)
        a.shift(np.array([0, 1, 0], np.int32),
                np.array([1.0, 2.0, 3.0], np.float32), 3)
        snap = json.loads(json.dumps(a.snapshot()))
        b = SegScan(capacity=8)
        b.restore(snap)
        oa = a.shift(np.array([0, 1], np.int32),
                     np.array([5.0, 6.0], np.float32), 2)
        ob = b.shift(np.array([0, 1], np.int32),
                     np.array([5.0, 6.0], np.float32), 2)
        for key in ("row_number", "lag", "lag_has", "run_sum"):
            np.testing.assert_allclose(
                np.asarray(oa[key], np.float64),
                np.asarray(ob[key], np.float64), err_msg=key)
        assert float(oa["lag"][0]) == 3.0


class TestSortParity:
    def test_randomized_ranks_match_host(self):
        rng = random.Random(5)
        dev = SegScan(capacity=8)
        for _ in range(8):
            n = rng.randint(1, 50)
            seg = np.array([rng.randrange(4) for _ in range(n)],
                           dtype=np.int32)
            vals = np.array([rng.choice([1.0, 2.0, 2.0, 5.0, np.nan])
                             for _ in range(n)], dtype=np.float32)
            d = dev.ranks(seg, vals, n)
            h = sort_host(seg, vals, n)
            for key in ("row_number", "rank", "dense_rank", "rank_has",
                        "lead", "lead_has"):
                np.testing.assert_allclose(
                    np.asarray(d[key], np.float64),
                    np.asarray(h[key], np.float64),
                    rtol=1e-6, err_msg=key)

    def test_rank_semantics(self):
        dev = SegScan(capacity=8)
        seg = np.zeros(4, np.int32)
        vals = np.array([2.0, 1.0, 2.0, np.nan], np.float32)
        out = dev.ranks(seg, vals, 4)
        assert [int(r) for r in out["rank"][:3]] == [2, 1, 2]
        assert [int(r) for r in out["dense_rank"][:3]] == [2, 1, 2]
        assert not out["rank_has"][3]  # NULL ranks as NULL

    def test_lead_is_next_row_within_segment(self):
        dev = SegScan(capacity=8)
        seg = np.array([0, 1, 0, 1], np.int32)
        vals = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        out = dev.ranks(seg, vals, 4)
        assert float(out["lead"][0]) == 3.0
        assert float(out["lead"][1]) == 4.0
        assert not out["lead_has"][2] and not out["lead_has"][3]
