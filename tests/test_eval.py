"""Expression evaluation tests: row interpreter, vectorized compiler, and
cross-checks that both paths agree (the compat invariant the TPU path must
hold against the reference's interpreter semantics)."""
import numpy as np
import pytest

from ekuiper_tpu.data.batch import from_tuples
from ekuiper_tpu.data.rows import GroupedTuples, Tuple
from ekuiper_tpu.sql import ast
from ekuiper_tpu.sql.compiler import NotVectorizable, compile_expr, try_compile
from ekuiper_tpu.sql.eval import EvalError, Evaluator
from ekuiper_tpu.sql.parser import parse_select


def expr_of(sql_expr: str) -> ast.Expr:
    return parse_select(f"SELECT {sql_expr} FROM demo").fields[0].expr


def cond_of(sql_cond: str) -> ast.Expr:
    return parse_select(f"SELECT * FROM demo WHERE {sql_cond}").condition


ROW = Tuple(
    emitter="demo",
    message={
        "a": 10, "b": 3, "f": 2.5, "s": "hello", "flag": True,
        "arr": [1, 2, 3], "obj": {"x": 1, "y": {"z": 9}}, "nul": None,
    },
    timestamp=1000,
    metadata={"topic": "t/1"},
)


class TestInterpreter:
    def setup_method(self):
        self.ev = Evaluator(rule_id="r1")

    def t(self, expr_sql, expected):
        assert self.ev.eval(expr_of(expr_sql), ROW) == expected

    def test_arith(self):
        self.t("a + b", 13)
        self.t("a - b", 7)
        self.t("a * b", 30)
        self.t("a / b", 3)  # int division like the reference
        self.t("a % b", 1)
        self.t("a / 4.0", 2.5)
        self.t("-a", -10)

    def test_comparison(self):
        self.t("a > b", True)
        self.t("a = 10", True)
        self.t("a != 10", False)
        self.t("f <= 2.5", True)
        self.t("s = 'hello'", True)

    def test_logic_null(self):
        self.t("a > 5 AND f < 3", True)
        self.t("a > 5 OR f > 3", True)
        self.t("NOT flag", False)
        # null propagation: null = null true; null = x false
        self.t("nul = nul", True)
        self.t("nul = a", False)
        assert self.ev.eval(cond_of("nul > 1"), ROW) is False

    def test_division_by_zero(self):
        with pytest.raises(EvalError):
            self.ev.eval(expr_of("a / 0"), ROW)

    def test_string_arith_error(self):
        with pytest.raises(EvalError):
            self.ev.eval(expr_of("s + 1"), ROW)

    def test_in_between_like(self):
        self.t("a IN (1, 10, 20)", True)
        self.t("a NOT IN (1, 2)", True)
        self.t("a BETWEEN 5 AND 15", True)
        self.t("a NOT BETWEEN 5 AND 15", False)
        self.t("s LIKE 'hel%'", True)
        self.t("s LIKE 'h_llo'", True)
        self.t("s NOT LIKE 'x%'", True)

    def test_case(self):
        self.t("CASE WHEN a > 5 THEN 'big' ELSE 'small' END", "big")
        self.t("CASE a WHEN 10 THEN 'ten' WHEN 20 THEN 'twenty' END", "ten")
        self.t("CASE WHEN a > 99 THEN 1 END", None)

    def test_json_access(self):
        self.t("arr[0]", 1)
        self.t("arr[-1]", 3)
        self.t("arr[1:3]", [2, 3])
        self.t("obj->x", 1)
        self.t("obj->y->z", 9)

    def test_functions(self):
        self.t("abs(0 - a)", 10)
        self.t("lower('ABC')", "abc")
        self.t("concat(s, '!')", "hello!")
        self.t("coalesce(nul, a)", 10)
        self.t("cast(f, 'bigint')", 2)
        self.t("power(2, 10)", 1024)

    def test_meta_function(self):
        assert self.ev.eval(expr_of("meta('topic')"), ROW) == "t/1"

    def test_wildcard(self):
        out = self.ev.eval(expr_of("*"), ROW)
        assert out["a"] == 10 and "s" in out


class TestAggregates:
    def setup_method(self):
        self.ev = Evaluator()
        rows = [
            Tuple(message={"v": 1.0, "d": "x"}),
            Tuple(message={"v": 2.0, "d": "x"}),
            Tuple(message={"v": 6.0, "d": "x"}),
        ]
        self.group = GroupedTuples(content=rows, group_key="x")

    def a(self, sql, expected):
        assert self.ev.eval(expr_of(sql), self.group) == expected

    def test_basic_aggs(self):
        self.a("avg(v)", 3.0)
        self.a("sum(v)", 9.0)
        self.a("count(*)", 3)
        self.a("count(v)", 3)
        self.a("min(v)", 1.0)
        self.a("max(v)", 6.0)
        self.a("collect(v)", [1.0, 2.0, 6.0])

    def test_agg_filter_clause(self):
        self.a("sum(v) FILTER (WHERE v > 1)", 8.0)

    def test_stddev(self):
        out = self.ev.eval(expr_of("stddev(v)"), self.group)
        assert abs(out - np.std([1, 2, 6])) < 1e-9

    def test_int_avg(self):
        rows = [Tuple(message={"n": 1}), Tuple(message={"n": 2})]
        g = GroupedTuples(content=rows)
        assert self.ev.eval(expr_of("avg(n)"), g) == 1  # int avg truncates

    def test_group_key_column(self):
        assert self.ev.eval(expr_of("d"), self.group) == "x"


class TestAnalytic:
    def test_lag(self):
        ev = Evaluator()
        e = expr_of("lag(a)")
        rows = [Tuple(message={"a": i}) for i in (10, 20, 30)]
        out = [ev.eval(e, r) for r in rows]
        assert out == [None, 10, 20]

    def test_lag_partitioned(self):
        ev = Evaluator()
        e = expr_of("lag(v) OVER (PARTITION BY dev)")
        rows = [
            Tuple(message={"dev": "a", "v": 1}),
            Tuple(message={"dev": "b", "v": 2}),
            Tuple(message={"dev": "a", "v": 3}),
            Tuple(message={"dev": "b", "v": 4}),
        ]
        out = [ev.eval(e, r) for r in rows]
        assert out == [None, None, 1, 2]

    def test_had_changed(self):
        ev = Evaluator()
        e = expr_of("had_changed(true, a)")
        rows = [Tuple(message={"a": 1}), Tuple(message={"a": 1}), Tuple(message={"a": 2})]
        assert [ev.eval(e, r) for r in rows] == [True, False, True]


def _batch():
    rows = [
        Tuple(message={"a": 10, "f": 1.5, "dev": "d1"}),
        Tuple(message={"a": 20, "f": 2.5, "dev": "d2"}),
        Tuple(message={"a": 30, "f": 3.5, "dev": "d1"}),
    ]
    return from_tuples(rows)


class TestCompilerHost:
    def c(self, sql):
        return compile_expr(expr_of(sql), mode="host")

    def test_arith_vec(self):
        b = _batch()
        out = self.c("a * 2 + f")(b.columns)
        assert list(out) == [21.5, 42.5, 63.5]

    def test_compare_logic(self):
        b = _batch()
        out = self.c("a > 15 AND f < 3.0")(b.columns)
        assert list(out) == [False, True, False]

    def test_case_where(self):
        b = _batch()
        out = self.c("CASE WHEN a > 15 THEN 1 ELSE 0 END")(b.columns)
        assert list(out) == [0, 1, 1]

    def test_in(self):
        b = _batch()
        out = self.c("a IN (10, 30)")(b.columns)
        assert list(out) == [True, False, True]

    def test_math_funcs(self):
        b = _batch()
        out = self.c("sqrt(f * f)")(b.columns)
        np.testing.assert_allclose(out, [1.5, 2.5, 3.5], rtol=1e-6)

    def test_string_like_host(self):
        b = _batch()
        out = self.c("dev LIKE 'd%'")(b.columns)
        assert list(out) == [True, True, True]

    def test_string_eq_host(self):
        b = _batch()
        out = self.c("dev = 'd1'")(b.columns)
        assert list(out) == [True, False, True]

    def test_not_vectorizable(self):
        assert try_compile(expr_of("lag(a)")) is None
        assert try_compile(expr_of("obj->x")) is None
        assert try_compile(expr_of("newuuid()")) is None

    def test_referenced_columns(self):
        ce = self.c("a + f > 2")
        assert ce.columns == {"a", "f"}


class TestCompilerDevice:
    def test_device_jit(self):
        import jax
        import jax.numpy as jnp

        ce = compile_expr(expr_of("a * 2.0 + sqrt(f)"), mode="device")
        fn = jax.jit(lambda cols: ce(cols))
        cols = {
            "a": jnp.asarray([1.0, 2.0], dtype=jnp.float32),
            "f": jnp.asarray([4.0, 9.0], dtype=jnp.float32),
        }
        out = np.asarray(fn(cols))
        np.testing.assert_allclose(out, [4.0, 7.0], rtol=1e-6)

    def test_device_rejects_strings(self):
        assert try_compile(expr_of("dev LIKE 'd%'"), mode="device") is None
        assert try_compile(expr_of("concat(dev, 'x')"), mode="device") is None

    def test_device_case_cond(self):
        import jax
        import jax.numpy as jnp

        ce = compile_expr(
            expr_of("CASE WHEN t > 30.0 THEN t - 30.0 ELSE 0.0 END"), mode="device"
        )
        out = jax.jit(ce.fn)({"t": jnp.asarray([25.0, 35.0])})
        np.testing.assert_allclose(np.asarray(out), [0.0, 5.0])


class TestCrossCheck:
    """Interpreter and compiled host path must agree."""

    EXPRS = [
        "a + f * 2",
        "a > 15",
        "a % 3",
        "a / 2",
        "abs(0 - a)",
        "CASE WHEN a >= 20 THEN f ELSE 0.0 END",
        "a BETWEEN 15 AND 25",
        "a IN (10, 20)",
        "NOT (a > 15)",
    ]

    @pytest.mark.parametrize("sql", EXPRS)
    def test_agree(self, sql):
        expr = expr_of(sql)
        b = _batch()
        ev = Evaluator()
        interp = [ev.eval(expr, r) for r in b.to_tuples()]
        compiled = compile_expr(expr, mode="host")(b.columns)
        for i, exp in enumerate(interp):
            got = compiled[i]
            if isinstance(exp, bool):
                assert bool(got) == exp, f"{sql} row {i}: {got} != {exp}"
            else:
                assert abs(float(got) - float(exp)) < 1e-5, f"{sql} row {i}"
