"""Video source: MJPEG-over-HTTP stream parsing + snapshot polling against
in-process camera mocks (reference: extensions/impl/video/source.go —
ffmpeg divergence documented in io/video_io.py)."""
import io
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ekuiper_tpu.io.video_io import VideoSource
from ekuiper_tpu.utils.infra import EngineError


def _jpeg(n):
    from PIL import Image

    img = Image.new("RGB", (8, 8), ((n * 40) % 256, (n * 80) % 256, 10))
    out = io.BytesIO()
    img.save(out, format="JPEG")
    return out.getvalue()


class _Camera:
    """Serves /stream (multipart/x-mixed-replace) and /snap (single jpeg)."""

    def __init__(self, frames, port=0):
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/snap":
                    body = frames[outer.snap_idx % len(frames)]
                    outer.snap_idx += 1
                    self.send_response(200)
                    self.send_header("Content-Type", "image/jpeg")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    'multipart/x-mixed-replace; boundary="frame"')
                self.end_headers()
                try:
                    # long-lived stream: cycle the frames far past the test
                    # duration so no reconnect replays confuse ordering
                    for i in range(300):
                        if outer.dead:
                            break
                        f = frames[i % len(frames)]
                        self.wfile.write(
                            b"--frame\r\nContent-Type: image/jpeg\r\n"
                            + f"Content-Length: {len(f)}\r\n\r\n".encode()
                            + f + b"\r\n")
                        time.sleep(0.02)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def log_message(self, *a):
                pass

        self.snap_idx = 0
        self.dead = False
        self.srv = HTTPServer(("127.0.0.1", port), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.dead = True  # unblock in-flight stream handlers
        self.srv.shutdown()
        self.srv.server_close()  # shutdown() alone leaves the listener open


@pytest.fixture
def camera():
    frames = [_jpeg(i) for i in range(6)]
    cam = _Camera(frames)
    cam.frames = frames
    yield cam
    cam.close()


def _drain(src, got, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline and len(got) < n:
        time.sleep(0.02)
    src.close()


def test_mjpeg_stream_frames(camera):
    src = VideoSource()
    src.configure("", {"url": f"http://127.0.0.1:{camera.port}/stream",
                       "interval": 10})
    got = []
    src.open(lambda payload, meta=None: got.append((payload, meta)))
    _drain(src, got, 3)
    assert len(got) >= 3
    payloads = [p for p, _ in got]
    # each emitted frame is a complete JPEG from the multipart stream
    assert all(p.startswith(b"\xff\xd8") and p.endswith(b"\xff\xd9")
               for p in payloads)
    # newest-wins sampling over a cycling stream: every payload is a real
    # stream frame and consecutive takes never return the same buffered
    # frame twice (take clears the slot)
    assert all(p in camera.frames for p in payloads)
    assert got[0][1]["frame"] == 1
    metas = [m["frame"] for _, m in got]
    assert metas == list(range(1, len(got) + 1))


def test_snapshot_polling(camera):
    src = VideoSource()
    src.configure("", {"url": f"http://127.0.0.1:{camera.port}/snap",
                       "interval": 20})
    got = []
    src.open(lambda payload, meta=None: got.append(payload))
    _drain(src, got, 3)
    assert len(got) >= 3
    assert all(p.startswith(b"\xff\xd8") for p in got)
    assert got[0] != got[1]  # successive snapshots advance


def test_decodes_with_image_functions(camera):
    """Frames feed the image function plugin (resize raw mode)."""
    from ekuiper_tpu.functions import registry as freg

    src = VideoSource()
    src.configure("", {"url": f"http://127.0.0.1:{camera.port}/snap",
                       "interval": 20})
    got = []
    src.open(lambda payload, meta=None: got.append(payload))
    _drain(src, got, 1)
    out = freg.lookup("resize").exec([got[0], 4, 4, True], {})
    assert len(out) == 4 * 4 * 3


def test_requires_url():
    with pytest.raises(EngineError, match="url"):
        VideoSource().configure("", {})


def test_reconnects_after_camera_restart():
    """Stream dies (camera reboot) — the source redials the SAME endpoint
    and frames resume."""
    import socket as pysock

    frames = [_jpeg(i) for i in range(3)]
    probe = pysock.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    cam = _Camera(frames, port=port)
    src = VideoSource()
    src.configure("", {"url": f"http://127.0.0.1:{port}/stream",
                       "interval": 20})
    got = []
    src.open(lambda payload, meta=None: got.append(meta["frame"]))
    deadline = time.time() + 10
    while time.time() < deadline and len(got) < 2:
        time.sleep(0.02)
    assert len(got) >= 2
    cam.close()  # camera reboots
    time.sleep(0.3)
    cam2 = None
    deadline = time.time() + 5
    while cam2 is None:
        try:
            cam2 = _Camera(frames, port=port)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)
    n_before = len(got)
    deadline = time.time() + 15
    while time.time() < deadline and len(got) <= n_before:
        time.sleep(0.05)
    src.close()
    cam2.close()
    assert len(got) > n_before, "frames never resumed after camera restart"
    assert got == sorted(got)  # frame counter kept increasing
