"""Sample portable plugin used by tests — analogue of the reference's
sdk/python/example/pysam plugin (mirror: revstr function, pyjson source,
file-writing sink)."""
import json
import time

from ekuiper_tpu.sdk import Function, Sink, Source, plugin_main


class Rev(Function):
    def exec(self, args, ctx):
        return str(args[0])[::-1]


class Add(Function):
    def validate(self, args):
        return "" if len(args) >= 2 else "add needs 2 args"

    def exec(self, args, ctx):
        return args[0] + args[1]


class CountSource(Source):
    def configure(self, datasource, conf):
        self.count = int(conf.get("count", 5))
        self.interval = float(conf.get("interval", 0.01))

    def open(self, emit, closed):
        for i in range(self.count):
            if closed():
                return
            emit({"seq": i, "val": i * 10})
            time.sleep(self.interval)


class FileSink(Sink):
    def configure(self, conf):
        self.path = conf["path"]

    def collect(self, data):
        with open(self.path, "a") as f:
            f.write(json.dumps(data) + "\n")


if __name__ == "__main__":
    plugin_main({
        "name": "sample",
        "functions": {"prev": Rev, "padd": Add},
        "sources": {"pycount": CountSource},
        "sinks": {"pyfile": FileSink},
    })
