#!/usr/bin/env python3
"""Byte-replay stand-in for a compiled Go SDK worker (sdk/go).

Installed with "language": "binary" so the engine execs it exactly like a
Go binary. It does NOT import the repo's ipc/sdk modules: transport is raw
unix sockets + 4-byte LE framing, re-implemented here straight from
docs/PLUGIN_WIRE_PROTOCOL.md the way sdk/go/connection/connection.go does,
and every worker->engine payload is the corresponding golden byte string
from frames.json — the exact bytes the Go runtime marshals. This proves the
Go SDK's wire bytes interoperate with the real engine side without a Go
toolchain in the image.

Engine->worker payloads are appended to $GO_WORKER_LOG (JSON lines) so the
test can assert what the engine actually sent.
"""
import json
import os
import socket
import struct
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
FRAMES = json.load(open(os.path.join(HERE, "frames.json")))
GOLD = {k: v.encode() for k, v in FRAMES["worker_to_engine"].items()}
LOG_PATH = os.environ.get("GO_WORKER_LOG", "")
_log_mu = threading.Lock()


def log_frame(channel, payload):
    if not LOG_PATH:
        return
    with _log_mu:
        with open(LOG_PATH, "a") as f:
            f.write(json.dumps({"channel": channel,
                                "payload": payload.decode()}) + "\n")


def runtime_dir():
    d = os.environ.get("EKUIPER_TPU_RUNTIME_DIR")
    if d:
        return d
    ns = os.environ.get("EKUIPER_TPU_IPC_NS", str(os.getpid()))
    return os.path.join("/tmp", f"ektpu_{ns}")


def dial(name, timeout=10.0):
    path = os.path.join(runtime_dir(), name + ".ipc")
    deadline = time.time() + timeout
    while True:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(path)
            return s
        except OSError:
            s.close()
            if time.time() > deadline:
                raise
            time.sleep(0.05)


def send_frame(s, payload):
    s.sendall(struct.pack("<I", len(payload)) + payload)


def recv_frame(s):
    hdr = b""
    while len(hdr) < 4:
        chunk = s.recv(4 - len(hdr))
        if not chunk:
            raise EOFError
        hdr += chunk
    n = struct.unpack("<I", hdr)[0]
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    return buf


def serve_function(sym):
    s = dial(f"func_{sym}")
    try:
        while True:
            raw = recv_frame(s)
            log_frame(f"func_{sym}", raw)
            req = json.loads(raw)
            fn = req.get("func")
            if fn == "Exec":
                # echo: mirror args[0]; the test invokes echo("abc") so the
                # golden reply bytes apply verbatim
                assert req["args"][0] == "abc", req
                send_frame(s, GOLD["reply_exec_echo"])
            elif fn == "Validate":
                send_frame(s, GOLD["reply_validate_ok"])
            elif fn == "IsAggregate":
                send_frame(s, GOLD["reply_is_aggregate"])
            else:
                send_frame(s, GOLD["reply_unknown_symbol"])
    except (EOFError, OSError):
        pass
    finally:
        s.close()


def serve_source(meta):
    tag = f"{meta.get('ruleId','r')}_{meta.get('opId','o')}_{meta.get('instanceId',0)}"
    s = dial(f"source_{tag}")
    try:
        for key in ("source_tuple_1", "source_tuple_2", "source_tuple_3"):
            send_frame(s, GOLD[key])
        time.sleep(5)  # hold the channel open until stopped
    except OSError:
        pass
    finally:
        s.close()


def serve_sink(meta):
    tag = f"{meta.get('ruleId','r')}_{meta.get('opId','o')}_{meta.get('instanceId',0)}"
    s = dial(f"sink_{tag}")
    try:
        while True:
            raw = recv_frame(s)
            log_frame(f"sink_{tag}", raw)
    except (EOFError, OSError):
        pass
    finally:
        s.close()


def main():
    ctrl = dial("plugin_gomirror", timeout=15.0)
    send_frame(ctrl, GOLD["handshake"])
    try:
        while True:
            raw = recv_frame(ctrl)
            log_frame("control", raw)
            cmd = json.loads(raw)
            op = cmd.get("cmd")
            c = cmd.get("ctrl") or {}
            sym = c.get("symbolName", "")
            if op == "start":
                kind = c.get("pluginType")
                if kind == "function" and sym == "echo":
                    threading.Thread(target=serve_function, args=(sym,),
                                     daemon=True).start()
                elif kind == "source" and sym == "random":
                    threading.Thread(target=serve_source,
                                     args=(c.get("meta") or {},),
                                     daemon=True).start()
                elif kind == "sink" and sym == "file":
                    threading.Thread(target=serve_sink,
                                     args=(c.get("meta") or {},),
                                     daemon=True).start()
                else:
                    send_frame(ctrl, GOLD["reply_unknown_symbol"])
                    continue
                send_frame(ctrl, GOLD["reply_ok"])
            elif op in ("stop", "ping"):
                send_frame(ctrl, GOLD["reply_ok"])
            else:
                send_frame(ctrl, GOLD["reply_unknown_symbol"])
    except (EOFError, OSError):
        pass
    finally:
        ctrl.close()


if __name__ == "__main__":
    sys.exit(main())
