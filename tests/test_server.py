"""REST API + rule registry + trial tests — modeled on the reference's FVT
suite (fvt/: boots the real server in-process, drives via an HTTP SDK)."""
import json
import socket
import time
import urllib.request

import pytest

from ekuiper_tpu.io import memory as mem
from ekuiper_tpu.server.rest import RestApi, serve
from ekuiper_tpu.store import kv


@pytest.fixture
def api():
    mem.reset()
    yield RestApi(kv.get_store())
    mem.reset()


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


STREAM_SQL = ('CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
              'WITH (DATASOURCE="t/demo", TYPE="memory")')


class TestDispatch:
    """Route-level tests (no socket)."""

    def test_stream_crud(self, api):
        code, res = api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        assert code == 201 and "created" in res
        code, res = api.dispatch("GET", "/streams", None)
        assert res == ["demo"]
        code, res = api.dispatch("GET", "/streams/demo", None)
        assert res["fields"][0]["name"] == "deviceId"
        code, res = api.dispatch("GET", "/streams/demo/schema", None)
        assert len(res) == 2
        code, res = api.dispatch("DELETE", "/streams/demo", None)
        assert code == 200
        code, res = api.dispatch("GET", "/streams/demo", None)
        assert code == 400 and "not found" in res["error"]

    def test_duplicate_stream(self, api):
        api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        code, res = api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        assert code == 400 and "already exists" in res["error"]

    def test_rule_lifecycle(self, api):
        api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        rule = {"id": "r1", "sql": "SELECT * FROM demo",
                "actions": [{"nop": {}}], "options": {"triggered": False}}
        code, res = api.dispatch("POST", "/rules", rule)
        assert code == 201
        code, res = api.dispatch("GET", "/rules", None)
        assert res[0]["id"] == "r1"
        code, res = api.dispatch("POST", "/rules/r1/start", None)
        assert code == 200
        deadline = time.time() + 5
        while time.time() < deadline:
            code, res = api.dispatch("GET", "/rules/r1/status", None)
            if res.get("status") == "running":
                break
            time.sleep(0.05)
        assert res["status"] == "running"
        code, res = api.dispatch("GET", "/rules/r1/explain", None)
        assert res["path"] in ("host", "device-fused")
        code, res = api.dispatch("GET", "/rules/r1/topo", None)
        assert "sources" in res
        # per-rule CPU-usage proxy (reference /rules/usage/cpu)
        import ekuiper_tpu.io.memory as _mem
        from ekuiper_tpu.utils import timex as _timex
        _mem.publish("t/demo", {"deviceId": "a", "temperature": 1.0})
        _timex.get_mock_clock().advance(20)  # linger flush
        deadline = time.time() + 5
        while time.time() < deadline:
            code, res = api.dispatch("GET", "/rules/usage/cpu", None)
            if code == 200 and res.get("r1", {}).get("total_ms", 0) > 0:
                break
            time.sleep(0.05)
        assert code == 200 and res["r1"]["total_ms"] > 0, res
        code, res = api.dispatch("POST", "/rules/r1/stop", None)
        assert code == 200
        code, res = api.dispatch("DELETE", "/rules/r1", None)
        assert code == 200
        code, res = api.dispatch("GET", "/rules", None)
        assert res == []

    def test_rule_validate(self, api):
        api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        code, res = api.dispatch("POST", "/rules/validate",
                                 {"id": "x", "sql": "SELECT * FROM demo"})
        assert res["valid"] is True
        code, res = api.dispatch("POST", "/rules/validate",
                                 {"id": "x", "sql": "SELECT * FROM missing"})
        assert res["valid"] is False and "not found" in res["error"]

    def test_bad_rule_rolls_back(self, api):
        # plan failure must not leave the definition behind
        code, res = api.dispatch("POST", "/rules",
                                 {"id": "bad", "sql": "SELECT * FROM missing"})
        assert code == 400
        code, res = api.dispatch("GET", "/rules", None)
        assert res == []

    def test_ruleset_roundtrip(self, api):
        api.dispatch("POST", "/streams", {"sql": STREAM_SQL})
        api.dispatch("POST", "/rules", {
            "id": "r1", "sql": "SELECT * FROM demo",
            "actions": [{"nop": {}}], "options": {"triggered": False},
        })
        code, doc = api.dispatch("GET", "/ruleset/export", None)
        assert "demo" in doc["streams"] and "r1" in doc["rules"]
        # import into a fresh store
        api2 = RestApi(kv.Store("memory"))
        code, res = api2.dispatch("POST", "/ruleset/import", doc)
        assert res == {"streams": 1, "tables": 0, "rules": 1, "scripts": 0}
        code, res = api2.dispatch("GET", "/streams", None)
        assert res == ["demo"]

    def test_404(self, api):
        code, res = api.dispatch("GET", "/bogus", None)
        assert code == 404


class TestHttpServer:
    """Over a real socket."""

    def test_end_to_end_http(self, api, mock_clock):
        port = free_port()
        server = serve(api, "127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"

        def call(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(base + path, data=data, method=method,
                                         headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read().decode())
            except urllib.error.HTTPError as exc:
                return exc.code, json.loads(exc.read().decode())

        try:
            code, info = call("GET", "/")
            assert code == 200 and info["engine"] == "ekuiper_tpu"
            code, _ = call("POST", "/streams", {"sql": STREAM_SQL})
            assert code == 201
            code, _ = call("POST", "/rules", {
                "id": "http_rule",
                "sql": "SELECT deviceId, temperature FROM demo WHERE temperature > 21",
                "actions": [{"memory": {"topic": "http_res"}}],
            })
            assert code == 201
            got = []
            mem.subscribe("http_res", lambda t, p: got.append(p))
            deadline = time.time() + 5
            while time.time() < deadline:
                _, status = call("GET", "/rules/http_rule/status")
                if status.get("status") == "running":
                    break
                time.sleep(0.05)
            mem.publish("t/demo", {"deviceId": "a", "temperature": 25.0})
            mock_clock.advance(20)
            deadline = time.time() + 5
            while not got and time.time() < deadline:
                time.sleep(0.02)
            assert got and got[0] == {"deviceId": "a", "temperature": 25.0}
            code, res = call("DELETE", "/rules/http_rule")
            assert code == 200
        finally:
            server.shutdown()

    def test_trial_over_http(self, api):
        port = free_port()
        server = serve(api, "127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"

        def call(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(base + path, data=data, method=method)
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read().decode())

        try:
            call("POST", "/streams", {"sql": STREAM_SQL})
            trial = call("POST", "/ruletest", {
                "sql": "SELECT deviceId, temperature * 2 AS t2 FROM demo",
                "mockSource": {"demo": {"data": [
                    {"deviceId": "a", "temperature": 1.0},
                    {"deviceId": "b", "temperature": 2.0},
                ], "interval": 0, "loop": False}},
            })
            tid = trial["id"]
            call("POST", f"/ruletest/{tid}/start")
            from ekuiper_tpu.utils import timex

            deadline = time.time() + 5
            results = []
            while time.time() < deadline:
                timex.get_mock_clock().advance(20)  # linger flush
                results = call("GET", f"/ruletest/{tid}")
                if results:
                    break
                time.sleep(0.05)
            call("DELETE", f"/ruletest/{tid}")
            flat = []
            for r in results:
                flat.extend(r if isinstance(r, list) else [r])
            assert {"deviceId": "a", "t2": 2.0} in flat
        finally:
            server.shutdown()
