"""ZMQ connector tests: ZMTP 3.0 wire conformance (golden greeting bytes
from rfc.zeromq.org/spec/23), PUB/SUB interop over real TCP, reconnects,
and a rule e2e — modeled on the reference zmq extension
(extensions/impl/zmq) and its test plugin (test/plugins/pub/zmq_pub.go)."""
import json
import struct
import socket
import time

import pytest

from ekuiper_tpu.io.zmq_io import ZmqSink, ZmqSource
from ekuiper_tpu.io.zmq_native import PubServer, SubClient, _greeting, _ready
from ekuiper_tpu.utils.infra import EngineError


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class TestZmtpWire:
    def test_greeting_layout(self):
        g = _greeting()
        assert len(g) == 64
        assert g[0] == 0xFF and g[9] == 0x7F          # signature
        assert g[10] == 3 and g[11] == 0              # version 3.0
        assert g[12:32] == b"NULL" + b"\x00" * 16     # mechanism
        assert g[32] == 0                             # as-server

    def test_ready_command_layout(self):
        body = _ready("SUB")
        assert body[:6] == b"\x05READY"
        nlen = body[6]
        assert body[7:7 + nlen] == b"Socket-Type"
        vlen = struct.unpack(">I", body[7 + nlen:11 + nlen])[0]
        assert body[11 + nlen:11 + nlen + vlen] == b"SUB"


class TestPubSub:
    def test_topic_filtering_and_multipart(self):
        pub = PubServer("tcp://127.0.0.1:0")
        got = []
        sub = SubClient(f"tcp://127.0.0.1:{pub.port}", "sensor",
                        lambda parts: got.append(parts))
        deadline = time.time() + 5
        while time.time() < deadline and pub.subscriber_count() < 1:
            time.sleep(0.02)
        time.sleep(0.2)  # let the subscribe frame land
        pub.send([b"sensor/1", b"hello"])
        pub.send([b"other", b"dropped"])
        pub.send([b"sensor/2", b"world"])
        deadline = time.time() + 5
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.02)
        sub.close()
        pub.close()
        assert got == [[b"sensor/1", b"hello"], [b"sensor/2", b"world"]]

    def test_large_frame(self):
        pub = PubServer("tcp://127.0.0.1:0")
        got = []
        sub = SubClient(f"tcp://127.0.0.1:{pub.port}", "",
                        lambda parts: got.append(parts))
        deadline = time.time() + 5
        while time.time() < deadline and pub.subscriber_count() < 1:
            time.sleep(0.02)
        time.sleep(0.2)
        big = b"x" * 100_000  # long-frame encoding (>255 bytes)
        pub.send([big])
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        sub.close()
        pub.close()
        assert got == [[big]]

    def test_sub_reconnects_after_pub_restart(self):
        port = _free_port()
        pub = PubServer(f"tcp://127.0.0.1:{port}")
        got = []
        sub = SubClient(f"tcp://127.0.0.1:{port}", "t",
                        lambda parts: got.append(parts))
        deadline = time.time() + 5
        while time.time() < deadline and pub.subscriber_count() < 1:
            time.sleep(0.02)
        pub.close()
        pub2 = None
        deadline = time.time() + 5
        while pub2 is None:
            try:
                pub2 = PubServer(f"tcp://127.0.0.1:{port}")
            except OSError:  # accepted sockets may linger briefly
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        deadline = time.time() + 20
        while time.time() < deadline and pub2.subscriber_count() < 1:
            time.sleep(0.05)
        # the subscribe frame races the reconnect under load — keep sending
        # until delivery (PUB drops pre-subscription sends by design)
        deadline = time.time() + 10
        while time.time() < deadline and not got:
            pub2.send([b"t", b"back"])
            time.sleep(0.1)
        sub.close()
        pub2.close()
        assert got and got[0] == [b"t", b"back"]


class TestConnector:
    def test_sink_to_source_roundtrip(self):
        sink = ZmqSink()
        sink.configure({"server": "tcp://127.0.0.1:0", "topic": "rules"})
        sink.connect()
        src = ZmqSource()
        src.configure("rules",
                      {"server": f"tcp://127.0.0.1:{sink._pub.port}"})
        got = []
        src.open(lambda payload, meta=None: got.append((payload, meta)))
        deadline = time.time() + 5
        while time.time() < deadline and sink._pub.subscriber_count() < 1:
            time.sleep(0.02)
        time.sleep(0.2)
        sink.collect({"a": 1})
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        src.close()
        sink.close()
        payload, meta = got[0]
        assert json.loads(payload) == {"a": 1}
        assert meta["topic"] == "rules"

    def test_no_topic_single_frame(self):
        sink = ZmqSink()
        sink.configure({"server": "tcp://127.0.0.1:0"})
        sink.connect()
        src = ZmqSource()
        src.configure("", {"server": f"tcp://127.0.0.1:{sink._pub.port}"})
        got = []
        src.open(lambda payload, meta=None: got.append((payload, meta)))
        deadline = time.time() + 5
        while time.time() < deadline and sink._pub.subscriber_count() < 1:
            time.sleep(0.02)
        time.sleep(0.2)
        sink.collect({"b": 2})
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        src.close()
        sink.close()
        assert json.loads(got[0][0]) == {"b": 2} and got[0][1] == {}

    def test_missing_server_errors(self):
        with pytest.raises(EngineError, match="server"):
            ZmqSource().configure("t", {})
        with pytest.raises(EngineError, match="server"):
            ZmqSink().configure({"topic": "t"})

    def test_rule_e2e_memory_to_zmq(self, mock_clock):
        """memory source -> SQL rule -> zmq sink action; a SUB client
        receives the rule output."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        port = _free_port()
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM zs (a FLOAT) '
            'WITH (DATASOURCE="t/z", TYPE="memory", FORMAT="JSON")')
        topo = plan_rule(RuleDef(
            id="zr1", sql="SELECT a * 2 AS b FROM zs",
            actions=[{"zmq": {"server": f"tcp://127.0.0.1:{port}",
                              "topic": "out"}}],
            options={}), store)
        got = []
        topo.open()
        try:
            sub = SubClient(f"tcp://127.0.0.1:{port}", "out",
                            lambda parts: got.append(parts))
            sink = topo.sinks[0]
            # the sink's PubServer binds lazily on first collect — feed one
            # row, then wait for the subscription to land and feed another
            mem.publish("t/z", {"a": 1.0})
            mock_clock.advance(20)  # memory-source linger flush
            time.sleep(0.5)
            deadline = time.time() + 25  # sub reconnect backoff can hit 5s
            while time.time() < deadline and not got:
                mem.publish("t/z", {"a": 21.0})
                mock_clock.advance(20)
                time.sleep(0.3)
        finally:
            sub.close()
            topo.close()
        vals = [json.loads(b"".join(p[1:])) for p in got]
        assert any(v.get("b") == 42.0 for v in vals), vals


class TestHandshakeFailure:
    def test_failed_handshake_releases_accepted_slot(self):
        """A peer that fails the ZMTP handshake must not leak its socket
        in _accepted (ADVICE r5 low: repeated failures grew the list until
        close)."""
        pub = PubServer("tcp://127.0.0.1:0")
        try:
            for _ in range(3):
                s = socket.create_connection(("127.0.0.1", pub.port),
                                             timeout=2)
                s.sendall(b"this is not a zmtp greeting at all" * 3)
                s.close()
            deadline = time.time() + 5
            while time.time() < deadline:
                with pub._mu:
                    if not pub._accepted:
                        break
                time.sleep(0.05)
            with pub._mu:
                assert not pub._accepted
            assert pub.subscriber_count() == 0
        finally:
            pub.close()
