"""Native columnar JSON decoder (native/jsoncol.cpp via io/fastjson.py):
parity with the Python decode→from_messages chain, fallback behavior, and
the SourceNode raw fast path end-to-end.
"""
import json

import numpy as np
import pytest

from ekuiper_tpu.data.batch import from_messages
from ekuiper_tpu.data.types import DataType, Field, Schema
from ekuiper_tpu.io import fastjson
from ekuiper_tpu.io.converters import JsonConverter
from ekuiper_tpu.runtime.nodes_source import SourceNode

SCHEMA = Schema(fields=[
    Field("deviceId", DataType.STRING),
    Field("temperature", DataType.FLOAT),
    Field("count", DataType.BIGINT),
    Field("ok", DataType.BOOLEAN),
])


@pytest.fixture(scope="module")
def native():
    fastjson.ensure_native(background=False)
    mod = fastjson._load()
    if mod is None:
        pytest.skip("native decoder unavailable (no toolchain)")
    return mod


def decode_both(payloads, schema=SCHEMA):
    spec = fastjson.schema_field_spec(schema)
    assert spec is not None
    out = fastjson.decode_columns(payloads, spec)
    msgs = []
    for p in payloads:
        try:
            msgs.append(json.loads(p))
        except Exception:
            msgs.append(None)
    good = [m for m in msgs if isinstance(m, dict)]
    ref, _ = from_messages(good, [0] * len(good), schema=schema)
    return out, ref


class TestNativeParity:
    def test_basic_types(self, native):
        payloads = [
            json.dumps({"deviceId": "d1", "temperature": 21.5,
                        "count": 7, "ok": True}).encode(),
            json.dumps({"deviceId": "d2", "temperature": -3.25,
                        "count": -12, "ok": False}).encode(),
        ]
        (cols, valid, bad), ref = decode_both(payloads)
        assert not bad.any()
        np.testing.assert_array_equal(cols["deviceId"], ref.columns["deviceId"])
        np.testing.assert_allclose(cols["temperature"],
                                   ref.columns["temperature"])
        np.testing.assert_array_equal(cols["count"], ref.columns["count"])
        np.testing.assert_array_equal(cols["ok"], ref.columns["ok"])

    def test_nulls_and_missing(self, native):
        payloads = [
            b'{"deviceId": null, "temperature": 1.0}',
            b'{"count": 3}',
        ]
        (cols, valid, bad), ref = decode_both(payloads)
        assert not bad.any()
        assert not valid["deviceId"].any()
        assert valid["temperature"].tolist() == [True, False]
        assert np.isnan(cols["temperature"][1])
        assert valid["count"].tolist() == [False, True]

    def test_numeric_strings_coerce(self, native):
        payloads = [b'{"temperature": "21.5", "count": "42", "ok": "true"}']
        (cols, valid, bad), ref = decode_both(payloads)
        assert not bad.any()
        assert cols["temperature"][0] == pytest.approx(21.5)
        assert cols["count"][0] == 42
        assert cols["ok"][0]

    def test_number_to_string_matches_python(self, native):
        payloads = [b'{"deviceId": 5.0}', b'{"deviceId": 2.5}',
                    b'{"deviceId": 17}', b'{"deviceId": true}']
        (cols, valid, bad), ref = decode_both(payloads)
        assert cols["deviceId"].tolist() == ["5", "2.5", "17", "true"]
        assert cols["deviceId"].tolist() == ref.columns["deviceId"].tolist()

    def test_bad_rows_marked(self, native):
        payloads = [b'{"count": 1}', b'not json', b'{"count": {"a": 1}}',
                    b'{"count": "xyz"}']
        (cols, valid, bad), _ = decode_both(payloads)
        assert bad.tolist() == [False, True, True, True]

    def test_escapes_and_unicode(self, native):
        s = 'a"b\\c\ndé☃\U0001F600'
        payloads = [json.dumps({"deviceId": s}).encode()]
        (cols, valid, bad), _ = decode_both(payloads)
        assert cols["deviceId"][0] == s

    def test_invalid_utf8_is_bad_row_like_python(self, native):
        # json.loads raises on these bytes -> python path drops the row;
        # the native path must classify them the same (not U+FFFD-replace)
        payloads = [b'{"deviceId": "ok"}',
                    b'{"deviceId": "\xff\xfe"}',      # not UTF-8
                    b'{"deviceId": "\xed\xa0\x80"}']  # raw surrogate bytes OK
        spec = fastjson.schema_field_spec(SCHEMA)
        cols, valid, bad = fastjson.decode_columns(payloads, spec)
        assert not bad[0] and bad[1]
        assert not bad[2]  # surrogatepass keeps raw-surrogate bytes decodable
        assert cols["deviceId"][0] == "ok"
        assert cols["deviceId"][2] == "\ud800"

    def test_lone_surrogate_escape_matches_python(self, native):
        # valid JSON: json.loads keeps the lone surrogate in the string
        payloads = [b'{"deviceId": "x\\ud800y"}']
        (cols, valid, bad), ref = decode_both(payloads)
        assert not bad.any()
        assert cols["deviceId"][0] == json.loads(payloads[0])["deviceId"]
        assert cols["deviceId"][0] == ref.columns["deviceId"][0]

    def test_plus_prefixed_number_is_bad_like_python(self, native):
        payloads = [b'{"count": +5}', b'{"other": +5}', b'{"count": 5}']
        (cols, valid, bad), _ = decode_both(payloads)
        assert bad.tolist() == [True, True, False]
        assert cols["count"][2] == 5

    def test_bytearray_payloads_are_copied_safely(self, native):
        # bytearrays can be resized by another thread while the GIL-free
        # parse runs; the decoder must copy them at prefetch time
        payloads = [bytearray(b'{"deviceId": "ba", "temperature": 1.5}'),
                    b'{"deviceId": "b2", "temperature": 2.5}']
        spec = fastjson.schema_field_spec(SCHEMA)
        cols, valid, bad = fastjson.decode_columns(payloads, spec)
        assert not bad.any()
        assert cols["deviceId"].tolist() == ["ba", "b2"]
        assert cols["temperature"][0] == pytest.approx(1.5)

    def test_interning_reuses_objects(self, native):
        payloads = [b'{"deviceId": "dev_1"}'] * 100
        (cols, _, _), _ = decode_both(payloads)
        assert all(v is cols["deviceId"][0] for v in cols["deviceId"])

    def test_int64_overflow_falls_back(self, native):
        spec = fastjson.schema_field_spec(SCHEMA)
        out = fastjson.decode_columns(
            [b'{"count": 99999999999999999999999}'], spec)
        assert out is None  # Fallback -> python path handles bigints

    def test_undeclared_nested_fields_skipped(self, native):
        payloads = [
            b'{"extra": {"deep": [1, {"x": "y"}]}, "count": 5, '
            b'"more": [true, null, "s"]}'
        ]
        (cols, valid, bad), _ = decode_both(payloads)
        assert not bad.any()
        assert cols["count"][0] == 5

    def test_schema_spec_gates(self):
        assert fastjson.schema_field_spec(None) is None
        assert fastjson.schema_field_spec(
            Schema(fields=[Field("a", DataType.ARRAY)])) is None
        assert fastjson.schema_field_spec(
            Schema(fields=[Field("a", DataType.BIGINT)])) is not None


class TestSourceFastPath:
    def make_source(self, timestamp_field=""):
        src = SourceNode(
            "s", connector=type("C", (), {
                "open": lambda self, cb: None,
                "close": lambda self: None})(),
            schema=SCHEMA, converter=JsonConverter(),
            micro_batch_rows=1000, timestamp_field=timestamp_field)
        got = []
        src.broadcast = lambda item: got.append(item)
        return src, got

    def test_raw_bytes_batch_to_columns(self, native):
        src, got = self.make_source()
        assert src._fast_spec is not None
        drain = [json.dumps({"deviceId": f"d{i % 3}", "temperature": 1.0 * i,
                             "count": i, "ok": i % 2 == 0}).encode()
                 for i in range(10)]
        src.ingest(drain)
        src._flush()
        assert len(got) == 1
        cb = got[0]
        assert cb.n == 10
        assert cb.columns["deviceId"][3] == "d0"
        assert cb.columns["count"].dtype == np.int64

    def test_aligned_flush_keeps_remainder_until_linger(self, native,
                                                        mock_clock):
        """An over-threshold raw drain flushes micro_batch-aligned slices
        (the fused kernel pads every chunk to a static micro-batch shape,
        so misaligned tails would upload ~2x the bytes) and the linger
        timer drains the remainder without losing rows."""
        src = SourceNode(
            "s", connector=type("C", (), {
                "open": lambda self, cb: None,
                "close": lambda self: None})(),
            schema=SCHEMA, converter=JsonConverter(),
            micro_batch_rows=8, linger_ms=20)
        got = []
        src.broadcast = lambda item: got.append(item)
        drain = [json.dumps({"deviceId": f"d{i}", "count": i}).encode()
                 for i in range(23)]
        src.ingest(drain)
        assert [b.n for b in got] == [16]  # aligned cut, remainder pending
        mock_clock.advance(20)
        assert [b.n for b in got] == [16, 7]
        ids = [d for b in got for d in b.columns["deviceId"].tolist()]
        assert ids == [f"d{i}" for i in range(23)]  # order, no loss

    def test_bad_rows_dropped_and_counted(self, native):
        src, got = self.make_source()
        src.ingest([b'{"count": 1}', b'garbage', b'{"count": 2}'])
        src._flush()
        assert got[0].n == 2
        assert src.stats.exceptions >= 1

    def test_event_time_int64_column(self, native):
        schema = Schema(fields=[Field("deviceId", DataType.STRING),
                                Field("ts", DataType.BIGINT)])
        src = SourceNode(
            "s", connector=type("C", (), {
                "open": lambda self, cb: None,
                "close": lambda self: None})(),
            schema=schema, converter=JsonConverter(),
            micro_batch_rows=1000, timestamp_field="ts")
        got = []
        src.broadcast = lambda item: got.append(item)
        assert src._fast_spec is not None
        src.ingest([b'{"deviceId": "a", "ts": 1234}',
                    b'{"deviceId": "b"}'])  # missing ts -> dropped
        src._flush()
        assert got[0].n == 1
        assert got[0].timestamps[0] == 1234

    def test_mixed_dict_and_raw_pendings(self, native):
        src, got = self.make_source()
        src.ingest({"deviceId": "x", "count": 1})
        src.ingest([b'{"deviceId": "y", "count": 2}'])
        src._flush()
        names = [cb.columns["deviceId"][0] for cb in got]
        assert set(names) == {"x", "y"}


class TestFromMessages:
    """Columnar preprocessor parity (data/batch.py from_messages)."""

    def test_typed_bulk_and_fallback(self):
        sch = Schema(fields=[Field("a", DataType.BIGINT),
                             Field("b", DataType.FLOAT)])
        msgs = [{"a": 1, "b": 2.5}, {"a": "3", "b": "4.5"}, {"a": None}]
        cb, drop = from_messages(msgs, [0, 1, 2], schema=sch)
        assert drop == 0
        assert cb.columns["a"].tolist() == [1, 3, 0]
        assert cb.valid["a"].tolist() == [True, True, False]
        assert cb.columns["b"][1] == pytest.approx(4.5)
        assert np.isnan(cb.columns["b"][2])

    def test_uncastable_row_drops(self):
        sch = Schema(fields=[Field("a", DataType.BIGINT)])
        errs = []
        cb, drop = from_messages(
            [{"a": 1}, {"a": "zebra"}, {"a": 2}], [0, 1, 2], schema=sch,
            on_error=lambda m, n=1: errs.append(m))
        assert drop == 1
        assert cb.n == 2 and cb.columns["a"].tolist() == [1, 2]
        assert errs

    def test_big_int_fallback_to_object(self):
        sch = Schema(fields=[Field("a", DataType.BIGINT)])
        big = 99999999999999999999999
        cb, drop = from_messages([{"a": big}, {"a": 1}], [0, 1], schema=sch)
        assert drop == 0
        assert cb.columns["a"][0] == big

    def test_timestamp_extraction_paths(self):
        sch = Schema(fields=[Field("ts", DataType.BIGINT)])
        cb, drop = from_messages(
            [{"ts": 5000}, {"ts": 6000}], [1, 2], schema=sch,
            timestamp_field="ts")
        assert cb.timestamps.tolist() == [5000, 6000]
        # missing -> drop
        cb, drop = from_messages(
            [{"ts": 5000}, {}], [1, 2], schema=sch, timestamp_field="ts")
        assert drop == 1 and cb.n == 1
        # iso string timestamps take the per-value path
        sch2 = Schema(fields=[Field("ts", DataType.STRING)])
        cb, drop = from_messages(
            [{"ts": "1970-01-01T00:00:10"}], [0], schema=sch2,
            timestamp_field="ts")
        assert cb.timestamps[0] == 10_000

    def test_schemaless_inference_with_project(self):
        cb, drop = from_messages(
            [{"a": 1, "b": "x", "c": 2.0}, {"a": 2}], [0, 1],
            schema=None, project={"a", "b"})
        assert set(cb.columns) == {"a", "b"}
        assert cb.columns["a"].dtype == np.int64


class TestReviewRegressions:
    def test_strict_streams_skip_fast_path(self):
        src = SourceNode(
            "s", connector=type("C", (), {
                "open": lambda self, cb: None,
                "close": lambda self: None})(),
            schema=SCHEMA, converter=JsonConverter(),
            micro_batch_rows=1000, strict_validation=True)
        assert src._fast_spec is None

    def test_array_payload_expands_rows(self, native):
        src, got = TestSourceFastPath().make_source()
        src.ingest([b'[{"count": 1}, {"count": 2}]', b'{"count": 3}'])
        src._flush()
        total = sum(cb.n for cb in got)
        assert total == 3  # array payloads expand via the python fallback

    def test_heterogeneous_list_does_not_crash(self, native):
        src, got = TestSourceFastPath().make_source()
        src.ingest([b'{"count": 1}', {"count": 2}])  # mixed bytes + dict
        src._flush()
        assert sum(cb.n for cb in got) == 2

    def test_tuple_timestamp_preserved_in_batch_mode(self):
        from ekuiper_tpu.data.rows import Tuple as Row

        src, got = TestSourceFastPath().make_source()
        src.ingest(Row(emitter="s", message={"count": 5}, timestamp=777))
        src._flush()
        assert got[0].timestamps[0] == 777

    def test_empty_object_with_trailing_garbage_is_bad(self, native):
        src, got = TestSourceFastPath().make_source()
        src.ingest([b'{} trailing', b'{}', b'{"count": 1}'])
        src._flush()
        # '{} trailing' drops; bare '{}' is a legal all-null row
        assert sum(cb.n for cb in got) == 2

    def test_interner_many_unique_strings_stable(self, native):
        # regression: storage growth must not dangle intern keys
        payloads = [json.dumps({"deviceId": f"dev_{i}"}).encode()
                    for i in range(5000)] * 2
        spec = fastjson.schema_field_spec(SCHEMA)
        cols, valid, bad = fastjson.decode_columns(payloads, spec)
        assert not bad.any()
        got = cols["deviceId"].tolist()
        assert got[:5000] == [f"dev_{i}" for i in range(5000)]
        assert got[5000:] == got[:5000]
