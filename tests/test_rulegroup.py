"""plan_rule_group: N homogeneous rules as one topology with a vmapped
kernel — output parity vs the same rules planned individually."""
import time

import numpy as np
import pytest

from ekuiper_tpu.planner.planner import (
    PlanError, RuleDef, plan_rule, plan_rule_group)
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _mk_stream(store):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="t/grp", TYPE="memory", FORMAT="JSON")'
    )


def _rule(rid, thresh):
    return RuleDef(
        id=rid,
        sql=(f"SELECT deviceId, avg(temperature) AS a, count(*) AS c "
             f"FROM demo WHERE temperature > {thresh} "
             f"GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        actions=[{"memory": {"topic": f"grp/{rid}"}}],
        options={},
    )


def _drain(sink):
    out = []
    for item in list(sink.results):
        items = item if isinstance(item, list) else [item]
        for m in items:
            out.append(m)
    return out


class TestRuleGroup:
    def test_group_matches_individual_rules(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        rules = [_rule(f"g{i}", t) for i, t in enumerate([10.0, 20.0, 28.0])]
        topo = plan_rule_group("grp", rules, store)
        sinks = {n.name: n for n in topo.sinks}
        assert len(topo.sinks) == 3
        topo.open()
        try:
            rows = [("a", 15.0), ("a", 25.0), ("b", 30.0), ("b", 12.0),
                    ("c", 22.0)]
            for d, t in rows:
                mem.publish("t/grp", {"deviceId": d, "temperature": t})
            mock_clock.advance(20)  # micro-batch linger
            time.sleep(0.3)
            mock_clock.advance(10_000)  # window fires
            deadline = time.time() + 8
            while time.time() < deadline and sum(
                len(s.results) for s in topo.sinks
            ) < 3:
                time.sleep(0.02)
        finally:
            topo.close()
        # expected per threshold
        def expect(th):
            by = {}
            for d, t in rows:
                if t > th:
                    by.setdefault(d, []).append(t)
            return {d: (round(sum(v) / len(v), 4), len(v))
                    for d, v in by.items()}

        got = []
        for s in topo.sinks:
            got.append({m["deviceId"]: (round(m["a"], 4), m["c"])
                        for m in _drain(s)})
        # sinks are in rule order
        assert got[0] == expect(10.0)
        assert got[1] == expect(20.0)
        assert got[2] == expect(28.0)

    def test_heterogeneous_group_rejected(self):
        store = kv.get_store()
        _mk_stream(store)
        bad = RuleDef(
            id="bad",
            sql=("SELECT deviceId, sum(temperature) AS a FROM demo "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "grp/bad"}}], options={},
        )
        with pytest.raises(PlanError):
            plan_rule_group("grp2", [_rule("g0", 10.0), bad], store)


class TestHeterogeneousFanout:
    """Heterogeneous fan-out (bench.py _hetero_main shape): families with
    DIFFERENT statements each plan as their own vmapped group, individual
    rules as their own fused nodes, all riding ONE shared source subtopo."""

    def test_families_and_solos_share_one_source(self, mock_clock):
        mem.reset()
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM het (deviceId STRING, temperature FLOAT, '
            'pressure FLOAT) '
            'WITH (DATASOURCE="t/het", TYPE="memory", FORMAT="JSON")')
        fam_a = [RuleDef(
            id=f"a{i}",
            sql=("SELECT deviceId, count(*) AS c FROM het "
                 f"WHERE temperature > {10 + i} "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": f"het/a{i}"}}], options={})
            for i in range(3)]
        fam_b = [RuleDef(
            id=f"b{i}",
            sql=("SELECT deviceId, max(pressure) AS mx FROM het "
                 f"WHERE pressure > {0.1 * i} "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": f"het/b{i}"}}], options={})
            for i in range(3)]
        solo = RuleDef(
            id="s0",
            sql=("SELECT deviceId, avg(temperature) AS a FROM het "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "het/s0"}}], options={})
        topos = [plan_rule_group("ga", fam_a, store),
                 plan_rule_group("gb", fam_b, store),
                 plan_rule(solo, store)]
        sinks = {t.rule_id: t.sinks for t in topos}
        for t in topos:
            t.open()
        try:
            shared = {id(t._live_shared[0][0]) for t in topos
                      if t._live_shared}
            assert len(shared) == 1  # ONE physical source for all three
            rows = [{"deviceId": "d1", "temperature": 20.0, "pressure": 0.5},
                    {"deviceId": "d1", "temperature": 12.0, "pressure": 0.05},
                    {"deviceId": "d2", "temperature": 30.0, "pressure": 0.9}]
            for r in rows:
                mem.publish("t/het", r)
            mock_clock.advance(20)
            time.sleep(0.4)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not all(
                    s.results for ss in sinks.values() for s in ss):
                time.sleep(0.05)
        finally:
            for t in topos:
                t.close()
            mem.reset()
        # family A rule a0 (temp > 10): d1 x2, d2 x1
        a0 = {m["deviceId"]: m for m in _drain(sinks["ga"][0])}
        assert a0["d1"]["c"] == 2 and a0["d2"]["c"] == 1
        # a2 (temp > 12): d1 x1 (20.0), d2 x1
        a2 = {m["deviceId"]: m for m in _drain(sinks["ga"][2])}
        assert a2["d1"]["c"] == 1 and a2["d2"]["c"] == 1
        # family B rule b2 (pressure > 0.2): d1 max 0.5, d2 max 0.9
        b2 = {m["deviceId"]: m for m in _drain(sinks["gb"][2])}
        assert b2["d1"]["mx"] == pytest.approx(0.5)
        assert b2["d2"]["mx"] == pytest.approx(0.9)
        # solo avg
        s0 = {m["deviceId"]: m for m in _drain(sinks["s0"][0])}
        assert s0["d1"]["a"] == pytest.approx(16.0)
