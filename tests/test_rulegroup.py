"""plan_rule_group: N homogeneous rules as one topology with a vmapped
kernel — output parity vs the same rules planned individually."""
import time

import numpy as np
import pytest

from ekuiper_tpu.planner.planner import (
    PlanError, RuleDef, plan_rule, plan_rule_group)
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _mk_stream(store):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="t/grp", TYPE="memory", FORMAT="JSON")'
    )


def _rule(rid, thresh):
    return RuleDef(
        id=rid,
        sql=(f"SELECT deviceId, avg(temperature) AS a, count(*) AS c "
             f"FROM demo WHERE temperature > {thresh} "
             f"GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        actions=[{"memory": {"topic": f"grp/{rid}"}}],
        options={},
    )


def _drain(sink):
    out = []
    for item in list(sink.results):
        items = item if isinstance(item, list) else [item]
        for m in items:
            out.append(m)
    return out


class TestRuleGroup:
    def test_group_matches_individual_rules(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        rules = [_rule(f"g{i}", t) for i, t in enumerate([10.0, 20.0, 28.0])]
        topo = plan_rule_group("grp", rules, store)
        sinks = {n.name: n for n in topo.sinks}
        assert len(topo.sinks) == 3
        topo.open()
        try:
            rows = [("a", 15.0), ("a", 25.0), ("b", 30.0), ("b", 12.0),
                    ("c", 22.0)]
            for d, t in rows:
                mem.publish("t/grp", {"deviceId": d, "temperature": t})
            mock_clock.advance(20)  # micro-batch linger
            time.sleep(0.3)
            mock_clock.advance(10_000)  # window fires
            deadline = time.time() + 8
            while time.time() < deadline and sum(
                len(s.results) for s in topo.sinks
            ) < 3:
                time.sleep(0.02)
        finally:
            topo.close()
        # expected per threshold
        def expect(th):
            by = {}
            for d, t in rows:
                if t > th:
                    by.setdefault(d, []).append(t)
            return {d: (round(sum(v) / len(v), 4), len(v))
                    for d, v in by.items()}

        got = []
        for s in topo.sinks:
            got.append({m["deviceId"]: (round(m["a"], 4), m["c"])
                        for m in _drain(s)})
        # sinks are in rule order
        assert got[0] == expect(10.0)
        assert got[1] == expect(20.0)
        assert got[2] == expect(28.0)

    def test_heterogeneous_group_rejected(self):
        store = kv.get_store()
        _mk_stream(store)
        bad = RuleDef(
            id="bad",
            sql=("SELECT deviceId, sum(temperature) AS a FROM demo "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "grp/bad"}}], options={},
        )
        with pytest.raises(PlanError):
            plan_rule_group("grp2", [_rule("g0", 10.0), bad], store)
