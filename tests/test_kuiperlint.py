"""kuiperlint (tools/kuiperlint/) — the invariant lint suite itself.

Two layers, mirroring test_metrics_lint.py's "the lint must both pass
on the tree AND provably catch violations" contract:

 * tier-1 gate: `python -m tools.kuiperlint ekuiper_tpu/` exits 0 on
   the real tree (every suppression pragma justified);
 * per-rule fixtures: for EVERY pass, a seeded violation fires and a
   justified pragma suppresses it (an allowlist that silently eats the
   violation would pass the gate vacuously).

Also covers the dynamic twin (ekuiper_tpu/utils/lockcheck.py): the
runtime acquisition-order graph flags an exercised ABBA, and
Condition.wait() bookkeeping never fabricates edges.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools import kuiperlint  # noqa: E402
from tools.kuiperlint import run as lint_run  # noqa: E402


def lint_tree(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint the tree with
    pass scopes anchored there. Returns the violation list."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    vs, n = lint_run([str(tmp_path)], root=tmp_path, rules=rules)
    assert n == len(files)
    return vs


def rules_of(violations):
    return {v.rule for v in violations}


# ------------------------------------------------------------- tier-1 gate
class TestTreeGate:
    def test_engine_tree_is_clean(self):
        """THE gate: the shipped tree lints clean (acceptance criterion —
        wired tier-1 exactly like test_metrics_lint / check_native)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint", "ekuiper_tpu/"],
            capture_output=True, text=True, timeout=300, cwd=str(REPO))
        assert proc.returncode == 0, (
            f"kuiperlint violations on the tree:\n{proc.stdout}\n"
            f"{proc.stderr}")
        assert "OK" in proc.stdout

    def test_cli_json_and_exit_codes(self, tmp_path):
        (tmp_path / "ekuiper_tpu" / "runtime").mkdir(parents=True)
        (tmp_path / "ekuiper_tpu" / "runtime" / "m.py").write_text(
            "import time\ntime.time()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint", "--json",
             "--root", str(tmp_path), str(tmp_path)],
            capture_output=True, text=True, timeout=120, cwd=str(REPO))
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["ok"] is False
        assert payload["violations"][0]["rule"] == "clock-discipline"
        # unknown rule -> usage error, not a silent pass
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint",
             "--rules", "no-such-rule", "ekuiper_tpu/"],
            capture_output=True, text=True, timeout=120, cwd=str(REPO))
        assert proc.returncode == 2

    def test_every_documented_pass_registered(self):
        names = set(kuiperlint.all_passes())
        assert {"clock-discipline", "jit-coverage", "lock-order",
                "host-sync", "donation-safety", "metric-hygiene",
                "cert-coverage", "sig-stability"} <= names


# --------------------------------------------------------- clock-discipline
class TestClockDiscipline:
    def test_seeded_violation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\nt0 = time.time()\n",
        })
        assert [v.rule for v in vs] == ["clock-discipline"]
        assert vs[0].line == 2

    def test_alias_and_from_import_resolve(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import time as _time
                from time import monotonic
                _time.sleep(1)
                monotonic()
            """,
        })
        assert [v.rule for v in vs] == ["clock-discipline"] * 2

    def test_perf_counter_stays_legal(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\nd = time.perf_counter()\n",
        }) == []

    def test_justified_pragma_suppresses(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "t = time.time()  # kuiperlint: ignore[clock-discipline]:"
                " real-thread deadline\n",
        })
        assert vs == []

    def test_unjustified_pragma_is_itself_a_violation(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "t = time.time()  # kuiperlint: ignore[clock-discipline]\n",
        })
        # an unjustified pragma does NOT suppress: both the hygiene
        # violation and the underlying one surface
        assert rules_of(vs) == {"pragma-hygiene", "clock-discipline"}

    def test_own_line_pragma_covers_next_line(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "# kuiperlint: ignore[clock-discipline]: wall poll\n"
                "t = time.time()\n",
        })
        assert vs == []

    def test_plugin_and_tools_allowlisted(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/plugin/ipc.py": "import time\ntime.sleep(1)\n",
            "ekuiper_tpu/tools/cli.py": "import time\ntime.time()\n",
            "ekuiper_tpu/io/src.py": "import time\ntime.time()\n",
        }) == []


# ------------------------------------------------------------- jit-coverage
class TestJitCoverage:
    def test_seeded_violation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py":
                "import jax\nfold = jax.jit(lambda s: s)\n",
        })
        assert [v.rule for v in vs] == ["jit-coverage"]

    def test_bare_decorator_fires(self, tmp_path):
        """`@jax.jit` with no parentheses is an Attribute in the
        decorator list, not a Call — the most common jit shape (review
        regression: it escaped the pass entirely)."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import jax

                @jax.jit
                def kernel(x):
                    return x
            """,
        })
        assert [v.rule for v in vs] == ["jit-coverage"]
        assert "decorator" in vs[0].message

    def test_partial_jit_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import functools
                import jax
                mk = functools.partial(jax.jit, donate_argnums=0)
            """,
        })
        assert [v.rule for v in vs] == ["jit-coverage"]

    def test_watched_jit_and_devwatch_allowed(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/ok.py":
                "from ekuiper_tpu.observability.devwatch import"
                " watched_jit\nfold = watched_jit(lambda s: s, op='groupby.fold')\n",
            "ekuiper_tpu/observability/devwatch.py":
                "import jax\n_impl = jax.jit(lambda s: s)\n",
        }) == []

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py":
                "import jax\n"
                "f = jax.jit(g)  # kuiperlint: ignore[jit-coverage]:"
                " bench-only microkernel, not an engine site\n",
        }) == []


# --------------------------------------------------------------- lock-order
class TestCertCoverage:
    """ISSUE 10: every watched_jit site in ops//parallel/ must resolve
    to a registered jitcert derivation."""

    def test_rogue_op_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit
                f = watched_jit(lambda s: s, op="rogue.site")
            """,
        }, rules=["cert-coverage"])
        assert [v.rule for v in vs] == ["cert-coverage"]
        assert "rogue.site" in vs[0].message

    def test_unresolvable_op_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit
                name = "dyn" + "amic"
                f = watched_jit(lambda s: s, op=name)
            """,
        }, rules=["cert-coverage"])
        assert [v.rule for v in vs] == ["cert-coverage"]
        assert "not statically resolvable" in vs[0].message

    def test_watch_op_with_literal_prefix_resolves(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class K:
                    watch_prefix = "groupby"

                    def _watch_op(self, s):
                        return f"{self.watch_prefix}.{s}"

                    def _fold_impl(self, state):
                        return state

                    def build(self):
                        return watched_jit(self._fold_impl,
                                           op=self._watch_op("fold"))
            """,
        }, rules=["cert-coverage"]) == []

    def test_watch_prefix_chases_same_file_base(self, tmp_path):
        """ShardedGroupBy-style: the subclass overrides watch_prefix;
        a subclass WITHOUT one inherits the base's literal."""
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/parallel/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Base:
                    watch_prefix = "sharded"

                    def _watch_op(self, s):
                        return f"{self.watch_prefix}.{s}"

                class Sub(Base):
                    def _step(self, state):
                        return state

                    def build(self):
                        return watched_jit(self._step,
                                           op=self._watch_op("fold_step"))
            """,
        }, rules=["cert-coverage"]) == []

    def test_outside_scope_ignored(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit
                f = watched_jit(lambda s: s, op="rogue.site")
            """,
        }, rules=["cert-coverage"]) == []

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit
                # kuiperlint: ignore[cert-coverage]: experimental site, certified next PR
                f = watched_jit(lambda s: s, op="rogue.site")
            """,
        }, rules=["cert-coverage"]) == []


class TestSigStability:
    """ISSUE 10: signature-unstable idioms inside jit bodies."""

    def test_traced_value_branch_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                def _impl(state, n):
                    if n > 3:
                        return state
                    return state

                f = watched_jit(_impl, op="groupby.fold")
            """,
        }, rules=["sig-stability"])
        assert [v.rule for v in vs] == ["sig-stability"]
        assert "branches on traced value 'n'" in vs[0].message

    def test_len_slice_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                def _impl(state, rows):
                    return state[:len(rows)]

                f = watched_jit(_impl, op="groupby.fold")
            """,
        }, rules=["sig-stability"])
        assert [v.rule for v in vs] == ["sig-stability"]
        assert "len()" in vs[0].message

    def test_scalar_closure_capture_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                def build():
                    out = []
                    for i in range(3):
                        out.append(watched_jit(lambda s: s * i,
                                               op="groupby.fold"))
                    return out
            """,
        }, rules=["sig-stability"])
        assert [v.rule for v in vs] == ["sig-stability"]
        assert "loop variable 'i'" in vs[0].message

    def test_taint_propagates_through_helper(self, tmp_path):
        """The entry body delegates to a same-class helper; branching on
        the traced value there must still fire."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class K:
                    def _impl(self, state, n):
                        return self._helper(state, n)

                    def _helper(self, st, count):
                        if count > 2:
                            return st
                        return st

                    def build(self):
                        return watched_jit(self._impl, op="groupby.fold")
            """,
        }, rules=["sig-stability"])
        assert [v.rule for v in vs] == ["sig-stability"]

    def test_static_forms_stay_legal(self, tmp_path):
        """Structure/shape tests and config closures are the engine's
        normal idiom (DeviceGroupBy._fold_core, sharded factories)."""
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class K:
                    def __init__(self, plan, mesh):
                        self.plan = plan
                        self.mesh = mesh

                    def _impl(self, state, mask, pane_idx):
                        if mask is not None:
                            state = state + 1
                        if getattr(pane_idx, "ndim", 0) == 1:
                            state = state + 2
                        if state.shape[0] > 4:
                            state = state + 3
                        if self.plan is not None:
                            state = state + 4
                        for comp in sorted(state.keys()):
                            pass
                        return state

                    def build(self):
                        plan = self.plan
                        specs = {"a": 1}

                        def step(state, mask):
                            if plan is not None:
                                return self._impl(state, mask, 0)
                            return state

                        return watched_jit(step, op="groupby.fold")
            """,
        }, rules=["sig-stability"]) == []

    def test_untainted_helper_params_stay_legal(self, tmp_path):
        """A helper called with a STATIC argument (loop var over plan
        config) may branch on it — only traced positions taint."""
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class K:
                    def _impl(self, state):
                        for comp in ("mn", "mx"):
                            state = self._merged(state, comp)
                        return state

                    def _merged(self, state, comp):
                        if comp == "mn":
                            return state
                        return state

                    def build(self):
                        return watched_jit(self._impl, op="groupby.fold")
            """,
        }, rules=["sig-stability"]) == []

    def test_sibling_nested_function_does_not_poison_closure_check(
            self, tmp_path):
        """Review regression: a sibling nested function's loop variable
        is a different scope — a jit body referencing an identically
        named enclosing CONFIG binding must not be flagged."""
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                def build(plan):
                    def unrelated():
                        for i in range(3):
                            pass
                        scale = 2.0
                        return scale

                    i = plan
                    scale = plan

                    def step(state):
                        return state + i + scale

                    return watched_jit(step, op="groupby.fold")
            """,
        }, rules=["sig-stability"]) == []

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                def _impl(state, n):
                    # kuiperlint: ignore[sig-stability]: bounded two-way respecialization, certified
                    if n > 3:
                        return state
                    return state

                f = watched_jit(_impl, op="groupby.fold")
            """,
        }, rules=["sig-stability"]) == []


class TestLockOrder:
    ABBA = """\
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """

    def test_seeded_abba_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {"ekuiper_tpu/runtime/m.py": self.ABBA})
        assert rules_of(vs) == {"lock-order"}
        assert "cycle" in vs[0].message

    def test_consistent_order_clean(self, tmp_path):
        src = self.ABBA.replace("with self._b:\n                    "
                                "with self._a:",
                                "with self._a:\n                    "
                                "with self._b:")
        assert lint_tree(tmp_path, {"ekuiper_tpu/runtime/m.py": src}) == []

    def test_except_handler_cycle_detected(self, tmp_path):
        """Exception paths are where ABBA cleanup acquisitions hide —
        `with` nesting inside an except handler must still build edges
        (review regression: handler bodies were skipped entirely)."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                class Pool:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def forward(self):
                        with self._a:
                            try:
                                pass
                            except Exception:
                                with self._b:
                                    pass

                    def backward(self):
                        with self._b:
                            with self._a:
                                pass
            """,
        })
        assert rules_of(vs) == {"lock-order"}

    def test_cross_module_call_mediated_cycle(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/a.py": """\
                import threading
                from ekuiper_tpu.runtime import b
                _lock = threading.Lock()

                def tick():
                    with _lock:
                        b.publish()

                def stat():
                    with _lock:
                        pass
            """,
            "ekuiper_tpu/runtime/b.py": """\
                import threading
                from ekuiper_tpu.runtime import a
                _pub = threading.Lock()

                def publish():
                    with _pub:
                        pass

                def scrape():
                    with _pub:
                        a.stat()
            """,
        })
        assert rules_of(vs) == {"lock-order"}

    def test_condition_aliases_to_wrapped_lock(self, tmp_path):
        # taking the Condition IS taking the lock — not a 2-lock cycle
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                class Q:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cv = threading.Condition(self._lock)

                    def a(self):
                        with self._lock:
                            pass

                    def b(self):
                        with self._cv:
                            pass
            """,
        }) == []

    def test_pragma_suppresses_at_witness(self, tmp_path):
        src = self.ABBA.replace(
            "with self._b:\n                    with self._a:",
            "with self._b:\n                    "
            "# kuiperlint: ignore[lock-order]: b->a only runs in "
            "teardown, forward paths are quiesced\n"
            "                    with self._a:")
        assert lint_tree(tmp_path,
                         {"ekuiper_tpu/runtime/m.py": src}) == []


# ---------------------------------------------------------------- host-sync
class TestLockOrderExplicitAcquire:
    """ISSUE 10 satellite: the pass must see explicit `lock.acquire()` /
    `try: ... finally: lock.release()` acquisitions, not only `with`."""

    ABBA = {
        "ekuiper_tpu/runtime/m.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def f1():
                A.acquire()
                try:
                    with B:
                        pass
                finally:
                    A.release()

            def f2():
                with B:
                    with A:
                        pass
        """,
    }

    def test_acquire_release_abba_fires(self, tmp_path):
        vs = lint_tree(tmp_path, dict(self.ABBA), rules=["lock-order"])
        assert [v.rule for v in vs] == ["lock-order"]
        assert "cycle" in vs[0].message

    def test_release_ends_the_hold(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f1():
                    A.acquire()
                    A.release()
                    B.acquire()
                    B.release()

                def f2():
                    with B:
                        with A:
                            pass
            """,
        }, rules=["lock-order"]) == []

    def test_self_attr_acquire_in_method(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                class C:
                    def __init__(self):
                        self._mu = threading.Lock()
                        self._nu = threading.Lock()

                    def f1(self):
                        self._mu.acquire()
                        try:
                            with self._nu:
                                pass
                        finally:
                            self._mu.release()

                    def f2(self):
                        with self._nu:
                            with self._mu:
                                pass
            """,
        }, rules=["lock-order"])
        assert [v.rule for v in vs] == ["lock-order"]

    def test_nonblocking_try_lock_skipped(self, tmp_path):
        """acquire(blocking=False) cannot deadlock an ABBA square — the
        health.py profile-capture idiom must stay legal."""
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f1():
                    if not A.acquire(blocking=False):
                        return
                    with B:
                        pass
                    A.release()

                def f2():
                    with B:
                        with A:
                            pass
            """,
        }, rules=["lock-order"]) == []

    def test_pragma_on_witness_edge_suppresses(self, tmp_path):
        files = {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f1():
                    A.acquire()
                    try:
                        # kuiperlint: ignore[lock-order]: A is init-only here, no concurrent f2 yet
                        with B:
                            pass
                    finally:
                        A.release()

                def f2():
                    with B:
                        with A:
                            pass
            """,
        }
        assert lint_tree(tmp_path, files, rules=["lock-order"]) == []

    def test_acquire_inside_with_outlives_the_block(self, tmp_path):
        """Review regression: `with A: B.acquire()` holds B past the
        with exit — the B->C edge taken afterwards must be recorded
        (the with-scoped copy used to swallow it)."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                A = threading.Lock()
                B = threading.Lock()
                C = threading.Lock()

                def f1():
                    with A:
                        B.acquire()
                    with C:
                        pass
                    B.release()

                def f2():
                    with C:
                        with B:
                            pass
            """,
        }, rules=["lock-order"])
        assert [v.rule for v in vs] == ["lock-order"]
        assert "m.B" in vs[0].message and "m.C" in vs[0].message


class TestHostSync:
    def test_seeded_violations_fire(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def fold_batch(dev, i):
                    a = np.asarray(dev)
                    b = dev.item()
                    c = float(dev[i])
                    return a, b, c
            """,
        })
        assert [v.rule for v in vs] == ["host-sync"] * 3

    def test_cold_path_not_flagged(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def snapshot_state(dev):
                    return np.asarray(dev)
            """,
        }) == []

    def test_pragma_names_the_sync_point(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def emit_worker(dev):
                    # kuiperlint: ignore[host-sync]: THE intended sync point
                    return np.asarray(dev)
            """,
        }) == []


# ---------------------------------------------------------- donation-safety
class TestDonationSafety:
    def test_read_after_donation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, op="groupby.fold",
                                                donate_argnums=0)

                    def step(self, state, xs):
                        out = self._fold(state, xs)
                        return out, state
            """,
        })
        assert [v.rule for v in vs] == ["donation-safety"]
        assert "state" in vs[0].message

    def test_rebind_is_the_blessed_shape(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, op="groupby.fold",
                                                donate_argnums=0)

                    def step(self, state, xs):
                        state = self._fold(state, xs)
                        return state
            """,
        }) == []

    def test_self_attribute_donation_tracked(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, op="groupby.fold",
                                                donate_argnums=(0, 1))

                    def step(self, xs):
                        out = self._fold(self.state, xs)
                        return self.state.shape
            """,
        })
        assert [v.rule for v in vs] == ["donation-safety"]

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, op="groupby.fold",
                                                donate_argnums=0)

                    def step(self, state, xs):
                        out = self._fold(state, xs)
                        # kuiperlint: ignore[donation-safety]: CPU-only debug helper, donation is ignored there
                        return out, state
            """,
        }) == []


# ----------------------------------------------------------- metric-hygiene
class TestMetricHygiene:
    def test_undocumented_family_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'FAMILY = "kuiper_totally_undocumented_total"\n',
        }, rules=["metric-hygiene"])
        assert [v.rule for v in vs] == ["metric-hygiene"]

    def test_documented_family_and_series_clean(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'A = "kuiper_uptime_seconds"\n'
                'B = "kuiper_rule_e2e_latency_ms_bucket"\n',
        }, rules=["metric-hygiene"]) == []

    def test_dynamic_prefix_fragment(self, tmp_path):
        # f"kuiper_node_{suffix}" -> fragment "kuiper_node_": fine while
        # some documented family extends it; a bogus prefix is not
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'A = f"kuiper_node_{n}"\nB = f"kuiper_bogusprefix_{n}"\n',
        }, rules=["metric-hygiene"])
        assert len(vs) == 1 and "kuiper_bogusprefix_" in vs[0].message

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'F = "kuiper_experimental_total"  '
                "# kuiperlint: ignore[metric-hygiene]: behind env flag, "
                "documented on graduation\n",
        }, rules=["metric-hygiene"]) == []


# ----------------------------------------------------------- pragma hygiene
class TestPragmaHygiene:
    def test_unknown_rule_in_pragma(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "x = 1  # kuiperlint: ignore[no-such-rule]: why\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}

    def test_empty_rule_list(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "x = 1  # kuiperlint: ignore[]: why\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}

    def test_unparseable_file_reported(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": "def broken(:\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}
        assert "unparseable" in vs[0].message


# --------------------------------------------------------- dynamic lockcheck
class TestDynamicLockcheck:
    """utils/lockcheck.py — the runtime twin. Tests drive _TrackedLock
    directly (the factory only wraps locks allocated from ekuiper_tpu
    code); the module-global edge graph is snapshotted and restored so
    fixture edges never leak into conftest's per-test teardown check."""

    @pytest.fixture(autouse=True)
    def _isolate_graph(self):
        from ekuiper_tpu.utils import lockcheck

        with lockcheck._state_lock:
            saved = dict(lockcheck._edges)
            lockcheck._edges.clear()
        yield
        with lockcheck._state_lock:
            lockcheck._edges.clear()
            lockcheck._edges.update(saved)

    def _mk(self, site, reentrant=False):
        import threading as th

        from ekuiper_tpu.utils import lockcheck

        inner = (lockcheck._ORIG_RLOCK() if reentrant
                 else lockcheck._ORIG_LOCK())
        return lockcheck._TrackedLock(inner, site, reentrant)

    def test_abba_cycle_detected(self):
        from ekuiper_tpu.utils import lockcheck

        a, b = self._mk("mod_a.py:10"), self._mk("mod_b.py:20")
        with a:
            with b:
                pass
        assert lockcheck.check() == []
        with b:
            with a:
                pass
        cycles = lockcheck.check()
        assert len(cycles) == 1
        assert "mod_a.py:10" in cycles[0] and "mod_b.py:20" in cycles[0]

    def test_consistent_order_stays_clean(self):
        from ekuiper_tpu.utils import lockcheck

        a, b, c = (self._mk(f"m.py:{i}") for i in (1, 2, 3))
        for _ in range(3):
            with a, b, c:
                pass
        with a, c:
            pass
        assert lockcheck.check() == []

    def test_rlock_reentry_not_an_edge(self):
        from ekuiper_tpu.utils import lockcheck

        a = self._mk("m.py:1", reentrant=True)
        with a:
            with a:
                pass
        assert lockcheck.edges() == {}

    def test_condition_wait_releases_held_entry(self):
        """cv.wait() drops the lock: another lock taken by THIS thread
        during someone else's wait must not edge against it."""
        import threading as th

        from ekuiper_tpu.utils import lockcheck

        a = self._mk("m.py:1")
        cv = th.Condition(a)
        other = self._mk("m.py:2")
        done = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                done.append(True)

        t = th.Thread(target=waiter)
        t.start()
        # wake the waiter; our notify path holds a then (legally) other
        import time
        time.sleep(0.05)
        with cv:
            cv.notify()
        t.join(timeout=5)
        assert done
        with other:
            pass
        assert lockcheck.check() == []

    def test_real_engine_locks_are_tracked_when_installed(self):
        """When conftest installed the checker, locks allocated by
        engine modules carry allocation sites — the wiring is live."""
        from ekuiper_tpu.utils import lockcheck

        if not lockcheck.installed():
            pytest.skip("KUIPER_LOCKCHECK=0 — checker not installed")
        from ekuiper_tpu.utils.metrics import StatManager

        sm = StatManager("n", "rule")
        assert isinstance(sm._lock, lockcheck._TrackedLock)
        assert "metrics.py" in sm._lock.site
