"""kuiperlint (tools/kuiperlint/) — the invariant lint suite itself.

Two layers, mirroring test_metrics_lint.py's "the lint must both pass
on the tree AND provably catch violations" contract:

 * tier-1 gate: `python -m tools.kuiperlint ekuiper_tpu/` exits 0 on
   the real tree (every suppression pragma justified);
 * per-rule fixtures: for EVERY pass, a seeded violation fires and a
   justified pragma suppresses it (an allowlist that silently eats the
   violation would pass the gate vacuously).

Also covers the dynamic twin (ekuiper_tpu/utils/lockcheck.py): the
runtime acquisition-order graph flags an exercised ABBA, and
Condition.wait() bookkeeping never fabricates edges.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools import kuiperlint  # noqa: E402
from tools.kuiperlint import run as lint_run  # noqa: E402


def lint_tree(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint the tree with
    pass scopes anchored there. Returns the violation list."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    vs, n = lint_run([str(tmp_path)], root=tmp_path, rules=rules)
    assert n == len(files)
    return vs


def rules_of(violations):
    return {v.rule for v in violations}


# ------------------------------------------------------------- tier-1 gate
class TestTreeGate:
    def test_engine_tree_is_clean(self):
        """THE gate: the shipped tree lints clean (acceptance criterion —
        wired tier-1 exactly like test_metrics_lint / check_native)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint", "ekuiper_tpu/"],
            capture_output=True, text=True, timeout=300, cwd=str(REPO))
        assert proc.returncode == 0, (
            f"kuiperlint violations on the tree:\n{proc.stdout}\n"
            f"{proc.stderr}")
        assert "OK" in proc.stdout

    def test_cli_json_and_exit_codes(self, tmp_path):
        (tmp_path / "ekuiper_tpu" / "runtime").mkdir(parents=True)
        (tmp_path / "ekuiper_tpu" / "runtime" / "m.py").write_text(
            "import time\ntime.time()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint", "--json",
             "--root", str(tmp_path), str(tmp_path)],
            capture_output=True, text=True, timeout=120, cwd=str(REPO))
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["ok"] is False
        assert payload["violations"][0]["rule"] == "clock-discipline"
        # unknown rule -> usage error, not a silent pass
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kuiperlint",
             "--rules", "no-such-rule", "ekuiper_tpu/"],
            capture_output=True, text=True, timeout=120, cwd=str(REPO))
        assert proc.returncode == 2

    def test_every_documented_pass_registered(self):
        names = set(kuiperlint.all_passes())
        assert {"clock-discipline", "jit-coverage", "lock-order",
                "host-sync", "donation-safety",
                "metric-hygiene"} <= names


# --------------------------------------------------------- clock-discipline
class TestClockDiscipline:
    def test_seeded_violation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\nt0 = time.time()\n",
        })
        assert [v.rule for v in vs] == ["clock-discipline"]
        assert vs[0].line == 2

    def test_alias_and_from_import_resolve(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import time as _time
                from time import monotonic
                _time.sleep(1)
                monotonic()
            """,
        })
        assert [v.rule for v in vs] == ["clock-discipline"] * 2

    def test_perf_counter_stays_legal(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\nd = time.perf_counter()\n",
        }) == []

    def test_justified_pragma_suppresses(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "t = time.time()  # kuiperlint: ignore[clock-discipline]:"
                " real-thread deadline\n",
        })
        assert vs == []

    def test_unjustified_pragma_is_itself_a_violation(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "t = time.time()  # kuiperlint: ignore[clock-discipline]\n",
        })
        # an unjustified pragma does NOT suppress: both the hygiene
        # violation and the underlying one surface
        assert rules_of(vs) == {"pragma-hygiene", "clock-discipline"}

    def test_own_line_pragma_covers_next_line(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "import time\n"
                "# kuiperlint: ignore[clock-discipline]: wall poll\n"
                "t = time.time()\n",
        })
        assert vs == []

    def test_plugin_and_tools_allowlisted(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/plugin/ipc.py": "import time\ntime.sleep(1)\n",
            "ekuiper_tpu/tools/cli.py": "import time\ntime.time()\n",
            "ekuiper_tpu/io/src.py": "import time\ntime.time()\n",
        }) == []


# ------------------------------------------------------------- jit-coverage
class TestJitCoverage:
    def test_seeded_violation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py":
                "import jax\nfold = jax.jit(lambda s: s)\n",
        })
        assert [v.rule for v in vs] == ["jit-coverage"]

    def test_bare_decorator_fires(self, tmp_path):
        """`@jax.jit` with no parentheses is an Attribute in the
        decorator list, not a Call — the most common jit shape (review
        regression: it escaped the pass entirely)."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import jax

                @jax.jit
                def kernel(x):
                    return x
            """,
        })
        assert [v.rule for v in vs] == ["jit-coverage"]
        assert "decorator" in vs[0].message

    def test_partial_jit_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                import functools
                import jax
                mk = functools.partial(jax.jit, donate_argnums=0)
            """,
        })
        assert [v.rule for v in vs] == ["jit-coverage"]

    def test_watched_jit_and_devwatch_allowed(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/ok.py":
                "from ekuiper_tpu.observability.devwatch import"
                " watched_jit\nfold = watched_jit(lambda s: s, op='f')\n",
            "ekuiper_tpu/observability/devwatch.py":
                "import jax\n_impl = jax.jit(lambda s: s)\n",
        }) == []

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py":
                "import jax\n"
                "f = jax.jit(g)  # kuiperlint: ignore[jit-coverage]:"
                " bench-only microkernel, not an engine site\n",
        }) == []


# --------------------------------------------------------------- lock-order
class TestLockOrder:
    ABBA = """\
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """

    def test_seeded_abba_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {"ekuiper_tpu/runtime/m.py": self.ABBA})
        assert rules_of(vs) == {"lock-order"}
        assert "cycle" in vs[0].message

    def test_consistent_order_clean(self, tmp_path):
        src = self.ABBA.replace("with self._b:\n                    "
                                "with self._a:",
                                "with self._a:\n                    "
                                "with self._b:")
        assert lint_tree(tmp_path, {"ekuiper_tpu/runtime/m.py": src}) == []

    def test_except_handler_cycle_detected(self, tmp_path):
        """Exception paths are where ABBA cleanup acquisitions hide —
        `with` nesting inside an except handler must still build edges
        (review regression: handler bodies were skipped entirely)."""
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                class Pool:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def forward(self):
                        with self._a:
                            try:
                                pass
                            except Exception:
                                with self._b:
                                    pass

                    def backward(self):
                        with self._b:
                            with self._a:
                                pass
            """,
        })
        assert rules_of(vs) == {"lock-order"}

    def test_cross_module_call_mediated_cycle(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/a.py": """\
                import threading
                from ekuiper_tpu.runtime import b
                _lock = threading.Lock()

                def tick():
                    with _lock:
                        b.publish()

                def stat():
                    with _lock:
                        pass
            """,
            "ekuiper_tpu/runtime/b.py": """\
                import threading
                from ekuiper_tpu.runtime import a
                _pub = threading.Lock()

                def publish():
                    with _pub:
                        pass

                def scrape():
                    with _pub:
                        a.stat()
            """,
        })
        assert rules_of(vs) == {"lock-order"}

    def test_condition_aliases_to_wrapped_lock(self, tmp_path):
        # taking the Condition IS taking the lock — not a 2-lock cycle
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import threading

                class Q:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cv = threading.Condition(self._lock)

                    def a(self):
                        with self._lock:
                            pass

                    def b(self):
                        with self._cv:
                            pass
            """,
        }) == []

    def test_pragma_suppresses_at_witness(self, tmp_path):
        src = self.ABBA.replace(
            "with self._b:\n                    with self._a:",
            "with self._b:\n                    "
            "# kuiperlint: ignore[lock-order]: b->a only runs in "
            "teardown, forward paths are quiesced\n"
            "                    with self._a:")
        assert lint_tree(tmp_path,
                         {"ekuiper_tpu/runtime/m.py": src}) == []


# ---------------------------------------------------------------- host-sync
class TestHostSync:
    def test_seeded_violations_fire(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def fold_batch(dev, i):
                    a = np.asarray(dev)
                    b = dev.item()
                    c = float(dev[i])
                    return a, b, c
            """,
        })
        assert [v.rule for v in vs] == ["host-sync"] * 3

    def test_cold_path_not_flagged(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def snapshot_state(dev):
                    return np.asarray(dev)
            """,
        }) == []

    def test_pragma_names_the_sync_point(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": """\
                import numpy as np

                def emit_worker(dev):
                    # kuiperlint: ignore[host-sync]: THE intended sync point
                    return np.asarray(dev)
            """,
        }) == []


# ---------------------------------------------------------- donation-safety
class TestDonationSafety:
    def test_read_after_donation_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, donate_argnums=0)

                    def step(self, state, xs):
                        out = self._fold(state, xs)
                        return out, state
            """,
        })
        assert [v.rule for v in vs] == ["donation-safety"]
        assert "state" in vs[0].message

    def test_rebind_is_the_blessed_shape(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, donate_argnums=0)

                    def step(self, state, xs):
                        state = self._fold(state, xs)
                        return state
            """,
        }) == []

    def test_self_attribute_donation_tracked(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, donate_argnums=(0, 1))

                    def step(self, xs):
                        out = self._fold(self.state, xs)
                        return self.state.shape
            """,
        })
        assert [v.rule for v in vs] == ["donation-safety"]

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/ops/m.py": """\
                from ekuiper_tpu.observability.devwatch import watched_jit

                class Agg:
                    def __init__(self, f):
                        self._fold = watched_jit(f, donate_argnums=0)

                    def step(self, state, xs):
                        out = self._fold(state, xs)
                        # kuiperlint: ignore[donation-safety]: CPU-only debug helper, donation is ignored there
                        return out, state
            """,
        }) == []


# ----------------------------------------------------------- metric-hygiene
class TestMetricHygiene:
    def test_undocumented_family_fires(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'FAMILY = "kuiper_totally_undocumented_total"\n',
        }, rules=["metric-hygiene"])
        assert [v.rule for v in vs] == ["metric-hygiene"]

    def test_documented_family_and_series_clean(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'A = "kuiper_uptime_seconds"\n'
                'B = "kuiper_rule_e2e_latency_ms_bucket"\n',
        }, rules=["metric-hygiene"]) == []

    def test_dynamic_prefix_fragment(self, tmp_path):
        # f"kuiper_node_{suffix}" -> fragment "kuiper_node_": fine while
        # some documented family extends it; a bogus prefix is not
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'A = f"kuiper_node_{n}"\nB = f"kuiper_bogusprefix_{n}"\n',
        }, rules=["metric-hygiene"])
        assert len(vs) == 1 and "kuiper_bogusprefix_" in vs[0].message

    def test_pragma_suppresses(self, tmp_path):
        assert lint_tree(tmp_path, {
            "ekuiper_tpu/observability/m.py":
                'F = "kuiper_experimental_total"  '
                "# kuiperlint: ignore[metric-hygiene]: behind env flag, "
                "documented on graduation\n",
        }, rules=["metric-hygiene"]) == []


# ----------------------------------------------------------- pragma hygiene
class TestPragmaHygiene:
    def test_unknown_rule_in_pragma(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "x = 1  # kuiperlint: ignore[no-such-rule]: why\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}

    def test_empty_rule_list(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py":
                "x = 1  # kuiperlint: ignore[]: why\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}

    def test_unparseable_file_reported(self, tmp_path):
        vs = lint_tree(tmp_path, {
            "ekuiper_tpu/runtime/m.py": "def broken(:\n",
        })
        assert rules_of(vs) == {"pragma-hygiene"}
        assert "unparseable" in vs[0].message


# --------------------------------------------------------- dynamic lockcheck
class TestDynamicLockcheck:
    """utils/lockcheck.py — the runtime twin. Tests drive _TrackedLock
    directly (the factory only wraps locks allocated from ekuiper_tpu
    code); the module-global edge graph is snapshotted and restored so
    fixture edges never leak into conftest's per-test teardown check."""

    @pytest.fixture(autouse=True)
    def _isolate_graph(self):
        from ekuiper_tpu.utils import lockcheck

        with lockcheck._state_lock:
            saved = dict(lockcheck._edges)
            lockcheck._edges.clear()
        yield
        with lockcheck._state_lock:
            lockcheck._edges.clear()
            lockcheck._edges.update(saved)

    def _mk(self, site, reentrant=False):
        import threading as th

        from ekuiper_tpu.utils import lockcheck

        inner = (lockcheck._ORIG_RLOCK() if reentrant
                 else lockcheck._ORIG_LOCK())
        return lockcheck._TrackedLock(inner, site, reentrant)

    def test_abba_cycle_detected(self):
        from ekuiper_tpu.utils import lockcheck

        a, b = self._mk("mod_a.py:10"), self._mk("mod_b.py:20")
        with a:
            with b:
                pass
        assert lockcheck.check() == []
        with b:
            with a:
                pass
        cycles = lockcheck.check()
        assert len(cycles) == 1
        assert "mod_a.py:10" in cycles[0] and "mod_b.py:20" in cycles[0]

    def test_consistent_order_stays_clean(self):
        from ekuiper_tpu.utils import lockcheck

        a, b, c = (self._mk(f"m.py:{i}") for i in (1, 2, 3))
        for _ in range(3):
            with a, b, c:
                pass
        with a, c:
            pass
        assert lockcheck.check() == []

    def test_rlock_reentry_not_an_edge(self):
        from ekuiper_tpu.utils import lockcheck

        a = self._mk("m.py:1", reentrant=True)
        with a:
            with a:
                pass
        assert lockcheck.edges() == {}

    def test_condition_wait_releases_held_entry(self):
        """cv.wait() drops the lock: another lock taken by THIS thread
        during someone else's wait must not edge against it."""
        import threading as th

        from ekuiper_tpu.utils import lockcheck

        a = self._mk("m.py:1")
        cv = th.Condition(a)
        other = self._mk("m.py:2")
        done = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                done.append(True)

        t = th.Thread(target=waiter)
        t.start()
        # wake the waiter; our notify path holds a then (legally) other
        import time
        time.sleep(0.05)
        with cv:
            cv.notify()
        t.join(timeout=5)
        assert done
        with other:
            pass
        assert lockcheck.check() == []

    def test_real_engine_locks_are_tracked_when_installed(self):
        """When conftest installed the checker, locks allocated by
        engine modules carry allocation sites — the wiring is live."""
        from ekuiper_tpu.utils import lockcheck

        if not lockcheck.installed():
            pytest.skip("KUIPER_LOCKCHECK=0 — checker not installed")
        from ekuiper_tpu.utils.metrics import StatManager

        sm = StatManager("n", "rule")
        assert isinstance(sm._lock, lockcheck._TrackedLock)
        assert "metrics.py" in sm._lock.site
