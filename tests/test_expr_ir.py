"""Expression-parity suite for the device-compiled expression IR
(sql/expr_ir.py): device backend == host twin == sql/eval.py row
interpreter across the CASE / temporal / IN / string-dict /
NULL-propagation operator classes, including NaN↔None object-column
round trips and three-valued-logic WHERE edge cases (NULL comparisons
must drop rows, not fold them)."""
import datetime as dt

import numpy as np
import pytest

from ekuiper_tpu.data.batch import from_messages
from ekuiper_tpu.sql import expr_ir
from ekuiper_tpu.sql.compiler import (
    host_fallback_counts, record_host_fallback, reset_host_fallbacks,
    try_compile,
)
from ekuiper_tpu.sql.eval import Evaluator
from ekuiper_tpu.sql.expr_ir import (
    IN_PAD_LADDER, NotVectorizable, SD_NULL, SD_OTHER, TS_NULL,
    compile_expr_ir, infer_column_types, try_compile_ir,
)
from ekuiper_tpu.sql.parser import parse_select

ANCHOR = (1754265600000 // 86_400_000) * 86_400_000  # UTC midnight


def expr_of(s: str):
    return parse_select(f"SELECT * FROM t WHERE {s}").condition


def _batch(msgs):
    b, _ = from_messages(msgs, [0] * len(msgs), emitter="t")
    return b


def _eval_rows(expr, batch):
    ev = Evaluator()
    return [ev.eval_condition(expr, r) for r in batch.to_tuples()]


def _run_ir(expr, batch, mode, want="bool"):
    ce = compile_expr_ir(expr, mode=mode, want=want, anchor_ms=ANCHOR)
    cols = dict(batch.columns)
    for name, vm in batch.valid.items():
        cols["__valid_" + name] = vm
    expr_ir.materialize_derived(ce.derived, cols, batch)
    if mode == "device":
        import jax.numpy as jnp

        conv = {}
        for k, v in cols.items():
            if k.startswith("__valid_"):
                conv[k] = jnp.asarray(v)
            elif getattr(v, "dtype", None) is not None and \
                    v.dtype != np.object_:
                dt_ = ce.col_dtypes.get(k, "float32")
                conv[k] = jnp.asarray(np.asarray(v).astype(np.dtype(dt_))
                                      if k in ce.col_dtypes
                                      else np.asarray(v, dtype=np.float32))
            else:
                conv[k] = v
        cols = conv
    out = np.broadcast_to(np.asarray(ce(cols)), (batch.n,))
    return out


MSGS = [
    {"a": 10, "f": 1.5, "dev": "d1", "status": "ok",
     "ts": ANCHOR + 3_600_000},
    {"a": 20, "f": 2.5, "dev": "d2", "status": "warn",
     "ts": ANCHOR + 5_400_000},
    {"a": None, "f": 3.5, "dev": None, "status": "err",
     "ts": ANCHOR + 86_400_000 + 123_456},
    {"a": 30, "f": None, "dev": "d1", "status": "zzz", "ts": None},
    {"a": -5, "f": 0.0, "dev": "d3", "status": None,
     "ts": ANCHOR - 7_200_000},
]

#: the operator-class battery: each expression must agree with the row
#: interpreter row-for-row on BOTH backends, nulls included
PARITY_EXPRS = [
    # numeric + logic + 3VL
    "a > 15", "a >= 20 AND f < 3.0", "a > 15 OR f > 3.0",
    "NOT (a > 15)",              # NULL a -> NULL -> row dropped
    "NOT (a > 15) OR f > 3.0",
    "a + f > 12", "a * 2 - f > 30", "a % 3 = 1",
    "a = a",                     # NULL = NULL is true (reference)
    "a != 10",                   # NULL != x is true (reference)
    "a BETWEEN 5 AND 25", "a NOT BETWEEN 5 AND 25",
    "f BETWEEN 0.0 AND 2.6",
    "a IN (10, 30)", "a NOT IN (10, 30)", "a IN (10, 'ok')",
    "a IN (f, 30)",              # dynamic item -> eq-chain path
    # string dictionary classes
    "dev = 'd1'", "dev != 'd1'", "'d1' = dev",
    "status IN ('ok', 'warn')", "status NOT IN ('ok', 'warn')",
    "dev = 'd1' AND status != 'err'",
    "dev = 'nope'",
    # CASE, both forms, incl. string-matched
    "CASE WHEN a > 15 THEN 1 ELSE 0 END > 0",
    "CASE WHEN a > 15 THEN f ELSE 0.0 END > 2.0",
    "CASE status WHEN 'ok' THEN 1 WHEN 'warn' THEN 2 ELSE 0 END >= 2",
    "CASE WHEN status = 'ok' THEN 1 WHEN f > 3.0 THEN 2 END = 2",
    # temporal (int64 event-time column, UTC)
    "hour(ts) >= 1", "minute(ts) = 30", "second(ts) = 0",
    "hour(ts) BETWEEN 0 AND 1",
    "year(ts) = 2025", "month(ts) = 8", "day(ts) = 4",
    "day_of_week(ts) > 0", "day_of_month(ts) IN (3, 4, 5)",
    f"ts > {ANCHOR + 4_000_000}",
    f"ts BETWEEN {ANCHOR} AND {ANCHOR + 5_400_000}",
    f"ts - {ANCHOR} > 4000000",
    # math functions with null propagation
    "sqrt(f * f) > 2.0", "abs(0 - a) >= 20", "floor(f) = 2",
]


class TestParity:
    @pytest.mark.parametrize("sql", PARITY_EXPRS)
    def test_backend_parity(self, sql):
        expr = expr_of(sql)
        b = _batch(MSGS)
        ref = _eval_rows(expr, b)
        for mode in ("host", "device"):
            got = _run_ir(expr, b, mode).tolist()
            assert got == ref, f"{mode}: {sql}: {got} != {ref}"

    def test_null_comparisons_drop_rows(self):
        """Three-valued logic: a WHERE whose comparison sees NULL must
        drop the row — never fold it. (NOT of a null comparison KEEPS
        the row, matching the reference's ordered-NULL-is-false rule —
        covered in the parity battery above.)"""
        b = _batch(MSGS)
        for sql in ("a > 0", "a > 0 OR a <= 0",
                    "f BETWEEN a AND 100", "a IN (10, 20, 30)",
                    "a NOT IN (10, 20)"):
            expr = expr_of(sql)
            ref = _eval_rows(expr, b)
            got = _run_ir(expr, b, "device").tolist()
            assert got == ref, sql
            # row 2 has a=None: every one of these must drop it
            assert not bool(got[2]), sql

    def test_nan_none_round_trip(self):
        """NaN in a float column and None in an object column are the
        same NULL to the IR — the upload coerces None to NaN, so both
        backends must agree with each other on every form, and null
        rows must drop from comparison masks."""
        msgs = [{"x": 1.0, "y": 1.0}, {"x": float("nan"), "y": None},
                {"x": 3.0, "y": 3.0}]
        b = _batch(msgs)
        for sql in ("x > 0", "y > 0", "x = y", "x != y", "x + y > 1"):
            expr = expr_of(sql)
            got_h = _run_ir(expr, b, "host").tolist()
            got_d = _run_ir(expr, b, "device").tolist()
            assert got_h == got_d, sql
        for sql in ("x > 0", "y > 0", "x + y > 1"):
            got = _run_ir(expr_of(sql), b, "device").tolist()
            assert not bool(got[1]), sql  # NULL row drops

    def test_number_want_nan_for_null(self):
        """Agg-arg compilation: NULL evaluates to NaN (the fold's
        null-skipping mask), values cast float32."""
        expr = parse_select(
            "SELECT * FROM t WHERE a + 1 > 0").condition.lhs
        b = _batch(MSGS)
        out = _run_ir(expr, b, "host", want="number")
        assert np.isnan(out[2])      # a None -> NaN
        assert out[0] == 11.0


class TestTyping:
    def test_usage_typing(self):
        types = infer_column_types(expr_of(
            "status = 'ok' AND hour(ts) < 9 AND v > 2"))
        assert types["status"] == expr_ir.STR
        assert types["ts"] == expr_ir.TS
        assert types.get("v", expr_ir.NUM) == expr_ir.NUM

    def test_epoch_literal_types_ts(self):
        types = infer_column_types(expr_of(f"ts > {ANCHOR + 1000}"))
        assert types["ts"] == expr_ir.TS

    def test_mixed_type_column_rejected(self):
        with pytest.raises(NotVectorizable) as ei:
            compile_expr_ir(expr_of("status = 'ok' AND sqrt(status) > 1"),
                            anchor_ms=ANCHOR)
        assert ei.value.reason == "mixed-type-column"

    def test_mismatched_comparison_is_constant_false(self):
        """`status > 3` with status a string column: the reference
        compares to None -> false; the IR folds it to a constant-false
        mask rather than rejecting the rule."""
        b = _batch(MSGS)
        expr = expr_of("status = 'ok' OR status > 3")
        assert _run_ir(expr, b, "device").tolist() == \
            _eval_rows(expr, b)

    def test_structured_reasons(self):
        for sql, reason in (
            ("dev LIKE 'd%'", "like"),
            ("obj->x = 1", "json-path"),
            ("dev = 'd1' AND status = 'ok' AND dev = status",
             "string-col-compare"),
            ("dev < 'd2'", "string-order-compare"),
            ("concat(dev, 'x') = 'd1x'", "string-value"),
        ):
            with pytest.raises(NotVectorizable) as ei:
                compile_expr_ir(expr_of(sql), anchor_ms=ANCHOR)
            assert ei.value.reason == reason, sql

    def test_fallback_counter(self):
        reset_host_fallbacks()
        record_host_fallback("like")
        record_host_fallback("like")
        record_host_fallback("json-path")
        assert host_fallback_counts() == {"like": 2, "json-path": 1}
        reset_host_fallbacks()


class TestPaddingDiscipline:
    def test_in_pow2_ladder(self):
        """IN constant vectors pad to the pow-2 ladder — the bucketed
        operand shapes jitcert's bounded-family argument rests on."""
        for n, expect in ((1, 4), (4, 4), (5, 8), (9, 16), (200, 256)):
            vals = ", ".join(str(i) for i in range(n))
            ce = compile_expr_ir(expr_of(f"a IN ({vals})"),
                                 mode="host", want="bool",
                                 anchor_ms=ANCHOR)
            # the padded vector is baked into the closure; verify via
            # the canonical key length
            assert f"[{expect}" not in ""  # structural: ladder rungs
            assert expect in IN_PAD_LADDER
        with pytest.raises(NotVectorizable) as ei:
            vals = ", ".join(str(i) for i in range(IN_PAD_LADDER[-1] + 1))
            compile_expr_ir(expr_of(f"a IN ({vals})"), anchor_ms=ANCHOR)
        assert ei.value.reason == "in-too-wide"

    def test_strdict_encode_sentinels(self):
        d = expr_ir.DerivedCol(name="__sd_x__s", raw="s", kind="strdict",
                               values=("a", "b"))
        col = np.array(["b", None, "zzz", 3], dtype=np.object_)
        out = d.encode(col, 4)
        assert out.dtype == np.int32
        assert out.tolist() == [1, SD_NULL, SD_OTHER, SD_OTHER]
        # numeric column against a string dict: nothing ever matches
        out = d.encode(np.array([1.0, np.nan]), 2)
        assert out.tolist() == [SD_OTHER, SD_NULL]

    def test_ts32_encode_sentinels(self):
        d = expr_ir.DerivedCol(name="__ts32_x__t", raw="t", kind="ts32",
                               anchor=ANCHOR)
        col = np.array([ANCHOR + 5, None, ANCHOR + 10**12],
                       dtype=np.object_)
        out = d.encode(col, 3)
        assert out.dtype == np.int32
        assert out[0] == 5
        assert out[1] == TS_NULL          # NULL
        assert out[2] == TS_NULL          # out of the ±24d device window

    def test_dict_codes_stable_across_rules(self):
        """Same (column, constant-set) pair -> same derived column name
        and codes, regardless of the expression around it — shared
        folds dedup the upload."""
        a = compile_expr_ir(expr_of("status IN ('x', 'y')"),
                            mode="host", anchor_ms=ANCHOR)
        b = compile_expr_ir(expr_of("status = 'y' OR status = 'x'"),
                            mode="host", anchor_ms=ANCHOR)
        assert {d.name for d in a.derived} == {d.name for d in b.derived}


class TestTemporalExact:
    def test_extraction_matches_datetime(self):
        """Device temporal extraction is exact integer arithmetic —
        cross-check every field against python datetime over a spread
        of instants (UTC, matching funcs_datetime.py)."""
        instants = [ANCHOR + k for k in
                    (0, 59_999, 3_600_000, 86_399_999, 86_400_000,
                     7 * 86_400_000 + 12_345_678, -1, -86_400_000,
                     30 * 86_400_000 // 2)]
        b = _batch([{"ts": t} for t in instants])
        for fn, pyf in (
            ("hour", lambda d: d.hour), ("minute", lambda d: d.minute),
            ("second", lambda d: d.second), ("day", lambda d: d.day),
            ("month", lambda d: d.month), ("year", lambda d: d.year),
            ("day_of_week",
             lambda d: (d.weekday() + 1) % 7 + 1),
        ):
            expr = parse_select(
                f"SELECT * FROM t WHERE {fn}(ts) >= 0").condition.lhs
            out = _run_ir(expr, b, "host", want="number")
            for i, t in enumerate(instants):
                d = dt.datetime.fromtimestamp(t / 1000.0,
                                              tz=dt.timezone.utc)
                assert int(out[i]) == pyf(d), (fn, t)


class TestCompilerIntegration:
    def test_device_mode_routes_through_ir(self):
        ce = try_compile(expr_of("status = 'ok'"), mode="device")
        assert ce is not None
        assert any(d.kind == "strdict" for d in ce.derived)

    def test_device_still_rejects_like(self):
        assert try_compile(expr_of("dev LIKE 'd%'"), mode="device") is None
        assert try_compile_ir(expr_of("dev LIKE 'd%'")) is None

    def test_plain_numeric_unchanged(self):
        import jax
        import jax.numpy as jnp

        ce = try_compile(expr_of("a * 2.0 + sqrt(f) > 0"), mode="device")
        out = jax.jit(ce.fn)({
            "a": jnp.asarray([1.0, 2.0], dtype=jnp.float32),
            "f": jnp.asarray([4.0, 9.0], dtype=jnp.float32)})
        assert np.asarray(out).tolist() == [True, True]


class TestExplainSection:
    def test_explain_reports_reasons(self):
        from ekuiper_tpu.ops.aggspec import explain_expressions

        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM s "
            "WHERE dev LIKE 'd%' GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)")
        out = explain_expressions(stmt)
        assert out["path"] == "host"
        assert out["pieces"][0]["reason"] == "like"
        stmt = parse_select(
            "SELECT deviceId, count(*) AS c FROM s "
            "WHERE status IN ('a','b') GROUP BY deviceId, "
            "TUMBLINGWINDOW(ss, 5)")
        out = explain_expressions(stmt)
        assert out["path"] == "device"
        assert out["pieces"][0]["derived"]


class TestHostExprStage:
    def test_filter_node_accrues_host_expr_stage(self):
        """FilterNode's WHERE evaluation accrues the `host_expr` stage,
        so the health plane's bottleneck attribution can name host
        expression eval instead of binning it as "other"."""
        from ekuiper_tpu.observability.health import STAGES, _STAGE_CANON
        from ekuiper_tpu.runtime.nodes_ops import FilterNode

        assert "host_expr" in STAGES
        assert _STAGE_CANON.get("host_expr") == "host_expr"
        node = FilterNode("filter", expr_of("a > 15"))
        node.outputs = []
        b = _batch(MSGS)
        node.process(b)
        snap = node.stats.snapshot()
        st = snap["stage_timings"].get("host_expr")
        assert st is not None and st["calls"] == 1 and st["rows"] == b.n
