"""bench.py phase-budget invariant (ISSUE 4 satellite — the r05 rc=124
post-mortem class of bug): phase budgets are carved from the remaining
global budget, so no sequence of phases can ever be ALLOWED to spend past
TOTAL_BUDGET_S — the driver's hard kill can then never land before the
bench's own watchdog flushes the artifact."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _simulate(nominals, total, reserve):
    """Carve each phase's budget from the simulated remaining budget and
    let the phase consume ALL of it (the worst case the clamp must bound).
    Returns (per-phase budgets, total spend)."""
    remaining = total
    budgets = []
    for nominal in nominals:
        b = bench.phase_budget(nominal, remaining_s=remaining,
                               reserve_s=reserve)
        assert b >= 0.0
        assert b <= nominal
        budgets.append(b)
        remaining -= b  # phase runs to its full allowance
    return budgets, total - remaining


def test_budgets_never_sum_past_global_budget():
    rng = np.random.default_rng(42)
    for _ in range(200):
        n = int(rng.integers(1, 12))
        nominals = rng.uniform(10.0, 2000.0, n).tolist()
        total = float(rng.uniform(30.0, 1200.0))
        reserve = float(rng.uniform(0.0, 30.0))
        budgets, spent = _simulate(nominals, total, reserve)
        assert spent <= total + 1e-9, (nominals, total, budgets)


def test_exhausted_budget_yields_zero():
    assert bench.phase_budget(600.0, remaining_s=10.0, reserve_s=15.0) == 0.0
    assert bench.phase_budget(600.0, remaining_s=-5.0) == 0.0


def test_reserve_is_kept_for_the_artifact_flush():
    # a phase can never be granted the final reserve_s of the budget
    b = bench.phase_budget(10_000.0, remaining_s=100.0, reserve_s=15.0)
    assert b == 85.0


def test_bench_registry_includes_multi_rule_shared():
    """The new phase is wired into main()'s budgeted phase table."""
    import inspect

    src = inspect.getsource(bench.main)
    assert "multi_rule_shared" in src
    assert "phase_budget" in src


# ------------------------------------------------- phase floors (r05 fix)
def test_floors_fit_the_global_budget():
    """The roster's floors plus the flush reserve must fit TOTAL_BUDGET_S
    with slack — otherwise the floor guarantee below is vacuous."""
    total_floor = sum(f for _, f in bench.PHASE_FLOORS)
    assert total_floor + 30.0 < bench.TOTAL_BUDGET_S, (
        f"floors sum to {total_floor}s against a "
        f"{bench.TOTAL_BUDGET_S}s budget")
    assert all(f > 0 for _, f in bench.PHASE_FLOORS)


def test_later_floor_sums_the_tail():
    names = [n for n, _ in bench.PHASE_FLOORS]
    assert bench.later_floor(names[-1]) == 0.0
    assert bench.later_floor(names[0]) == sum(
        f for _, f in bench.PHASE_FLOORS[1:])
    # ad-hoc tags outside the roster get the plain greedy carve
    assert bench.later_floor("not-a-phase") == 0.0


def test_greedy_phase_cannot_starve_the_roster():
    """THE r05 regression: full_pipe alone was allowed the whole 900s, so
    nothing after it ever ran. With floors, even when every phase asks
    for (and spends) its maximum, every later phase is still offered at
    least its floor."""
    remaining = bench.TOTAL_BUDGET_S
    reserve = 15.0
    for tag, floor in bench.PHASE_FLOORS:
        b = bench.phase_budget(10_000.0, remaining_s=remaining,
                               reserve_s=reserve,
                               later_floor_s=bench.later_floor(tag))
        assert b >= floor - 1e-9, (
            f"{tag} offered {b:.1f}s < its {floor:.0f}s floor")
        remaining -= b  # worst case: the phase spends everything offered
    assert remaining >= reserve - 1e-9  # the final-JSON flush survives


def test_floors_still_respect_the_global_cap():
    """Floors carve opportunity, never extra spend: the summed grants
    stay within the global budget for random spend patterns too."""
    rng = np.random.default_rng(7)
    for _ in range(100):
        remaining = total = float(rng.uniform(100.0, 1200.0))
        spent = 0.0
        for tag, _ in bench.PHASE_FLOORS:
            b = bench.phase_budget(
                float(rng.uniform(10.0, 2000.0)), remaining_s=remaining,
                reserve_s=15.0, later_floor_s=bench.later_floor(tag))
            use = b * float(rng.uniform(0.0, 1.0))
            spent += use
            remaining -= use
        assert spent <= total + 1e-9


def test_block_marker_tolerates_donation_only():
    """The pacing marker skips donated/deleted state buffers (CPU jax
    honors donate_argnums — blocking one raises) but a real device fault
    must still propagate, or the loop loses its in-flight bound."""

    class Deleted:
        def is_deleted(self):
            return True

    bench._block_marker(None)
    bench._block_marker(Deleted())  # donated: silently skipped

    class DonationRace:
        def is_deleted(self):
            raise RuntimeError(
                "BlockHostUntilReady() called on deleted or donated buffer")

    bench._block_marker(DonationRace())  # the benign race class

    class TunnelFault:
        def is_deleted(self):
            raise RuntimeError("socket closed")

    import pytest

    with pytest.raises(RuntimeError, match="socket closed"):
        bench._block_marker(TunnelFault())


# -------------------------------------- child watchdog dump harvest (r05)
def test_flush_record_dump_roundtrips_through_harvest(capsys):
    """A killed child's dying `#R` dump must restore its phases into the
    parent's RESULTS — the exact r05 failure (child exceeded the
    watchdog, stdout JSON discarded, artifact `parsed` came back null)."""
    saved = dict(bench.RESULTS)
    try:
        bench.RESULTS.clear()
        bench.RESULTS["full_pipe"] = {"rows_per_sec": 1.0e6,
                                      "e2e_p99_ms": 4.0}
        bench.RESULTS["full_pipe_error"] = "watchdog: exceeded 500s"
        bench._flush_record_dump()
        child_stderr = capsys.readouterr().err
        assert child_stderr.startswith("#R ")
        # the parent re-parses the child's stderr after the kill
        bench.RESULTS.clear()
        bench._harvest_phase_stderr(child_stderr, "full-pipe")
        assert bench.RESULTS["full_pipe"]["rows_per_sec"] == 1.0e6
        assert "watchdog" in bench.RESULTS["full_pipe_error"]
    finally:
        bench.RESULTS.clear()
        bench.RESULTS.update(saved)


def test_flush_record_dump_survives_unserializable_entries(capsys):
    """The dying gasp must never throw — a bad RESULTS entry degrades to
    no dump line, not a crash in the watchdog thread."""
    saved = dict(bench.RESULTS)
    try:
        bench.RESULTS.clear()
        bench.RESULTS["bad"] = object()  # not JSON-serializable
        bench._flush_record_dump()  # must not raise
        capsys.readouterr()
    finally:
        bench.RESULTS.clear()
        bench.RESULTS.update(saved)
