"""bench.py phase-budget invariant (ISSUE 4 satellite — the r05 rc=124
post-mortem class of bug): phase budgets are carved from the remaining
global budget, so no sequence of phases can ever be ALLOWED to spend past
TOTAL_BUDGET_S — the driver's hard kill can then never land before the
bench's own watchdog flushes the artifact."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _simulate(nominals, total, reserve):
    """Carve each phase's budget from the simulated remaining budget and
    let the phase consume ALL of it (the worst case the clamp must bound).
    Returns (per-phase budgets, total spend)."""
    remaining = total
    budgets = []
    for nominal in nominals:
        b = bench.phase_budget(nominal, remaining_s=remaining,
                               reserve_s=reserve)
        assert b >= 0.0
        assert b <= nominal
        budgets.append(b)
        remaining -= b  # phase runs to its full allowance
    return budgets, total - remaining


def test_budgets_never_sum_past_global_budget():
    rng = np.random.default_rng(42)
    for _ in range(200):
        n = int(rng.integers(1, 12))
        nominals = rng.uniform(10.0, 2000.0, n).tolist()
        total = float(rng.uniform(30.0, 1200.0))
        reserve = float(rng.uniform(0.0, 30.0))
        budgets, spent = _simulate(nominals, total, reserve)
        assert spent <= total + 1e-9, (nominals, total, budgets)


def test_exhausted_budget_yields_zero():
    assert bench.phase_budget(600.0, remaining_s=10.0, reserve_s=15.0) == 0.0
    assert bench.phase_budget(600.0, remaining_s=-5.0) == 0.0


def test_reserve_is_kept_for_the_artifact_flush():
    # a phase can never be granted the final reserve_s of the budget
    b = bench.phase_budget(10_000.0, remaining_s=100.0, reserve_s=15.0)
    assert b == 85.0


def test_bench_registry_includes_multi_rule_shared():
    """The new phase is wired into main()'s budgeted phase table."""
    import inspect

    src = inspect.getsource(bench.main)
    assert "multi_rule_shared" in src
    assert "phase_budget" in src
