"""Direct (vectorized) emit tail tests — cross-checked against the row-path
evaluator on the same statements."""
import time

import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.sql.parser import parse_select


def _direct(sql, dims=("k",)):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    return stmt, plan, build_direct_emit(stmt, plan, list(dims))


class TestBuildDirectEmit:
    def test_simple_fields(self):
        _, plan, de = _direct(
            "SELECT k, avg(v) AS a, count(*) AS c FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        assert de is not None
        assert [f.kind for f in de.fields] == ["dim", "agg", "agg"]

    def test_expr_over_aggs(self):
        _, plan, de = _direct(
            "SELECT k, avg(v) * 2 + 1 AS scaled FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        assert de is not None and de.fields[1].kind == "expr"

    def test_window_bounds(self):
        _, plan, de = _direct(
            "SELECT k, window_start() AS ws, window_end() AS we, sum(v) AS s "
            "FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        assert de is not None

    def test_fallback_on_string_func(self):
        stmt = parse_select(
            "SELECT upper(k) AS ku, count(*) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        plan = extract_kernel_plan(stmt)
        assert build_direct_emit(stmt, plan, ["k"]) is None  # upper() not vectorized


class TestRunDirectEmit:
    def _env(self):
        dim = np.array(["a", "b", "c", None], dtype=np.object_)
        aggs = [
            np.array([10.0, 30.0, 20.0, 5.0]),  # avg
            np.array([2.0, 3.0, 1.0, 1.0]),     # count
        ]
        return dim, aggs

    def test_having_order_limit(self):
        _, plan, de = _direct(
            "SELECT k, avg(v) AS a FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10) "
            "HAVING count(*) >= 1 ORDER BY avg(v) DESC LIMIT 2"
        )
        dim, aggs = self._env()
        out = de.run({"k": dim}, aggs, 0, 10_000)
        assert out == [{"k": "b", "a": 30.0}, {"k": "c", "a": 20.0}]

    def test_order_by_null_dim_key(self):
        # None group key must not crash the vectorized sort
        _, plan, de = _direct(
            "SELECT k, count(*) AS c FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10) "
            "ORDER BY k"
        )
        dim, aggs = self._env()
        out = de.run({"k": dim}, aggs, 0, 10_000)
        assert [r["k"] for r in out] == [None, "a", "b", "c"]  # "" sorts first

    def test_order_desc_string(self):
        _, plan, de = _direct(
            "SELECT k, count(*) AS c FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10) "
            "ORDER BY k DESC"
        )
        dim, aggs = self._env()
        out = de.run({"k": dim}, aggs, 0, 10_000)
        assert [r["k"] for r in out] == ["c", "b", "a", None]

    def test_nan_agg_to_none_and_having_nan(self):
        _, plan, de = _direct(
            "SELECT k, avg(v) AS a FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10) "
            "HAVING avg(v) > 15"
        )
        dim = np.array(["a", "b"], dtype=np.object_)
        aggs = [np.array([np.nan, 30.0]), np.array([0.0, 3.0])]
        out = de.run({"k": dim}, aggs, 0, 10_000)
        assert out == [{"k": "b", "a": 30.0}]  # NaN (NULL) fails HAVING

    def test_empty_after_having(self):
        _, plan, de = _direct(
            "SELECT k FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10) HAVING count(*) > 99"
        )
        dim, aggs = self._env()
        assert de.run({"k": dim}, aggs, 0, 10_000) == []


class TestDirectEmitE2E:
    """Through the full rule surface (planner folds the tail)."""

    def test_order_limit_through_rule(self, mock_clock):
        from ekuiper_tpu.io import memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="t/d", TYPE="memory")'
        )
        topo = plan_rule(RuleDef(id="de", sql=(
            "SELECT deviceId, max(temperature) AS mx FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10) "
            "ORDER BY max(temperature) DESC LIMIT 2"
        ), actions=[{"memory": {"topic": "de_res"}}]), store)
        # tail folded: only the shared-source entry + fused node remain
        assert [n.name for n in topo.ops] == ["demo_shared", "window_agg"]
        got = []
        mem.subscribe("de_res", lambda t, p: got.append(p))
        topo.open()
        try:
            for d, t in [("a", 5.0), ("b", 50.0), ("c", 25.0)]:
                mem.publish("t/d", {"deviceId": d, "temperature": t})
            mock_clock.advance(20)
            topo.wait_idle()
            mock_clock.advance(10_000)
            deadline = time.time() + 5
            while not got and time.time() < deadline:
                time.sleep(0.01)
            assert got and got[0] == [
                {"deviceId": "b", "mx": 50.0},
                {"deviceId": "c", "mx": 25.0},
            ]
        finally:
            topo.close()
            mem.reset()
