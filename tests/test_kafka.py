"""Kafka connector tests against the scripted mock broker
(tests/kafka_broker_mock.py — independent struct encoding, so the client's
wire layout is cross-validated, not self-validated). Modeled on the
reference's kafka extension tests (extensions/impl/kafka/source_test.go,
sink_test.go) with the checkpoint-offset divergence exercised explicitly."""
import json
import time

import pytest

from ekuiper_tpu.io.kafka_io import KafkaSink, KafkaSource
from ekuiper_tpu.io.kafka_wire import KafkaClient
from ekuiper_tpu.utils.infra import EngineError

from kafka_broker_mock import MockBroker


@pytest.fixture
def broker():
    b = MockBroker({"t1": 2, "t2": 1})
    yield b
    b.close()


# ------------------------------------------------------------------ wire
class TestWireClient:
    def test_api_versions(self, broker):
        c = KafkaClient(broker.bootstrap)
        vers = c.api_versions()
        assert vers[0] == (0, 2) and 18 in vers
        c.close()

    def test_metadata_routing(self, broker):
        c = KafkaClient(broker.bootstrap)
        md = c.metadata(["t1", "t2"])
        assert sorted(md["t1"]) == [0, 1]
        assert md["t2"][0] == (broker.host, broker.port)
        assert c.partitions("t1") == [0, 1]
        c.close()

    def test_unknown_topic_errors(self, broker):
        c = KafkaClient(broker.bootstrap)
        with pytest.raises(EngineError, match="UNKNOWN_TOPIC"):
            c.metadata(["nope"])
        c.close()

    def test_produce_fetch_roundtrip(self, broker):
        c = KafkaClient(broker.bootstrap)
        base = c.produce("t1", 0, [(b"k1", b"v1", 111), (None, b"v2", 222)])
        assert base == 0
        assert c.produce("t1", 0, [(None, b"v3", 333)]) == 2
        hw, msgs = c.fetch("t1", 0, 0)
        assert hw == 3
        assert [(o, k, v, t) for o, k, v, t in msgs] == [
            (0, b"k1", b"v1", 111), (1, None, b"v2", 222),
            (2, None, b"v3", 333)]
        # fetch from mid-log
        _, tail = c.fetch("t1", 0, 2)
        assert [m[0] for m in tail] == [2]
        c.close()

    def test_list_offsets(self, broker):
        c = KafkaClient(broker.bootstrap)
        assert c.earliest_offset("t2", 0) == 0
        assert c.latest_offset("t2", 0) == 0
        c.produce("t2", 0, [(None, b"x", 0)])
        assert c.latest_offset("t2", 0) == 1
        c.close()

    def test_produce_not_leader_refreshes_and_recovers(self, broker):
        """NOT_LEADER invalidates the leader cache and retries once via
        fresh metadata (leader-migration recovery); a second consecutive
        NOT_LEADER surfaces to the SinkNode retry path."""
        c = KafkaClient(broker.bootstrap)
        broker.fail_produces = 1
        assert c.produce("t2", 0, [(None, b"x", 0)]) >= 0  # in-call retry
        broker.fail_produces = 2
        with pytest.raises(EngineError, match="NOT_LEADER"):
            c.produce("t2", 0, [(None, b"y", 0)])
        assert c.produce("t2", 0, [(None, b"z", 0)]) >= 0
        c.close()

    def test_fetch_grows_past_oversized_message(self, broker):
        """A message bigger than max_bytes truncates the v2 fetch response;
        the client doubles max_bytes instead of busy-polling forever."""
        big = b"x" * 4096
        broker.append("t2", 0, None, big)
        broker.append("t2", 0, None, b"after")
        c = KafkaClient(broker.bootstrap)
        hw, msgs = c.fetch("t2", 0, 0, max_bytes=512)
        assert hw == 2
        assert [v for _, _, v, _ in msgs] == [big, b"after"]
        c.close()

    def test_oversized_beyond_cap_errors(self, broker):
        broker.append("t2", 0, None, b"y" * 4096)
        c = KafkaClient(broker.bootstrap)
        c.MAX_FETCH_BYTES = 1024
        with pytest.raises(EngineError, match="exceeds MAX_FETCH_BYTES"):
            c.fetch("t2", 0, 0, max_bytes=512)
        c.close()

    def test_gzip_message_set_decode(self):
        """A gzip wrapper message (codec bit 1, relative inner offsets
        anchored to the wrapper offset) decodes to the inner records."""
        import gzip as _gz
        import struct as _st
        import zlib as _zl

        from ekuiper_tpu.io.kafka_wire import (decode_message_set,
                                               encode_message_set)

        inner = encode_message_set([(None, b"a", 1), (None, b"b", 2)])
        wrapped = _gz.compress(inner)
        body = _st.pack(">bb", 1, 1) + _st.pack(">q", 2) \
            + _st.pack(">i", -1) + _st.pack(">i", len(wrapped)) + wrapped
        crc = _zl.crc32(body) & 0xFFFFFFFF
        msg = _st.pack(">I", crc) + body
        # wrapper carries the offset of its LAST inner record (=6)
        mset = _st.pack(">qi", 6, len(msg)) + msg
        got = decode_message_set(mset)
        assert [(o, v) for o, _, v, _ in got] == [(5, b"a"), (6, b"b")]

    def test_snappy_rejected_clearly(self):
        import struct as _st
        import zlib as _zl

        from ekuiper_tpu.io.kafka_wire import decode_message_set

        body = _st.pack(">bb", 1, 2) + _st.pack(">q", 0) \
            + _st.pack(">i", -1) + _st.pack(">i", 0)
        msg = _st.pack(">I", _zl.crc32(body) & 0xFFFFFFFF) + body
        mset = _st.pack(">qi", 0, len(msg)) + msg
        with pytest.raises(EngineError, match="snappy"):
            decode_message_set(mset)

    def test_acks_zero_no_response(self, broker):
        c = KafkaClient(broker.bootstrap)
        assert c.produce("t2", 0, [(None, b"fire", 1)], acks=0) == -1
        deadline = time.time() + 2
        while time.time() < deadline and not broker.data[("t2", 0)]:
            time.sleep(0.01)
        assert broker.data[("t2", 0)][0][1] == b"fire"
        # channel still usable for acked requests afterwards
        assert c.produce("t2", 0, [(None, b"ack", 2)]) == 1
        c.close()


# ---------------------------------------------------------------- source
class TestKafkaSource:
    def _drain(self, got, n, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline and len(got) < n:
            time.sleep(0.02)
        return got

    def test_ingest_all_partitions_with_meta(self, broker):
        for p, v in ((0, b'{"a":1}'), (1, b'{"a":2}'), (0, b'{"a":3}')):
            broker.append("t1", p, b"key", v, ts=99)
        src = KafkaSource()
        src.configure("t1", {"brokers": broker.bootstrap,
                             "pollInterval": 20})
        got = []
        src.open(lambda payload, meta=None: got.append((payload, meta)))
        self._drain(got, 3)
        src.close()
        assert {g[0] for g in got} == {b'{"a":1}', b'{"a":2}', b'{"a":3}'}
        metas = {(m["partition"], m["offset"]) for _, m in got}
        assert metas == {(0, 0), (1, 0), (0, 1)}
        assert all(m["topic"] == "t1" and m["key"] == "key" for _, m in got)

    def test_offset_latest_skips_seed(self, broker):
        broker.append("t2", 0, None, b"old")
        src = KafkaSource()
        src.configure("t2", {"brokers": broker.bootstrap, "offset": "latest",
                             "pollInterval": 20})
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        time.sleep(0.3)
        broker.append("t2", 0, None, b"new")
        self._drain(got, 1)
        src.close()
        assert got == [b"new"]

    def test_checkpoint_offset_roundtrip(self, broker):
        """get_offset/rewind — the Rewindable contract the checkpoint
        machinery drives (runtime/nodes_source.py:284)."""
        for i in range(4):
            broker.append("t2", 0, None, f"m{i}".encode())
        src = KafkaSource()
        src.configure("t2", {"brokers": broker.bootstrap, "pollInterval": 20})
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        self._drain(got, 4)
        snap = src.get_offset()
        assert snap == {"0": 4}
        # crash/recovery: rewind to the checkpointed position, replay
        src.rewind({"0": 2})
        self._drain(got, 6)
        src.close()
        assert got[4:6] == [b"m2", b"m3"]  # at-least-once replay

    def test_rewind_before_open_wins_over_start(self, broker):
        for i in range(3):
            broker.append("t2", 0, None, f"m{i}".encode())
        src = KafkaSource()
        src.configure("t2", {"brokers": broker.bootstrap, "pollInterval": 20})
        src.rewind({"0": 2})  # restored checkpoint arrives before open
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        self._drain(got, 1)
        src.close()
        assert got == [b"m2"]

    def test_offset_out_of_range_resets_to_earliest(self, broker):
        """A checkpointed offset past the log (retention truncation / topic
        recreation) can never succeed — the source clamps to earliest with
        a loud data-loss error instead of stalling forever."""
        for i in range(3):
            broker.append("t2", 0, None, f"m{i}".encode())
        src = KafkaSource()
        src.configure("t2", {"brokers": broker.bootstrap, "pollInterval": 20})
        src.rewind({"0": 999})  # stale checkpoint beyond the log
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        self._drain(got, 3)
        src.close()
        assert got[:3] == [b"m0", b"m1", b"m2"]

    def test_groupid_ignored_with_warning(self, broker):
        src = KafkaSource()
        src.configure("t2", {"brokers": broker.bootstrap, "groupID": "g1"})
        assert src.topic == "t2"  # configure succeeded


# ------------------------------------------------------------------ sink
class TestKafkaSink:
    def test_collect_single_and_batch(self, broker):
        sink = KafkaSink()
        sink.configure({"topic": "t2", "brokers": broker.bootstrap,
                        "key": "dev1"})
        sink.connect()
        sink.collect({"a": 1})
        sink.collect([{"b": 2}, {"b": 3}])
        sink.close()
        log = broker.data[("t2", 0)]
        assert [json.loads(v) for _, v, _ in log] == [
            {"a": 1}, {"b": 2}, {"b": 3}]
        assert log[0][0] == b"dev1"

    def test_round_robin_partitions(self, broker):
        sink = KafkaSink()
        sink.configure({"topic": "t1", "brokers": broker.bootstrap})
        sink.connect()
        for i in range(4):
            sink.collect({"i": i})
        sink.close()
        assert len(broker.data[("t1", 0)]) == 2
        assert len(broker.data[("t1", 1)]) == 2

    def test_requires_topic_and_brokers(self):
        with pytest.raises(EngineError, match="topic"):
            KafkaSink().configure({"brokers": "x:1"})
        with pytest.raises(EngineError, match="brokers"):
            KafkaSink().configure({"topic": "t"})


# ------------------------------------------------------------------- e2e
class TestKafkaRuleE2E:
    def test_kafka_to_rule_to_kafka(self, broker, mock_clock):
        """Full pipe: kafka source -> windowed SQL rule -> kafka sink, both
        ends on the mock broker (reference fvt: kafka_sink_source_test)."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        store = kv.get_store()
        store.kv("source_conf").set("kafka:default", {
            "brokers": broker.bootstrap, "pollInterval": 20})
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM kdemo (deviceId STRING, v FLOAT) '
            'WITH (DATASOURCE="t2", TYPE="kafka", CONF_KEY="default", '
            'FORMAT="JSON")')
        topo = plan_rule(RuleDef(
            id="kr1",
            sql=("SELECT deviceId, count(*) AS c FROM kdemo "
                 "GROUP BY deviceId, TUMBLINGWINDOW(ss, 2)"),
            actions=[{"kafka": {"topic": "t1", "partition": 0,
                                "brokers": broker.bootstrap}}],
            options={"use_device_kernel": False}), store)
        topo.open()
        try:
            for i in range(5):
                broker.append("t2", 0, None,
                              json.dumps({"deviceId": "d", "v": i}).encode())
            window = next(n for n in topo.ops if "Window" in type(n).__name__)
            deadline = time.time() + 10
            while time.time() < deadline and window.stats.records_in < 5:
                time.sleep(0.05)
                mock_clock.advance(20)  # linger flush only; window still open
            mock_clock.advance(2000)
            deadline = time.time() + 10
            while time.time() < deadline and not broker.data[("t1", 0)]:
                time.sleep(0.05)
                mock_clock.advance(10)
        finally:
            topo.close()
        out = [json.loads(v) for _, v, _ in broker.data[("t1", 0)]]
        assert out and out[0] == {"deviceId": "d", "c": 5}


class TestSaslPlain:
    @pytest.fixture
    def sasl_broker(self):
        b = MockBroker({"t1": 1}, sasl_users={"alice": "secret"})
        yield b
        b.close()

    def test_authenticated_roundtrip(self, sasl_broker):
        c = KafkaClient(sasl_broker.bootstrap,
                        sasl=("PLAIN", "alice", "secret"))
        assert c.produce("t1", 0, [(None, b"hi", 1)]) == 0
        _, msgs = c.fetch("t1", 0, 0)
        assert [v for _, _, v, _ in msgs] == [b"hi"]
        c.close()

    def test_wrong_password_rejected(self, sasl_broker):
        c = KafkaClient(sasl_broker.bootstrap,
                        sasl=("PLAIN", "alice", "nope"))
        with pytest.raises(EngineError, match="[Aa]uthentication"):
            c.partitions("t1")
        c.close()

    def test_unauthenticated_conn_refused(self, sasl_broker):
        c = KafkaClient(sasl_broker.bootstrap)
        with pytest.raises(EngineError):
            c.partitions("t1")
        c.close()

    def test_source_sink_props(self, sasl_broker):
        sink = KafkaSink()
        sink.configure({"topic": "t1", "brokers": sasl_broker.bootstrap,
                        "saslAuthType": "plain", "saslUserName": "alice",
                        "password": "secret"})
        sink.connect()
        sink.collect({"x": 1})
        sink.close()
        src = KafkaSource()
        src.configure("t1", {"brokers": sasl_broker.bootstrap,
                             "saslAuthType": "plain",
                             "saslUserName": "alice", "password": "secret",
                             "pollInterval": 20})
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
        src.close()
        assert got and json.loads(got[0]) == {"x": 1}

    def test_unknown_mechanism_rejected_clearly(self):
        with pytest.raises(EngineError, match="unsupported saslAuthType"):
            KafkaSource().configure("t", {
                "brokers": "h:1", "saslAuthType": "gssapi"})


class TestScram:
    def test_rfc7677_test_vector(self, monkeypatch):
        """The client side reproduces the RFC 7677 SCRAM-SHA-256 example
        exchange byte-for-byte (external golden — no self-validation)."""
        from ekuiper_tpu.io import kafka_wire as kw
        import base64 as b64

        # pin the client nonce from the RFC example
        monkeypatch.setattr(
            kw.os, "urandom",
            lambda n: b64.b64decode("rOprNGfwEbeRWgbNEkqO" + "=="))
        monkeypatch.setattr(kw.base64, "b64encode",
                            b64.b64encode)  # unchanged, explicitness
        sent = []

        def step(payload):
            sent.append(payload)
            if len(sent) == 1:
                assert payload == b"n,,n=user,r=rOprNGfwEbeRWgbNEkqO"
                return (b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF"
                        b"$k0,s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096")
            assert payload == (
                b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF"
                b"$k0,p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ=")
            return b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4="

        kw._scram_client("SCRAM-SHA-256", "user", "pencil", step)
        assert len(sent) == 2

    @pytest.fixture
    def scram_broker(self):
        b = MockBroker({"t1": 1}, sasl_users={"alice": "secret"})
        yield b
        b.close()

    @pytest.mark.parametrize("mech", ["scram_sha_256", "scram_sha_512"])
    def test_scram_roundtrip(self, scram_broker, mech):
        sink = KafkaSink()
        sink.configure({"topic": "t1", "brokers": scram_broker.bootstrap,
                        "saslAuthType": mech, "saslUserName": "alice",
                        "password": "secret"})
        sink.connect()
        sink.collect({"s": mech})
        sink.close()
        vals = [json.loads(v) for _, v, _ in scram_broker.data[("t1", 0)]]
        assert {"s": mech} in vals

    def test_scram_wrong_password(self, scram_broker):
        c = KafkaClient(scram_broker.bootstrap,
                        sasl=("SCRAM-SHA-256", "alice", "wrong"))
        with pytest.raises(EngineError):
            c.partitions("t1")
        c.close()


class TestKafkaCheckpointReplay:
    def test_offset_rewind_across_crash(self, broker, mock_clock):
        """VERDICT r4 #5 'done' criterion: kafka offsets ride rule
        checkpoints. Kill a qos=1 rule after consuming past a checkpoint,
        restore — the source rewinds to the checkpointed offset and
        re-fetches the tail from the BROKER itself (no re-publish; that is
        the point of a rewindable log source). Window result equals an
        uninterrupted run."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        store = kv.get_store()
        store.kv("source_conf").set("kafka:ck", {
            "brokers": broker.bootstrap, "pollInterval": 20})
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM kck (deviceId STRING, v FLOAT) '
            'WITH (DATASOURCE="t2", TYPE="kafka", CONF_KEY="ck", '
            'FORMAT="JSON")')

        def make_topo():
            return plan_rule(RuleDef(
                id="kck1", sql=(
                    "SELECT deviceId, count(*) AS c, avg(v) AS a FROM kck "
                    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
                actions=[{"memory": {"topic": "kck/out"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000}), store)

        def feed(rows):
            for d, v in rows:
                broker.append("t2", 0, None,
                              json.dumps({"deviceId": d, "v": v}).encode())

        def consumed(topo, n):
            deadline = time.time() + 10
            src = (topo.sources[0] if topo.sources
                   else topo._live_shared[0][0].source)
            while time.time() < deadline:
                off = getattr(src.connector, "get_offset", lambda: {})()
                if off.get("0", 0) >= n:
                    mock_clock.advance(20)
                    if topo.wait_idle(10):
                        return True
                time.sleep(0.02)
            return False

        topo = make_topo()
        topo.open()
        feed([("a", 10.0), ("a", 20.0), ("b", 30.0)])
        assert consumed(topo, 3)
        from conftest import wait_for_checkpoint

        cid = topo.trigger_checkpoint()
        snap = wait_for_checkpoint(store, "kck1", cid)
        feed([("a", 30.0), ("b", 10.0)])
        assert consumed(topo, 5)
        topo.close()  # crash: no graceful save

        # PIN the checkpointed offset itself: the snapshot must carry the
        # source at offset 3 — not 0/absent (an earliest-fallback restart
        # would coincidentally produce the same window result on an empty
        # restored state, masking a broken checkpoint path)
        offsets = [st["offset"] for st in snap.get("states", {}).values()
                   if isinstance(st, dict) and "offset" in st]
        assert {"0": 3} in offsets, snap

        from conftest import collect_window_result

        topo2 = make_topo()
        topo2.open()
        # NOTHING is re-published: the rewound source re-fetches rows 3-4
        # from the broker's log on its own
        assert consumed(topo2, 5)
        msgs = collect_window_result(mem, "kck/out", mock_clock)
        topo2.close()
        res = {m["deviceId"]: (m["c"], round(m["a"], 4)) for m in msgs}
        assert res == {"a": (3, 20.0), "b": (2, 20.0)}, res


class TestTombstones:
    def test_null_value_stays_none(self):
        """A delete tombstone (null value) must survive decode as None —
        coercing to b"" made it indistinguishable from an empty payload
        (ADVICE r5 low)."""
        from ekuiper_tpu.io.kafka_wire import (decode_message_set,
                                               encode_message_set)

        mset = encode_message_set(
            [(b"k", None, 5), (None, b"", 6), (None, b"x", 7)])
        got = decode_message_set(mset)
        assert [(k, v) for _, k, v, _ in got] == [
            (b"k", None), (None, b""), (None, b"x")]
