"""AddressSanitizer twin of tests/test_native_tsan.py for the native
decoder (native/jsoncol.cpp).

TSAN proves the GIL-free shard fan-out is race-free; ASAN proves its
MEMORY discipline: the shard parse writes disjoint row slices of one
shared allocation (an off-by-one there is a heap-buffer-overflow TSAN
cannot see), and the keytab encode's appendix-append + mid-batch
rollback path frees/reuses table storage whose misuse would be a
use-after-free. The test builds `make asan` (mtime-cached), then drives
multi-shard decodes — including the bad-row and string-cast paths, whose
error handling is where buffer math historically goes wrong — plus
keytab encodes across a growing table, inside a subprocess running
under libasan, and fails on any AddressSanitizer report.

Skips with an explicit reason when the sanitizer toolchain is missing
(no g++/make, no libasan, or the instrumented build fails) — the suite
must stay green on minimal images. docs/STATIC_ANALYSIS.md § Sanitizer
builds.
"""
import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
ASAN_SO = NATIVE / "build" / "asan" / "ekjsoncol.so"

# the stress driver runs inside the ASAN-preloaded subprocess; kept as a
# string so the test file itself never imports the instrumented module
DRIVER = r"""
import sys
sys.path.insert(0, sys.argv[1])  # build/asan — shadows any regular build
import ekjsoncol

ROWS = [
    (b'{"dev": "sensor-%d", "temp": %d.5, "n": %d, "ok": true}'
     % (i % 13, i % 90, i)) for i in range(4096)
]
SPEC = (("temp", 0), ("n", 1), ("ok", 2), ("dev", 3))
BAD = list(ROWS)
BAD[17] = b'{"temp": not-json'             # bad-row marking across shards
BAD[4090] = b'{"dev": "x", "temp": "4.25"}'  # string->float cast path
BAD[-1] = b'{"dev": "' + b'x' * 5000 + b'"}'  # oversized string tail

for shards in (1, 2, 4):
    for _ in range(3):
        cols, valid, bad = ekjsoncol.decode(ROWS, SPEC, shards)
        assert not bad.any()
        cols, valid, bad = ekjsoncol.decode(BAD, SPEC, shards)
        assert bad[17] and not bad[4090]

tab = ekjsoncol.keytab_new()
seen = 0
for round_ in range(6):
    # growing key population: appendix append + storage growth; the
    # surrogate/fallback rows exercise the no-mutate rollback path
    keys = [f"dev-{i % (257 * (round_ + 1))}" for i in range(4096)]
    slots, appendix = ekjsoncol.keytab_encode(tab, keys)
    assert len(slots) == len(keys)
    seen += len(appendix)
    try:
        ekjsoncol.keytab_encode(tab, ["ok", 42, "also-ok"])
    except Exception:
        pass  # non-str key: must roll back without touching storage
print("ASAN_STRESS_OK", seen)
"""


def _libasan() -> str:
    """Absolute path of libasan, or '' when the toolchain can't provide
    it (g++ echoes the bare name back when the library is unknown)."""
    gxx = shutil.which("g++")
    if gxx is None:
        return ""
    for name in ("libasan.so", "libasan.so.6", "libasan.so.8",
                 "libasan.so.5"):
        try:
            out = subprocess.run(
                [gxx, f"-print-file-name={name}"], capture_output=True,
                text=True, timeout=30).stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            return ""
        if out and out != name and os.path.exists(out):
            return out
    return ""


def _ensure_asan_build() -> None:
    """`make asan`, cached on source mtime like the TSAN build."""
    src = NATIVE / "jsoncol.cpp"
    if ASAN_SO.exists() and ASAN_SO.stat().st_mtime >= src.stat().st_mtime:
        return
    proc = subprocess.run(
        ["make", "-C", str(NATIVE), "asan", f"PYTHON={sys.executable}"],
        capture_output=True, text=True, timeout=300)
    if proc.returncode != 0 or not ASAN_SO.exists():
        pytest.skip("sanitizer build failed — no ASAN coverage on this "
                    f"toolchain:\n{proc.stdout}\n{proc.stderr}")


def test_shard_parse_keytab_memory_safe():
    if not shutil.which("g++") or not shutil.which("make"):
        pytest.skip("no g++/make — sanitizer toolchain not present")
    libasan = _libasan()
    if not libasan:
        pytest.skip("g++ has no libasan — sanitizer runtime not present")
    _ensure_asan_build()

    env = dict(os.environ)
    # preload: the instrumented .so needs the ASAN runtime resident
    # before the (uninstrumented) python binary maps it
    env["LD_PRELOAD"] = libasan
    # leak detection off: CPython itself "leaks" interned/static
    # allocations at exit, which would drown real reports; the target
    # classes here (overflow, use-after-free) abort at the fault site
    env["ASAN_OPTIONS"] = ("detect_leaks=0:abort_on_error=0:"
                           "exitcode=66:allocator_may_return_null=1")
    proc = subprocess.run(
        [sys.executable, "-c", DRIVER, str(ASAN_SO.parent)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    report = f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    assert "ERROR: AddressSanitizer" not in report, (
        "memory fault in the native shard parse/keytab path:\n" + report)
    assert proc.returncode == 0 and "ASAN_STRESS_OK" in proc.stdout, (
        "ASAN stress driver did not complete cleanly:\n" + report)
