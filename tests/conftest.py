"""Test harness config.

All tests run JAX on a virtual 8-device CPU mesh (no real TPU needed) so
sharding/collective paths are exercised the way the reference tests exercise
multi-goroutine topologies in one process. Mirrors eKuiper's auto-mock-clock
under `go test` (pkg/timex): every test starts with a fresh mock clock.
"""
import os

# Must happen before jax import anywhere. Force CPU even when the outer
# environment selects a TPU platform (axon) — tests must not need a chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone does not win over an installed TPU platform plugin
# (axon); the config update does.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Dynamic lock-order checker (utils/lockcheck.py): must install BEFORE
# any ekuiper_tpu module allocates its locks, so every engine lock is
# tracked. KUIPER_LOCKCHECK=0 opts out.
from ekuiper_tpu.utils import lockcheck  # noqa: E402

if os.environ.get("KUIPER_LOCKCHECK", "1") != "0":
    lockcheck.install()

#: cycles already reported by a teardown — later teardowns skip them
_reported_lock_cycles: set = set()

from ekuiper_tpu.utils import timex  # noqa: E402
from ekuiper_tpu.store import kv  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_engine_state():
    """Fresh mock clock + in-memory store + empty subtopo/shared-fold
    pools per test."""
    from ekuiper_tpu.planner import sharing
    from ekuiper_tpu.runtime import control, nodes_sharedfold, subtopo

    from ekuiper_tpu.observability import (devwatch, health, jitcert,
                                           kernwatch, memwatch)
    from ekuiper_tpu.runtime.events import recorder

    clock = timex.set_mock_clock(0)
    kv.setup("memory")
    nodes_sharedfold.reset()
    subtopo.reset()
    sharing.reset()
    recorder().clear()
    health.reset()
    control.reset()
    yield clock
    control.reset()
    health.reset()
    nodes_sharedfold.reset()
    subtopo.reset()
    sharing.reset()
    recorder().clear()
    devwatch.registry().clear()
    kernwatch.reset()
    memwatch.registry().clear()
    jitcert.reset()
    from ekuiper_tpu.ops import tierstore

    tierstore.reset()
    from ekuiper_tpu.parallel import sharded

    sharded.reset()
    from ekuiper_tpu.observability import meshwatch, timeline

    meshwatch.reset()
    timeline.reset()
    from ekuiper_tpu.runtime import aotcache

    aotcache.reset()
    timex.use_real_clock()
    # dynamic lock-order teardown check: the acquisition graph
    # accumulates across tests (a consistent GLOBAL order is the
    # invariant); the test that closes an ABBA cycle fails here. Only
    # NEW cycles fail — the graph is never pruned, so without the memo
    # one inversion would cascade into every later test's teardown and
    # bury the culprit
    if lockcheck.installed():
        fresh = [c for c in lockcheck.check()
                 if c not in _reported_lock_cycles]
        _reported_lock_cycles.update(fresh)
        assert not fresh, "\n".join(fresh)


@pytest.fixture
def mock_clock():
    return timex.get_mock_clock()


def wait_for_checkpoint(store, rule_id, cid, timeout=5.0):
    """Poll the persisted checkpoint until `cid` lands; returns the snap.
    Shared by the crash-replay e2e tests (test_checkpoint, test_kafka)."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        snap, ok = store.kv(f"checkpoint:{rule_id}").get_ok("latest")
        if ok and snap.get("checkpoint_id") == cid:
            return snap
        time.sleep(0.01)
    raise AssertionError(f"checkpoint {cid} for {rule_id} never persisted")


def collect_window_result(mem, topic, mock_clock, advance_ms=10_000,
                          timeout=8.0):
    """Subscribe, fire the window boundary, and flatten the emissions to a
    {key_field: ...} message list."""
    import time

    got = []
    mem.subscribe(topic, lambda t, p: got.append(p))
    mock_clock.advance(advance_ms)
    deadline = time.time() + timeout
    while time.time() < deadline and not got:
        time.sleep(0.02)
    msgs = []
    for p in got:
        msgs.extend(p if isinstance(p, list) else [p])
    return msgs
