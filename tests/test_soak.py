"""Small soak tests: rule churn, shared-source attach/detach cycling, and
repeated checkpoint cycles must not leak or wedge the engine."""
import gc
import threading
import time

from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.runtime import subtopo
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _mk_stream(store, name="soak", topic="soak/t"):
    StreamProcessor(store).exec_stmt(
        f'CREATE STREAM {name} (deviceId STRING, v FLOAT) '
        f'WITH (DATASOURCE="{topic}", TYPE="memory", FORMAT="JSON")')


class TestSoak:
    def test_rule_churn_no_thread_leak(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        base_threads = threading.active_count()
        for i in range(10):
            topo = plan_rule(RuleDef(
                id=f"churn{i}", sql="SELECT deviceId, v FROM soak WHERE v > 0",
                actions=[{"memory": {"topic": f"soak/out{i}"}}],
                options={}), store)
            topo.open()
            mem.publish("soak/t", {"deviceId": "a", "v": 1.0})
            mock_clock.advance(20)
            topo.close()
        assert subtopo.pool_size() == 0  # every shared pipeline released
        gc.collect()
        deadline = time.time() + 5
        while time.time() < deadline and \
                threading.active_count() > base_threads + 3:
            time.sleep(0.05)
        # a handful of daemon timers may linger briefly; no unbounded growth
        assert threading.active_count() <= base_threads + 6, \
            [t.name for t in threading.enumerate()]

    def test_concurrent_riders_cycling(self, mock_clock):
        """Rules attaching/detaching the same shared source concurrently
        must neither deadlock nor kill the surviving riders' flow."""
        store = kv.get_store()
        _mk_stream(store, "soak2", "soak2/t")
        stable = plan_rule(RuleDef(
            id="stable", sql="SELECT deviceId FROM soak2",
            actions=[{"memory": {"topic": "soak2/stable"}}], options={}),
            store)
        got = []
        mem.subscribe("soak2/stable", lambda t, p: got.append(p))
        stable.open()
        try:
            for i in range(6):
                t = plan_rule(RuleDef(
                    id=f"cyc{i}", sql="SELECT v FROM soak2",
                    actions=[{"memory": {"topic": f"soak2/c{i}"}}],
                    options={}), store)
                t.open()
                t.close()
            mem.publish("soak2/t", {"deviceId": "alive", "v": 1.0})
            mock_clock.advance(20)
            deadline = time.time() + 5
            while time.time() < deadline and not got:
                time.sleep(0.02)
            assert got, "stable rider lost its feed after churn"
        finally:
            stable.close()
        assert subtopo.pool_size() == 0

    def test_repeated_checkpoints(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store, "soak3", "soak3/t")
        topo = plan_rule(RuleDef(
            id="ck3", sql=("SELECT deviceId, count(*) AS c FROM soak3 "
                           "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "soak3/out"}}],
            options={"qos": 1, "checkpointInterval": 3_600_000}), store)
        topo.open()
        try:
            ck_kv = store.kv("checkpoint:ck3")
            for i in range(5):
                mem.publish("soak3/t", {"deviceId": "a", "v": float(i)})
                mock_clock.advance(20)
                assert topo.wait_idle(10)
                cid = topo.trigger_checkpoint()
                deadline = time.time() + 5
                while time.time() < deadline:
                    snap, ok = ck_kv.get_ok("latest")
                    if ok and snap.get("checkpoint_id") == cid:
                        break
                    time.sleep(0.02)
                assert ok and snap["checkpoint_id"] == cid
            assert not topo._ckpt_pending  # no orphaned pending entries
        finally:
            topo.close()
