"""Shared-source fan-out ingest prep (runtime/subtopo.py SharedPrepCtx +
nodes_fused.py _shared_encode/_shared_device_inputs): N consumers of one
batch share ONE key encode and ONE device upload, with bit-parity against
the self-encoded path and a safe fallback when a consumer's key table
diverged (e.g. restored from a checkpoint)."""
import numpy as np

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.runtime.subtopo import SharedPrepCtx
from ekuiper_tpu.sql.parser import parse_select

SQL = ("SELECT deviceId, avg(temperature) AS a, count(*) AS c, "
       "min(temperature) AS mn FROM s "
       "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")


def make_node(name="f"):
    stmt = parse_select(SQL)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        name, stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=128,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True)
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    return node, got


def batch(n, rng, ctx=None, nulls=False):
    ids = np.array([f"d{rng.integers(0, 40)}" for _ in range(n)],
                   dtype=np.object_)
    temp = rng.normal(20, 5, n).astype(np.float32)
    valid = {}
    if nulls:
        valid["temperature"] = rng.random(n) > 0.15
    b = ColumnBatch(n=n, columns={"deviceId": ids, "temperature": temp},
                    valid=valid,
                    timestamps=np.full(n, 1000, dtype=np.int64), emitter="s")
    if ctx is not None:
        b.ensure_share_state()
        b.shared_ctx = ctx
    return b


def emit_dict(node, got):
    from ekuiper_tpu.data.rows import WindowRange

    node._emit(WindowRange(0, 10_000))
    cb = got[-1]
    return {cb.columns["deviceId"][i]: (
        round(float(cb.columns["a"][i]), 4),
        int(cb.columns["c"][i]),
        round(float(cb.columns["mn"][i]), 4))
        for i in range(cb.n)}


class TestSharedPrepParity:
    def test_two_consumers_share_and_match_self_encoded(self):
        ctx = SharedPrepCtx()
        a, got_a = make_node("a")
        b, got_b = make_node("b")
        ref, got_r = make_node("ref")
        rng = np.random.default_rng(7)
        batches = [batch(100 + 9 * i, rng, ctx=ctx, nulls=(i % 2 == 0))
                   for i in range(4)]
        plain = [ColumnBatch(n=x.n, columns=x.columns, valid=x.valid,
                             timestamps=x.timestamps, emitter=x.emitter)
                 for x in batches]
        for x in batches:
            a.process(x)
            b.process(x)
        for x in plain:
            ref.process(x)
        assert a._shared_slots_ok is True and b._shared_slots_ok is True
        # one shared encode + upload per batch: the share cache holds them
        for x in batches:
            assert ("slots", "deviceId") in x.share_state
            assert any(k[0] == "dcol" for k in x.share_state if k != "__lock__")
        ra, rb, rr = emit_dict(a, got_a), emit_dict(b, got_b), \
            emit_dict(ref, got_r)
        assert ra == rb == rr
        assert sum(c for _, c, _ in ra.values()) == sum(x.n for x in batches)

    def test_diverged_table_falls_back_to_self_encode(self):
        ctx = SharedPrepCtx()
        n, got = make_node("n")
        # a checkpoint restore pre-populated this node's key table with ids
        # the neutral table will never reproduce
        n.kt.encode_column(np.array(["old_x", "old_y"], dtype=np.object_))
        ref, got_r = make_node("ref")
        ref.kt.encode_column(np.array(["old_x", "old_y"], dtype=np.object_))
        rng = np.random.default_rng(8)
        shared = batch(90, rng, ctx=ctx)
        plain = ColumnBatch(n=shared.n, columns=shared.columns,
                            valid=shared.valid,
                            timestamps=shared.timestamps, emitter="s")
        n.process(shared)
        ref.process(plain)
        assert n._shared_slots_ok is False  # detected, self-encoding
        assert emit_dict(n, got) == emit_dict(ref, got_r)

    def test_shared_batch_still_pickles(self):
        """The share cache carries a lock + device arrays; a sink-cache
        disk spill pickles parked items, so pickling must drop the
        per-process share state instead of crashing."""
        import pickle

        ctx = SharedPrepCtx()
        rng = np.random.default_rng(10)
        b = batch(50, rng, ctx=ctx)
        ctx.encode(b, "deviceId")  # populate the share cache
        b2 = pickle.loads(pickle.dumps(b))
        assert b2.n == b.n and b2.share_state is None and b2.shared_ctx is None
        np.testing.assert_array_equal(b2.columns["deviceId"],
                                      b.columns["deviceId"])

    def test_empty_batch_respects_omit_if_empty_on_batch_sink(self):
        from ekuiper_tpu.io.sinks import NopSink
        from ekuiper_tpu.runtime.nodes_sink import SinkNode

        sink = NopSink()
        sink.configure({})
        node = SinkNode("snk", sink, omit_if_empty=True)
        node.process(ColumnBatch(n=0, columns={}, emitter="s"))
        assert node.results == []  # suppressed, not fast-pathed
        full = ColumnBatch(
            n=1, columns={"deviceId": np.array(["a"], dtype=np.object_)},
            emitter="s")
        node.process(full)
        assert node.results == [full]  # columnar fast path, no dict rows

    def test_pruned_copy_rides_same_cache(self):
        ctx = SharedPrepCtx()
        rng = np.random.default_rng(9)
        orig = batch(80, rng, ctx=ctx)
        pruned = ColumnBatch(
            n=orig.n,
            columns={"deviceId": orig.columns["deviceId"],
                     "temperature": orig.columns["temperature"]},
            valid=orig.valid, timestamps=orig.timestamps, emitter="s",
            shared_ctx=orig.shared_ctx, share_state=orig.share_state)
        a, got_a = make_node("a")
        b, got_b = make_node("b")
        a.process(orig)
        b.process(pruned)
        assert orig.share_state is pruned.share_state
        assert ("slots", "deviceId") in orig.share_state
        assert emit_dict(a, got_a) == emit_dict(b, got_b)
