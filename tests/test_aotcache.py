"""runtime/aotcache.py — the AOT executable cache (ISSUE 16).

The zero-compile-serving contract, unit-level: cache keys derived from
jitcert certificate signature strings are byte-stable across fresh
processes (same plan + capacity ladder → identical keys → disk hits),
a toolchain/mesh fingerprint change is a clean miss (never a poisoned
load), disk entries round-trip through serialize/deserialize, warmup
failures land in the counter + flight recorder instead of a debug log,
and admission's compile ledger prices exactly the uncached remainder.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from ekuiper_tpu.runtime import aotcache
from ekuiper_tpu.runtime.events import recorder

REPO = Path(__file__).resolve().parent.parent

# Drives the same plan + one capacity doubling against a shared cache
# dir and prints the cert-derived cache keys plus the aotcache stats —
# two fresh interpreters running THIS must agree byte-for-byte.
_DRIVE = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["KUIPER_AOT_CACHE_DIR"] = sys.argv[1]
import numpy as np
from ekuiper_tpu.observability import jitcert
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.runtime import aotcache
from ekuiper_tpu.sql.parser import parse_select

stmt = parse_select("SELECT deviceId, avg(v) AS a, count(*) AS c "
                    "FROM s GROUP BY deviceId, TUMBLINGWINDOW(ss, 5)")
plan = extract_kernel_plan(stmt)
kt = KeyTable(32)
keys = np.array([f"k{i % 8}" for i in range(16)], dtype=np.object_)
slots, _ = kt.encode_column(keys)
vals = np.arange(16, dtype=np.float32)
for cap in (32, 64):  # the capacity ladder: two rungs, same plan
    gb = DeviceGroupBy(plan, capacity=cap, micro_batch=16)
    state = gb.fold(gb.init_state(), {"v": vals}, slots)
    gb.finalize(state, kt.n_keys)
certs = jitcert.estimate_plan_certs(plan, 1, 16, 32)
print(json.dumps({
    "cert_keys": sorted(
        aotcache.cache_key(c.op, s)
        for c in certs if not c.truncated for s in c.signatures),
    "fingerprint": aotcache.fingerprint(),
    "stats": aotcache.stats().snapshot(),
}))
"""


def _drive_process(cache_dir: str) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([sys.executable, "-c", _DRIVE, cache_dir],
                       capture_output=True, text=True, timeout=300,
                       cwd=str(REPO), env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_cache_keys_stable_and_warm_across_processes(tmp_path):
    """THE stability contract: two fresh interpreters derive
    byte-identical cert cache keys for the same plan + capacity ladder,
    and the second serves every executable from the first's disk cache
    (zero compiles — a process restart costs deserialization only)."""
    cache = str(tmp_path / "aot")
    first = _drive_process(cache)
    second = _drive_process(cache)
    assert first["cert_keys"] == second["cert_keys"]
    assert first["fingerprint"] == second["fingerprint"]
    assert first["stats"]["builds"] > 0
    assert first["stats"]["executables"] > 0
    # the restart: everything the drive traces comes off disk
    assert second["stats"]["builds"] == 0
    assert second["stats"]["misses"] == 0
    assert second["stats"]["disk_loads"] > 0


def test_fingerprint_change_is_clean_miss(tmp_path, monkeypatch):
    """A jaxlib-version or mesh-shape change re-keys every entry: the
    old executables are unreachable (clean miss + rebuild), never a
    poisoned load."""
    monkeypatch.setenv("KUIPER_AOT_CACHE_DIR", str(tmp_path))
    sig = "float32[8]"
    key = aotcache.cache_key("op.x", sig)
    (tmp_path / f"{key}.aotx").write_bytes(b"placeholder")
    assert aotcache.is_cached("op.x", sig)
    real = aotcache._fingerprint_parts()
    monkeypatch.setattr(
        aotcache, "_fingerprint_parts",
        lambda: tuple("jaxlib=9.9.9" if p.startswith("jaxlib=") else p
                      for p in real))
    assert aotcache.cache_key("op.x", sig) != key
    assert not aotcache.is_cached("op.x", sig)
    mesh = tuple("mesh=2x4" if p.startswith("mesh=") else p for p in real)
    monkeypatch.setattr(aotcache, "_fingerprint_parts", lambda: mesh)
    assert aotcache.cache_key("op.x", sig) != key
    assert not aotcache.is_cached("op.x", sig)


def test_disk_roundtrip_and_probe(tmp_path, monkeypatch):
    """An aot_jit site persists on first trace and a FRESH site object
    (same op — a restart's new kernel instance) serves from disk."""
    monkeypatch.setenv("KUIPER_AOT_CACHE_DIR", str(tmp_path))

    def f(x):
        return x * 2.0

    site = aotcache.aot_jit(f, op="test.roundtrip")
    x = jnp.arange(8, dtype=jnp.float32)
    assert site.probe(x) == "built"  # warmup's compile, nothing executed
    np.testing.assert_allclose(site(x), np.arange(8) * 2.0)
    assert site.probe(x) == "mem"
    assert aotcache.stats().snapshot()["builds"] == 1
    assert any(p.suffix == ".aotx" for p in tmp_path.iterdir())
    fresh = aotcache.aot_jit(f, op="test.roundtrip")
    assert fresh.probe(x) == "disk"
    np.testing.assert_allclose(fresh(x), np.arange(8) * 2.0)
    snap = aotcache.stats().snapshot()
    assert snap["builds"] == 1  # no recompile on the fresh site
    assert snap["disk_loads"] >= 1


def test_corrupt_entry_is_rebuilt(tmp_path, monkeypatch):
    """A truncated/corrupt .aotx must never poison serving: the load
    fails closed, the entry is dropped, and the site rebuilds."""
    monkeypatch.setenv("KUIPER_AOT_CACHE_DIR", str(tmp_path))

    def f(x):
        return x + 1.0

    x = jnp.arange(4, dtype=jnp.float32)
    site = aotcache.aot_jit(f, op="test.corrupt")
    site(x)
    entries = [p for p in tmp_path.iterdir() if p.suffix == ".aotx"]
    assert len(entries) == 1
    entries[0].write_bytes(b"\x80garbage")
    fresh = aotcache.aot_jit(f, op="test.corrupt")
    np.testing.assert_allclose(fresh(x), np.arange(4) + 1.0)
    assert aotcache.stats().snapshot()["builds"] == 2  # rebuilt
    assert not entries[0].exists() or entries[0].read_bytes() != b"\x80garbage"


def test_serve_miss_outside_build_scope_leaves_paper_trail(tmp_path,
                                                          monkeypatch):
    """A compile at serve time (outside aotcache.building()) is the
    failure mode this subsystem exists to eliminate: it must count as a
    serve miss AND drop an aot_cache_miss flight event."""
    monkeypatch.setenv("KUIPER_AOT_CACHE_DIR", str(tmp_path))
    recorder().clear()

    def f(x):
        return x - 1.0

    site = aotcache.aot_jit(f, op="test.servemiss")
    site(jnp.arange(4, dtype=jnp.float32))
    assert aotcache.stats().snapshot()["serve_misses"] == 1
    evs = recorder().events(kind="aot_cache_miss")
    assert evs and evs[-1]["op"] == "test.servemiss"
    # the same compile INSIDE a build scope is not a serve miss
    recorder().clear()
    with aotcache.building():
        site(jnp.arange(16, dtype=jnp.float32))
    assert aotcache.stats().snapshot()["serve_misses"] == 1
    assert not recorder().events(kind="aot_cache_miss")


def test_warmup_failure_counter_and_flight_event():
    """Satellite 2: a swallowed warmup failure was a silent serve-time
    compile storm — it now lands in kuiper_warmup_failures_total and
    the flight recorder with the failing stage attached."""
    recorder().clear()
    aotcache.note_warmup_failure("r_test", "ring",
                                 RuntimeError("synthetic"))
    assert aotcache.stats().snapshot()["warmup_failures"] == 1
    evs = recorder().events(kind="warmup_failure")
    assert evs
    ev = evs[-1]
    assert ev["rule"] == "r_test"
    assert ev["severity"] == "warn"
    assert ev["stage"] == "ring"
    assert "synthetic" in ev["error"]


def test_plan_compile_price_prices_uncached_remainder(tmp_path,
                                                     monkeypatch):
    """Admission's ledger: certified counts come from the cert product
    formula; cached counts from disk probes; uncached is the compile
    debt a new rule actually pays on a warm image."""
    from ekuiper_tpu.observability.jitcert import SiteCert

    monkeypatch.setenv("KUIPER_AOT_CACHE_DIR", str(tmp_path))

    def f(x):
        return x * 3.0

    site = aotcache.aot_jit(f, op="test.price")
    site(jnp.arange(8, dtype=jnp.float32))  # persists "float32[8]"
    certs = [
        SiteCert(op="test.price", rule=None, builder="b", params={},
                 signatures=frozenset({"float32[8]", "float32[16]"}),
                 full_count=2),
    ]
    ledger = aotcache.plan_compile_price(certs)
    assert ledger["enabled"] is True
    assert ledger["certified"] == 2
    assert ledger["cached"] == 1
    assert ledger["uncached"] == 1
    assert ledger["sites"] == [
        {"op": "test.price", "certified": 2, "cached": 1}]


def test_disabled_falls_back_to_plain_watched_jit(monkeypatch):
    """KUIPER_AOT=0 keeps serving on the plain devwatch path — the
    cache must be an opt-out, not a dependency."""
    monkeypatch.setenv("KUIPER_AOT", "0")
    assert not aotcache.enabled()

    def f(x):
        return x

    site = aotcache.aot_jit(f, op="test.disabled")
    assert not isinstance(site, aotcache._AotJit)
    np.testing.assert_allclose(site(jnp.arange(4.0)), np.arange(4.0))


def test_prometheus_families_render():
    out = []
    aotcache.render_prometheus(out, lambda s: s)
    text = "\n".join(out)
    for fam in ("kuiper_aot_hits_total", "kuiper_aot_misses_total",
                "kuiper_aot_serve_misses_total",
                "kuiper_aot_disk_loads_total",
                "kuiper_aot_build_seconds", "kuiper_aot_executables",
                "kuiper_warmup_failures_total"):
        assert f"# TYPE {fam}" in text, fam
