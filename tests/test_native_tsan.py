"""ThreadSanitizer stress for the native shard parser (native/jsoncol.cpp).

The GIL-free decode pass fans N std::threads over ONE shared set of
output allocations (disjoint row slices of the same numpy buffers) and
had zero sanitizer coverage before this suite: a torn write there would
corrupt columns silently, and only on multi-shard configs. The test
builds the `make tsan` module, then stress-drives multi-shard decodes
from several Python threads (plus keytab encodes, whose appendix/commit
path shares the table across batches) in a subprocess running under
libtsan, and fails on any ThreadSanitizer report.

Skips with an explicit reason when the sanitizer toolchain is missing
(no g++, no libtsan, or the instrumented build fails) — the suite must
stay green on minimal images. docs/STATIC_ANALYSIS.md § Sanitizer builds.
"""
import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
TSAN_SO = NATIVE / "build" / "tsan" / "ekjsoncol.so"

# the stress driver runs inside the TSAN-preloaded subprocess; kept as a
# string so the test file itself never imports the instrumented module
DRIVER = r"""
import sys, threading
sys.path.insert(0, sys.argv[1])  # build/tsan — shadows any regular build
import ekjsoncol

ROWS = [
    (b'{"dev": "sensor-%d", "temp": %d.5, "n": %d, "ok": true}'
     % (i % 13, i % 90, i)) for i in range(4096)
]
SPEC = (("temp", 0), ("n", 1), ("ok", 2), ("dev", 3))
BAD = list(ROWS)
BAD[17] = b'{"temp": not-json'            # bad-row marking across shards
BAD[4090] = b'{"dev": "x", "temp": "4.25"}'  # string->float cast path

errs = []

def decode_loop():
    try:
        for _ in range(6):
            cols, valid, bad = ekjsoncol.decode(ROWS, SPEC, 4)
            assert not bad.any()
            cols, valid, bad = ekjsoncol.decode(BAD, SPEC, 4)
            assert bad[17] and not bad[4090]
    except BaseException as exc:  # noqa: BLE001 - surfaced below
        errs.append(exc)

def keytab_loop():
    try:
        tab = ekjsoncol.keytab_new()
        keys = [f"dev-{i % 257}" for i in range(4096)]
        for _ in range(6):
            slots, appendix = ekjsoncol.keytab_encode(tab, keys)
            assert len(slots) == len(keys)
    except BaseException as exc:  # noqa: BLE001
        errs.append(exc)

threads = [threading.Thread(target=decode_loop) for _ in range(3)]
threads.append(threading.Thread(target=keytab_loop))
for t in threads:
    t.start()
for t in threads:
    t.join()
if errs:
    raise SystemExit(f"stress driver failed: {errs[0]!r}")
print("TSAN_STRESS_OK")
"""


def _libtsan() -> str:
    """Absolute path of libtsan, or '' when the toolchain can't provide
    it (g++ echoes the bare name back when the library is unknown)."""
    gxx = shutil.which("g++")
    if gxx is None:
        return ""
    for name in ("libtsan.so", "libtsan.so.0", "libtsan.so.2"):
        try:
            out = subprocess.run(
                [gxx, f"-print-file-name={name}"], capture_output=True,
                text=True, timeout=30).stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            return ""
        if out and out != name and os.path.exists(out):
            return out
    return ""


def _ensure_tsan_build() -> None:
    """`make tsan`, cached on source mtime like check_native's build."""
    src = NATIVE / "jsoncol.cpp"
    if TSAN_SO.exists() and TSAN_SO.stat().st_mtime >= src.stat().st_mtime:
        return
    proc = subprocess.run(
        ["make", "-C", str(NATIVE), "tsan", f"PYTHON={sys.executable}"],
        capture_output=True, text=True, timeout=300)
    if proc.returncode != 0 or not TSAN_SO.exists():
        pytest.skip("sanitizer build failed — no TSAN coverage on this "
                    f"toolchain:\n{proc.stdout}\n{proc.stderr}")


def test_shard_parse_keytab_race_free():
    if not shutil.which("g++") or not shutil.which("make"):
        pytest.skip("no g++/make — sanitizer toolchain not present")
    libtsan = _libtsan()
    if not libtsan:
        pytest.skip("g++ has no libtsan — sanitizer runtime not present")
    _ensure_tsan_build()

    env = dict(os.environ)
    # preload: the instrumented .so needs the TSAN runtime resident
    # before the (uninstrumented) python binary maps it
    env["LD_PRELOAD"] = libtsan
    # keep running past a report so every race in the run is captured;
    # exitcode=66 still fails the subprocess at exit when any fired
    env["TSAN_OPTIONS"] = "exitcode=66 halt_on_error=0"
    proc = subprocess.run(
        [sys.executable, "-c", DRIVER, str(TSAN_SO.parent)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    report = f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    assert "WARNING: ThreadSanitizer" not in report, (
        "data race in the native shard parse/keytab path:\n" + report)
    assert proc.returncode == 0 and "TSAN_STRESS_OK" in proc.stdout, (
        "TSAN stress driver did not complete cleanly:\n" + report)
