"""Column pruning (planner/optimizer.py) through the planner and runtime."""
import time

from ekuiper_tpu.planner.optimizer import referenced_columns
from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


class TestReferencedColumns:
    def test_collects_all_clauses(self):
        stmt = parse_select(
            "SELECT a, avg(b) AS x FROM s WHERE c > 1 "
            "GROUP BY a, TUMBLINGWINDOW(ss, 10) HAVING avg(b) > 2 "
            "ORDER BY d")
        assert referenced_columns(stmt) == {"a", "b", "c", "d"}

    def test_wildcard_disables(self):
        assert referenced_columns(parse_select("SELECT * FROM s")) is None

    def test_count_star_is_fine(self):
        stmt = parse_select(
            "SELECT count(*) AS c, a FROM s GROUP BY a, TUMBLINGWINDOW(ss, 5)")
        assert referenced_columns(stmt) == {"a"}

    def test_join_on_included(self):
        stmt = parse_select(
            "SELECT l.a FROM l INNER JOIN r ON l.k = r.k2 "
            "GROUP BY TUMBLINGWINDOW(ss, 5)")
        assert referenced_columns(stmt) == {"a", "k", "k2"}


class TestPruningE2E:
    def _run(self, sql, row, options=None):
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo () '
            'WITH (DATASOURCE="pr/demo", TYPE="memory", FORMAT="JSON")')
        topo = plan_rule(RuleDef(
            id="pr1", sql=sql, actions=[{"memory": {"topic": "pr/out"}}],
            options=options or {}), store)
        got = []
        mem.subscribe("pr/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("pr/demo", row)
            from ekuiper_tpu.utils import timex

            timex.get_mock_clock().advance(20)
            deadline = time.time() + 5
            while time.time() < deadline and not got:
                time.sleep(0.02)
        finally:
            topo.close()
        out = []
        for p in got:
            out.extend(p if isinstance(p, list) else [p])
        return out, topo

    def test_shared_entry_prunes(self, mock_clock):
        row = {"a": 1, "b": 2.5, "noise1": "x" * 100, "noise2": [1, 2, 3]}
        out, topo = self._run("SELECT a, b FROM demo WHERE b > 1", row)
        assert out == [{"a": 1, "b": 2.5}]
        entry = next(n for n in topo.ops if n.name.endswith("_shared"))
        assert entry.project_columns == {"a", "b"}

    def test_private_source_prunes(self, mock_clock):
        row = {"a": 7, "junk": "drop me"}
        out, topo = self._run("SELECT a FROM demo", row,
                              options={"share_source": False})
        assert out == [{"a": 7}]
        assert topo.sources[0].project_columns == {"a"}

    def test_select_star_keeps_everything(self, mock_clock):
        row = {"a": 1, "keep": "yes"}
        out, topo = self._run("SELECT * FROM demo", row)
        assert out and out[0] == row
        entry = next(n for n in topo.ops if n.name.endswith("_shared"))
        assert entry.project_columns is None
