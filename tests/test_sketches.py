"""Sketch UDF tests: HLL distinct count, approximate percentiles, count-min
heavy hitters — accuracy bounds and device/host/sharded consistency."""
import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.ops.sketches import CountMinSketch
from ekuiper_tpu.sql.parser import parse_select


def _plan(sql):
    plan = extract_kernel_plan(parse_select(sql))
    assert plan is not None
    return plan


class TestHLL:
    def test_distinct_count_accuracy(self):
        plan = _plan(
            "SELECT hll(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=4096)
        kt = KeyTable(8)
        rng = np.random.default_rng(7)
        true_distinct = 5000
        vals = rng.permutation(
            np.repeat(np.arange(true_distinct, dtype=np.float32), 3)
        )
        slots, _ = kt.encode_column(np.array(["a"] * len(vals), dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, act = gb.finalize(state, kt.n_keys)
        est = int(outs[0][0])
        # m=256 registers -> ~6.5% std error; allow 3 sigma
        assert abs(est - true_distinct) / true_distinct < 0.20, est
        assert outs[0].dtype == np.int64

    def test_small_cardinality_exactish(self):
        plan = _plan("SELECT distinct_count_approx(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=64)
        kt = KeyTable(8)
        vals = np.array([1.0, 2.0, 3.0, 1.0, 2.0], dtype=np.float32)
        slots, _ = kt.encode_column(np.array(["a"] * 5, dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert 2 <= outs[0][0] <= 4  # small-range correction keeps it close

    def test_per_key_isolation(self):
        plan = _plan("SELECT hll(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=256)
        kt = KeyTable(8)
        keys = np.array(["a"] * 100 + ["b"] * 10, dtype=np.object_)
        vals = np.concatenate([
            np.arange(100, dtype=np.float32),
            np.arange(10, dtype=np.float32),
        ])
        slots, _ = kt.encode_column(keys)
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        a, b = outs[0][0], outs[0][1]
        assert abs(a - 100) / 100 < 0.3 and abs(b - 10) <= 3

    def test_pane_merge(self):
        # hll over hopping panes merges registers by max (distinct across panes)
        plan = _plan("SELECT hll(v) FROM s GROUP BY k, HOPPINGWINDOW(ss, 10, 5)")
        gb = DeviceGroupBy(plan, capacity=8, n_panes=2, micro_batch=64)
        kt = KeyTable(8)
        slots, _ = kt.encode_column(np.array(["a"] * 10, dtype=np.object_))
        v1 = np.arange(10, dtype=np.float32)
        v2 = np.arange(10, dtype=np.float32)  # same values in pane 2
        state = gb.init_state()
        state = gb.fold(state, {"v": v1}, slots, pane_idx=0)
        state = gb.fold(state, {"v": v2}, slots, pane_idx=1)
        outs, _ = gb.finalize(state, kt.n_keys)
        # same 10 distinct values in both panes -> still ~10, not ~20
        assert outs[0][0] <= 14


class TestPercentileApprox:
    def test_quantiles(self):
        plan = _plan(
            "SELECT percentile_approx(v, 0.5), percentile_approx(v, 0.99) "
            "FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8192)
        kt = KeyTable(8)
        rng = np.random.default_rng(1)
        vals = rng.lognormal(3.0, 1.0, 8192).astype(np.float32)
        slots, _ = kt.encode_column(np.array(["a"] * len(vals), dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        p50_true = float(np.percentile(vals, 50))
        p99_true = float(np.percentile(vals, 99))
        assert abs(outs[0][0] - p50_true) / p50_true < 0.10
        assert abs(outs[1][0] - p99_true) / p99_true < 0.10

    def test_empty_group_nan(self):
        plan = _plan("SELECT percentile_approx(v, 0.5) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        slots, _ = kt.encode_column(np.array(["a"], dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": np.array([np.nan], np.float32)}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert np.isnan(outs[0][0])

    def test_non_literal_frac_rejected(self):
        stmt = parse_select(
            "SELECT percentile_approx(v, f) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        assert extract_kernel_plan(stmt) is None


class TestCountMin:
    def test_heavy_hitters(self):
        cms = CountMinSketch(depth=4, width=2048)
        rng = np.random.default_rng(2)
        # zipf-ish: value i appears ~1000/i times
        vals = []
        for i in range(1, 50):
            vals.extend([float(i)] * (1000 // i))
        vals = np.array(vals, dtype=np.float32)
        rng.shuffle(vals)
        for start in range(0, len(vals), 1000):
            cms.update(vals[start:start + 1000])
        top = cms.heavy_hitters(3)
        top_vals = [v for v, _ in top]
        assert top_vals[0] == 1.0 and set(top_vals) == {1.0, 2.0, 3.0}
        # estimates within cm error bound (overestimate only)
        assert top[0][1] >= 1000 and top[0][1] < 1000 * 1.2

    def test_reset(self):
        cms = CountMinSketch(depth=2, width=64)
        cms.update(np.array([1.0, 1.0], dtype=np.float32))
        cms.reset()
        assert cms.heavy_hitters(1) == []


class TestSketchHostFallback:
    def test_host_exec(self):
        from ekuiper_tpu.data.rows import GroupedTuples, Tuple
        from ekuiper_tpu.sql.eval import Evaluator

        rows = [Tuple(message={"v": float(i % 3), "w": i}) for i in range(9)]
        g = GroupedTuples(content=rows)
        ev = Evaluator()
        e = parse_select("SELECT hll(v) FROM t").fields[0].expr
        assert ev.eval(e, g) == 3
        e2 = parse_select("SELECT heavy_hitters(v, 1) FROM t").fields[0].expr
        assert ev.eval(e2, g)[0]["count"] == 3
        e3 = parse_select("SELECT percentile_approx(w, 0.5) FROM t").fields[0].expr
        assert ev.eval(e3, g) == 4.0


class TestShardedSketch:
    def test_hll_sharded_matches(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        from ekuiper_tpu.parallel.mesh import make_mesh
        from ekuiper_tpu.parallel.sharded import ShardedGroupBy

        sql = "SELECT hll(v), count(*) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        plan_s = _plan(sql)
        plan_d = _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan_s, mesh, capacity=32, micro_batch=128)
        gb = DeviceGroupBy(plan_d, capacity=32, micro_batch=128)
        kt = KeyTable(32)
        rng = np.random.default_rng(3)
        keys = np.array([f"k{rng.integers(6)}" for _ in range(600)], dtype=np.object_)
        vals = rng.integers(0, 200, 600).astype(np.float32)
        slots, _ = kt.encode_column(keys)
        s_state = sgb.fold(sgb.init_state(), {"v": vals}, slots)
        d_state = gb.fold(gb.init_state(), {"v": vals}, slots)
        s_outs, _ = sgb.finalize(s_state, kt.n_keys)
        d_outs, _ = gb.finalize(d_state, kt.n_keys)
        np.testing.assert_array_equal(s_outs[0], d_outs[0])  # same registers -> same estimate
        np.testing.assert_array_equal(s_outs[1], d_outs[1])


class TestSketchRegressions:
    """Regressions from code review: shared-column corruption, cross-batch
    hash consistency, signed percentiles, heavy_hitters validation."""

    def test_hll_does_not_corrupt_shared_column(self):
        # avg(v) and hll(v) over the SAME column: avg must see raw numerics
        # even when the batch dtype is object (mixed stream)
        plan = _plan(
            "SELECT hll(v), avg(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=64)
        kt = KeyTable(8)
        vals = np.array([10.0, 20.0, 30.0, "oops"], dtype=np.object_)
        slots, _ = kt.encode_column(np.array(["a"] * 4, dtype=np.object_))
        # object column reaches fold as in FusedWindowAggNode: raw coerced
        coerced = np.array([10.0, 20.0, 30.0, np.nan], dtype=np.float32)
        state = gb.fold(gb.init_state(), {"v": coerced}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        avg_idx = next(
            i for i, s in enumerate(plan.specs) if s.kind == "avg"
        )
        assert outs[avg_idx][0] == 20.0  # mean of raw values, not hashes

    def test_hll_consistent_across_batch_dtypes(self):
        # the same numeric value must fold to the same register whether its
        # micro-batch inferred float32 or object dtype
        plan = _plan("SELECT hll(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=64)
        kt = KeyTable(8)
        slots, _ = kt.encode_column(np.array(["a"] * 3, dtype=np.object_))
        state = gb.init_state()
        # batch 1: clean float batch
        state = gb.fold(state, {"v": np.array([1.0, 2.0, 3.0], dtype=np.float32)}, slots)
        # batch 2: same values but object dtype (one stray string elsewhere)
        state = gb.fold(state, {"v": np.array([1.0, 2.0, 3.0], dtype=np.object_)}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert 2 <= outs[0][0] <= 4  # ~3 distinct, NOT ~6

    def test_percentile_negative_values(self):
        plan = _plan(
            "SELECT percentile_approx(v, 0.5) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=256)
        kt = KeyTable(8)
        vals = np.linspace(-30.0, -5.0, 101).astype(np.float32)
        slots, _ = kt.encode_column(np.array(["a"] * len(vals), dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        med = float(outs[0][0])
        assert -19.5 <= med <= -15.5, med  # true median -17.5, ~5% bins

    def test_percentile_mixed_sign(self):
        plan = _plan(
            "SELECT percentile_approx(v, 0.5) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=256)
        kt = KeyTable(8)
        vals = np.array([-10.0] * 40 + [0.0] * 30 + [10.0] * 40, dtype=np.float32)
        slots, _ = kt.encode_column(np.array(["a"] * len(vals), dtype=np.object_))
        state = gb.fold(gb.init_state(), {"v": vals}, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert abs(float(outs[0][0])) < 1e-6  # median is the zero bin

    def test_heavy_hitters_arity_rejected_at_parse(self):
        from ekuiper_tpu.sql.parser import ParseError

        with pytest.raises(ParseError, match="heavy_hitters"):
            parse_select("SELECT heavy_hitters(v) FROM s GROUP BY COUNTWINDOW(5)")

    def test_heavy_hitters_unhashable_values(self):
        from ekuiper_tpu.functions.funcs_sketch import f_heavy_hitters

        rows = [{"a": 1}, {"a": 1}, {"b": 2}]
        out = f_heavy_hitters([rows, 2], None)
        assert out[0]["count"] == 2

    def test_hll_large_integer_ids(self):
        # ~1e9-range IDs differ below float32 resolution; encoding must not
        # collapse them (and int vs object batches must agree)
        plan = _plan("SELECT hll(v) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=256)
        kt = KeyTable(8)
        ids = np.arange(1_000_000_000, 1_000_000_100, dtype=np.int64)
        slots, _ = kt.encode_column(np.array(["a"] * len(ids), dtype=np.object_))
        state = gb.init_state()
        state = gb.fold(state, {"v": ids}, slots)                       # int batch
        state = gb.fold(state, {"v": ids.astype(np.object_)}, slots)   # object batch
        outs, _ = gb.finalize(state, kt.n_keys)
        est = int(outs[0][0])
        assert 75 <= est <= 130, est  # ~100 distinct, not ~3 or ~200

    def test_countmin_late_heavy_hitter_displaces(self):
        from ekuiper_tpu.ops.sketches import CountMinSketch

        cms = CountMinSketch(depth=4, width=8192, max_candidates=8)
        cms.update(np.arange(8, dtype=np.float32))       # fill candidates
        cms.update(np.full(50, 99.0, dtype=np.float32))  # late frequent value
        top = cms.heavy_hitters(1)
        assert top and top[0][0] == 99.0, top
