"""Fleet observatory (meshwatch + durable timeline): shard-skew
attribution and the rebalance-hint hysteresis, the collective split's
bounds, delta-encoded timeline segments with hard-kill recovery and
byte/age retention, the flight-recorder mirror, and the REST handlers.
All CPU — fake sharded kernels stand in for the mesh; the real 8-device
integration lives in tools/probe_fleetobs.py and
tests/test_multichip_serving.py."""
import json
import os
import types

import pytest

from ekuiper_tpu.observability import health, meshwatch
from ekuiper_tpu.observability import timeline as tmod
from ekuiper_tpu.parallel import sharded as sharded_mod
from ekuiper_tpu.runtime import control
from ekuiper_tpu.runtime.events import FlightRecorder, recorder
from ekuiper_tpu.utils import timex


class FakeSharded:
    """Just enough surface for the observatory: mutable per-shard rows,
    a mesh tag, and a collective payload estimate."""

    def __init__(self, rows, mesh_tag="2x4", bytes_per_fold=256):
        self.rows = list(rows)
        self.keys = [max(r // 10, 1) for r in self.rows]
        self.mesh_tag = mesh_tag
        self._bpf = bytes_per_fold
        self.capacity = 64

    def shard_stats(self):
        return [{"shard": i, "rows": r, "keys": k, "slots": 32,
                 "state_bytes": 128}
                for i, (r, k) in enumerate(zip(self.rows, self.keys))]

    def collective_bytes_per_fold(self):
        return self._bpf


def _register(kernel, rule):
    sharded_mod.registry().register(kernel, rule)
    return kernel


# ---------------------------------------------------------------- meshwatch
class TestMeshWatch:
    def test_skew_flagged_above_threshold(self, mock_clock):
        k = _register(FakeSharded([800, 100, 50, 50]), "r_hot")
        rep = meshwatch.observe()
        e = rep["r_hot"]
        assert e["skewed"] and e["hot_shard"] == 0
        assert e["skew_ratio"] == pytest.approx(800 / 250.0)
        assert e["mesh"] == "2x4"
        assert len(e["shards"]) == 4
        del k

    def test_uniform_not_flagged(self, mock_clock):
        k = _register(FakeSharded([260, 250, 240, 255]), "r_flat")
        e = meshwatch.observe()["r_flat"]
        assert not e["skewed"]
        assert e["skew_ratio"] < meshwatch.skew_threshold()
        del k

    def test_quiet_window_carries_prior_skew(self, mock_clock):
        k = _register(FakeSharded([900, 60, 20, 20]), "r_carry")
        first = meshwatch.observe()["r_carry"]
        assert first["skewed"]
        # no new rows: the delta window is 0 < min_rows — a quiet
        # interval is NOT evidence the imbalance cleared
        mock_clock.advance(1000)
        second = meshwatch.observe()["r_carry"]
        assert second["skewed"]
        assert second["skew_ratio"] == pytest.approx(first["skew_ratio"])
        del k

    def test_window_delta_and_rebaseline(self, mock_clock):
        k = _register(FakeSharded([250, 250, 250, 250]), "r_delta")
        assert not meshwatch.observe()["r_delta"]["skewed"]
        # the NEXT window is skewed even though cumulative looks flat
        k.rows = [1250, 270, 260, 260]
        mock_clock.advance(1000)
        e = meshwatch.observe()["r_delta"]
        assert e["skewed"] and e["window_rows"] == 1040
        # restore drops the counters: negative delta re-baselines off
        # the fresh cumulative instead of going negative
        k.rows = [400, 10, 0, 0]
        mock_clock.advance(1000)
        e = meshwatch.observe()["r_delta"]
        assert e["window_rows"] == 410
        del k

    def test_threshold_env_override(self, mock_clock, monkeypatch):
        monkeypatch.setenv("KUIPER_MESH_SKEW_THRESHOLD", "10.0")
        meshwatch.reset()
        k = _register(FakeSharded([800, 100, 50, 50]), "r_env")
        e = meshwatch.observe()["r_env"]
        assert e["skew_ratio"] > 3 and not e["skewed"]
        del k

    def test_collective_split_bounded_by_device_time(self, mock_clock):
        from ekuiper_tpu.observability import devwatch

        k = _register(FakeSharded([300, 300], bytes_per_fold=10 ** 9),
                      "r_coll")
        meshwatch.observe()  # primes the bytes cache off the kernel
        site = devwatch.registry().register("sharded.fold_step", "r_coll")
        site.kern.record_sample(dispatch_us=10.0, total_us=500.0)
        split = meshwatch.collective_split()
        v = split[("sharded.fold_step", "r_coll")]
        # an absurd payload must clamp to the sampled device time, and
        # the share can never exceed 1.0
        assert v["collective_us"] == pytest.approx(v["device_us"])
        assert 0.0 <= v["share"] <= 1.0
        assert v["compute_us"] == pytest.approx(0.0)
        devwatch.registry().clear()
        del k

    def test_render_families(self, mock_clock):
        from ekuiper_tpu.observability import devwatch

        k = _register(FakeSharded([700, 100]), "r_render")
        meshwatch.observe()
        mock_clock.advance(1000)
        k.rows = [1400, 200]
        meshwatch.observe()  # second pass -> rows/s EWMA has a rate
        site = devwatch.registry().register("sharded.fold_step",
                                            "r_render")
        site.kern.record_sample(dispatch_us=5.0, total_us=100.0)
        out: list = []
        meshwatch.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        assert 'kuiper_mesh_skew_ratio{rule="r_render"}' in text
        assert 'kuiper_mesh_shard_rows_per_s{rule="r_render",shard="0"}' \
            in text
        assert "kuiper_mesh_collective_ms" in text
        assert "kuiper_mesh_collective_share" in text
        devwatch.registry().clear()
        del k


# ------------------------------------------------- health + control wiring
class TestSkewVerdictAndHint:
    def _tick_both(self, hv, ctl, clock, n):
        for _ in range(n):
            hv.tick()
            ctl.tick()
            clock.advance(1000)

    def test_shard_skew_verdict_and_single_hint(self, mock_clock):
        k = _register(FakeSharded([800, 100, 50, 50]), "r_skew")
        stub = types.SimpleNamespace()
        triples = [("r_skew", stub, {})]
        hv = health.install(lambda: triples, start=False)
        ctl = control.install(lambda: triples, start=False,
                              verdicts_fn=lambda: hv.verdicts())
        self._tick_both(hv, ctl, mock_clock, ctl.up_ticks + 2)
        v = hv.verdicts()["r_skew"]
        bn = v["bottleneck"]
        assert bn["stage"] == "shard_skew"
        assert bn["node"] == "shard:0"
        assert bn["mesh"]["skewed"] and bn["mesh"]["hot_shard"] == 0
        # hysteresis: exactly ONE warn hint however long the skew holds
        hints = recorder().events(kind="rebalance_hint")
        assert len(hints) == 1
        assert hints[0]["severity"] == "warn"
        assert hints[0]["rule"] == "r_skew"
        assert hints[0]["skew_ratio"] > 2
        md = ctl.diagnostics()["mesh"]
        assert md["rebalance_hints_total"] == 1
        assert md["rules"]["r_skew"]["hint_active"]

        # drain: balanced windows clear the run and emit ONE info event
        mock_clock.advance(1000)
        k.rows = [r + 500 for r in k.rows]  # uniform delta
        self._tick_both(hv, ctl, mock_clock, ctl.up_ticks + 2)
        evs = recorder().events(kind="rebalance_hint")
        cleared = [e for e in evs if e.get("cleared")]
        assert len(cleared) == 1 and cleared[0]["severity"] == "info"
        # a fully drained rule is pruned from the hysteresis view
        assert "r_skew" not in ctl.diagnostics()["mesh"]["rules"]
        del k

    def test_uniform_rule_never_hints(self, mock_clock):
        k = _register(FakeSharded([300, 280, 290, 310]), "r_ok")
        stub = types.SimpleNamespace()
        triples = [("r_ok", stub, {})]
        hv = health.install(lambda: triples, start=False)
        ctl = control.install(lambda: triples, start=False,
                              verdicts_fn=lambda: hv.verdicts())
        self._tick_both(hv, ctl, mock_clock, 4)
        bn = hv.verdicts()["r_ok"]["bottleneck"]
        assert bn.get("stage") != "shard_skew"
        assert bn["mesh"]["skewed"] is False  # detail present, signal off
        assert recorder().events(kind="rebalance_hint") == []
        del k

    def test_explain_mesh_section(self, mock_clock, monkeypatch):
        from ekuiper_tpu.planner.planner import RuleDef, explain
        from ekuiper_tpu.store import kv

        monkeypatch.setenv("KUIPER_MESH", "2x4")
        k = _register(FakeSharded([800, 100, 50, 50]), "exp_rule")
        meshwatch.observe()
        out = explain(RuleDef(
            id="exp_rule",
            sql=("SELECT k, count(*) AS c FROM d "
                 "GROUP BY k, TUMBLINGWINDOW(ss, 10)"),
            options={"planOptimizeStrategy": {"shards": "auto"}}),
            kv.get_store())
        assert out["shards"]["mode"] == "sharded"
        mesh = out.get("mesh")
        assert mesh is not None
        assert mesh["skew"]["skewed"]
        assert mesh["threshold"] == meshwatch.skew_threshold()
        del k


# ----------------------------------------------------------------- timeline
class TestTimeline:
    def _mk(self, tmp_path, scrape, **kw):
        return tmod.Timeline(scrape, base_dir=str(tmp_path / "tl"),
                             interval_ms=0, **kw)

    def test_delta_encoding_and_replay(self, tmp_path, mock_clock):
        vals = {"a": 1, "b": 2}

        def scrape():
            return "".join(f"kuiper_x_{k} {v}\n" for k, v in vals.items())

        tl = self._mk(tmp_path, scrape)
        r1 = tl.snapshot()
        assert r1["full"] and r1["d"] == {"kuiper_x_a": 1, "kuiper_x_b": 2}
        mock_clock.advance(1000)
        vals["a"] = 5
        r2 = tl.snapshot()
        assert "full" not in r2 and r2["d"] == {"kuiper_x_a": 5}
        mock_clock.advance(1000)
        del vals["b"]
        r3 = tl.snapshot()
        assert r3["x"] == ["kuiper_x_b"]
        q = tl.query(family="kuiper_x_a")
        assert [r["series"]["kuiper_x_a"] for r in q["records"]] == [1, 5]

    def test_query_filters(self, tmp_path, mock_clock):
        tl = self._mk(
            tmp_path, lambda:
            'kuiper_shard_rows_total{rule="r1",shard="0"} 5\n'
            'kuiper_shard_keys{rule="r2",shard="1"} 3\n'
            "kuiper_uptime_seconds 1\n")
        tl.snapshot()
        tl.note_event({"kind": "rebalance_hint", "rule": "r1",
                       "ts_ms": timex.now_ms()})
        # exact family, prefix family, rule, since, limit
        assert tl.query(family="kuiper_uptime_seconds")["returned"] == 1
        pre = tl.query(family="kuiper_shard_*")["records"]
        assert len(pre[0]["series"]) == 2
        by_rule = tl.query(family="kuiper_shard_*", rule="r2")["records"]
        assert list(by_rule[0]["series"]) == \
            ['kuiper_shard_keys{rule="r2",shard="1"}']
        ev = tl.query(family="events", rule="r1")["records"]
        assert ev and ev[-1]["event"]["kind"] == "rebalance_hint"
        assert tl.query(since=timex.now_ms())["returned"] == 0
        mock_clock.advance(10)
        tl.snapshot()
        assert tl.query(limit=1)["returned"] == 1

    def test_hard_kill_recovery_appends(self, tmp_path, mock_clock):
        beat = [0]

        def scrape():
            beat[0] += 1
            return f"kuiper_beat {beat[0]}\n"

        tl = self._mk(tmp_path, scrape)
        tl.snapshot()
        mock_clock.advance(5)
        tl.snapshot()
        # hard kill: no stop(), no gasp — a fresh instance over the same
        # dir resumes the segment sequence past the dead one's tail
        tl2 = self._mk(tmp_path, scrape)
        tl2.snapshot()
        q = tl2.query(family="kuiper_beat")
        assert [r["series"]["kuiper_beat"] for r in q["records"]] == \
            [1, 2, 3]
        names = sorted(os.listdir(tl2.dir))
        assert len(names) == len(set(names))

    def test_torn_tail_line_skipped(self, tmp_path, mock_clock):
        tl = self._mk(tmp_path, lambda: "kuiper_beat 1\n")
        tl.snapshot()
        with open(tl._fh_path, "a") as fh:  # simulated mid-write kill
            fh.write('{"t": 99, "k": "snap", "d": {"kuiper_be')
        tl2 = self._mk(tmp_path, lambda: "kuiper_beat 2\n")
        assert tl2.query(family="kuiper_beat")["returned"] == 1

    def test_byte_cap_retention(self, tmp_path, mock_clock):
        n = [0]

        def scrape():
            n[0] += 1
            return f"kuiper_beat {n[0]}\n"

        tl = self._mk(tmp_path, scrape)
        tl.seg_bytes, tl.max_bytes = 256, 1024
        for _ in range(100):
            mock_clock.advance(100)
            tl.snapshot()
        st = tl.stats()
        assert st["bytes"] <= tl.max_bytes + tl.seg_bytes
        assert st["segments"] >= 2
        q = tl.query(family="kuiper_beat")
        assert q["returned"] > 0  # the live tail survives
        # oldest records were truly deleted, newest kept
        assert q["records"][-1]["series"]["kuiper_beat"] == 100

    def test_age_cap_retention(self, tmp_path, mock_clock):
        # non-zero start: a segment stamped t0=0 is indistinguishable
        # from a foreign file and exempt from the age cap
        mock_clock.advance(1000)
        tl = self._mk(tmp_path, lambda: f"kuiper_t {timex.now_ms()}\n")
        tl.seg_bytes = 1  # rotate on every write
        tl.max_age_ms = 5000
        tl.snapshot()
        mock_clock.advance(60_000)
        tl.snapshot()
        mock_clock.advance(10)
        tl.snapshot()
        q = tl.query(family="kuiper_t")
        assert all(r["t"] >= 61_000 for r in q["records"])

    def test_dying_gasp_forces_full_and_is_once(self, tmp_path,
                                                mock_clock):
        tl = self._mk(tmp_path, lambda: "kuiper_beat 1\n")
        tl.snapshot()
        mock_clock.advance(5)
        snaps_before = tl.snapshots
        tl.dying_gasp()
        assert tl.snapshots == snaps_before + 1
        tl.dying_gasp()  # double-gasp is a no-op
        assert tl.snapshots == snaps_before + 1
        recs = tl.query(family="kuiper_beat")["records"]
        assert recs[-1].get("full")

    def test_recorder_mirror_and_env_capacity(self, tmp_path, mock_clock,
                                              monkeypatch):
        monkeypatch.setenv("KUIPER_EVENTS_RING", "5")
        ring = FlightRecorder()
        assert ring.capacity == 5
        for i in range(9):
            ring.record(f"k{i}", rule="r")
        assert len(ring.events()) == 5
        monkeypatch.setenv("KUIPER_EVENTS_RING", "not-a-number")
        assert FlightRecorder().capacity == \
            FlightRecorder.DEFAULT_CAPACITY

        # the installed singleton mirrors the GLOBAL recorder's events
        tmod.install(scrape_fn=lambda: "", base_dir=str(tmp_path / "m"),
                     interval_ms=0, start=False)
        recorder().record("mirror_probe", rule="r9",
                          ts_ms=timex.now_ms())
        q = tmod.timeline().query(family="events", rule="r9")
        assert q["returned"] == 1
        assert q["records"][0]["event"]["kind"] == "mirror_probe"

    def test_health_pseudo_series(self, tmp_path, mock_clock):
        tl = self._mk(tmp_path, lambda: "kuiper_beat 1\n",
                      verdicts_fn=lambda: {"r1": {"state": "breaching"}})
        tl.snapshot()
        q = tl.query(rule="r1")
        assert q["records"][0]["series"]["health|r1"] == "breaching"


# --------------------------------------------------------------------- REST
class TestRestHandlers:
    def test_diagnostics_mesh(self, mock_clock):
        from ekuiper_tpu.server.rest import RestApi

        k = _register(FakeSharded([900, 60, 20, 20]), "r_rest")
        meshwatch.observe()
        out = RestApi.diagnostics_mesh()
        assert out["skew"]["r_rest"]["skewed"]
        assert isinstance(out["collective"], list)
        del k

    def test_diagnostics_timeline(self, tmp_path, mock_clock):
        from ekuiper_tpu.server.rest import EngineError, RestApi

        stub = types.SimpleNamespace(timeline=None)
        with pytest.raises(EngineError):
            RestApi.diagnostics_timeline(stub, {})
        tmod.install(scrape_fn=lambda: "kuiper_beat 1\n",
                     base_dir=str(tmp_path / "r"), interval_ms=0,
                     start=False)
        tmod.timeline().snapshot()
        out = RestApi.diagnostics_timeline(stub, {"limit": "10"})
        assert out["returned"] == 1
        dumped = RestApi.diagnostics_timeline(stub, {"dump": "1"})
        assert dumped["segment_dump"]
        with pytest.raises(EngineError):
            RestApi.diagnostics_timeline(stub, {"since": "nope"})
        # the bundle must stay one JSON document
        json.dumps(dumped)
