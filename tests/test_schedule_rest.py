"""Cron/duration rule scheduling + the REST surface additions (tags,
uploads, config patch, data import/export, JWT auth)."""
import base64
import hashlib
import hmac
import json
import time
import urllib.error
import urllib.request

import pytest

from ekuiper_tpu.planner.planner import RuleDef
from ekuiper_tpu.runtime.rule import RuleState, RunState
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.server.rest import RestApi, serve
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils import cron as cronlib
from ekuiper_tpu.utils.config import get_config
import ekuiper_tpu.io.memory as mem


class TestCronParser:
    def test_next_fire(self):
        c = cronlib.Cron("*/15 * * * *")
        # from 00:07 local on a fixed minute boundary
        base = (int(time.time()) // 3600) * 3600 * 1000  # top of an hour
        nxt = c.next_fire_ms(base + 7 * 60_000)
        assert nxt == base + 15 * 60_000

    def test_fields(self):
        c = cronlib.Cron("0 9-17 * * mon-fri")
        assert c.minutes == {0}
        assert c.hours == set(range(9, 18))
        assert c.dow == {1, 2, 3, 4, 5}

    def test_six_field_seconds_dropped(self):
        c = cronlib.Cron("30 */5 * * * *")
        assert c.minutes == {0, 5, 10, 15, 20, 25, 30, 35, 40, 45, 50, 55}

    def test_bad_exprs(self):
        for bad in ("* * *", "61 * * * *", "* 25 * * *"):
            with pytest.raises(Exception):
                cronlib.Cron(bad)

    def test_duration(self):
        assert cronlib.parse_duration_ms("10s") == 10_000
        assert cronlib.parse_duration_ms("1h30m") == 5_400_000
        assert cronlib.parse_duration_ms("500ms") == 500
        assert cronlib.parse_duration_ms(250) == 250
        with pytest.raises(Exception):
            cronlib.parse_duration_ms("10 parsecs")

    def test_ranges(self):
        assert cronlib.in_ranges(5, None)
        assert cronlib.in_ranges(
            5_000, [{"beginTimestamp": 1_000, "endTimestamp": 10_000}])
        assert not cronlib.in_ranges(
            50_000, [{"beginTimestamp": 1_000, "endTimestamp": 10_000}])


class TestScheduledRule:
    def _mk(self, store, options):
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="sch/demo", TYPE="memory", FORMAT="JSON")')
        return RuleState(RuleDef(
            id="sch1", sql="SELECT deviceId FROM demo",
            actions=[{"memory": {"topic": "sch/out"}}],
            options=options), store)

    def _wait_state(self, rs, state, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if rs.state == state:
                return True
            time.sleep(0.02)
        return False

    def test_cron_cycle(self, mock_clock):
        store = kv.get_store()
        # fire every minute, run for 10s
        rs = self._mk(store, {"cron": "* * * * *", "duration": "10s"})
        rs.start()
        assert self._wait_state(rs, RunState.SCHEDULED)
        assert rs.topo is None
        mock_clock.advance(60_000)  # next minute boundary -> fire
        assert self._wait_state(rs, RunState.RUNNING)
        assert rs.topo is not None
        mock_clock.advance(10_000)  # duration elapses -> back to waiting
        assert self._wait_state(rs, RunState.SCHEDULED)
        assert rs.topo is None
        mock_clock.advance(50_000)  # next boundary -> runs again
        assert self._wait_state(rs, RunState.RUNNING)
        rs.stop()
        assert self._wait_state(rs, RunState.STOPPED)

    def test_duration_only_runs_once(self, mock_clock):
        store = kv.get_store()
        rs = self._mk(store, {"duration": "5s"})
        rs.start()
        assert self._wait_state(rs, RunState.RUNNING)
        mock_clock.advance(5_000)
        assert self._wait_state(rs, RunState.STOPPED)

    def test_cron_requires_duration(self):
        store = kv.get_store()
        with pytest.raises(ValueError, match="duration"):
            self._mk(store, {"cron": "* * * * *"})

    def test_out_of_range_skips_activation(self, mock_clock):
        store = kv.get_store()
        rs = self._mk(store, {
            "cron": "* * * * *", "duration": "10s",
            "cronDatetimeRange": [
                {"beginTimestamp": 10_000_000, "endTimestamp": 20_000_000}],
        })
        rs.start()
        assert self._wait_state(rs, RunState.SCHEDULED)
        mock_clock.advance(60_000)  # fires, but now (60s) is out of range
        time.sleep(0.3)
        assert rs.state == RunState.SCHEDULED and rs.topo is None
        rs.stop()


@pytest.fixture
def api_server():
    store = kv.get_store()
    api = RestApi(store)
    srv = serve(api, "127.0.0.1", 0)
    port = srv.server_address[1]

    def req(method, path, body=None, headers=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json", **(headers or {})})
        with urllib.request.urlopen(r, timeout=5) as resp:
            return json.loads(resp.read() or b"null")

    yield api, req
    api.rules.stop_all()
    srv.shutdown()


class TestRestGaps:
    def test_tags_filter(self, api_server):
        api, req = api_server
        StreamProcessor(api.store).exec_stmt(
            'CREATE STREAM demo (a STRING) '
            'WITH (DATASOURCE="t/x", TYPE="memory", FORMAT="JSON")')
        req("POST", "/rules", {"id": "tag1", "sql": "SELECT a FROM demo",
                               "actions": [{"log": {}}], "tags": ["edge"]})
        req("POST", "/rules", {"id": "tag2", "sql": "SELECT a FROM demo",
                               "actions": [{"log": {}}]})
        all_rules = {r["id"] for r in req("GET", "/rules")}
        assert {"tag1", "tag2"} <= all_rules
        tagged = [r["id"] for r in req("GET", "/rules?tags=edge")]
        assert tagged == ["tag1"]
        req("PUT", "/rules/tag2/tags", {"tags": ["edge", "prod"]})
        assert {r["id"] for r in req("GET", "/rules?tags=edge")} == \
            {"tag1", "tag2"}
        req("DELETE", "/rules/tag2/tags", {"tags": ["edge"]})
        assert [r["id"] for r in req("GET", "/rules?tags=edge")] == ["tag1"]

    def test_uploads(self, api_server):
        api, req = api_server
        path = req("POST", "/config/uploads",
                   {"name": "cert.pem", "content": "hello"})
        assert path.endswith("cert.pem")
        assert "cert.pem" in req("GET", "/config/uploads")
        with open(path) as f:
            assert f.read() == "hello"
        req("POST", "/config/uploads", {
            "name": "bin.dat",
            "base64": base64.b64encode(b"\x00\x01").decode()})
        assert req("DELETE", "/config/uploads/cert.pem") == \
            "Upload cert.pem is deleted."
        assert "cert.pem" not in req("GET", "/config/uploads")
        with pytest.raises(urllib.error.HTTPError):
            req("POST", "/config/uploads", {"name": "../evil", "content": "x"})

    def test_config_patch(self, api_server):
        api, req = api_server
        out = req("PATCH", "/configs", {"basic": {"log_level": "debug"}})
        assert "log_level" in out
        assert req("GET", "/configs")["basic"]["log_level"] == "debug"
        with pytest.raises(urllib.error.HTTPError):
            req("PATCH", "/configs", {"basic": {"rest_port": 1}})

    def test_data_import_export(self, api_server):
        api, req = api_server
        StreamProcessor(api.store).exec_stmt(
            'CREATE STREAM exp (a STRING) '
            'WITH (DATASOURCE="t/e", TYPE="memory", FORMAT="JSON")')
        req("POST", "/rules", {"id": "expr1", "sql": "SELECT a FROM exp",
                               "actions": [{"log": {}}]})
        doc = req("GET", "/data/export")
        assert "expr1" in doc["rules"] and "exp" in doc["streams"]
        # async import into the same store (idempotent overwrite semantics)
        req("POST", "/data/import?async=true", {"content": doc})
        deadline = time.time() + 5
        while time.time() < deadline:
            st = req("GET", "/data/import/status")
            if st["status"] in ("done", "error"):
                break
            time.sleep(0.05)
        assert st["status"] == "done", st

    def test_jwt_auth(self, api_server):
        api, req = api_server
        cfg = get_config()
        cfg.basic.authentication = True
        cfg.basic.jwt_secret = "s3cret"
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                req("GET", "/rules")
            assert e.value.code == 401

            def b64u(b):
                return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

            head = b64u(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
            payload = b64u(json.dumps(
                {"iss": "test", "exp": time.time() + 60}).encode())
            sig = b64u(hmac.new(b"s3cret", f"{head}.{payload}".encode(),
                                hashlib.sha256).digest())
            token = f"{head}.{payload}.{sig}"
            assert isinstance(
                req("GET", "/rules",
                    headers={"Authorization": f"Bearer {token}"}), list)
            bad = f"{head}.{payload}.{b64u(b'nope')}"
            with pytest.raises(urllib.error.HTTPError) as e:
                req("GET", "/rules",
                    headers={"Authorization": f"Bearer {bad}"})
            assert e.value.code == 401
        finally:
            cfg.basic.authentication = False
            cfg.basic.jwt_secret = ""
