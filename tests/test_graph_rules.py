"""Graph-API rule tests — modeled on the reference's planner_graph tests
(internal/topo/planner/planner_graph_test.go) plus end-to-end runs through
the memory pubsub, mirroring topotest style."""
import time

import pytest

from ekuiper_tpu.io.memory import publish, subscribe
from ekuiper_tpu.planner.graph import plan_by_graph
from ekuiper_tpu.planner.planner import RuleDef
from ekuiper_tpu.server.rule_manager import RuleRegistry
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.infra import PlanError


def graph_rule(rid, graph):
    return RuleDef(id=rid, sql="", actions=[], graph=graph)


def run_rule(rule, feeds, out_topic, wait=1.0, settle=0.3):
    """Start a graph rule, publish feeds, gather sink output."""
    store = kv.get_store()
    got = []
    unsub = subscribe(out_topic, lambda t, d: got.append(d))
    from ekuiper_tpu.utils import timex

    timex.use_real_clock()  # runtime nodes use wall timers here
    topo = plan_by_graph(rule, store)
    topo.open()
    time.sleep(settle)
    for topic, payload in feeds:
        publish(topic, payload)
    deadline = time.monotonic() + wait
    while time.monotonic() < deadline:
        time.sleep(0.05)
    topo.close()
    unsub()
    rows = []
    for g in got:
        rows.extend(g if isinstance(g, list) else [g])
    return rows


def test_graph_filter_pick_e2e():
    rule = graph_rule("g1", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gt1"}},
            "flt": {"type": "operator", "nodeType": "filter",
                    "props": {"expr": "temperature > 20"}},
            "pick": {"type": "operator", "nodeType": "pick",
                     "props": {"fields": ["temperature as t", "device"]}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "gout1"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["flt"], "flt": ["pick"], "pick": ["out"]}},
    })
    rows = run_rule(rule, [("gt1", {"temperature": 25, "device": "a"}),
                           ("gt1", {"temperature": 10, "device": "b"}),
                           ("gt1", {"temperature": 30, "device": "c"})],
                    "gout1")
    assert sorted(r["t"] for r in rows) == [25, 30]
    assert all(set(r) == {"t", "device"} for r in rows)


def test_graph_function_appends_column():
    rule = graph_rule("g2", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gt2"}},
            "fn": {"type": "operator", "nodeType": "function",
                   "props": {"expr": "upper(name) as uname"}},
            "pick": {"type": "operator", "nodeType": "pick",
                     "props": {"fields": ["name", "uname"]}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "gout2"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["fn"], "fn": ["pick"], "pick": ["out"]}},
    })
    rows = run_rule(rule, [("gt2", {"name": "abc"})], "gout2")
    assert rows and rows[0] == {"name": "abc", "uname": "ABC"}


def test_graph_switch_routes_cases():
    rule = graph_rule("g3", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gt3"}},
            "sw": {"type": "operator", "nodeType": "switch",
                   "props": {"cases": ["v > 10", "v <= 10"],
                             "stopAtFirstMatch": True}},
            "hi": {"type": "sink", "nodeType": "memory",
                   "props": {"topic": "gout3hi"}},
            "lo": {"type": "sink", "nodeType": "memory",
                   "props": {"topic": "gout3lo"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["sw"], "sw": [["hi"], ["lo"]]}},
    })
    store = kv.get_store()
    hi, lo = [], []
    u1 = subscribe("gout3hi", lambda t, d: hi.append(d))
    u2 = subscribe("gout3lo", lambda t, d: lo.append(d))
    from ekuiper_tpu.utils import timex

    timex.use_real_clock()
    topo = plan_by_graph(rule, store)
    topo.open()
    time.sleep(0.3)
    for v in (5, 15, 8, 20):
        publish("gt3", {"v": v})
    time.sleep(1.0)
    topo.close()
    u1()
    u2()
    flat = lambda xs: sorted(  # noqa: E731
        r["v"] for g in xs for r in (g if isinstance(g, list) else [g]))
    assert flat(hi) == [15, 20]
    assert flat(lo) == [5, 8]


def test_graph_window_aggfunc_e2e():
    rule = graph_rule("g4", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gt4"}},
            "win": {"type": "operator", "nodeType": "window",
                    "props": {"type": "countwindow", "size": 3}},
            "agg": {"type": "operator", "nodeType": "aggfunc",
                    "props": {"expr": "avg(v) as av"}},
            "pick": {"type": "operator", "nodeType": "pick",
                     "props": {"fields": ["av"]}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "gout4"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["win"], "win": ["agg"], "agg": ["pick"],
                           "pick": ["out"]}},
    })
    rows = run_rule(rule, [("gt4", {"v": v}) for v in (1, 2, 3)], "gout4",
                    wait=2.0)
    assert rows and rows[0]["av"] == 2


def test_graph_io_type_mismatch_rejected():
    rule = graph_rule("gbad", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "x"}},
            "agg": {"type": "operator", "nodeType": "aggfunc",
                    "props": {"expr": "avg(v) as av"}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "y"}},
        },
        # aggfunc directly on a row source: collection input required
        "topo": {"sources": ["src"],
                 "edges": {"src": ["agg"], "agg": ["out"]}},
    })
    with pytest.raises(PlanError, match="collection"):
        plan_by_graph(rule, kv.get_store())


def test_graph_undefined_edge_rejected():
    rule = graph_rule("gbad2", {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "x"}},
        },
        "topo": {"sources": ["src"], "edges": {"src": ["missing"]}},
    })
    with pytest.raises(PlanError):
        plan_by_graph(rule, kv.get_store())


def test_graph_rule_through_registry():
    """Graph rules flow through the same CRUD/lifecycle as SQL rules."""
    store = kv.get_store()
    rr = RuleRegistry(store)
    got = []
    unsub = subscribe("gout5", lambda t, d: got.append(d))
    from ekuiper_tpu.utils import timex

    timex.use_real_clock()
    rr.create({"id": "g5", "graph": {
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gt5"}},
            "flt": {"type": "operator", "nodeType": "filter",
                    "props": {"expr": "v > 0"}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "gout5"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["flt"], "flt": ["out"]}},
    }})
    time.sleep(0.3)
    publish("gt5", {"v": 1})
    publish("gt5", {"v": -1})
    time.sleep(1.0)
    status = rr.status("g5")
    rr.stop("g5")
    rr.delete("g5")
    unsub()
    rows = [r for g in got for r in (g if isinstance(g, list) else [g])]
    assert [r["v"] for r in rows] == [1]
    assert status["status"] in ("running", "stopped")


def test_graph_function_then_filter_batch():
    """Regression: a function node fed a multi-row ColumnBatch must emit rows
    that downstream filter/pick nodes actually process (they ignored bare
    Python lists), so filtering applies per row."""
    from ekuiper_tpu.data.batch import from_tuples
    from ekuiper_tpu.data.rows import Tuple
    from ekuiper_tpu.planner.graph import _GraphFuncNode, _parse_fields
    from ekuiper_tpu.runtime.nodes_ops import FilterNode
    from ekuiper_tpu.sql.parser import Parser

    fn = _GraphFuncNode("fn", _parse_fields(["v * 2 as dbl"]), is_agg=False)
    flt = FilterNode("flt", Parser("dbl > 4").parse_expr())
    out = []

    class _Cap:
        name = "cap"

        def put(self, item, from_name=None):
            out.append(item)

    fn.outputs.append(flt)
    flt.outputs.append(_Cap())
    batch = from_tuples([Tuple(message={"v": v}) for v in (1, 2, 3, 4)])
    fn.process(batch)
    # drain the filter's input queue synchronously (no worker threads here)
    from ekuiper_tpu.runtime.node import _Tagged
    while not flt.inq.empty():
        entry = flt.inq.get_nowait()
        flt.process(entry.item if isinstance(entry, _Tagged) else entry)
    vals = sorted(r.value("dbl")[0] for r in out)
    assert vals == [6, 8]
