"""Sharded group-by tests on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.parallel.mesh import ensure_devices, make_mesh
from ekuiper_tpu.parallel.sharded import ShardedGroupBy
from ekuiper_tpu.sql.parser import parse_select


@pytest.fixture(scope="module")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


def _plan(sql):
    return extract_kernel_plan(parse_select(sql))


class TestShardedGroupBy:
    def test_matches_single_chip(self, eight_devices):
        sql = ("SELECT avg(v), count(*), min(v), max(v), stddev(v) "
               "FROM d WHERE v > 0.1 GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan, mesh, capacity=64, micro_batch=128)
        plan2 = _plan(sql)
        gb = DeviceGroupBy(plan2, capacity=64, micro_batch=128)
        kt = KeyTable(64)

        rng = np.random.default_rng(1)
        keys = np.array([f"k{rng.integers(12)}" for _ in range(500)], dtype=np.object_)
        vals = rng.normal(1.0, 2.0, 500).astype(np.float32)
        slots, _ = kt.encode_column(keys)
        cols = {"v": vals}

        sstate = sgb.fold(sgb.init_state(), cols, slots)
        souts, sact = sgb.finalize(sstate, kt.n_keys)

        dstate = gb.fold(gb.init_state(), cols, slots)
        douts, dact = gb.finalize(dstate, kt.n_keys)

        np.testing.assert_allclose(sact, dact, rtol=1e-5)
        for i in range(len(plan.specs)):
            np.testing.assert_allclose(
                souts[i], douts[i], rtol=1e-3, atol=1e-3,
                err_msg=f"spec {i} ({plan.specs[i].kind})",
            )

    def test_panes_match_single_chip(self, eight_devices):
        """Hopping-window pane axis: fold into 3 panes, emit merged, expire
        the oldest — sharded must equal single-chip at every step."""
        sql = ("SELECT sum(v), avg(v), min(v), max(v) "
               "FROM d GROUP BY k, HOPPINGWINDOW(ss, 30, 10)")
        plan, plan2 = _plan(sql), _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan, mesh, capacity=32, n_panes=3, micro_batch=64)
        gb = DeviceGroupBy(plan2, capacity=32, n_panes=3, micro_batch=64)
        kt = KeyTable(32)

        rng = np.random.default_rng(7)
        sstate, dstate = sgb.init_state(), gb.init_state()
        for pane in range(3):
            n = 120
            keys = np.array([f"k{rng.integers(9)}" for _ in range(n)], dtype=np.object_)
            slots, _ = kt.encode_column(keys)
            cols = {"v": rng.normal(0, 3, n).astype(np.float32)}
            sstate = sgb.fold(sstate, cols, slots, pane_idx=pane)
            dstate = gb.fold(dstate, cols, slots, pane_idx=pane)

        # merged emit over panes {0,1,2} then over the live set {1,2}
        for panes in (None, [1, 2]):
            souts, sact = sgb.finalize(sstate, kt.n_keys, panes=panes)
            douts, dact = gb.finalize(dstate, kt.n_keys, panes=panes)
            np.testing.assert_array_equal(sact, dact)
            for i in range(len(souts)):
                np.testing.assert_allclose(souts[i], douts[i], rtol=1e-5,
                                           atol=1e-5)

        sstate = sgb.reset_pane(sstate, 0)
        dstate = gb.reset_pane(dstate, 0)
        souts, _ = sgb.finalize(sstate, kt.n_keys)
        douts, _ = gb.finalize(dstate, kt.n_keys)
        for i in range(len(souts)):
            np.testing.assert_allclose(souts[i], douts[i], rtol=1e-5, atol=1e-5)

    def test_validity_masks_match_single_chip(self, eight_devices):
        """Null-bearing int column: sharded must honor per-column validity
        masks the way the single-chip fold does (not just NaN)."""
        sql = ("SELECT count(v), sum(v), min(v), avg(v) "
               "FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan, plan2 = _plan(sql), _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan, mesh, capacity=16, micro_batch=64)
        gb = DeviceGroupBy(plan2, capacity=16, micro_batch=64)
        kt = KeyTable(16)

        rng = np.random.default_rng(3)
        n = 200
        keys = np.array([f"k{rng.integers(5)}" for _ in range(n)], dtype=np.object_)
        slots, _ = kt.encode_column(keys)
        vals = rng.integers(0, 100, n).astype(np.int64)
        valid = rng.random(n) > 0.3  # 30% nulls
        cols = {"v": vals}

        sgb.observe_dtypes(cols)
        gb.observe_dtypes(cols)
        sstate = sgb.fold(sgb.init_state(), cols, slots, {"v": valid})
        dstate = gb.fold(gb.init_state(), cols, slots, {"v": valid})
        souts, sact = sgb.finalize(sstate, kt.n_keys)
        douts, dact = gb.finalize(dstate, kt.n_keys)
        np.testing.assert_array_equal(sact, dact)
        for i in range(len(souts)):
            np.testing.assert_allclose(souts[i], douts[i], rtol=1e-5, atol=1e-5)
        # count(v) skips nulls, act counts rows
        assert souts[0].sum() == valid.sum()
        assert sact.sum() == n

    def test_grow_preserves_partials(self, eight_devices):
        """Key overflow: grow redistributes slots across key shards and
        keeps prior partials."""
        plan = _plan("SELECT sum(v), count(*) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=16, micro_batch=64)
        kt = KeyTable(16)

        k1 = np.array([f"k{i}" for i in range(12)], dtype=np.object_)
        slots, grew = kt.encode_column(k1)
        assert not grew
        state = sgb.fold(sgb.init_state(), {"v": np.ones(12, np.float32)}, slots)

        k2 = np.array([f"k{i}" for i in range(40)], dtype=np.object_)
        slots2, grew2 = kt.encode_column(k2)
        assert grew2
        state = sgb.grow(state, kt.capacity)
        assert sgb.capacity == kt.capacity
        state = sgb.fold(state, {"v": np.full(40, 2.0, np.float32)}, slots2)

        outs, act = sgb.finalize(state, kt.n_keys)
        # first 12 keys: 1 + 2 per key; rest: 2
        expect = np.where(np.arange(40) < 12, 3.0, 2.0)
        np.testing.assert_allclose(outs[0], expect)
        assert act.sum() == 52

    def test_all_devices_on_keys_axis(self, eight_devices):
        plan = _plan("SELECT sum(v) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=32, micro_batch=64)
        kt = KeyTable(32)
        slots, _ = kt.encode_column(
            np.array([f"k{i % 20}" for i in range(200)], dtype=np.object_)
        )
        state = sgb.fold(sgb.init_state(), {"v": np.ones(200, np.float32)}, slots)
        outs, act = sgb.finalize(state, kt.n_keys)
        assert outs[0].sum() == 200.0
        assert act.sum() == 200.0

    def test_state_is_actually_sharded(self, eight_devices):
        plan = _plan("SELECT count(*) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=64, micro_batch=64)
        state = sgb.init_state()
        # capacity axis (axis 1 of (n_panes, capacity, k)) split across 8
        assert len(state["n"].addressable_shards) == 8
        assert state["n"].addressable_shards[0].data.shape[1] == 8

    def test_mesh_validation(self, eight_devices):
        with pytest.raises(ValueError):
            make_mesh(rows=3, keys=3)
        plan = _plan("SELECT count(*) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        # odd capacity rounds up to an even shard split instead of raising
        sgb = ShardedGroupBy(plan, make_mesh(rows=1, keys=8), capacity=30)
        assert sgb.capacity == 32

    def test_ensure_devices(self, eight_devices):
        devs = ensure_devices(8)
        assert len(devs) == 8


class TestPlannerMeshIntegration:
    """A real rule with planOptimizeStrategy.mesh runs sharded end-to-end
    and matches the unsharded rule exactly (VERDICT r1 #1: the sharded path
    must be reachable from a rule, not just from tests)."""

    def _run_rule(self, mock_clock, rule_id, options):
        import time

        from ekuiper_tpu.io import memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        from ekuiper_tpu.utils.infra import PlanError

        store = kv.get_store()
        try:
            StreamProcessor(store).exec_stmt(
                'CREATE STREAM sh_demo (k STRING, v FLOAT) '
                'WITH (DATASOURCE="sh/in", TYPE="memory", FORMAT="JSON")'
            )
        except PlanError:
            pass  # second rule in the same test reuses the stream
        rule = RuleDef(
            id=rule_id,
            sql=("SELECT k, avg(v) AS a, count(*) AS c, max(v) AS mx "
                 "FROM sh_demo GROUP BY k, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": f"sh/out/{rule_id}"}}],
            options=options,
        )
        topo = plan_rule(rule, store)
        fused = [n for n in topo.ops if isinstance(n, FusedWindowAggNode)]
        assert len(fused) == 1
        sink = topo.sinks[0]
        topo.open()
        try:
            rng = np.random.default_rng(11)
            for i in range(50):
                mem.publish(
                    "sh/in",
                    {"v": float(np.round(rng.normal(10, 2), 3)),
                     "k": f"k{i % 7}"},
                )
            mock_clock.advance(20)  # linger flush
            topo.wait_idle()
            mock_clock.advance(10_000)  # window fires
            deadline = time.time() + 5.0
            while time.time() < deadline and not sink.results:
                time.sleep(0.01)
            results = list(sink.results)
        finally:
            topo.close()
        assert results, f"no window emit from {rule_id}"
        rows = results[0] if isinstance(results[0], list) else [results[0]]
        return sorted(rows, key=lambda m: m["k"]), fused[0]

    def test_rule_runs_sharded_and_matches(self, eight_devices, mock_clock):
        from ekuiper_tpu.io import memory as mem
        from ekuiper_tpu.parallel.sharded import ShardedGroupBy

        mem.reset()
        plain, node_plain = self._run_rule(mock_clock, "r_plain", {})
        mem.reset()
        sharded, node_sh = self._run_rule(
            mock_clock, "r_sharded",
            {"planOptimizeStrategy": {"mesh": {"rows": 2, "keys": 4}}},
        )
        mem.reset()
        assert isinstance(node_sh.gb, ShardedGroupBy)
        assert not isinstance(node_plain.gb, ShardedGroupBy)
        assert len(plain) == 7
        assert plain == sharded


class TestShardedEventTime:
    """Event-time × mesh: per-row pane vectors under shard_map
    (parallel/sharded.py _build_fold_vec) match the single-chip kernel."""

    def test_pane_vector_fold_matches_single_chip(self, eight_devices):
        sql = ("SELECT avg(v), count(*), min(v), max(v), hll(v) "
               "FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        n_panes = 4
        sgb = ShardedGroupBy(plan, mesh, capacity=32, n_panes=n_panes,
                             micro_batch=64)
        gb = DeviceGroupBy(_plan(sql), capacity=32, n_panes=n_panes,
                           micro_batch=64)
        kt = KeyTable(32)
        rng = np.random.default_rng(5)
        n = 300
        keys = np.array([f"k{rng.integers(9)}" for _ in range(n)],
                        dtype=np.object_)
        vals = rng.normal(1.0, 2.0, n).astype(np.float32)
        panes = rng.integers(0, n_panes, n).astype(np.uint8)
        slots, _ = kt.encode_column(keys)
        cols = {"v": vals}

        sstate = sgb.fold(sgb.init_state(), dict(cols), slots,
                          pane_idx=panes)
        dstate = gb.fold(gb.init_state(), dict(cols), slots, pane_idx=panes)
        # also a scalar-pane fold on top (the single-bucket fast path)
        sstate = sgb.fold(sstate, dict(cols), slots, pane_idx=1)
        dstate = gb.fold(dstate, dict(cols), slots, pane_idx=1)

        for subset in ([0, 1], [2], None, [1, 3]):
            souts, sact = sgb.finalize(sstate, kt.n_keys, panes=subset)
            douts, dact = gb.finalize(dstate, kt.n_keys, panes=subset)
            np.testing.assert_allclose(sact, dact, rtol=1e-5)
            for i in range(len(plan.specs)):
                np.testing.assert_allclose(
                    np.asarray(souts[i], dtype=np.float64),
                    np.asarray(douts[i], dtype=np.float64),
                    rtol=1e-4, atol=1e-4)

    def test_event_time_mesh_plans_to_device(self, eight_devices):
        from ekuiper_tpu.planner.planner import device_path_eligible
        from ekuiper_tpu.utils.config import RuleOptionConfig

        stmt = parse_select(
            "SELECT k, avg(v) AS a FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        opts = RuleOptionConfig(
            is_event_time=True,
            plan_optimize_strategy={"mesh": {"rows": 2, "keys": 4}})
        assert device_path_eligible(stmt, opts) is not None

    def test_fused_node_event_time_on_mesh(self, eight_devices):
        """End-to-end: FusedWindowAggNode with a mesh + event time, batches
        spanning several buckets, watermark-driven emission parity against
        the single-chip node."""
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.events import Watermark
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode

        sql = ("SELECT k, avg(v) AS a, count(*) AS c FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 2)")
        stmt = parse_select(sql)

        def make(mesh):
            plan = _plan(sql)
            node = FusedWindowAggNode(
                "ev", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=32, micro_batch=64,
                direct_emit=build_direct_emit(stmt, plan, ["k"]),
                mesh=mesh, is_event_time=True, late_tolerance_ms=500)
            node.state = node.gb.init_state()
            got = []
            node.broadcast = lambda item: got.append(item)
            return node, got

        mnode, mgot = make(make_mesh(rows=2, keys=4))
        snode, sgot = make(None)
        rng = np.random.default_rng(9)
        t = 10_000
        for _ in range(6):
            n = 120
            ts = t + np.sort(rng.integers(0, 3_000, n)).astype(np.int64)
            b = ColumnBatch(
                n=n,
                columns={"k": np.array(
                    [f"k{i}" for i in rng.integers(0, 6, n)],
                    dtype=np.object_),
                    "v": rng.normal(5, 2, n).astype(np.float32)},
                timestamps=ts, emitter="d")
            for node in (mnode, snode):
                node.process(b)
            t += 2_500
            for node in (mnode, snode):
                node.on_watermark(Watermark(ts=t - 1_000))

        def collect(got):
            wins = []
            for item in got:
                if isinstance(item, Watermark):
                    continue
                msgs = item if isinstance(item, list) else [item]
                if hasattr(item, "to_messages"):
                    msgs = item.to_messages()
                wins.append(sorted(
                    (m["k"], m["c"], round(m["a"], 3)) for m in msgs))
            return wins

        assert collect(mgot) == collect(sgot)
        assert len(collect(mgot)) >= 4


class TestShardedSliding:
    """Sliding windows on the mesh: pane-vector folds + scratch refold +
    dynamic-mask finalize all run sharded; output parity with ground truth
    computed from the raw rows (same oracle as test_sliding_device)."""

    def test_eligibility_accepts_mesh(self, eight_devices):
        from ekuiper_tpu.planner.planner import device_path_eligible
        from ekuiper_tpu.utils.config import RuleOptionConfig

        stmt = parse_select(
            "SELECT k, count(*) AS c FROM s GROUP BY k, "
            "SLIDINGWINDOW(ss, 2) OVER (WHEN v > 90)")
        assert device_path_eligible(stmt, RuleOptionConfig(
            plan_optimize_strategy={"mesh": {"rows": 2, "keys": 4}})
        ) is not None
        # event-time sliding stays host-side, mesh or not
        assert device_path_eligible(stmt, RuleOptionConfig(
            is_event_time=True,
            plan_optimize_strategy={"mesh": {"rows": 2, "keys": 4}})) is None

    def test_sharded_matches_ground_truth(self, eight_devices):
        from test_sliding_device import (SQL, mkbatches, per_trigger,
                                         run_host_expected)
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
        from ekuiper_tpu.sql.parser import parse_select as _ps

        stmt = _ps(SQL)
        plan = _plan(SQL)
        mesh = make_mesh(rows=2, keys=4)
        node = FusedWindowAggNode(
            "ssl", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128, mesh=mesh,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        assert isinstance(node.gb, ShardedGroupBy)
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        rng = np.random.default_rng(7)
        batches = mkbatches(rng)
        for b in batches:
            node.process(b)
        node._drain_async_emits()
        expected = run_host_expected(SQL, batches)
        triggers = per_trigger(got)
        assert len(triggers) == len(expected) >= 1
        for trig, (t, per) in zip(triggers, expected):
            assert set(trig) == set(per)
            for k, vals in per.items():
                m = trig[k]
                assert m["c"] == len(vals)
                np.testing.assert_allclose(m["a"], np.mean(vals), rtol=1e-4)
                np.testing.assert_allclose(m["mn"], min(vals), rtol=1e-6)
                np.testing.assert_allclose(m["mx"], max(vals), rtol=1e-6)


class TestShardedStateAndSession:
    """STATE windows and event-time SESSION windows on the mesh: the toggle
    scan / session split are host-side; every fold and the sync finalize
    run through the sharded kernel — output must match single-chip."""

    def _state_node(self, mesh):
        from test_state_device import SQL as SSQL
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode

        stmt = parse_select(SSQL)
        plan = _plan(SSQL)
        node = FusedWindowAggNode(
            "sst", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128, mesh=mesh,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        return node, got

    def test_state_window_sharded_matches_single_chip(self, eight_devices):
        from test_state_device import batch, msgs_of

        mesh = make_mesh(rows=2, keys=4)
        sh, sh_got = self._state_node(mesh)
        assert isinstance(sh.gb, ShardedGroupBy)
        single, si_got = self._state_node(None)
        feeds = [
            batch(["x", "a", "a", "b", "a", "x", "b", "b"],
                  [9.0, 1.0, 2.0, 3.0, 4.0, 9.0, 10.0, 20.0],
                  [5, 1, 5, 5, 0, 5, 1, 0]),
            batch(["a", "b", "a"], [7.0, 8.0, 9.0], [1, 5, 0]),
        ]
        for b in feeds:
            sh.process(b)
            single.process(b)
        assert msgs_of(sh_got) == msgs_of(si_got)
        assert len(msgs_of(sh_got)) >= 2

    def test_event_session_sharded_matches_single_chip(self, eight_devices):
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.events import Watermark
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode

        sql = ("SELECT k, count(*) AS c, avg(v) AS a FROM s "
               "GROUP BY k, SESSIONWINDOW(ss, 10, 2)")
        stmt = parse_select(sql)

        def mk(mesh):
            plan = _plan(sql)
            node = FusedWindowAggNode(
                "evs", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=32, micro_batch=64, mesh=mesh, is_event_time=True,
                direct_emit=build_direct_emit(stmt, plan, ["k"]))
            node.state = node.gb.init_state()
            got = []
            node.broadcast = lambda item: got.append(item)
            return node, got

        def feed(node):
            # two sessions per key, split by a >2s gap; watermark closes
            # the first
            ts = np.array([1000, 1200, 1500, 4000, 4100], dtype=np.int64)
            node.process(ColumnBatch(
                n=5,
                columns={"k": np.array(["a", "a", "b", "a", "b"],
                                       dtype=np.object_),
                         "v": np.asarray([1, 2, 3, 4, 5], np.float32)},
                timestamps=ts, emitter="s"))
            node.on_watermark(Watermark(ts=10_000))

        sh, sh_got = mk(make_mesh(rows=2, keys=4))
        assert isinstance(sh.gb, ShardedGroupBy)
        si, si_got = mk(None)
        feed(sh)
        feed(si)

        def norm(got):
            out = []
            for item in got:
                if isinstance(item, list):
                    out.append(sorted(
                        (m["k"], m["c"], round(m["a"], 4)) for m in item))
            return out

        assert norm(sh_got) == norm(si_got)
        assert norm(sh_got), "no session emitted"


def test_event_time_mesh_state_parity(eight_devices, mock_clock):
    """Both newly-allowed flags TOGETHER: event-time STATE window on a
    mesh, parity with the host path (review finding r5 coverage gap)."""
    import time

    import ekuiper_tpu.io.memory as mem
    from ekuiper_tpu.planner.planner import RuleDef, plan_rule
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.store import kv

    mem.reset()
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM ems (deviceId STRING, t FLOAT, ts BIGINT) '
        'WITH (DATASOURCE="in/ems", TYPE="memory", FORMAT="JSON", '
        'TIMESTAMP="ts")')
    rows = [
        {"deviceId": "a", "t": 30.0, "ts": 1000},  # begin
        {"deviceId": "b", "t": 12.0, "ts": 2000},
        {"deviceId": "a", "t": 5.0, "ts": 3000},   # emit
        {"deviceId": "b", "t": 40.0, "ts": 4000},  # begin
        {"deviceId": "a", "t": 2.0, "ts": 5000},   # emit
    ]

    def run(rule_id, options):
        topo = plan_rule(RuleDef(
            id=rule_id,
            sql=("SELECT deviceId, count(*) AS c, avg(t) AS a FROM ems "
                 "GROUP BY deviceId, STATEWINDOW(t > 25, t < 8)"),
            actions=[{"memory": {"topic": f"o/{rule_id}"}}],
            options=options), store)
        got = []
        mem.subscribe(f"o/{rule_id}", lambda tp, p: got.append(p))
        topo.open()
        try:
            for r in rows:
                mem.publish("in/ems", r)
            mock_clock.advance(20)
            assert topo.wait_idle(30)
            deadline = time.time() + 10
            while time.time() < deadline and len(got) < 2:
                time.sleep(0.02)
        finally:
            topo.close()
        out = []
        for p in got:
            out.extend(p if isinstance(p, list) else [p])
        return sorted((m["deviceId"], m["c"], round(m["a"], 4)) for m in out), topo

    fused, ft = run("emsd", {
        "isEventTime": True, "lateTolerance": 500,
        "planOptimizeStrategy": {"mesh": {"rows": 2, "keys": 4}}})
    assert any(isinstance(n, FusedWindowAggNode) for n in ft.ops)
    host, ht = run("emsh", {
        "isEventTime": True, "lateTolerance": 500,
        "use_device_kernel": False})
    assert not any(isinstance(n, FusedWindowAggNode) for n in ht.ops)
    assert fused and fused == host
