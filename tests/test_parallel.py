"""Sharded group-by tests on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.parallel.mesh import make_mesh
from ekuiper_tpu.parallel.sharded import ShardedGroupBy
from ekuiper_tpu.sql.parser import parse_select


@pytest.fixture(scope="module")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


def _plan(sql):
    return extract_kernel_plan(parse_select(sql))


class TestShardedGroupBy:
    def test_matches_single_chip(self, eight_devices):
        sql = ("SELECT avg(v), count(*), min(v), max(v), stddev(v) "
               "FROM d WHERE v > 0.1 GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = _plan(sql)
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan, mesh, capacity=64, micro_batch=128)
        plan2 = _plan(sql)
        gb = DeviceGroupBy(plan2, capacity=64, micro_batch=128)
        kt = KeyTable(64)

        rng = np.random.default_rng(1)
        keys = np.array([f"k{rng.integers(12)}" for _ in range(500)], dtype=np.object_)
        vals = rng.normal(1.0, 2.0, 500).astype(np.float32)
        slots, _ = kt.encode_column(keys)
        cols = {"v": vals}

        sstate = sgb.fold(sgb.init_state(), cols, slots)
        souts, sact = sgb.finalize(sstate, kt.n_keys)

        dstate = gb.fold(gb.init_state(), cols, slots)
        douts, dact = gb.finalize(dstate, kt.n_keys)

        np.testing.assert_allclose(sact, dact, rtol=1e-5)
        for i in range(len(plan.specs)):
            np.testing.assert_allclose(
                souts[i], douts[i], rtol=1e-3, atol=1e-3,
                err_msg=f"spec {i} ({plan.specs[i].kind})",
            )

    def test_all_devices_on_keys_axis(self, eight_devices):
        plan = _plan("SELECT sum(v) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=32, micro_batch=64)
        kt = KeyTable(32)
        slots, _ = kt.encode_column(
            np.array([f"k{i % 20}" for i in range(200)], dtype=np.object_)
        )
        state = sgb.fold(sgb.init_state(), {"v": np.ones(200, np.float32)}, slots)
        outs, act = sgb.finalize(state, kt.n_keys)
        assert outs[0].sum() == 200.0
        assert act.sum() == 200.0

    def test_state_is_actually_sharded(self, eight_devices):
        import jax

        plan = _plan("SELECT count(*) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=64, micro_batch=64)
        state = sgb.init_state()
        shards = state["n"].sharding
        # capacity axis split across 8 devices -> each shard is 8 slots
        assert len(state["n"].addressable_shards) == 8
        assert state["n"].addressable_shards[0].data.shape[0] == 8

    def test_mesh_validation(self, eight_devices):
        with pytest.raises(ValueError):
            make_mesh(rows=3, keys=3)
        plan = _plan("SELECT count(*) FROM d GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        with pytest.raises(ValueError):
            ShardedGroupBy(plan, make_mesh(rows=1, keys=8), capacity=30)
